"""Sequence / ragged ops — the LoD policy (SURVEY.md §7 hard parts).

Reference: the LoD ragged-batch representation (lod_tensor.h:114) feeding
operators/sequence_ops/ (sequence_pad_op, sequence_unpad_op,
sequence_mask_op, sequence_pool_op, ...). LoD offsets do not exist on TPU
— dynamic row partitions defeat XLA's static shapes — so the policy is
**dense + lengths/segment-ids**: every ragged value travels as a padded
dense tensor plus an int lengths (or segment-ids) tensor, and sequence
ops take the lengths explicitly. segment_* mirror the reference's
sequence_pool kernels (sum/mean/max/min over rows of one sequence) in
segment-ids form, implemented on jax.ops.segment_* so XLA lowers them to
one-hot matmuls/scatters that tile onto the MXU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import autograd as AG
from ..core.tensor import Tensor
from ._dispatch import as_tensor, nondiff

__all__ = [
    "sequence_mask", "sequence_pad", "sequence_unpad",
    "segment_sum", "segment_mean", "segment_max", "segment_min",
]


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """[N] lengths -> [N, maxlen] 0/1 mask (sequence_mask_op.cc parity).
    `maxlen` must be static (None -> needs concrete lengths; prefer
    passing maxlen under jit)."""
    x = as_tensor(x)
    if maxlen is None:
        import numpy as np

        maxlen = int(np.asarray(jax.device_get(x._data)).max())
    from ..core.dtype import convert_dtype

    d = convert_dtype(dtype)

    def f(lens):
        r = jnp.arange(maxlen)
        return (r[None, :] < lens[..., None]).astype(d)

    return AG.apply_nondiff(f, (x,))


def sequence_pad(x, pad_value, maxlen, lengths, name=None):
    """Ragged rows (concatenated [total, ...] + lengths) -> padded
    [batch, maxlen, ...] (sequence_pad_op parity; LoD -> lengths).
    Returns (padded, lengths)."""
    x, lengths = as_tensor(x), as_tensor(lengths)
    pv = float(pad_value) if not isinstance(pad_value, Tensor) else pad_value

    def f(vals, lens, *pvt):
        pad = pvt[0] if pvt else jnp.asarray(pv, vals.dtype)
        starts = jnp.concatenate(
            [jnp.zeros((1,), lens.dtype), jnp.cumsum(lens)[:-1]]
        )
        pos = jnp.arange(maxlen)
        idx = starts[:, None] + pos[None, :]           # [n, maxlen]
        valid = pos[None, :] < lens[:, None]
        safe = jnp.clip(idx, 0, vals.shape[0] - 1)
        out = vals[safe]                                # [n, maxlen, ...]
        mask = valid.reshape(valid.shape + (1,) * (out.ndim - 2))
        return jnp.where(mask, out, pad.astype(vals.dtype))

    args = (x, lengths) + (
        (pad_value,) if isinstance(pad_value, Tensor) else ()
    )
    padded = AG.apply(f, args, name="sequence_pad")
    return padded, lengths


def sequence_unpad(x, length, name=None):
    """Padded [batch, maxlen, ...] + lengths -> concatenated [total, ...]
    (sequence_unpad_op parity). `length` must be host-concrete (the output
    row count is data-dependent — outside jit only, like every dynamic-
    shape op under XLA)."""
    import numpy as np

    x, length = as_tensor(x), as_tensor(length)
    lens = np.asarray(jax.device_get(length._data))

    def f(vals):
        rows = [vals[i, : int(l)] for i, l in enumerate(lens)]
        return jnp.concatenate(rows, axis=0)

    return AG.apply(f, (x,), name="sequence_unpad")


def _segment(pool):
    def op(data, segment_ids, name=None, *, num_segments=None):
        data, segment_ids = as_tensor(data), as_tensor(segment_ids)
        import numpy as np

        n = num_segments
        if n is None:
            n = int(np.asarray(jax.device_get(segment_ids._data)).max()) + 1

        def f(vals, ids):
            if pool == "sum":
                return jax.ops.segment_sum(vals, ids, num_segments=n)
            if pool == "mean":
                s = jax.ops.segment_sum(vals, ids, num_segments=n)
                cnt = jax.ops.segment_sum(
                    jnp.ones((vals.shape[0],), vals.dtype), ids,
                    num_segments=n,
                )
                cnt = jnp.maximum(cnt, 1).reshape(
                    (n,) + (1,) * (vals.ndim - 1)
                )
                return s / cnt
            if pool == "max":
                return jax.ops.segment_max(vals, ids, num_segments=n)
            return jax.ops.segment_min(vals, ids, num_segments=n)

        return AG.apply(f, (data, segment_ids), name=f"segment_{pool}")

    op.__name__ = f"segment_{pool}"
    return op


segment_sum = _segment("sum")
segment_mean = _segment("mean")
segment_max = _segment("max")
segment_min = _segment("min")
