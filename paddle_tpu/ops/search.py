"""Search/sort ops (paddle.tensor.search parity).

reference: python/paddle/tensor/search.py over arg_max_op, top_k_v2_op,
argsort_op.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import autograd as AG
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor

__all__ = ["argmax", "argmin", "argsort", "index_of_max", "kthvalue", "mode", "searchsorted", "sort", "topk"]


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = convert_dtype(dtype)

    def f(a):
        r = jnp.argmax(a.reshape(-1) if axis is None else a, axis=0 if axis is None else axis)
        if keepdim and axis is not None:
            r = jnp.expand_dims(r, axis)
        return r.astype(d)

    return AG.apply_nondiff(f, (x,))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = convert_dtype(dtype)

    def f(a):
        r = jnp.argmin(a.reshape(-1) if axis is None else a, axis=0 if axis is None else axis)
        if keepdim and axis is not None:
            r = jnp.expand_dims(r, axis)
        return r.astype(d)

    return AG.apply_nondiff(f, (x,))


def argsort(x, axis=-1, descending=False, name=None):
    def f(a):
        r = jnp.argsort(a, axis=axis)
        if descending:
            r = jnp.flip(r, axis=axis)
        return r

    return AG.apply_nondiff(f, (x,))


def sort(x, axis=-1, descending=False, name=None):
    def f(a):
        r = jnp.sort(a, axis=axis)
        if descending:
            r = jnp.flip(r, axis=axis)
        return r

    return AG.apply(f, (x,), name="sort")


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())
    ax = -1 if axis is None else axis

    def f(a):
        src = a if largest else -a
        src = jnp.moveaxis(src, ax, -1)
        vals, idx = jax.lax.top_k(src, k)
        if not largest:
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax)

    vals, idx = AG.apply(f, (x,), name="topk")
    idx.stop_gradient = True
    return vals, idx


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(a):
        s = jnp.sort(a, axis=axis)
        si = jnp.argsort(a, axis=axis)
        v = jnp.take(s, k - 1, axis=axis)
        i = jnp.take(si, k - 1, axis=axis)
        if keepdim:
            v = jnp.expand_dims(v, axis)
            i = jnp.expand_dims(i, axis)
        return v, i

    vals, idx = AG.apply(f, (x,), name="kthvalue")
    idx.stop_gradient = True
    return vals, idx


def mode(x, axis=-1, keepdim=False, name=None):
    """Most frequent value along axis. O(n^2) compare — fine for the small
    tensors this API sees; large-tensor mode is not on any hot path."""

    def f(a):
        # count[i] = number of elements equal to a[i] along axis
        cnt = jnp.sum(
            jnp.expand_dims(a, axis) == jnp.expand_dims(a, axis - 1 if axis < 0 else axis + 1),
            axis=axis,
        )
        # tie-break toward smallest value like paddle: sort not needed for parity here
        best = jnp.argmax(cnt, axis=axis)
        v = jnp.take_along_axis(a, jnp.expand_dims(best, axis), axis=axis)
        i = jnp.expand_dims(best, axis)
        if not keepdim:
            v = jnp.squeeze(v, axis=axis)
            i = jnp.squeeze(i, axis=axis)
        return v, i

    v, i = AG.apply_nondiff(f, (x,))
    return v, i


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"

    def f(seq, v):
        r = jnp.searchsorted(seq, v, side=side)
        return r.astype(jnp.int32) if out_int32 else r

    return AG.apply_nondiff(f, (sorted_sequence, values))


def index_of_max(x):
    return argmax(x)
