"""paddle_tpu.ops — the op library.

Aggregates all op namespaces and applies the Tensor method patch
(math_op_patch analog, reference:
python/paddle/fluid/dygraph/math_op_patch.py).
"""
from . import creation, linalg, logic, manipulation, math, search  # noqa: F401
from . import sequence  # noqa: F401
from .creation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .sequence import *  # noqa: F401,F403
from . import patch as _patch  # noqa: F401  (side effect: Tensor methods)
