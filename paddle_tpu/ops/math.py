"""Elementwise math + reductions (paddle.tensor.math parity).

reference: python/paddle/tensor/math.py over
paddle/fluid/operators/elementwise/*, activation_op.*, reduce_ops/*.
Every op is an XLA HLO; fusion of elementwise chains into surrounding
matmuls is XLA's job (SURVEY.md §2.4 TPU mapping).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import autograd as AG
from ..core.tensor import Tensor
from ._dispatch import binary, nondiff, unary

__all__ = ["abs", "acos", "acosh", "add", "all", "amax", "amin", "angle", "any", "asin", "asinh", "atan", "atan2", "atanh", "ceil", "clip", "conj", "copysign", "cos", "cosh", "count_nonzero", "cummax", "cummin", "cumprod", "cumsum", "deg2rad", "diff", "digamma", "divide", "erf", "erfinv", "exp", "expm1", "exponential_", "floor", "floor_divide", "floor_mod", "fmax", "fmin", "frac", "gcd", "heaviside", "hypot", "imag", "increment", "inner", "kron", "lcm", "lerp", "lgamma", "log", "log10", "log1p", "log2", "logaddexp", "logit", "logsumexp", "max", "maximum", "mean", "median", "min", "minimum", "mod", "multiplex", "multiply", "nanmean", "nansum", "neg", "nextafter", "outer", "pow", "prod", "quantile", "rad2deg", "real", "reciprocal", "remainder", "round", "rsqrt", "scale", "sigmoid", "sign", "sin", "sinh", "sqrt", "square", "stanh", "std", "subtract", "sum", "tan", "tanh", "trace", "trunc", "var"]

# -- binary elementwise ------------------------------------------------------
add = binary(jnp.add, "add")
subtract = binary(jnp.subtract, "subtract")
multiply = binary(jnp.multiply, "multiply")
divide = binary(jnp.divide, "divide")
floor_divide = binary(jnp.floor_divide, "floor_divide")
mod = binary(jnp.mod, "mod")
remainder = mod
floor_mod = mod
pow = binary(jnp.power, "pow")
maximum = binary(jnp.maximum, "maximum")
minimum = binary(jnp.minimum, "minimum")
fmax = binary(jnp.fmax, "fmax")
fmin = binary(jnp.fmin, "fmin")
atan2 = binary(jnp.arctan2, "atan2")
hypot = binary(jnp.hypot, "hypot")
logaddexp = binary(jnp.logaddexp, "logaddexp")
heaviside = binary(jnp.heaviside, "heaviside")
nextafter = binary(jnp.nextafter, "nextafter")
copysign = binary(jnp.copysign, "copysign")
gcd = nondiff(jnp.gcd, "gcd")
lcm = nondiff(jnp.lcm, "lcm")

# -- unary elementwise -------------------------------------------------------
exp = unary(jnp.exp, "exp")
expm1 = unary(jnp.expm1, "expm1")
log = unary(jnp.log, "log")
log2 = unary(jnp.log2, "log2")
log10 = unary(jnp.log10, "log10")
log1p = unary(jnp.log1p, "log1p")
sqrt = unary(jnp.sqrt, "sqrt")
rsqrt = unary(jax.lax.rsqrt, "rsqrt")
square = unary(jnp.square, "square")
abs = unary(jnp.abs, "abs")
sign = unary(jnp.sign, "sign")
neg = unary(jnp.negative, "neg")
reciprocal = unary(jnp.reciprocal, "reciprocal")
floor = unary(jnp.floor, "floor")
ceil = unary(jnp.ceil, "ceil")
round = unary(jnp.round, "round")
trunc = unary(jnp.trunc, "trunc")
frac = unary(lambda x: x - jnp.trunc(x), "frac")
sin = unary(jnp.sin, "sin")
cos = unary(jnp.cos, "cos")
tan = unary(jnp.tan, "tan")
asin = unary(jnp.arcsin, "asin")
acos = unary(jnp.arccos, "acos")
atan = unary(jnp.arctan, "atan")
sinh = unary(jnp.sinh, "sinh")
cosh = unary(jnp.cosh, "cosh")
tanh = unary(jnp.tanh, "tanh")
asinh = unary(jnp.arcsinh, "asinh")
acosh = unary(jnp.arccosh, "acosh")
atanh = unary(jnp.arctanh, "atanh")
erf = unary(jax.scipy.special.erf, "erf")
erfinv = unary(jax.scipy.special.erfinv, "erfinv")
lgamma = unary(jax.scipy.special.gammaln, "lgamma")
digamma = unary(jax.scipy.special.digamma, "digamma")
sigmoid = unary(jax.nn.sigmoid, "sigmoid")
logit = unary(jax.scipy.special.logit, "logit")
angle = unary(jnp.angle, "angle")
conj = unary(jnp.conj, "conj")
real = unary(jnp.real, "real")
imag = unary(jnp.imag, "imag")
rad2deg = unary(jnp.rad2deg, "rad2deg")
deg2rad = unary(jnp.deg2rad, "deg2rad")
exponential_ = unary(jnp.exp, "exponential_")  # shim


def increment(x, value=1.0, name=None):
    out = AG.apply(lambda a: a + value, (x,), name="increment")
    x._data = out._data
    return x


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    """paddle.scale (operators/scale_op.cc)."""
    s = scale._data if isinstance(scale, Tensor) else scale

    def f(a):
        if bias_after_scale:
            r = a * s + bias
        else:
            r = (a + bias) * s
        return r

    out = AG.apply(f, (x,), name="scale")
    if act is not None:
        from ..nn import functional as F

        out = getattr(F, act)(out)
    return out


def clip(x, min=None, max=None, name=None):
    mn = min._data if isinstance(min, Tensor) else min
    mx = max._data if isinstance(max, Tensor) else max
    return AG.apply(lambda a: jnp.clip(a, mn, mx), (x,), name="clip")


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return AG.apply(lambda a, b, w: a + w * (b - a), (x, y, weight),
                        name="lerp")
    return AG.apply(lambda a, b: a + weight * (b - a), (x, y), name="lerp")


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return AG.apply(lambda a: scale_b * jnp.tanh(scale_a * a), (x,), name="stanh")


def multiplex(inputs, index, name=None):
    stacked = AG.apply(
        lambda *rs: jnp.stack(rs, axis=0), tuple(inputs), name="multiplex_stack"
    )
    idx = index._data.reshape(-1)
    return AG.apply(
        lambda s: s[idx, jnp.arange(s.shape[1])], (stacked,), name="multiplex"
    )


# -- reductions --------------------------------------------------------------


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _reduce(jfn, name):
    def op(x, axis=None, keepdim=False, name_=None, **kw):
        ax = _axis(axis)
        return AG.apply(
            lambda a: jfn(a, axis=ax, keepdims=keepdim, **kw), (x,), name=name
        )

    op.__name__ = name
    return op


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    from ..core.dtype import convert_dtype

    ax = _axis(axis)
    d = convert_dtype(dtype) if dtype is not None else None
    return AG.apply(
        lambda a: jnp.sum(a, axis=ax, keepdims=keepdim, dtype=d), (x,), name="sum"
    )


def mean(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return AG.apply(lambda a: jnp.mean(a, axis=ax, keepdims=keepdim), (x,), name="mean")


prod = _reduce(jnp.prod, "prod")
max = _reduce(jnp.max, "max")
min = _reduce(jnp.min, "min")
amax = _reduce(jnp.max, "amax")
amin = _reduce(jnp.min, "amin")


def all(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return AG.apply_nondiff(lambda a: jnp.all(a, axis=ax, keepdims=keepdim), (x,))


def any(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return AG.apply_nondiff(lambda a: jnp.any(a, axis=ax, keepdims=keepdim), (x,))


def logsumexp(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return AG.apply(
        lambda a: jax.scipy.special.logsumexp(a, axis=ax, keepdims=keepdim),
        (x,),
        name="logsumexp",
    )


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _axis(axis)
    ddof = 1 if unbiased else 0
    return AG.apply(
        lambda a: jnp.std(a, axis=ax, ddof=ddof, keepdims=keepdim), (x,), name="std"
    )


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _axis(axis)
    ddof = 1 if unbiased else 0
    return AG.apply(
        lambda a: jnp.var(a, axis=ax, ddof=ddof, keepdims=keepdim), (x,), name="var"
    )


def median(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return AG.apply(
        lambda a: jnp.median(a, axis=ax, keepdims=keepdim), (x,), name="median"
    )


def quantile(x, q, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return AG.apply(
        lambda a: jnp.quantile(a, q, axis=ax, keepdims=keepdim), (x,), name="quantile"
    )


def nanmean(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return AG.apply(
        lambda a: jnp.nanmean(a, axis=ax, keepdims=keepdim), (x,), name="nanmean"
    )


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    from ..core.dtype import convert_dtype

    ax = _axis(axis)
    d = convert_dtype(dtype) if dtype is not None else None
    return AG.apply(
        lambda a: jnp.nansum(a, axis=ax, keepdims=keepdim, dtype=d),
        (x,),
        name="nansum",
    )


def cumsum(x, axis=None, dtype=None, name=None):
    from ..core.dtype import convert_dtype

    d = convert_dtype(dtype) if dtype is not None else None

    def f(a):
        if axis is None:
            return jnp.cumsum(a.reshape(-1), dtype=d)
        return jnp.cumsum(a, axis=int(axis), dtype=d)

    return AG.apply(f, (x,), name="cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    from ..core.dtype import convert_dtype

    d = convert_dtype(dtype) if dtype is not None else None

    def f(a):
        if dim is None:
            return jnp.cumprod(a.reshape(-1), dtype=d)
        return jnp.cumprod(a, axis=int(dim), dtype=d)

    return AG.apply(f, (x,), name="cumprod")


def cummax(x, axis=None, dtype=None, name=None):
    def f(a):
        if axis is None:
            a = a.reshape(-1)
            ax = 0
        else:
            ax = int(axis)
        return jax.lax.cummax(a, axis=ax)

    return AG.apply(f, (x,), name="cummax")


def cummin(x, axis=None, dtype=None, name=None):
    def f(a):
        if axis is None:
            a = a.reshape(-1)
            ax = 0
        else:
            ax = int(axis)
        return jax.lax.cummin(a, axis=ax)

    return AG.apply(f, (x,), name="cummin")


def count_nonzero(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return AG.apply_nondiff(
        lambda a: jnp.count_nonzero(a, axis=ax, keepdims=keepdim), (x,)
    )


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return AG.apply(
        lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2),
        (x,),
        name="trace",
    )


def kron(x, y, name=None):
    return AG.apply(jnp.kron, (x, y), name="kron")


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    pre = prepend._data if isinstance(prepend, Tensor) else prepend
    app = append._data if isinstance(append, Tensor) else append
    return AG.apply(
        lambda a: jnp.diff(a, n=n, axis=axis, prepend=pre, append=app),
        (x,),
        name="diff",
    )


def inner(x, y, name=None):
    return AG.apply(jnp.inner, (x, y), name="inner")


def outer(x, y, name=None):
    return AG.apply(jnp.outer, (x, y), name="outer")


# -- round-4 op-gap closure (reference op-library parity, VERDICT r3 #6) ----
def logcumsumexp(x, axis=None, dtype=None, name=None):
    x = x if isinstance(x, Tensor) else Tensor(x)
    if dtype is not None:
        x = x.astype(dtype)

    def f(a):
        if axis is None:
            return jax.lax.cumlogsumexp(a.reshape(-1), axis=0)
        return jax.lax.cumlogsumexp(a, axis=axis)

    return AG.apply(f, (x,), name="logcumsumexp")


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return AG.apply(
        lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf),
        (x if isinstance(x, Tensor) else Tensor(x),),
        name="nan_to_num",
    )


sgn = unary(jnp.sign, "sgn")
signbit = nondiff(jnp.signbit, "signbit")
isposinf = nondiff(jnp.isposinf, "isposinf")
isneginf = nondiff(jnp.isneginf, "isneginf")
isreal = nondiff(jnp.isreal, "isreal")
i0 = unary(jax.scipy.special.i0, "i0")
i0e = unary(jax.scipy.special.i0e, "i0e")
i1 = unary(jax.scipy.special.i1, "i1")
i1e = unary(jax.scipy.special.i1e, "i1e")


def polygamma(x, n, name=None):
    return AG.apply(
        lambda a: jax.scipy.special.polygamma(n, a),
        (x if isinstance(x, Tensor) else Tensor(x),),
        name="polygamma",
    )


def _trapz_fn():
    fn = getattr(jnp, "trapezoid", None)
    return fn if fn is not None else jnp.trapz


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    f = _trapz_fn()
    y = y if isinstance(y, Tensor) else Tensor(y)
    if x is not None:
        return AG.apply(
            lambda yy, xx: f(yy, x=xx, axis=axis), (y, x), name="trapezoid"
        )
    return AG.apply(
        lambda yy: f(yy, dx=1.0 if dx is None else dx, axis=axis),
        (y,), name="trapezoid",
    )


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = y if isinstance(y, Tensor) else Tensor(y)

    def ct(yy, spacing):
        y1 = jax.lax.slice_in_dim(yy, 1, None, axis=axis)
        y0 = jax.lax.slice_in_dim(yy, 0, yy.shape[axis] - 1, axis=axis)
        return jnp.cumsum((y0 + y1) / 2 * spacing, axis=axis)

    if x is not None:
        def f(yy, xx):
            d = jnp.diff(xx, axis=axis)
            return ct(yy, d)

        return AG.apply(f, (y, x), name="cumulative_trapezoid")
    return AG.apply(
        lambda yy: ct(yy, 1.0 if dx is None else dx), (y,),
        name="cumulative_trapezoid",
    )


def vander(x, n=None, increasing=False, name=None):
    return AG.apply(
        lambda a: jnp.vander(a, N=n, increasing=increasing),
        (x if isinstance(x, Tensor) else Tensor(x),),
        name="vander",
    )


ldexp = binary(lambda a, b: jnp.ldexp(a, b.astype(jnp.int32)), "ldexp")


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    from ._dispatch import as_tensor as _at

    d = jnp.int32 if (out_int32 or not jax.config.read("jax_enable_x64")) \
        else jnp.int64
    return AG.apply_nondiff(
        lambda a, s: jnp.searchsorted(
            s, a, side="right" if right else "left"
        ).astype(d),
        (_at(x), _at(sorted_sequence)),
    )


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    from ._dispatch import as_tensor as _at

    return AG.apply_nondiff(
        lambda a, t: jnp.isin(a, t, assume_unique=assume_unique,
                              invert=invert),
        (_at(x), _at(test_x)),
    )


def take(x, index, mode="raise", name=None):
    """Flattened gather (paddle.take): index into x.flatten(). mode=
    "raise" bounds-checks eagerly on concrete indices (under jit, where a
    data-dependent raise cannot exist, it degrades to clip)."""
    import numpy as _np

    from ._dispatch import as_tensor as _at

    if mode == "raise":
        it = index if isinstance(index, Tensor) else Tensor(index)
        try:
            idx_np = _np.asarray(jax.device_get(it._data))
        except Exception:
            idx_np = None  # traced index: data-dependent raise impossible
        if idx_np is not None and idx_np.size:
            xt = x if isinstance(x, Tensor) else Tensor(x)
            n = 1
            for s in xt.shape:
                n *= s
            if idx_np.max() >= n or idx_np.min() < -n:
                raise IndexError(
                    f"take: index out of range for tensor with {n} elements"
                )
    jmode = {"raise": "clip", "clip": "clip", "wrap": "wrap"}[mode]

    def f(a, i):
        flat = a.reshape(-1)
        if mode != "clip":
            # python-style negatives ('clip' keeps numpy semantics:
            # negative indices clamp to 0)
            i = jnp.where(i < 0, i + flat.shape[0], i)
        return jnp.take(flat, i, mode=jmode)

    return AG.apply(f, (_at(x), _at(index)), name="take")


def renorm(x, p, axis, max_norm, name=None):
    """Per-slice p-norm clipping along `axis` (renorm_op parity)."""

    def f(a):
        moved = jnp.moveaxis(a, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
        scale = jnp.where(norms > max_norm,
                          max_norm / jnp.maximum(norms, 1e-12), 1.0)
        out = flat * scale[:, None]
        return jnp.moveaxis(out.reshape(moved.shape), 0, axis)

    return AG.apply(f, (x if isinstance(x, Tensor) else Tensor(x),),
                    name="renorm")


def numel(x, name=None):
    import numpy as _np

    x = x if isinstance(x, Tensor) else Tensor(x)
    return Tensor(_np.int64(int(_np.prod(x.shape)) if x.shape else 1))


def nanmedian(x, axis=None, keepdim=False, name=None):
    return AG.apply(
        lambda a: jnp.nanmedian(a, axis=axis, keepdims=keepdim),
        (x if isinstance(x, Tensor) else Tensor(x),), name="nanmedian",
    )


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return AG.apply(
        lambda a: jnp.nanquantile(a, q, axis=axis, keepdims=keepdim),
        (x if isinstance(x, Tensor) else Tensor(x),), name="nanquantile",
    )


__all__ += [
    "logcumsumexp", "nan_to_num", "sgn", "signbit", "isposinf", "isneginf",
    "isreal", "i0", "i0e", "i1", "i1e", "polygamma", "trapezoid",
    "cumulative_trapezoid", "vander", "ldexp", "bucketize", "isin", "take",
    "renorm", "numel", "nanmedian", "nanquantile",
]
