"""Comparison & logical ops (paddle.tensor.logic parity).

reference: python/paddle/tensor/logic.py over compare_op.cc, logical_op.cc.
All non-differentiable; never recorded on the tape.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core import autograd as AG
from ..core.tensor import Tensor
from ._dispatch import as_tensor

__all__ = ["allclose", "bitwise_and", "bitwise_not", "bitwise_or", "bitwise_xor", "equal", "equal_all", "greater_equal", "greater_than", "is_empty", "is_tensor", "isclose", "isfinite", "isinf", "isnan", "less_equal", "less_than", "logical_and", "logical_not", "logical_or", "logical_xor", "not_equal"]


def _cmp(jfn, name):
    def op(x, y, name_=None):
        xt, yt = isinstance(x, Tensor), isinstance(y, Tensor)
        if xt and yt:
            return AG.apply_nondiff(jfn, (x, y))
        if xt:
            return AG.apply_nondiff(lambda a: jfn(a, y), (x,))
        if yt:
            return AG.apply_nondiff(lambda b: jfn(x, b), (y,))
        return AG.apply_nondiff(jfn, (as_tensor(x), as_tensor(y)))

    op.__name__ = name
    return op


equal = _cmp(jnp.equal, "equal")
not_equal = _cmp(jnp.not_equal, "not_equal")
less_than = _cmp(jnp.less, "less_than")
less_equal = _cmp(jnp.less_equal, "less_equal")
greater_than = _cmp(jnp.greater, "greater_than")
greater_equal = _cmp(jnp.greater_equal, "greater_equal")
logical_and = _cmp(jnp.logical_and, "logical_and")
logical_or = _cmp(jnp.logical_or, "logical_or")
logical_xor = _cmp(jnp.logical_xor, "logical_xor")
bitwise_and = _cmp(jnp.bitwise_and, "bitwise_and")
bitwise_or = _cmp(jnp.bitwise_or, "bitwise_or")
bitwise_xor = _cmp(jnp.bitwise_xor, "bitwise_xor")


def logical_not(x, out=None, name=None):
    return AG.apply_nondiff(jnp.logical_not, (as_tensor(x),))


def bitwise_not(x, out=None, name=None):
    return AG.apply_nondiff(jnp.bitwise_not, (as_tensor(x),))


def isnan(x, name=None):
    return AG.apply_nondiff(jnp.isnan, (x,))


def isinf(x, name=None):
    return AG.apply_nondiff(jnp.isinf, (x,))


def isfinite(x, name=None):
    return AG.apply_nondiff(jnp.isfinite, (x,))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return AG.apply_nondiff(
        lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        (as_tensor(x), as_tensor(y)),
    )


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return AG.apply_nondiff(
        lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        (as_tensor(x), as_tensor(y)),
    )


def equal_all(x, y, name=None):
    return AG.apply_nondiff(
        lambda a, b: jnp.array_equal(a, b), (as_tensor(x), as_tensor(y))
    )


def is_empty(x, name=None):
    return Tensor._wrap(jnp.asarray(int(np.prod(x._data.shape)) == 0))


def is_tensor(x):
    return isinstance(x, Tensor)
