"""Tensor creation ops (paddle.tensor.creation parity).

reference: python/paddle/tensor/creation.py; kernel side
paddle/fluid/operators/fill_constant_op.cc etc. All creation lowers to XLA
constants / iota; random ops draw from the global generator
(paddle_tpu.core.random) in eager mode.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import random as rnd
from ..core.dtype import convert_dtype, default_float_dtype
from ..core.tensor import Tensor, to_tensor  # re-export to_tensor

__all__ = [
    "to_tensor",
    "zeros",
    "ones",
    "full",
    "empty",
    "zeros_like",
    "ones_like",
    "full_like",
    "empty_like",
    "arange",
    "linspace",
    "eye",
    "diag",
    "diagflat",
    "tril",
    "triu",
    "meshgrid",
    "assign",
    "clone",
    "rand",
    "randn",
    "randint",
    "randperm",
    "uniform",
    "normal",
    "bernoulli",
    "multinomial",
    "standard_normal",
]


from ._dispatch import canon_shape as _shape  # noqa: E402


def _dt(dtype, default=None):
    if dtype is None:
        return default if default is not None else default_float_dtype()
    return convert_dtype(dtype)


def zeros(shape, dtype=None, name=None):
    return Tensor._wrap(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor._wrap(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        dtype = _infer_fill_dtype(fill_value)
    return Tensor._wrap(jnp.full(_shape(shape), fill_value, _dt(dtype)))


def _infer_fill_dtype(v):
    if isinstance(v, bool):
        return "bool"
    if isinstance(v, int):
        return "int64" if jax.config.read("jax_enable_x64") else "int32"
    return None


def empty(shape, dtype=None, name=None):
    # XLA has no uninitialized memory; zeros is the honest equivalent.
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    return Tensor._wrap(jnp.zeros(x._data.shape, _dt(dtype, x._data.dtype)))


def ones_like(x, dtype=None, name=None):
    return Tensor._wrap(jnp.ones(x._data.shape, _dt(dtype, x._data.dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    return Tensor._wrap(
        jnp.full(x._data.shape, fill_value, _dt(dtype, x._data.dtype))
    )


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x

    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        if all(isinstance(v, int) for v in (start, end, step)):
            dtype = "int64" if jax.config.read("jax_enable_x64") else "int32"
    return Tensor._wrap(jnp.arange(start, end, step, dtype=_dt(dtype, None)))


def linspace(start, stop, num, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x

    return Tensor._wrap(
        jnp.linspace(_v(start), _v(stop), int(_v(num)), dtype=_dt(dtype))
    )


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor._wrap(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    from ..core import autograd as AG

    if padding_value != 0 and x._data.ndim == 1:
        def f(a):
            d = jnp.diag(a, k=offset)
            mask = jnp.eye(d.shape[0], d.shape[1], k=offset, dtype=bool)
            return jnp.where(mask, d, jnp.asarray(padding_value, d.dtype))

        return AG.apply(f, (x,), name="diag")
    return AG.apply(lambda a: jnp.diag(a, k=offset), (x,), name="diag")


def diagflat(x, offset=0, name=None):
    from ..core import autograd as AG

    return AG.apply(lambda a: jnp.diagflat(a, k=offset), (x,), name="diagflat")


def tril(x, diagonal=0, name=None):
    from ..core import autograd as AG

    return AG.apply(lambda a: jnp.tril(a, k=diagonal), (x,), name="tril")


def triu(x, diagonal=0, name=None):
    from ..core import autograd as AG

    return AG.apply(lambda a: jnp.triu(a, k=diagonal), (x,), name="triu")


def meshgrid(*args, **kwargs):
    from ..core import autograd as AG

    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    outs = AG.apply(lambda *rs: tuple(jnp.meshgrid(*rs, indexing="ij")), args)
    return list(outs)


def assign(x, output=None):
    """paddle.assign — copy a value into a (new or given) tensor."""
    src = x if isinstance(x, Tensor) else Tensor(x)
    if output is None:
        return src.clone()
    output.set_value(src)
    return output


def clone(x, name=None):
    return x.clone()


# -- random -----------------------------------------------------------------


def rand(shape, dtype=None, name=None):
    return Tensor._wrap(
        jax.random.uniform(rnd.next_key(), _shape(shape), _dt(dtype))
    )


def randn(shape, dtype=None, name=None):
    return Tensor._wrap(
        jax.random.normal(rnd.next_key(), _shape(shape), _dt(dtype))
    )


standard_normal = randn


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    d = _dt(dtype, jnp.int32)
    return Tensor._wrap(
        jax.random.randint(rnd.next_key(), _shape(shape), low, high, dtype=d)
    )


def randperm(n, dtype=None, name=None):
    d = _dt(dtype, jnp.int32)
    return Tensor._wrap(
        jax.random.permutation(rnd.next_key(), jnp.arange(n)).astype(d)
    )


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.PRNGKey(seed) if seed else rnd.next_key()
    return Tensor._wrap(
        jax.random.uniform(key, _shape(shape), _dt(dtype), minval=min, maxval=max)
    )


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(
            getattr(m, "shape", ()), getattr(s, "shape", ())
        )
        return Tensor._wrap(
            jax.random.normal(rnd.next_key(), shp, default_float_dtype()) * s + m
        )
    shp = _shape(shape) if shape is not None else ()
    return Tensor._wrap(
        jax.random.normal(rnd.next_key(), shp, default_float_dtype()) * std + mean
    )


def bernoulli(x, name=None):
    return Tensor._wrap(
        jax.random.bernoulli(rnd.next_key(), x._data).astype(x._data.dtype)
    )


def multinomial(x, num_samples=1, replacement=False, name=None):
    logits = jnp.log(jnp.maximum(x._data, 1e-30))
    if x._data.ndim == 1:
        out = jax.random.choice(
            rnd.next_key(),
            x._data.shape[-1],
            shape=(num_samples,),
            replace=replacement,
            p=x._data / x._data.sum(),
        )
    else:
        keys = jax.random.split(rnd.next_key(), x._data.shape[0])
        out = jnp.stack(
            [
                jax.random.choice(
                    k,
                    x._data.shape[-1],
                    shape=(num_samples,),
                    replace=replacement,
                    p=row / row.sum(),
                )
                for k, row in zip(keys, x._data)
            ]
        )
    return Tensor._wrap(out.astype(jnp.int64 if jax.config.read("jax_enable_x64") else jnp.int32))


# -- round-4 op-gap closure (VERDICT r3 #6) ---------------------------------
def tril_indices(row, col=None, offset=0, dtype="int64"):
    from ..core.dtype import convert_dtype

    col = row if col is None else col
    r, c = jnp.tril_indices(row, k=offset, m=col)
    return Tensor(jnp.stack([r, c]).astype(convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    from ..core.dtype import convert_dtype

    col = row if col is None else col
    r, c = jnp.triu_indices(row, k=offset, m=col)
    return Tensor(jnp.stack([r, c]).astype(convert_dtype(dtype)))


def poisson(x, name=None):
    """Per-element Poisson draw with rate x (poisson_op parity)."""
    x = x if isinstance(x, Tensor) else Tensor(x)
    return Tensor._wrap(
        jax.random.poisson(rnd.next_key(), x._data).astype(x._data.dtype)
    )


def polar(abs, angle, name=None):
    from ..core import autograd as AG

    a = abs if isinstance(abs, Tensor) else Tensor(abs)
    g = angle if isinstance(angle, Tensor) else Tensor(angle)
    return AG.apply(
        lambda r, t: jax.lax.complex(r * jnp.cos(t), r * jnp.sin(t)),
        (a, g), name="polar",
    )


def complex(real, imag, name=None):
    from ..core import autograd as AG

    r = real if isinstance(real, Tensor) else Tensor(real)
    i = imag if isinstance(imag, Tensor) else Tensor(imag)
    return AG.apply(lambda a, b: jax.lax.complex(a, b), (r, i),
                    name="complex")


__all__ += [
    "tril_indices", "triu_indices", "poisson", "polar", "complex",
]
