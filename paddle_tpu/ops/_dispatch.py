"""Op dispatch helpers.

The analog of the reference's OperatorWithKernel dispatch + generated
`core.ops.*` fast path (reference: paddle/fluid/framework/operator.cc:1068
RunImpl, paddle/fluid/pybind/op_function_generator.cc:242,488). There is no
kernel table here: every op lowers to XLA through jax, and the "kernel
choice" (device, fusion, tiling) is the compiler's job. What this layer does
is (a) Tensor<->raw marshalling, (b) scalar-vs-tensor argument handling with
weak-type preservation, (c) tape recording via autograd.apply.
"""
from __future__ import annotations

import numpy as np

from ..core import autograd as AG
from ..core.tensor import Tensor


def canon_shape(shape):
    """Coerce a user shape spec (int | sequence of int/Tensor | Tensor) to a
    tuple of python ints — the single shape-normalization point for
    creation/manipulation ops."""
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in shape.tolist())
    if isinstance(shape, int):
        return (shape,)
    out = []
    for s in shape:
        out.append(int(s.item()) if isinstance(s, Tensor) else int(s))
    return tuple(out)


def as_tensor(x, like=None):
    """Coerce x to Tensor. Python scalars stay scalars at call sites (weak
    typing keeps result dtype anchored to the tensor operand, matching
    paddle's scalar-op semantics)."""
    if isinstance(x, Tensor):
        return x
    return Tensor(x)


def unary(fn, name=None):
    def op(x, *, _fn=fn, **kw):
        x = as_tensor(x)
        kw.pop("name", None)  # paddle-API name= is documentation only
        if kw:
            return AG.apply(lambda a: _fn(a, **kw), (x,), name=name)
        return AG.apply(_fn, (x,), name=name)

    op.__name__ = name or fn.__name__
    return op


def binary(fn, name=None):
    """Binary op accepting Tensor|scalar on either side (math_op_patch analog)."""

    def op(x, y, name_=None, *, _fn=fn):
        xt = isinstance(x, Tensor)
        yt = isinstance(y, Tensor)
        if xt and yt:
            return AG.apply(_fn, (x, y), name=name)
        if xt:
            if isinstance(y, np.ndarray):
                return AG.apply(_fn, (x, Tensor(y)), name=name)
            return AG.apply(lambda a: _fn(a, y), (x,), name=name)
        if yt:
            if isinstance(x, np.ndarray):
                return AG.apply(_fn, (Tensor(x), y), name=name)
            return AG.apply(lambda b: _fn(x, b), (y,), name=name)
        return AG.apply(_fn, (Tensor(x), Tensor(y)), name=name)

    op.__name__ = name or fn.__name__
    return op


def nondiff(fn, name=None):
    """Op with no gradient (comparisons, int outputs, argmax...)."""

    def op(*args, _fn=fn, **kw):
        ts = tuple(as_tensor(a) for a in args)
        kw.pop("name", None)
        if kw:
            return AG.apply_nondiff(lambda *r: _fn(*r, **kw), ts)
        return AG.apply_nondiff(_fn, ts)

    op.__name__ = name or fn.__name__
    return op
