"""paddle_tpu.text — NLP datasets (reference: python/paddle/text/:
__init__.py re-exports datasets/; SURVEY.md §2.8 paddle.text row)."""
from .datasets import *  # noqa: F401,F403
from . import datasets  # noqa: F401

__all__ = datasets.__all__
