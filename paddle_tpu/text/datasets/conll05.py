"""CoNLL-2005 SRL test dataset (reference: text/datasets/conll05.py —
conll05st-release tarball: gzipped words/props column files; props
bracket notation decoded to B-/I-/O tag sequences, one sample per
(sentence, predicate))."""
from __future__ import annotations

import gzip
import tarfile

import numpy as np

from ...io.dataset import Dataset
from ._common import resolve_data_file

__all__ = ["Conll05st"]

URL = "http://paddlemodels.bj.bcebos.com/conll05st/conll05st-tests.tar.gz"

_WORDS = "conll05st-release/test.wsj/words/test.wsj.words.gz"
_PROPS = "conll05st-release/test.wsj/props/test.wsj.props.gz"


class Conll05st(Dataset):
    """Samples are (sentence words, predicate word, BIO label sequence);
    ids are left to the caller's vocabulary (the reference additionally
    ships frozen word/verb/target dicts — pass them through
    `word_dict`/`verb_dict`/`label_dict` to get id arrays)."""

    def __init__(self, data_file=None, word_dict=None, verb_dict=None,
                 label_dict=None, download=True):
        self.data_file = resolve_data_file(
            data_file, download, "conll05st", URL
        )
        self.word_dict = word_dict
        self.verb_dict = verb_dict
        self.label_dict = label_dict
        self._load()

    @staticmethod
    def _decode_props(col):
        """One predicate's bracket column -> BIO tags."""
        tags, cur, inside = [], "O", False
        for tok in col:
            if tok == "*":
                tags.append("I-" + cur if inside else "O")
            elif tok == "*)":
                tags.append("I-" + cur)
                inside = False
            elif "(" in tok:
                cur = tok[1:tok.find("*")]
                tags.append("B-" + cur)
                inside = ")" not in tok
            else:
                raise RuntimeError(f"unexpected props label: {tok}")
        return tags

    def _load(self):
        self.sentences, self.predicates, self.labels = [], [], []
        with tarfile.open(self.data_file) as tf, \
                gzip.GzipFile(fileobj=tf.extractfile(_WORDS)) as wf, \
                gzip.GzipFile(fileobj=tf.extractfile(_PROPS)) as pf:
            words, cols = [], []
            for wline, pline in zip(wf, pf):
                word = wline.decode("utf-8", "ignore").strip()
                parts = pline.decode("utf-8", "ignore").strip().split()
                if not parts:  # sentence boundary
                    self._emit(words, cols)
                    words, cols = [], []
                    continue
                words.append(word)
                cols.append(parts)
            self._emit(words, cols)

    def _emit(self, words, cols):
        if not words:
            return
        verbs = [v for v in (row[0] for row in cols) if v != "-"]
        n_pred = len(cols[0]) - 1
        for i in range(n_pred):
            col = [row[i + 1] for row in cols]
            self.sentences.append(list(words))
            self.predicates.append(verbs[i])
            self.labels.append(self._decode_props(col))

    def __getitem__(self, idx):
        sent, pred, labels = (
            self.sentences[idx], self.predicates[idx], self.labels[idx]
        )
        if self.word_dict is not None:
            unk = self.word_dict.get("<unk>", 0)
            sent = np.array([self.word_dict.get(w.lower(), unk)
                             for w in sent])
            pred = np.array([self.verb_dict.get(pred, 0)])
            labels = np.array([self.label_dict[t] for t in labels])
        return sent, pred, labels

    def __len__(self):
        return len(self.sentences)
