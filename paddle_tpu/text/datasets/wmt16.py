"""WMT16 (Multi30K) en<->de dataset (reference: text/datasets/wmt16.py —
tar with wmt16/{train,test,val} tab-separated parallel corpus; word dicts
BUILT from the train split with <s>/<e>/<unk> heading the vocab)."""
from __future__ import annotations

import tarfile
from collections import defaultdict

import numpy as np

from ...io.dataset import Dataset
from ._common import resolve_data_file

__all__ = ["WMT16"]

URL = "http://paddlemodels.bj.bcebos.com/wmt/wmt16.tar.gz"

START_MARK = "<s>"
END_MARK = "<e>"
UNK_MARK = "<unk>"


class WMT16(Dataset):
    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", download=True):
        if mode.lower() not in ("train", "test", "val"):
            raise ValueError(
                f"mode should be 'train', 'test' or 'val', got {mode}"
            )
        if lang not in ("en", "de"):
            raise ValueError(f"lang should be 'en' or 'de', got {lang}")
        self.mode = mode.lower()
        self.lang = lang
        self.data_file = resolve_data_file(data_file, download, "wmt16", URL)
        self.src_dict = self._build_dict(src_dict_size, lang)
        self.trg_dict = self._build_dict(
            trg_dict_size, "de" if lang == "en" else "en"
        )
        self._load()

    def _build_dict(self, dict_size, lang):
        freq = defaultdict(int)
        col = 0 if lang == "en" else 1
        with tarfile.open(self.data_file) as tf:
            for line in tf.extractfile("wmt16/train"):
                parts = line.decode("utf-8", "ignore").strip().split("\t")
                if len(parts) != 2:
                    continue
                for w in parts[col].split():
                    freq[w] += 1
        words = [START_MARK, END_MARK, UNK_MARK] + [
            w for w, _ in sorted(freq.items(), key=lambda x: -x[1])
        ]
        if dict_size > 0:
            words = words[:dict_size]
        return {w: i for i, w in enumerate(words)}

    def _load(self):
        start = self.src_dict[START_MARK]
        end = self.src_dict[END_MARK]
        unk = self.src_dict[UNK_MARK]
        src_col = 0 if self.lang == "en" else 1
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self.data_file) as tf:
            for line in tf.extractfile(f"wmt16/{self.mode}"):
                parts = line.decode("utf-8", "ignore").strip().split("\t")
                if len(parts) != 2:
                    continue
                src = [start] + [
                    self.src_dict.get(w, unk)
                    for w in parts[src_col].split()
                ] + [end]
                trg = [
                    self.trg_dict.get(w, unk)
                    for w in parts[1 - src_col].split()
                ]
                self.src_ids.append(src)
                self.trg_ids_next.append(trg + [end])
                self.trg_ids.append([start] + trg)

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)

    def get_dict(self, lang, reverse=False):
        d = self.src_dict if lang == self.lang else self.trg_dict
        return {v: k for k, v in d.items()} if reverse else d
