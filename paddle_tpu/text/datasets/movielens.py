"""MovieLens-1M dataset (reference: text/datasets/movielens.py — ml-1m
zip: movies.dat/users.dat/ratings.dat with '::' separators; sample =
(user fields, movie fields, title ids, category one-hot, rating) with a
seeded random train/test split)."""
from __future__ import annotations

import random
import re
import zipfile

import numpy as np

from ...io.dataset import Dataset
from ._common import resolve_data_file

__all__ = ["Movielens"]

URL = "https://dataset.bj.bcebos.com/movielens%2Fml-1m.zip"

age_table = [1, 18, 25, 35, 45, 50, 56]


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, categories_dict, movie_title_dict):
        return [
            [self.index],
            [categories_dict[c] for c in self.categories],
            [movie_title_dict[w.lower()] for w in self.title.split()],
        ]


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [[self.index], [0 if self.is_male else 1], [self.age],
                [self.job_id]]


class Movielens(Dataset):
    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        if mode.lower() not in ("train", "test"):
            raise ValueError(f"mode should be 'train' or 'test', got {mode}")
        self.mode = mode.lower()
        self.test_ratio = test_ratio
        self.rand_seed = rand_seed
        self.data_file = resolve_data_file(
            data_file, download, "movielens", URL
        )
        self._load_meta()
        self._load_data()

    def _load_meta(self):
        pattern = re.compile(r"^(.*)\((\d{4})\)$")
        self.movie_info, self.movie_title_dict = {}, {}
        self.categories_dict, self.user_info = {}, {}
        with zipfile.ZipFile(self.data_file) as z:
            with z.open("ml-1m/movies.dat") as f:
                for line in f:
                    line = line.decode("latin1").strip()
                    movie_id, title, categories = line.split("::")
                    categories = categories.split("|")
                    m = pattern.match(title)
                    title = m.group(1).strip() if m else title
                    self.movie_info[int(movie_id)] = MovieInfo(
                        movie_id, categories, title
                    )
                    for c in categories:
                        self.categories_dict.setdefault(
                            c, len(self.categories_dict)
                        )
                    for w in title.split():
                        self.movie_title_dict.setdefault(
                            w.lower(), len(self.movie_title_dict)
                        )
            with z.open("ml-1m/users.dat") as f:
                for line in f:
                    line = line.decode("latin1").strip()
                    uid, gender, age, job, _ = line.split("::")
                    self.user_info[int(uid)] = UserInfo(
                        uid, gender, age, job
                    )

    def _load_data(self):
        self.data = []
        is_test = self.mode == "test"
        rng = random.Random(self.rand_seed)
        with zipfile.ZipFile(self.data_file) as z:
            with z.open("ml-1m/ratings.dat") as f:
                for line in f:
                    line = line.decode("latin1").strip()
                    uid, mid, rating, _ = line.split("::")
                    if (rng.random() < self.test_ratio) == is_test:
                        usr = self.user_info[int(uid)]
                        mov = self.movie_info[int(mid)]
                        self.data.append(
                            usr.value()
                            + mov.value(self.categories_dict,
                                        self.movie_title_dict)
                            + [[float(rating)]]
                        )

    def __getitem__(self, idx):
        return tuple(np.array(v) for v in self.data[idx])

    def __len__(self):
        return len(self.data)
