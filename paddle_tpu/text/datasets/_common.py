"""Shared helpers for text datasets."""
from __future__ import annotations

import os

from ...utils.download import dataset_home  # noqa: F401  (shared root)


def resolve_data_file(data_file, download, name, url):
    """Reference _check_exists_and_download analog, egress-free: the file
    must exist locally; otherwise tell the user exactly what to stage."""
    if data_file is not None:
        if not os.path.exists(data_file):
            raise FileNotFoundError(
                f"{name}: data_file {data_file!r} does not exist"
            )
        return data_file
    if not download:
        raise AssertionError(
            "data_file is not set and downloading automatically is disabled"
        )
    cache = os.path.join(dataset_home(), name, os.path.basename(url))
    if os.path.exists(cache):
        return cache
    raise RuntimeError(
        f"{name}: automatic download is unavailable in this environment; "
        f"fetch {url} and pass data_file= (or place it at {cache})"
    )
