"""WMT14 en->fr dataset (reference: text/datasets/wmt14.py — tarball with
{mode}/{mode} tab-separated parallel files + src.dict/trg.dict; sequences
get <s>/<e> sentinels, UNK id 2, length-80 train filter)."""
from __future__ import annotations

import tarfile

import numpy as np

from ...io.dataset import Dataset
from ._common import resolve_data_file

__all__ = ["WMT14"]

URL = "http://paddlemodels.bj.bcebos.com/wmt/wmt14.tgz"

START = "<s>"
END = "<e>"
UNK = "<unk>"
UNK_IDX = 2


class WMT14(Dataset):
    def __init__(self, data_file=None, mode="train", dict_size=-1,
                 download=True):
        if mode.lower() not in ("train", "test", "gen"):
            raise ValueError(
                f"mode should be 'train', 'test' or 'gen', got {mode}"
            )
        self.mode = mode.lower()
        if dict_size <= 0:
            raise ValueError("dict_size should be a positive number")
        self.dict_size = dict_size
        self.data_file = resolve_data_file(data_file, download, "wmt14", URL)
        self._load()

    @staticmethod
    def _to_dict(fd, size):
        out = {}
        for i, line in enumerate(fd):
            if i >= size:
                break
            out[line.decode("utf-8", "ignore").strip()] = i
        return out

    def _load(self):
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self.data_file) as tf:
            src_dicts = [n for n in tf.getnames() if n.endswith("src.dict")]
            trg_dicts = [n for n in tf.getnames() if n.endswith("trg.dict")]
            if len(src_dicts) != 1 or len(trg_dicts) != 1:
                raise ValueError(
                    "wmt14 archive must contain exactly one src.dict and "
                    "one trg.dict"
                )
            self.src_dict = self._to_dict(
                tf.extractfile(src_dicts[0]), self.dict_size
            )
            self.trg_dict = self._to_dict(
                tf.extractfile(trg_dicts[0]), self.dict_size
            )
            suffix = f"{self.mode}/{self.mode}"
            for name in tf.getnames():
                if not name.endswith(suffix):
                    continue
                for line in tf.extractfile(name):
                    parts = line.decode("utf-8", "ignore").strip().split(
                        "\t"
                    )
                    if len(parts) != 2:
                        continue
                    src = [
                        self.src_dict.get(w, UNK_IDX)
                        for w in [START] + parts[0].split() + [END]
                    ]
                    trg = [
                        self.trg_dict.get(w, UNK_IDX)
                        for w in parts[1].split()
                    ]
                    if len(src) > 80 or len(trg) > 80:
                        continue
                    self.src_ids.append(src)
                    self.trg_ids_next.append(trg + [self.trg_dict[END]])
                    self.trg_ids.append([self.trg_dict[START]] + trg)

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)

    def get_dict(self, reverse=False):
        if reverse:
            return (
                {v: k for k, v in self.src_dict.items()},
                {v: k for k, v in self.trg_dict.items()},
            )
        return self.src_dict, self.trg_dict
