"""paddle.text.datasets parity (reference: python/paddle/text/datasets/).

Each dataset consumes the SAME on-disk artifact format as the reference
(housing.data floats, aclImdb tar, PTB simple-examples tar, ml-1m zip,
WMT tarballs, CoNLL05 gzipped column files), passed via `data_file`.
Auto-download (download=True with data_file=None) raises with the
artifact URL — this build runs in egress-free environments, and silently
fabricating data would be worse than asking the user to stage the file.
"""
from .uci_housing import UCIHousing  # noqa: F401
from .imdb import Imdb  # noqa: F401
from .imikolov import Imikolov  # noqa: F401
from .movielens import Movielens  # noqa: F401
from .wmt14 import WMT14  # noqa: F401
from .wmt16 import WMT16  # noqa: F401
from .conll05 import Conll05st  # noqa: F401

__all__ = [
    "UCIHousing", "Imdb", "Imikolov", "Movielens", "WMT14", "WMT16",
    "Conll05st",
]
