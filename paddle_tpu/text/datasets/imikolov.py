"""Imikolov / PTB language-model dataset (reference:
text/datasets/imikolov.py — simple-examples tarball; vocab over
train+valid with <s>/<e> sentinels and min-frequency cutoff; NGRAM or
SEQ sample shapes)."""
from __future__ import annotations

import tarfile

import numpy as np

from ...io.dataset import Dataset
from ._common import resolve_data_file

__all__ = ["Imikolov"]

URL = "https://dataset.bj.bcebos.com/imikolov%2Fsimple-examples.tar.gz"

_TRAIN = "./simple-examples/data/ptb.train.txt"
_VALID = "./simple-examples/data/ptb.valid.txt"


class Imikolov(Dataset):
    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=True):
        if data_type.upper() not in ("NGRAM", "SEQ"):
            raise ValueError(
                f"data_type should be 'NGRAM' or 'SEQ', got {data_type}"
            )
        self.data_type = data_type.upper()
        if mode.lower() not in ("train", "test"):
            raise ValueError(f"mode should be 'train' or 'test', got {mode}")
        self.mode = mode.lower()
        self.window_size = window_size
        self.data_file = resolve_data_file(
            data_file, download, "imikolov", URL
        )
        self.word_idx = self._build_dict(min_word_freq)
        self._load()

    @staticmethod
    def _count(f, freq):
        for line in f:
            for w in line.decode("utf-8", "ignore").strip().split():
                freq[w] = freq.get(w, 0) + 1
            freq["<s>"] = freq.get("<s>", 0) + 1
            freq["<e>"] = freq.get("<e>", 0) + 1
        return freq

    def _member(self, tf, path):
        try:
            return tf.extractfile(path)
        except KeyError:
            return tf.extractfile(path.lstrip("./"))

    def _build_dict(self, cutoff):
        with tarfile.open(self.data_file) as tf:
            freq = self._count(self._member(tf, _TRAIN), {})
            freq = self._count(self._member(tf, _VALID), freq)
        freq.pop("<unk>", None)
        kept = [(w, c) for w, c in freq.items() if c > cutoff]
        kept.sort(key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _load(self):
        path = _TRAIN if self.mode == "train" else _VALID
        unk = self.word_idx["<unk>"]
        self.data = []
        with tarfile.open(self.data_file) as tf:
            for line in self._member(tf, path):
                words = line.decode("utf-8", "ignore").strip().split()
                ids = (
                    [self.word_idx["<s>"]]
                    + [self.word_idx.get(w, unk) for w in words]
                    + [self.word_idx["<e>"]]
                )
                if self.data_type == "SEQ":
                    self.data.append(ids)
                else:
                    if self.window_size <= 0:
                        raise ValueError(
                            "NGRAM data_type needs window_size > 0"
                        )
                    for i in range(len(ids) - self.window_size + 1):
                        self.data.append(ids[i:i + self.window_size])

    def __getitem__(self, idx):
        return tuple(np.array([v]) for v in self.data[idx]) \
            if self.data_type == "NGRAM" else np.array(self.data[idx])

    def __len__(self):
        return len(self.data)
