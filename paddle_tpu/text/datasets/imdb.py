"""IMDB sentiment dataset (reference: text/datasets/imdb.py — aclImdb
tarball; vocabulary from train docs over a frequency cutoff, punctuation
stripped, label 0=pos 1=neg per the reference's ordering)."""
from __future__ import annotations

import re
import string
import tarfile

import numpy as np

from ...io.dataset import Dataset
from ._common import resolve_data_file

__all__ = ["Imdb"]

URL = "https://dataset.bj.bcebos.com/imdb%2FaclImdb_v1.tar.gz"


class Imdb(Dataset):
    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        if mode.lower() not in ("train", "test"):
            raise ValueError(f"mode should be 'train' or 'test', got {mode}")
        self.mode = mode.lower()
        self.data_file = resolve_data_file(data_file, download, "imdb", URL)
        # ONE archive walk collects the dict corpus (train pos+neg only —
        # the reference vocabulary) and this mode's documents together
        groups = self._tokenize_groups({
            "dict_pos": re.compile(r"aclImdb/train/pos/.*\.txt$"),
            "dict_neg": re.compile(r"aclImdb/train/neg/.*\.txt$"),
            "pos": re.compile(rf"aclImdb/{self.mode}/pos/.*\.txt$"),
            "neg": re.compile(rf"aclImdb/{self.mode}/neg/.*\.txt$"),
        })
        self.word_idx = self._build_dict(
            groups["dict_pos"] + groups["dict_neg"], cutoff
        )
        self._load(groups["pos"], groups["neg"])

    def _tokenize_groups(self, patterns):
        groups = {k: [] for k in patterns}
        punct = str.maketrans("", "", string.punctuation)
        with tarfile.open(self.data_file) as tf:
            for member in tf:
                if not member.isfile():
                    continue
                doc = None
                for key, pattern in patterns.items():
                    if pattern.match(member.name):
                        if doc is None:
                            text = tf.extractfile(member).read().decode(
                                "utf-8", "ignore"
                            )
                            doc = text.rstrip("\n\r").translate(
                                punct
                            ).lower().split()
                        groups[key].append(doc)
        return groups

    @staticmethod
    def _build_dict(docs, cutoff):
        freq = {}
        for doc in docs:
            for w in doc:
                freq[w] = freq.get(w, 0) + 1
        kept = [(w, c) for w, c in freq.items() if c > cutoff]
        kept.sort(key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _load(self, pos_docs, neg_docs):
        unk = self.word_idx["<unk>"]
        self.docs, self.labels = [], []
        for label, docs in ((0, pos_docs), (1, neg_docs)):
            for doc in docs:
                self.docs.append([self.word_idx.get(w, unk) for w in doc])
                self.labels.append(label)

    def __getitem__(self, idx):
        return np.array(self.docs[idx]), np.array([self.labels[idx]])

    def __len__(self):
        return len(self.docs)
