"""IMDB sentiment dataset (reference: text/datasets/imdb.py — aclImdb
tarball; vocabulary from train docs over a frequency cutoff, punctuation
stripped, label 0=pos 1=neg per the reference's ordering)."""
from __future__ import annotations

import re
import string
import tarfile

import numpy as np

from ...io.dataset import Dataset
from ._common import resolve_data_file

__all__ = ["Imdb"]

URL = "https://dataset.bj.bcebos.com/imdb%2FaclImdb_v1.tar.gz"


class Imdb(Dataset):
    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        if mode.lower() not in ("train", "test"):
            raise ValueError(f"mode should be 'train' or 'test', got {mode}")
        self.mode = mode.lower()
        self.data_file = resolve_data_file(data_file, download, "imdb", URL)
        self.word_idx = self._build_dict(
            re.compile(r"aclImdb/train/(pos|neg)/.*\.txt$"), cutoff
        )
        self._load()

    def _tokenize(self, pattern):
        docs = []
        punct = str.maketrans("", "", string.punctuation)
        with tarfile.open(self.data_file) as tf:
            for member in tf:
                if member.isfile() and pattern.match(member.name):
                    text = tf.extractfile(member).read().decode(
                        "utf-8", "ignore"
                    )
                    docs.append(
                        text.rstrip("\n\r").translate(punct).lower().split()
                    )
        return docs

    def _build_dict(self, pattern, cutoff):
        freq = {}
        for doc in self._tokenize(pattern):
            for w in doc:
                freq[w] = freq.get(w, 0) + 1
        kept = [(w, c) for w, c in freq.items() if c > cutoff]
        kept.sort(key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _load(self):
        unk = self.word_idx["<unk>"]
        self.docs, self.labels = [], []
        for label, kind in ((0, "pos"), (1, "neg")):
            pattern = re.compile(
                rf"aclImdb/{self.mode}/{kind}/.*\.txt$"
            )
            for doc in self._tokenize(pattern):
                self.docs.append([self.word_idx.get(w, unk) for w in doc])
                self.labels.append(label)

    def __getitem__(self, idx):
        return np.array(self.docs[idx]), np.array([self.labels[idx]])

    def __len__(self):
        return len(self.docs)
