"""UCI housing dataset (reference: text/datasets/uci_housing.py — parses
`housing.data` whitespace floats, per-feature (x-avg)/(max-min) scaling,
80/20 train/test split)."""
from __future__ import annotations

import numpy as np

from ...io.dataset import Dataset
from ._common import resolve_data_file

__all__ = ["UCIHousing"]

URL = "http://paddlemodels.bj.bcebos.com/uci_housing/housing.data"

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS", "RAD", "TAX",
    "PTRATIO", "B", "LSTAT",
]


class UCIHousing(Dataset):
    def __init__(self, data_file=None, mode="train", download=True):
        if mode.lower() not in ("train", "test"):
            raise ValueError(f"mode should be 'train' or 'test', got {mode}")
        self.mode = mode.lower()
        self.data_file = resolve_data_file(
            data_file, download, "uci_housing", URL
        )
        self._load(feature_num=14, ratio=0.8)

    def _load(self, feature_num, ratio):
        raw = np.fromfile(self.data_file, sep=" ")
        raw = raw.reshape(raw.shape[0] // feature_num, feature_num)
        mx, mn = raw.max(axis=0), raw.min(axis=0)
        avg = raw.mean(axis=0)
        for i in range(feature_num - 1):
            span = mx[i] - mn[i]
            raw[:, i] = (raw[:, i] - avg[i]) / (span if span else 1.0)
        cut = int(raw.shape[0] * ratio)
        self.data = raw[:cut] if self.mode == "train" else raw[cut:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return (row[:-1].astype("float32"), row[-1:].astype("float32"))

    def __len__(self):
        return len(self.data)
