"""paddle_tpu.static — the static-graph facade (L8, SURVEY.md §2.7).

Reference: python/paddle/static/ over fluid Program/Executor/
append_backward (framework.py, executor.py:916, backward.py:1337).

TPU-native "static mode" is deferred trace-and-compile: `paddle.static.data`
creates symbolic placeholders; ops touching them record into the default
Program (program.py); `opt.minimize(loss)` records the backward+update
directive; `Executor.run(prog, feed, fetch_list)` compiles the whole thing
— forward, backward, optimizer — into one jitted XLA program per feed
signature and executes it. An unmodified Paddle static training script
maps 1:1 onto this surface.
"""
from __future__ import annotations

_STATIC_MODE = False


def _enable():
    global _STATIC_MODE
    _STATIC_MODE = True


def _disable():
    global _STATIC_MODE
    _STATIC_MODE = False


def _static_mode_on() -> bool:
    return _STATIC_MODE


from . import nn  # noqa: E402,F401
from .program import (  # noqa: E402,F401
    Program,
    Variable,
    data,
    default_main_program,
    default_startup_program,
    program_guard,
)
from .executor import (  # noqa: E402,F401
    CompiledProgram,
    Executor,
    global_scope,
)

__all__ = [
    "CompiledProgram",
    "Program", "Variable", "data", "default_main_program",
    "default_startup_program", "program_guard", "Executor", "global_scope",
]
