"""paddle_tpu.static — static-graph facade (stage 3; stub switches for now).

reference: python/paddle/static/ over fluid Program/Executor. In the TPU
build "static mode" is trace-and-compile: programs are captured by tracing
(paddle_tpu.jit) rather than built op-desc-by-op-desc; this module will hold
the Program/Executor-compatible API shells.
"""
from __future__ import annotations

_STATIC_MODE = False


def _enable():
    global _STATIC_MODE
    _STATIC_MODE = True


def _disable():
    global _STATIC_MODE
    _STATIC_MODE = False


def _static_mode_on() -> bool:
    return _STATIC_MODE
