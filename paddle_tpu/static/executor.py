"""Static-graph Executor.

Reference: python/paddle/fluid/executor.py `Executor.run` (:916) →
`_run_impl` (:1112) → `_run_program` (:1253) feed/fetch + program cache,
over the C++ op-loop interpreter (framework/executor.cc:166,414).

TPU-native: `run` compiles the recorded Program (plus, when
`opt.minimize(loss)` was recorded, its backward + optimizer update — the
append_backward analog, fluid/backward.py:1337) into ONE jitted XLA
program per (program version, feed signature, fetch set), then executes
it. Feed/fetch ops are just function arguments/results; the program cache
is the jit cache.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter, Tensor
from .program import Program, Variable, default_main_program

__all__ = ["CompiledProgram", "Executor", "global_scope"]


class CompiledProgram:
    """fluid/compiler.py:88 CompiledProgram.with_data_parallel analog.

    Wrapping a Program marks it for SPMD data parallelism: Executor.run
    feeds shard over the default mesh's dp axis and parameters replicate,
    so XLA partitions the one compiled program across devices and inserts
    the gradient all-reduce (the multi_devices_graph_pass +
    ParallelExecutor pipeline collapsed into sharding propagation)."""

    def __init__(self, program, build_strategy=None):
        self.program = program
        self._data_parallel = False
        self._loss_name = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        self._data_parallel = True
        self._loss_name = loss_name
        return self


class _Scope:
    def find_var(self, name):
        return None


_scope = _Scope()


def global_scope():
    return _scope


class Executor:
    """executor.py:916 parity surface (run/close); place is accepted for
    script parity — XLA owns placement."""

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict = {}

    def close(self):
        self._cache.clear()

    # -- compile -------------------------------------------------------------
    def _build(self, program: Program, feed_names, fetch_vars):
        # leaf tensors: concrete Tensors recorded as op inputs (params +
        # captured constants); resolved from the live objects at call time
        leaves, leaf_idx = [], {}
        rng_vars, rng_pos = [], {}
        for op in program.ops:
            for t in op.inputs:
                if isinstance(t, Tensor) and id(t) not in leaf_idx:
                    leaf_idx[id(t)] = len(leaves)
                    leaves.append(t)
                elif isinstance(t, Variable) and t.is_rng \
                        and t.id not in rng_pos:
                    rng_pos[t.id] = len(rng_vars)
                    rng_vars.append(t)
        params = [
            t for t in leaves
            if isinstance(t, Parameter) and t.trainable
        ]
        # the optimizer trains ITS parameter subset (optimizer.py minimize
        # sets _parameter_list; frozen-backbone scripts rely on this)
        if program.optimize_directives:
            opt0 = program.optimize_directives[0][0]
            if opt0._parameter_list is not None:
                allowed = {id(p) for p in opt0._parameter_list}
                params = [p for p in params if id(p) in allowed]
        p_idx = {id(p): i for i, p in enumerate(params)}
        feed_pos = {n: i for i, n in enumerate(feed_names)}
        for v in fetch_vars:
            if isinstance(v, Tensor) and id(v) not in leaf_idx:
                raise ValueError(
                    "fetch_list contains a concrete Tensor that never "
                    "appears in the program; fetch program variables or "
                    "tensors the ops consume"
                )

        def replay(p_raws, leaf_raws, feed_raws, rng_raws):
            env = {}

            def resolve(inp):
                if isinstance(inp, Variable):
                    if inp.id in env:
                        return env[inp.id]
                    if inp.is_rng:
                        return rng_raws[rng_pos[inp.id]]
                    if inp.is_data:
                        return feed_raws[feed_pos[inp.name]]
                    raise KeyError(
                        f"variable '{inp.name}' has no producer op and is "
                        "not fed"
                    )
                i = id(inp)
                if i in p_idx:
                    return p_raws[p_idx[i]]
                return leaf_raws[leaf_idx[i]]

            for op in program.ops:
                outs = op.fn(*[resolve(i) for i in op.inputs])
                outs = tuple(outs) if op.multi else (outs,)
                for var, o in zip(op.out_vars, outs):
                    env[var.id] = o
            fetches = tuple(resolve(v) for v in fetch_vars)
            state_vals = tuple(
                resolve(var) for _, var in program.state_writes
            )
            return fetches, env, state_vals

        directives = program.optimize_directives
        if not directives:
            def run_fn(p_raws, leaf_raws, feed_raws, rng_raws):
                fetches, _, state_vals = replay(
                    p_raws, leaf_raws, feed_raws, rng_raws
                )
                return fetches, p_raws, (), state_vals

            return jax.jit(run_fn), leaves, params, None, rng_vars

        if len(directives) > 1:
            raise NotImplementedError(
                "multiple minimize() calls in one Program"
            )
        opt, loss_var = directives[0]

        from ..jit.train_step import process_grads

        def run_fn(p_raws, leaf_raws, feed_raws, rng_raws, opt_state, lr, t):
            def loss_of(p_tuple):
                fetches, env, state_vals = replay(
                    p_tuple, leaf_raws, feed_raws, rng_raws
                )
                return env[loss_var.id], (fetches, state_vals)

            (loss, (fetches, state_vals)), grads = jax.value_and_grad(
                loss_of, has_aux=True
            )(tuple(p_raws))
            grads = process_grads(opt, params, list(p_raws), list(grads))
            new_p, new_state = opt._functional_update(
                params, list(p_raws), grads, opt_state, lr, t
            )
            return fetches, new_p, new_state, state_vals

        donate = (0, 4) if jax.default_backend() != "cpu" else ()
        return (jax.jit(run_fn, donate_argnums=donate), leaves, params, opt,
                rng_vars)

    # -- run -----------------------------------------------------------------
    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list=None, return_numpy=True):
        """executor.py:916. Returns fetched values in fetch_list order.
        A CompiledProgram.with_data_parallel shards feeds over the dp
        mesh axis (ParallelExecutor path, executor.py:1112)."""
        dp_mesh = None
        if isinstance(program, CompiledProgram):
            if program._data_parallel:
                from ..distributed import comm

                dp_mesh = comm._default_group().mesh
            program = program.program
        program = program if program is not None else default_main_program()
        feed = dict(feed or {})
        fetch_list = list(fetch_list or [])
        if not program.ops:
            return []  # startup program: params initialize eagerly

        fetch_vars = []
        for f in fetch_list:
            v = getattr(f, "_static_var", None)
            if v is None and isinstance(f, Variable):
                v = f
            if v is None and isinstance(f, Tensor):
                v = f  # concrete tensor fetch (e.g. a parameter)
            if v is None:
                raise TypeError(f"cannot fetch {type(f)}")
            fetch_vars.append(v)

        feed_names = tuple(sorted(feed))
        feed_raws = tuple(
            f._data if isinstance(f, Tensor) else jnp.asarray(feed[n])
            for n, f in ((n, feed[n]) for n in feed_names)
        )
        if dp_mesh is not None:
            from ..distributed import comm as _comm

            n_dev = dp_mesh.devices.size
            for name, r in zip(feed_names, feed_raws):
                if r.ndim > 0 and r.shape[0] % n_dev != 0:
                    raise ValueError(
                        f"CompiledProgram.with_data_parallel: feed "
                        f"'{name}' batch {r.shape[0]} is not divisible "
                        f"by the {n_dev} devices (ParallelExecutor "
                        "raises here too; pad or drop the tail batch)"
                    )
            feed_raws = tuple(
                _comm.shard_rank_axis(r) if r.ndim > 0 else r
                for r in feed_raws
            )
        sig = tuple(
            (n, tuple(r.shape), str(r.dtype))
            for n, r in zip(feed_names, feed_raws)
        )
        key = (
            id(program), program._version, sig,
            tuple(
                v.id if isinstance(v, Variable) else id(v)
                for v in fetch_vars
            ),
        )
        if key not in self._cache:
            self._cache[key] = self._build(program, feed_names, fetch_vars)
        run_fn, leaves, params, opt, rng_vars = self._cache[key]

        p_raws = tuple(p._data for p in params)
        leaf_raws = tuple(t._data for t in leaves)
        # fresh key data per run for every rng placeholder (dropout masks
        # vary across runs; see program.rng_feed)
        from ..core import random as rnd

        rng_raws = tuple(
            jax.random.key_data(rnd.next_key()) for _ in rng_vars
        )
        if opt is None:
            fetches, _, _, state_vals = run_fn(
                p_raws, leaf_raws, feed_raws, rng_raws
            )
        else:
            opt_state = opt._functional_state(params)
            opt._step_count += 1
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            t = jnp.asarray(opt._step_count, jnp.float32)
            fetches, new_p, new_state, state_vals = run_fn(
                p_raws, leaf_raws, feed_raws, rng_raws, opt_state, lr, t
            )
            for p, raw in zip(params, new_p):
                p._data = raw
                p._node = None
                p.grad = None
            opt._load_functional_state(params, new_state)
        # persistable-state write-back (batch-norm running stats):
        # updated values land in the LIVE buffer objects after each run
        for (obj, _), val in zip(program.state_writes, state_vals):
            obj._data = val
            obj._node = None
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor._wrap(f, stop_gradient=True) for f in fetches]
