"""paddle.static.nn — fluid-style layer BUILDERS for static-graph scripts.

Reference: python/paddle/static/nn/__init__.py re-exporting
fluid/layers/nn.py builders (fc :87, conv2d :1402, batch_norm :2634,
embedding, layer_norm, ...). Each call constructs fresh parameters (via
the corresponding paddle_tpu.nn Layer) and applies them to the symbolic
input — the parameters become leaves of the recorded Program exactly like
LayerHelper.create_parameter's variables enter the reference's Program.

Channel/feature counts are inferred from the symbolic input's shape, so
reference scripts port with only the import changed.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..jit.control_flow import case, cond, switch_case, while_loop  # noqa: F401

__all__ = [
    "fc", "batch_norm", "embedding", "conv2d", "conv2d_transpose",
    "conv3d", "create_parameter", "layer_norm", "group_norm",
    "instance_norm", "prelu", "deform_conv2d",
    "cond", "case", "switch_case", "while_loop",
]


def _shape_of(x) -> tuple:
    var = getattr(x, "_static_var", None)
    if var is not None:
        return tuple(var.shape)
    return tuple(x.shape)


def _dim(x, axis, what):
    s = _shape_of(x)
    d = s[axis]
    if d is None or (isinstance(d, int) and d < 0):
        raise ValueError(
            f"{what}: input dim {axis} must be static to size the "
            f"parameters, got shape {s}"
        )
    return int(d)


def _act(out, act):
    if act is None:
        return out
    from ..nn import functional as F

    fn = getattr(F, act, None)
    if fn is None:
        raise ValueError(f"unknown activation {act!r}")
    return fn(out)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """fluid.layers.fc (fluid/layers/nn.py:87): flatten trailing dims,
    one linear per input (single-input form), optional activation."""
    from .. import ops
    from ..nn import Linear

    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = []
    for xi in xs:
        shape = _shape_of(xi)
        in_features = int(np.prod([
            _dim(xi, a, "fc") for a in range(num_flatten_dims, len(shape))
        ]))
        lin = Linear(in_features, size, weight_attr=weight_attr,
                     bias_attr=bias_attr)
        flat = xi if len(shape) == num_flatten_dims + 1 else ops.reshape(
            xi, [0] * num_flatten_dims + [in_features]
            if num_flatten_dims > 1 else [-1, in_features]
        )
        outs.append(lin(flat))
    out = outs[0]
    for o in outs[1:]:
        out = out + o
    return _act(out, activation)


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCHW"):
    """fluid.layers.conv2d (nn.py:1402)."""
    from ..nn import Conv2D

    cin = _dim(input, 1 if data_format == "NCHW" else 3, "conv2d")
    conv = Conv2D(cin, num_filters, filter_size, stride=stride,
                  padding=padding, dilation=dilation, groups=groups,
                  weight_attr=param_attr, bias_attr=bias_attr,
                  data_format=data_format)
    return _act(conv(input), act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    from ..nn import Conv2DTranspose

    cin = _dim(input, 1 if data_format == "NCHW" else 3,
               "conv2d_transpose")
    conv = Conv2DTranspose(cin, num_filters, filter_size, stride=stride,
                           padding=padding, dilation=dilation,
                           groups=groups, weight_attr=param_attr,
                           bias_attr=bias_attr, data_format=data_format)
    return _act(conv(input), act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCDHW"):
    from ..nn import Conv3D

    cin = _dim(input, 1 if data_format == "NCDHW" else 4, "conv3d")
    conv = Conv3D(cin, num_filters, filter_size, stride=stride,
                  padding=padding, dilation=dilation, groups=groups,
                  weight_attr=param_attr, bias_attr=bias_attr,
                  data_format=data_format)
    return _act(conv(input), act)


def batch_norm(input, act=None, is_test=False, momentum=0.9,
               epsilon=1e-5, param_attr=None, bias_attr=None,
               data_layout="NCHW", name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    """fluid.layers.batch_norm (nn.py:2634). is_test selects inference
    stats (the recorded op uses batch stats otherwise, refreshing the
    layer's running buffers through the program's buffer threading)."""
    from ..nn.layers.norm import BatchNorm

    ch = _dim(input, 1 if data_layout == "NCHW" else -1, "batch_norm")
    bn = BatchNorm(ch, momentum=momentum, epsilon=epsilon,
                   param_attr=param_attr, bias_attr=bias_attr,
                   data_layout=data_layout,
                   use_global_stats=use_global_stats)
    if is_test:
        bn.eval()
    return _act(bn(input), act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """fluid.layers.embedding: size = [vocab, dim]."""
    from ..nn import Embedding

    emb = Embedding(int(size[0]), int(size[1]), padding_idx=padding_idx,
                    weight_attr=param_attr)
    return emb(input)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    from ..nn import LayerNorm

    shape = _shape_of(input)
    normalized = [
        _dim(input, a, "layer_norm") for a in range(begin_norm_axis,
                                                    len(shape))
    ]
    ln = LayerNorm(normalized, epsilon=epsilon, weight_attr=param_attr,
                   bias_attr=bias_attr)
    return _act(ln(input), act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    from ..nn import GroupNorm

    ch = _dim(input, 1 if data_layout == "NCHW" else -1, "group_norm")
    gn = GroupNorm(groups, ch, epsilon=epsilon, weight_attr=param_attr,
                   bias_attr=bias_attr, data_format=data_layout)
    return _act(gn(input), act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    from ..nn import InstanceNorm2D

    ch = _dim(input, 1, "instance_norm")
    inorm = InstanceNorm2D(ch, epsilon=epsilon, weight_attr=param_attr,
                           bias_attr=bias_attr)
    return inorm(input)


def prelu(x, mode="all", param_attr=None, name=None):
    from ..nn import PReLU

    if mode == "all":
        num = 1
    elif mode == "channel":
        num = _dim(x, 1, "prelu")
    else:
        raise ValueError("prelu mode must be 'all' or 'channel'")
    return PReLU(num_parameters=num, weight_attr=param_attr)(x)


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None,
                  name=None):
    from ..vision.ops import DeformConv2D

    cin = _dim(x, 1, "deform_conv2d")
    conv = DeformConv2D(cin, num_filters, filter_size, stride=stride,
                        padding=padding, dilation=dilation,
                        deformable_groups=deformable_groups,
                        groups=groups, weight_attr=param_attr,
                        bias_attr=bias_attr)
    return conv(x, offset, mask=mask)


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """fluid.layers.create_parameter via the Layer-free path."""
    from ..nn.layer import Layer

    holder = Layer()
    return holder.create_parameter(
        shape=list(shape), attr=attr, dtype=dtype, is_bias=is_bias,
        default_initializer=default_initializer,
    )
