"""Static-graph Program: the deferred-execution graph builder.

Reference: python/paddle/fluid/framework.py (`Program`/`Block`/`Variable`
over C++ OpDescs, framework.proto:43-202) + `paddle.static.data`
(static/input.py). There, graph building appends protobuf OpDescs which an
interpreter later runs op-by-op (executor.cc:414).

TPU-native: a Program records (pure_fn, inputs, outputs) triples as ops —
the SAME jnp closures eager dispatch runs — with symbolic placeholder
outputs shaped by `jax.eval_shape` (no device work at build time). The
Executor replays the op list inside ONE `jax.jit` per (program, feed
signature, fetch set): XLA is the interpreter, so "static mode" compiles
to exactly the same machine program the jit path produces. Concrete
tensors touched during building (parameters, captured constants) become
program leaves resolved at run time from the live objects, so optimizer
updates are visible across runs.
"""
from __future__ import annotations

import contextlib
from typing import Callable, List, Optional, Sequence

import jax
import numpy as np

from ..core.dtype import convert_dtype
from ..core.tensor import Tensor

__all__ = [
    "Program", "Variable", "data", "program_guard",
    "default_main_program", "default_startup_program",
]


class Variable:
    """A symbolic graph edge (framework.py Variable analog)."""

    _counter = 0

    def __init__(self, name: Optional[str], shape, dtype, is_data=False):
        Variable._counter += 1
        self.id = Variable._counter
        self.name = name or f"tmp_var_{self.id}"
        self.shape = tuple(shape)
        self.dtype = dtype
        self.is_data = is_data  # a feed placeholder
        self.is_rng = False     # a per-run RNG key feed (see rng_feed)

    def aval(self, dyn: int = 1):
        """Concrete aval with dynamic (-1/None) dims placed at `dyn`."""
        return jax.ShapeDtypeStruct(
            tuple(dyn if (d is None or d < 0) else d for d in self.shape),
            self.dtype,
        )


class StaticOp:
    """One recorded op: raw_fn over resolved inputs -> output vars.

    Inputs are either Variables (edges) or live Tensor objects (parameters
    and captured constants — resolved to their CURRENT ._data at run time,
    the scope-lookup analog of executor.cc feed/fetch variable resolution).
    """

    def __init__(self, fn: Callable, inputs: Sequence, out_vars: List[Variable],
                 multi: bool, name: str):
        self.fn = fn
        self.inputs = list(inputs)
        self.out_vars = out_vars
        self.multi = multi
        self.name = name


class Program:
    """framework.py Program. One block (control flow lowers to lax ops in
    this build, so nested BlockDescs are unnecessary)."""

    def __init__(self):
        self.ops: List[StaticOp] = []
        self.vars = {}
        # recorded `opt.minimize(loss)` directives: (optimizer, loss_var)
        self.optimize_directives = []
        # persistable-state writes: (live Tensor, producing Variable) —
        # the executor fetches the var each run and writes it back into
        # the live object (the scope-variable update of batch-norm
        # running stats, executor.cc persistable vars)
        self.state_writes = []
        self._version = 0

    def _add_var(self, var: Variable):
        self.vars[var.name] = var
        return var

    def record(self, fn, inputs, out_avals, multi, name):
        out_vars = [
            self._add_var(Variable(None, a.shape, a.dtype))
            for a in out_avals
        ]
        self.ops.append(StaticOp(fn, inputs, out_vars, multi, name))
        self._version += 1
        return out_vars

    def global_block(self):
        return self

    def all_parameters(self):
        from ..core.tensor import Parameter

        seen, out = set(), []
        for op in self.ops:
            for i in op.inputs:
                if isinstance(i, Tensor) and isinstance(i, Parameter) \
                        and id(i) not in seen:
                    seen.add(id(i))
                    out.append(i)
        return out

    def list_vars(self):
        return list(self.vars.values())

    def record_state_write(self, tensor, symbolic):
        var = getattr(symbolic, "_static_var", None)
        if var is None:
            raise ValueError("state write source must be symbolic")
        self.state_writes.append((tensor, var))
        self._version += 1

    def clone(self, for_test=False):
        p = Program()
        p.ops = list(self.ops)
        p.vars = dict(self.vars)
        p.state_writes = list(self.state_writes)
        if not for_test:
            p.optimize_directives = list(self.optimize_directives)
        return p

    def __repr__(self):
        return (f"Program(ops={len(self.ops)}, vars={len(self.vars)}, "
                f"optimized={bool(self.optimize_directives)})")


_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    """framework.py program_guard."""
    global _main_program, _startup_program
    prev_m, prev_s = _main_program, _startup_program
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    try:
        yield
    finally:
        _main_program, _startup_program = prev_m, prev_s


def data(name: str, shape, dtype="float32", lod_level=0) -> Tensor:
    """paddle.static.data: declare a feed placeholder. Returns a symbolic
    Tensor; ops consuming it record into the default main program."""
    from . import _static_mode_on

    if not _static_mode_on():
        raise RuntimeError(
            "paddle.static.data requires static mode: call "
            "paddle.enable_static() first"
        )
    var = Variable(name, shape, convert_dtype(dtype), is_data=True)
    _main_program._add_var(var)
    t = Tensor._wrap(var.aval(), stop_gradient=True)
    t._static_var = var
    return t


def is_symbolic(t) -> bool:
    return getattr(t, "_static_var", None) is not None


def rng_feed() -> Tensor:
    """A per-run RNG key placeholder (raw uint32 key data).

    Random ops recorded into a Program (dropout, uniform noise) must NOT
    bake a concrete key into their closure — that would replay the same
    mask on every `exe.run` (the reference reseeds its Generator per
    dropout kernel launch, operators/dropout_op.h). The Executor feeds
    each rng Variable a fresh `key_data(next_key())` on every run, as an
    implicit feed argument of the compiled program."""
    import numpy as np

    var = Variable(None, (2,), np.uint32)
    var.is_rng = True
    _main_program._add_var(var)
    t = Tensor._wrap(var.aval(), stop_gradient=True)
    t._static_var = var
    return t


def record_apply(raw_fn, tensors, name, differentiable=True):
    """The AG.apply hook in static mode: symbolic inputs mean 'record into
    the program' instead of executing (LayerHelper.append_op analog).

    Differentiability is decided at Executor compile time by jax.grad over
    the replayed program, so `differentiable` is advisory only.

    Dynamic-dim propagation: placeholder dims declared -1/None are
    shape-inferred TWICE (at probe extents 1 and 2); output dims that
    move with the probe are recorded as -1 so interior variables report
    the batch dim the way feed placeholders do (framework.py Variable
    shape semantics)."""
    avals1, avals2, any_dyn = [], [], False
    for t in tensors:
        if is_symbolic(t):
            v = t._static_var
            avals1.append(v.aval(1))
            avals2.append(v.aval(2))
            any_dyn = any_dyn or any(
                d is None or (isinstance(d, int) and d < 0) for d in v.shape
            )
        else:
            avals1.append(t._data)
            avals2.append(t._data)
    out_aval = jax.eval_shape(raw_fn, *avals1)
    multi = isinstance(out_aval, (tuple, list))
    outs = tuple(out_aval) if multi else (out_aval,)
    dyn_masks = [None] * len(outs)
    if any_dyn:
        try:
            out2 = jax.eval_shape(raw_fn, *avals2)
            outs2 = tuple(out2) if multi else (out2,)
            dyn_masks = [
                tuple(a != b for a, b in zip(o1.shape, o2.shape))
                if len(o1.shape) == len(o2.shape) else None
                for o1, o2 in zip(outs, outs2)
            ]
        except Exception:
            pass  # op incompatible with the probe extent: static shapes
    inputs = [
        t._static_var if is_symbolic(t) else t for t in tensors
    ]
    out_vars = _main_program.record(raw_fn, inputs, outs, multi, name or "op")
    for v, mask in zip(out_vars, dyn_masks):
        if mask:
            v.shape = tuple(
                -1 if d else s for s, d in zip(v.shape, mask)
            )
    wrapped = []
    for v in out_vars:
        w = Tensor._wrap(v.aval(), stop_gradient=not differentiable)
        w._static_var = v
        wrapped.append(w)
    return tuple(wrapped) if multi else wrapped[0]
