"""DataLoader (reference: python/paddle/fluid/reader.py:149 DataLoader,
fluid/dataloader/dataloader_iter.py:265 single-process iter, :469
multi-process iter with shared-memory workers + watchdog).

TPU-first design: collation happens on a thread pool (numpy releases the
GIL for the copies that matter) with a bounded prefetch queue, and the
device transfer is one `jax.device_put` per batch — the double-buffer H2D
prefetch of the reference's buffered_reader. A process pool is used when
num_workers > 0 AND the dataset is picklable; otherwise threads (on TPU
hosts the transform work is rarely the bottleneck the GPU world needs
worker processes for).
"""
from __future__ import annotations

import itertools
import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

import numpy as np

from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler


def default_collate_fn(batch):
    """Stack samples into batch arrays (reference:
    fluid/dataloader/collate.py default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        import jax.numpy as jnp

        return Tensor._wrap(jnp.stack([s._data for s in batch]))
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, np.float32)
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn(list(col)) for col in zip(*batch))
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    return np.asarray(batch)


def _to_tensor_tree(obj):
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, Tensor):
        return obj
    if isinstance(obj, tuple):
        return tuple(_to_tensor_tree(o) for o in obj)
    if isinstance(obj, list):
        return [_to_tensor_tree(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _to_tensor_tree(v) for k, v in obj.items()}
    return Tensor(np.asarray(obj))


class DataLoader:
    def __init__(
        self,
        dataset: Dataset,
        feed_list=None,
        places=None,
        return_list=True,
        batch_sampler: Optional[BatchSampler] = None,
        batch_size=1,
        shuffle=False,
        drop_last=False,
        collate_fn: Optional[Callable] = None,
        num_workers=0,
        use_buffer_reader=True,
        use_shared_memory=True,
        prefetch_factor=2,
        timeout=0,
        worker_init_fn=None,
        persistent_workers=False,
    ):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = int(num_workers)
        self.prefetch_factor = max(int(prefetch_factor), 1)
        self.use_buffer_reader = use_buffer_reader
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last,
            )

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def __call__(self):
        return self.__iter__()

    def __iter__(self):
        if self._iterable_mode:
            yield from self._iter_iterable()
        elif self.num_workers == 0 or not self.use_buffer_reader:
            yield from self._iter_sync()
        else:
            yield from self._iter_prefetch()

    # -- paths ---------------------------------------------------------------
    def _fetch(self, indices):
        batch = [self.dataset[i] for i in indices]
        return self.collate_fn(batch)

    def _iter_sync(self):
        for indices in self.batch_sampler:
            yield _to_tensor_tree(self._fetch(indices))

    def _iter_iterable(self):
        it = iter(self.dataset)
        while True:
            batch = list(itertools.islice(it, self.batch_size))
            if not batch:
                return
            if len(batch) < self.batch_size and self.drop_last:
                return
            yield _to_tensor_tree(self.collate_fn(batch))

    def _iter_prefetch(self):
        """Thread-pool fetch + bounded queue — the buffered_reader analog."""
        depth = self.num_workers * self.prefetch_factor
        pool = ThreadPoolExecutor(max_workers=self.num_workers)
        q: "queue.Queue" = queue.Queue(maxsize=depth)
        sentinel = object()

        def producer():
            try:
                futures = []
                for indices in self.batch_sampler:
                    futures.append(pool.submit(self._fetch, indices))
                    while len(futures) >= depth:
                        q.put(futures.pop(0))
                for f in futures:
                    q.put(f)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                yield _to_tensor_tree(item.result())
        finally:
            pool.shutdown(wait=False, cancel_futures=True)

    # -- legacy constructors (fluid reader API shims) ------------------------
    @staticmethod
    def from_generator(feed_list=None, capacity=None, use_double_buffer=True,
                       iterable=True, return_list=False, use_multiprocess=False,
                       drop_last=True):
        raise NotImplementedError(
            "Legacy fluid DataLoader.from_generator: build a paddle_tpu.io."
            "Dataset and use DataLoader(dataset=...) instead"
        )

    @staticmethod
    def from_dataset(dataset, places=None, drop_last=True):
        return DataLoader(dataset, drop_last=drop_last)
