"""DataLoader (reference: python/paddle/fluid/reader.py:149 DataLoader,
fluid/dataloader/dataloader_iter.py:265 single-process iter, :469
multi-process iter with shared-memory workers + watchdog).

TPU-first design: workers fetch+collate ahead of the consumer through a
bounded prefetch queue, and the device transfer is one `jax.device_put`
per batch — the double-buffer H2D prefetch of the reference's
buffered_reader. With num_workers > 0, a spawned PROCESS pool is used
when use_shared_memory=True and the dataset/collate pickle cleanly
(dataset ships once via the worker initializer); otherwise a thread pool
(numpy releases the GIL for the copies that matter).
"""
from __future__ import annotations

import itertools
import pickle
import queue
import threading
from concurrent.futures import ProcessPoolExecutor, ThreadPoolExecutor
from typing import Callable, Optional

import numpy as np

from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler


_PROC_STATE = {}


def _proc_worker_init(dataset, collate_fn):
    """Runs once per spawned worker: bind the dataset/collate globally
    (the mmap-shared-dataset analog — spawn ships them exactly once).
    Workers pin jax to CPU FIRST — a child touching jnp (e.g. a dataset
    returning Tensors) must never grab the parent's TPU."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["JAX_PLATFORM_NAME"] = "cpu"
    _PROC_STATE["dataset"] = dataset
    _PROC_STATE["collate"] = collate_fn


def _proc_worker_fetch(indices):
    ds = _PROC_STATE["dataset"]
    return _PROC_STATE["collate"]([ds[i] for i in indices])


# Shared-memory return transport (reference: the use_shared_memory path of
# fluid/dataloader/dataloader_iter.py — workers place batch arrays in
# /dev/shm segments and send only metadata through the result pipe,
# instead of pickling megabytes of batch data through it).
_SHM_MIN_BYTES = 1 << 16  # small arrays pickle cheaper than a shm segment


def _shm_encode(obj):
    import numpy as _np

    if isinstance(obj, _np.ndarray) and obj.nbytes >= _SHM_MIN_BYTES:
        from multiprocessing import resource_tracker, shared_memory

        arr = _np.ascontiguousarray(obj)
        shm = shared_memory.SharedMemory(create=True, size=arr.nbytes)
        _np.ndarray(arr.shape, arr.dtype, buffer=shm.buf)[...] = arr
        name = shm.name
        shm.close()
        # the PARENT owns the segment's lifetime (it unlinks after the
        # device transfer); stop this process's resource tracker from
        # unlinking it again at worker exit
        try:
            resource_tracker.unregister("/" + name, "shared_memory")
        except Exception:
            pass
        return ("__shm__", name, arr.shape, str(arr.dtype))
    if isinstance(obj, tuple):
        return tuple(_shm_encode(o) for o in obj)
    if isinstance(obj, list):
        return [_shm_encode(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _shm_encode(v) for k, v in obj.items()}
    return obj


def _shm_decode(obj):
    import numpy as _np

    if isinstance(obj, tuple) and len(obj) == 4 and obj[0] == "__shm__":
        from multiprocessing import shared_memory

        _, name, shape, dtype = obj
        shm = shared_memory.SharedMemory(name=name)
        try:
            view = _np.ndarray(shape, _np.dtype(dtype), buffer=shm.buf)
            out = _np.array(view)  # own the data before freeing the block
        finally:
            shm.close()
            try:
                shm.unlink()
            except FileNotFoundError:
                pass
        return out
    if isinstance(obj, tuple):
        return tuple(_shm_decode(o) for o in obj)
    if isinstance(obj, list):
        return [_shm_decode(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _shm_decode(v) for k, v in obj.items()}
    return obj


def _proc_worker_fetch_shm(indices):
    return _shm_encode(_proc_worker_fetch(indices))


def default_collate_fn(batch):
    """Stack samples into batch arrays (reference:
    fluid/dataloader/collate.py default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        import jax.numpy as jnp

        return Tensor._wrap(jnp.stack([s._data for s in batch]))
    if isinstance(sample, np.ndarray):
        if (len(batch) > 1 and sample.ndim > 0
                and not sample.dtype.hasobject
                and all(s.shape == sample.shape
                        and s.dtype == sample.dtype
                        and s.flags.c_contiguous for s in batch)):
            # native GIL-free collation (staging.cpp pt_stack; numpy
            # fallback inside when no toolchain built the library)
            from .. import native

            return native.stack_samples(batch)
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, np.float32)
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn(list(col)) for col in zip(*batch))
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    return np.asarray(batch)


def vision_collate_fn(batch):
    """Collate for (uint8 image, label) vision samples with the native
    FUSED stack + uint8->float32 /255 normalize (staging.cpp
    pt_stack_u8_to_f32) — use as DataLoader(collate_fn=vision_collate_fn)
    with datasets that keep images uint8 and skip transforms.ToTensor's
    per-sample division. Non-(img, label) batches defer to the default."""
    sample = batch[0]
    if (isinstance(sample, (tuple, list)) and len(sample) == 2
            and isinstance(sample[0], np.ndarray)
            and sample[0].dtype == np.uint8
            and all(s[0].shape == sample[0].shape
                    and s[0].flags.c_contiguous for s in batch)):
        from .. import native

        imgs = native.stack_u8_to_f32([s[0] for s in batch])
        labels = default_collate_fn([s[1] for s in batch])
        return imgs, labels
    return default_collate_fn(batch)


def _to_tensor_tree(obj):
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, Tensor):
        return obj
    if isinstance(obj, tuple):
        return tuple(_to_tensor_tree(o) for o in obj)
    if isinstance(obj, list):
        return [_to_tensor_tree(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _to_tensor_tree(v) for k, v in obj.items()}
    return Tensor(np.asarray(obj))


class DataLoader:
    def __init__(
        self,
        dataset: Dataset,
        feed_list=None,
        places=None,
        return_list=True,
        batch_sampler: Optional[BatchSampler] = None,
        batch_size=1,
        shuffle=False,
        drop_last=False,
        collate_fn: Optional[Callable] = None,
        num_workers=0,
        use_buffer_reader=True,
        use_shared_memory=True,
        prefetch_factor=2,
        timeout=0,
        worker_init_fn=None,
        persistent_workers=False,
    ):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = int(num_workers)
        self.prefetch_factor = max(int(prefetch_factor), 1)
        self.use_buffer_reader = use_buffer_reader
        self.use_shared_memory = use_shared_memory
        self.persistent_workers = persistent_workers
        self._pool = None
        self._pool_is_proc = False
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last,
            )

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def __call__(self):
        return self.__iter__()

    def __iter__(self):
        if self._iterable_mode:
            yield from self._iter_iterable()
        elif self.num_workers == 0 or not self.use_buffer_reader:
            yield from self._iter_sync()
        else:
            yield from self._iter_prefetch()

    # -- paths ---------------------------------------------------------------
    def _fetch(self, indices):
        batch = [self.dataset[i] for i in indices]
        return self.collate_fn(batch)

    def _iter_sync(self):
        for indices in self.batch_sampler:
            yield _to_tensor_tree(self._fetch(indices))

    def _iter_iterable(self):
        it = iter(self.dataset)
        while True:
            batch = list(itertools.islice(it, self.batch_size))
            if not batch:
                return
            if len(batch) < self.batch_size and self.drop_last:
                return
            yield _to_tensor_tree(self.collate_fn(batch))

    def _make_pool(self):
        """Worker pool choice (dataloader_iter.py:469 multiprocess path):
        process workers when shared memory is requested and the dataset/
        collate pickle cleanly (children are spawned, so the dataset
        travels once via the initializer); thread pool otherwise. The
        pool persists across epochs when persistent_workers=True."""
        if self._pool is not None:
            return self._pool
        pool = None
        if self.use_shared_memory:
            try:
                # probe picklability WITHOUT materializing the bytes (a
                # large in-RAM dataset must not be copied just to probe)
                class _Null:
                    def write(self, b):
                        return len(b)

                pickle.Pickler(_Null(), protocol=4).dump(self.dataset)
                pickle.Pickler(_Null(), protocol=4).dump(self.collate_fn)
            except Exception:
                pool = ThreadPoolExecutor(max_workers=self.num_workers)
                self._pool_is_proc = False
            else:
                import multiprocessing as mp

                pool = ProcessPoolExecutor(
                    max_workers=self.num_workers,
                    mp_context=mp.get_context("spawn"),
                    initializer=_proc_worker_init,
                    initargs=(self.dataset, self.collate_fn),
                )
                self._pool_is_proc = True
        else:
            pool = ThreadPoolExecutor(max_workers=self.num_workers)
            self._pool_is_proc = False
        if self.persistent_workers:
            self._pool = pool
        return pool

    def _iter_prefetch(self):
        """Worker-pool fetch + bounded queue — the buffered_reader analog
        (one device transfer per batch on the consumer side)."""
        depth = self.num_workers * self.prefetch_factor
        pool = self._make_pool()
        is_proc = self._pool_is_proc
        q: "queue.Queue" = queue.Queue(maxsize=depth)
        sentinel = object()

        def submit(indices):
            if is_proc:
                return pool.submit(_proc_worker_fetch_shm, list(indices))
            return pool.submit(self._fetch, indices)

        stop = threading.Event()

        def reap(fut):
            """Cancel a pending fetch; if it already completed, decode its
            shm descriptors so the segments are unlinked, not leaked."""
            if not fut.cancel() and is_proc:
                try:
                    _shm_decode(fut.result(timeout=5))
                except Exception:
                    pass

        def put_or_cancel(item):
            """Blocking put that aborts when the consumer is gone — the
            producer must never deadlock on a full queue nobody drains."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return True
                except queue.Full:
                    continue
            if item is not sentinel and hasattr(item, "cancel"):
                reap(item)
            return False

        def producer():
            try:
                futures = []
                for indices in self.batch_sampler:
                    if stop.is_set():
                        break
                    futures.append(submit(indices))
                    while len(futures) >= depth:
                        if not put_or_cancel(futures.pop(0)):
                            break
                for f in futures:
                    if stop.is_set():
                        reap(f)
                    else:
                        put_or_cancel(f)
            finally:
                put_or_cancel(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                out = item.result()
                if is_proc:
                    out = _shm_decode(out)
                yield _to_tensor_tree(out)
        finally:
            # early break: stop the producer and cancel queued fetches so
            # a persistent pool is clean for the next epoch; q is drained
            # so the producer can never deadlock on q.put
            stop.set()
            while True:
                try:
                    item = q.get_nowait()
                except queue.Empty:
                    break
                if item is not sentinel:
                    reap(item)
            if pool is not self._pool:
                pool.shutdown(wait=False, cancel_futures=True)

    # -- legacy constructors (fluid reader API shims) ------------------------
    @staticmethod
    def from_generator(feed_list=None, capacity=None, use_double_buffer=True,
                       iterable=True, return_list=False, use_multiprocess=False,
                       drop_last=True):
        raise NotImplementedError(
            "Legacy fluid DataLoader.from_generator: build a paddle_tpu.io."
            "Dataset and use DataLoader(dataset=...) instead"
        )

    @staticmethod
    def from_dataset(dataset, places=None, drop_last=True):
        return DataLoader(dataset, drop_last=drop_last)
