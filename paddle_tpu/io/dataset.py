"""Datasets (reference: python/paddle/fluid/dataloader/dataset.py)."""
from __future__ import annotations

import bisect
from typing import List, Sequence

import numpy as np


class Dataset:
    """Map-style dataset (dataset.py:30)."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    """Stream-style dataset (dataset.py:71)."""

    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        from ..core.tensor import Tensor

        self.tensors = [
            t.numpy() if isinstance(t, Tensor) else np.asarray(t)
            for t in tensors
        ]
        n = len(self.tensors[0])
        if any(len(t) != n for t in self.tensors):
            raise ValueError("all tensors must share dim 0")

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    """Zip datasets column-wise (dataset.py ComposeDataset)."""

    def __init__(self, datasets: List[Dataset]):
        self.datasets = list(datasets)
        n = len(self.datasets[0])
        if any(len(d) != n for d in self.datasets):
            raise ValueError("all datasets must have the same length")

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            if isinstance(item, tuple):
                out.extend(item)
            else:
                out.append(item)
        return tuple(out)

    def __len__(self):
        return len(self.datasets[0])


class ChainDataset(IterableDataset):
    def __init__(self, datasets: List[IterableDataset]):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets: List[Dataset]):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = self.cumulative_sizes[ds_idx - 1] if ds_idx > 0 else 0
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset: Dataset, indices: Sequence[int]):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset: Dataset, lengths: Sequence[int], generator=None):
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths must equal dataset length")
    perm = np.random.permutation(len(dataset))
    out = []
    off = 0
    for n in lengths:
        out.append(Subset(dataset, perm[off : off + n].tolist()))
        off += n
    return out
