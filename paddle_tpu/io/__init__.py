"""paddle_tpu.io — Dataset / Sampler / DataLoader.

reference: python/paddle/fluid/dataloader/ (dataset.py, batch_sampler.py,
dataloader_iter.py:265 single-process, :469 multi-process) and
python/paddle/fluid/reader.py:149 DataLoader.

TPU-first: the loader's job is keeping the host→HBM pipe full. Batches are
collated to numpy on worker threads/processes and transferred once per batch
(the analog of the reference's buffered_reader double-buffer H2D prefetch,
operators/reader/buffered_reader.cc) with a configurable prefetch depth.
"""
from .dataset import (  # noqa: F401
    ChainDataset,
    ComposeDataset,
    ConcatDataset,
    Dataset,
    IterableDataset,
    Subset,
    TensorDataset,
    random_split,
)
from .sampler import (  # noqa: F401
    BatchSampler,
    DistributedBatchSampler,
    RandomSampler,
    Sampler,
    SequenceSampler,
    SubsetRandomSampler,
    WeightedRandomSampler,
)
from .dataloader import (  # noqa: F401
    DataLoader,
    default_collate_fn,
    vision_collate_fn,
)
