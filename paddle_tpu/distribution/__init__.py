"""paddle.distribution — probability distributions.

Reference: python/paddle/distribution.py (Distribution :41, Uniform :168,
Normal :390, Categorical :640). Same math, TPU-native sampling: draws come
from the framework RNG (core/random.py threaded keys — traced key under
jit/to_static, so sampling inside a compiled step stays pure), broadcast
semantics via jnp instead of the reference's elementwise_* op chains.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import autograd as AG
from ..core import random as rnd
from ..core.tensor import Tensor

__all__ = ["Distribution", "Uniform", "Normal", "Categorical"]


def _as_raw(v, dtype=jnp.float32):
    if isinstance(v, Tensor):
        return v._data.astype(dtype)
    return jnp.asarray(np.asarray(v), dtype)


class Distribution:
    """Abstract base (distribution.py:41)."""

    def sample(self, shape, seed=0):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        raise NotImplementedError


class Uniform(Distribution):
    """U[low, high) (distribution.py:168). Broadcasts like the reference:
    sample shape = sample_shape + broadcast(low, high).shape."""

    def __init__(self, low, high, name=None):
        self.low = _as_raw(low)
        self.high = _as_raw(high)
        self.name = name or "Uniform"

    def _bshape(self, shape):
        base = jnp.broadcast_shapes(self.low.shape, self.high.shape)
        return tuple(shape) + tuple(base)

    def sample(self, shape, seed=0):
        key = rnd.next_key() if seed == 0 else jax.random.PRNGKey(seed)
        u = jax.random.uniform(key, self._bshape(shape), jnp.float32)
        out = self.low + u * (self.high - self.low)
        return Tensor._wrap(out, stop_gradient=True)

    def log_prob(self, value):
        def f(v):
            inside = (v > self.low) & (v < self.high)
            lp = -jnp.log(self.high - self.low)
            return jnp.where(inside, lp, -jnp.inf)

        v = value if isinstance(value, Tensor) else Tensor(value)
        return AG.apply(f, (v,), name="uniform_log_prob")

    def probs(self, value):
        def f(v):
            inside = (v > self.low) & (v < self.high)
            return jnp.where(inside, 1.0 / (self.high - self.low), 0.0)

        v = value if isinstance(value, Tensor) else Tensor(value)
        return AG.apply(f, (v,), name="uniform_probs")

    def entropy(self):
        return Tensor._wrap(jnp.log(self.high - self.low),
                            stop_gradient=True)


class Normal(Distribution):
    """N(loc, scale^2) (distribution.py:390)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _as_raw(loc)
        self.scale = _as_raw(scale)
        self.name = name or "Normal"

    def _bshape(self, shape):
        base = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        return tuple(shape) + tuple(base)

    def sample(self, shape, seed=0):
        key = rnd.next_key() if seed == 0 else jax.random.PRNGKey(seed)
        z = jax.random.normal(key, self._bshape(shape), jnp.float32)
        return Tensor._wrap(self.loc + z * self.scale, stop_gradient=True)

    def entropy(self):
        # 0.5 + 0.5 log(2 pi) + log(scale), broadcast to loc's shape
        ent = 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(
            jnp.broadcast_to(self.scale, jnp.broadcast_shapes(
                self.loc.shape, self.scale.shape))
        )
        return Tensor._wrap(ent, stop_gradient=True)

    def log_prob(self, value):
        def f(v):
            var = self.scale * self.scale
            return (
                -((v - self.loc) ** 2) / (2 * var)
                - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi)
            )

        v = value if isinstance(value, Tensor) else Tensor(value)
        return AG.apply(f, (v,), name="normal_log_prob")

    def probs(self, value):
        def f(v):
            var = self.scale * self.scale
            return jnp.exp(-((v - self.loc) ** 2) / (2 * var)) / jnp.sqrt(
                2 * math.pi * var
            )

        v = value if isinstance(value, Tensor) else Tensor(value)
        return AG.apply(f, (v,), name="normal_probs")

    def kl_divergence(self, other: "Normal"):
        """KL(self || other) (distribution.py:595)."""
        ratio = self.scale / other.scale
        t1 = (self.loc - other.loc) / other.scale
        kl = 0.5 * (ratio * ratio + t1 * t1) - 0.5 - jnp.log(ratio)
        return Tensor._wrap(kl, stop_gradient=True)


class Categorical(Distribution):
    """Categorical (distribution.py:640). Reference semantics: `logits`
    are non-negative RELATIVE WEIGHTS — probs = logits / sum(logits)
    (its probs() normalizes by the sum and sample() feeds them to the
    multinomial op), NOT log-probabilities. EXCEPT entropy() and
    kl_divergence() (:812-860), which exp-normalize: softmax(logits)
    after max-subtraction — the two normalizations deliberately coexist
    in the reference, so the same weights yield different entropy than
    -(probs * log probs).sum would."""

    def __init__(self, logits, name=None):
        self.logits = _as_raw(logits)
        self.name = name or "Categorical"

    def _log_probs(self):
        w = self.logits
        return jnp.log(
            jnp.maximum(w, 1e-30)
        ) - jnp.log(jnp.maximum(w.sum(-1, keepdims=True), 1e-30))

    def _softmax_log_probs(self):
        """exp-normalized log-probs (the entropy/kl path)."""
        z = self.logits - jnp.max(self.logits, axis=-1, keepdims=True)
        return z - jnp.log(jnp.sum(jnp.exp(z), axis=-1, keepdims=True))

    def sample(self, shape):
        key = rnd.next_key()
        idx = jax.random.categorical(
            key, self._log_probs(), axis=-1,
            shape=tuple(shape) + tuple(self.logits.shape[:-1]),
        )
        return Tensor._wrap(idx.astype(jnp.int64), stop_gradient=True)

    def entropy(self):
        lp = self._softmax_log_probs()
        ent = -jnp.sum(jnp.exp(lp) * lp, axis=-1)
        return Tensor._wrap(ent, stop_gradient=True)

    def kl_divergence(self, other: "Categorical"):
        lp = self._softmax_log_probs()
        lq = other._softmax_log_probs()
        kl = jnp.sum(jnp.exp(lp) * (lp - lq), axis=-1)
        return Tensor._wrap(kl, stop_gradient=True)

    def _select(self, table, v):
        if self.logits.ndim == 1:
            return jnp.take(table, v.astype(jnp.int32), axis=-1)
        return jnp.take_along_axis(
            table, v.astype(jnp.int32)[..., None], axis=-1
        )[..., 0]

    def probs(self, value):
        def f(v):
            return self._select(jnp.exp(self._log_probs()), v)

        v = value if isinstance(value, Tensor) else Tensor(np.asarray(value))
        return AG.apply_nondiff(f, (v,))

    def log_prob(self, value):
        def f(v):
            return self._select(self._log_probs(), v)

        v = value if isinstance(value, Tensor) else Tensor(np.asarray(value))
        return AG.apply_nondiff(f, (v,))
