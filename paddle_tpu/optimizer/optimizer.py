"""Optimizers (reference: python/paddle/optimizer/optimizer.py base +
adam.py/adamw.py/momentum.py/... over operators/optimizers/*).

TPU-first: each update rule is a pure jax function jitted once per
param-shape (the analog of the reference's fused CUDA optimizer kernels);
state lives in per-param jax arrays. The same rules power the jit/to_static
training path (they are pure functions of (param, grad, state)) — see
paddle_tpu.jit for whole-step fusion.
"""
from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..core import autograd
from ..core.tensor import Parameter, Tensor
from ..regularizer import WeightDecayRegularizer
from .lr import LRScheduler

__all__ = [
    "Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad",
    "Adadelta", "RMSProp", "Lamb", "Lars",
]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._lr = learning_rate
        if parameters is not None:
            parameters = list(parameters)
        self._parameter_list = parameters
        self._grad_clip = grad_clip
        if isinstance(weight_decay, WeightDecayRegularizer):
            self._regularization = weight_decay
            self._wd_coeff = 0.0
        elif isinstance(weight_decay, (int, float)) and not isinstance(
            weight_decay, bool
        ):
            from ..regularizer import L2Decay

            self._regularization = L2Decay(weight_decay)
            self._wd_coeff = weight_decay
        else:
            self._regularization = None
            self._wd_coeff = 0.0
        self._accumulators: Dict[str, Dict[int, jax.Array]] = {}
        self._step_count = 0

    # -- lr -----------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return float(self._lr())
        return float(self._lr)

    def set_lr(self, value):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = float(value)

    @property
    def _learning_rate(self):
        return self._lr

    # -- state --------------------------------------------------------------
    def _acc(self, name: str, p: Parameter, init=None):
        store = self._accumulators.setdefault(name, {})
        if id(p) not in store:
            # default seed routes through _acc_init so optimizers with a
            # non-zeros_like accumulator layout (quantized moments:
            # int8 payload + f32 scale leaves) seed the eager path and
            # the functional path identically
            store[id(p)] = (
                self._acc_init(name, p) if init is None else init
            )
        return store[id(p)]

    def _set_acc(self, name: str, p: Parameter, value):
        self._accumulators[name][id(p)] = value

    def state_dict(self) -> Dict:
        """Accumulators + LR state (optimizer.py state_dict parity)."""
        out = {}
        params = self._get_params()
        name_of = {id(p): (p.name or f"param_{i}") for i, p in enumerate(params)}
        for acc_name, store in self._accumulators.items():
            for pid, arr in store.items():
                if pid in name_of:
                    out[f"{name_of[pid]}.{acc_name}"] = Tensor._wrap(arr)
        out["@step"] = self._step_count
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        return out

    def set_state_dict(self, state):
        params = self._get_params()
        name_of = {(p.name or f"param_{i}"): p for i, p in enumerate(params)}
        self._step_count = int(state.get("@step", 0))
        if "LR_Scheduler" in state and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(state["LR_Scheduler"])
        for key, val in state.items():
            if key in ("@step", "LR_Scheduler"):
                continue
            pname, acc_name = key.rsplit(".", 1)
            if pname in name_of:
                p = name_of[pname]
                raw = val._data if isinstance(val, Tensor) else jnp.asarray(val)
                self._accumulators.setdefault(acc_name, {})[id(p)] = raw

    set_dict = set_state_dict

    # -- the step -----------------------------------------------------------
    def _get_params(self) -> List[Parameter]:
        if self._parameter_list is None:
            raise ValueError(
                "Optimizer constructed without parameters; pass parameters= "
                "or use minimize(loss, parameter_list=...)"
            )
        return self._parameter_list

    def step(self):
        """Apply one update from accumulated .grad (dygraph step path —
        reference: optimizer.py _apply_optimize → core.ops.adam etc.)."""
        params = [
            p for p in self._get_params()
            if not p.stop_gradient or p.grad is not None
        ]
        params_grads = [(p, p.grad) for p in params if p.grad is not None]
        if not params_grads:
            return
        # regularization (L2/L1 -> grad term; reference appends regularization
        # ops before clip). Per-param regularizer overrides the optimizer one.
        if self._regularization is not None or any(
            p.regularizer is not None for p, _ in params_grads
        ):
            regularized = []
            for p, g in params_grads:
                reg = p.regularizer or self._regularization
                if reg is not None:
                    g = Tensor._wrap(g._data + reg.grad_term(p._data))
                regularized.append((p, g))
            params_grads = regularized
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = self.get_lr()
        self._step_count += 1
        with autograd.no_grad():
            for p, g in params_grads:
                p_lr = lr * p.optimize_attr.get("learning_rate", 1.0)
                self._apply_one(p, g._data.astype(p._data.dtype), p_lr)

    def _apply_one(self, p: Parameter, g, lr: float):
        raise NotImplementedError

    def clear_grad(self):
        for p in self._get_params():
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        """loss.backward() + step() convenience. In static mode this
        RECORDS the backward+update directive into the program (the
        append_backward analog, fluid/backward.py:1337); Executor.run
        compiles and applies it."""
        if parameters is not None:
            self._parameter_list = list(parameters)
        loss_var = getattr(loss, "_static_var", None)
        if loss_var is not None:
            from ..static.program import default_main_program

            prog = default_main_program()
            if self._parameter_list is None:
                self._parameter_list = [
                    p for p in prog.all_parameters() if p.trainable
                ]
            prog.optimize_directives.append((self, loss_var))
            prog._version += 1
            return None, None
        # dygraph reference semantics (optimizer.py minimize under
        # imperative mode): when the user already ran backward on THIS
        # loss — the stock 1.x idiom `loss.backward(); opt.minimize()` —
        # apply the existing grads; a second backward would double every
        # gradient. A minimize-only loop (no explicit backward) still
        # gets backward here, fresh each call.
        if not getattr(loss, "_backward_ran", False):
            loss.backward()
        self.step()
        return None, None

    # -- pure-functional path (the fused/jitted train-step hot path) ---------
    # Each optimizer exposes its update as a pure function over an explicit
    # state pytree so the whole step (fwd+bwd+update) compiles into ONE XLA
    # program — the TPU analog of the reference's fused optimizer kernels
    # (paddle/fluid/operators/optimizers/*) reached through run_program.

    _acc_tree_names: tuple = ()

    def _acc_init(self, name: str, p: Parameter):
        z = jnp.zeros_like(p._data)
        # match the PARAM's placement: a moment born on the default device
        # while its param carries a NamedSharding gives the first fused
        # step a different input signature than every later one — one full
        # retrace+recompile of the whole train step (tens of seconds on a
        # big model) for nothing
        sh = getattr(p._data, "sharding", None)
        if sh is not None:
            import jax

            z = jax.device_put(z, sh)
        return z

    def _functional_state(self, params: List[Parameter]):
        """State pytree: {acc_name: tuple aligned with params}. Seeds from /
        shares storage with the eager accumulators so the two paths interop."""
        state = {}
        for name in self._acc_tree_names:
            store = self._accumulators.setdefault(name, {})
            vals = []
            for p in params:
                if id(p) not in store:
                    store[id(p)] = self._acc_init(name, p)
                vals.append(store[id(p)])
            state[name] = tuple(vals)
        return state

    def _load_functional_state(self, params: List[Parameter], state):
        for name in self._acc_tree_names:
            store = self._accumulators.setdefault(name, {})
            for p, v in zip(params, state[name]):
                store[id(p)] = v

    def _pure_one(self, p, p_raw, g_raw, accs: dict, lr, t):
        """One param's pure update: (new_p, new_accs). lr/t are traced arrays;
        `p` is the Parameter object for host-side metadata only."""
        raise NotImplementedError(
            f"{type(self).__name__} has no pure update rule"
        )

    def _functional_update(self, params: List[Parameter], p_raws, g_raws,
                           state, lr, t):
        """Apply the update across the param list. Returns (new_p_raws,
        new_state). `params` supplies host-side metadata (per-param lr
        multipliers, weight-decay exclusions); math sees only raws."""
        new_ps, new_state = [], {n: [] for n in self._acc_tree_names}
        for i, (p, praw, graw) in enumerate(zip(params, p_raws, g_raws)):
            d = praw.dtype
            mult = p.optimize_attr.get("learning_rate", 1.0)
            p_lr = lr.astype(d) * jnp.asarray(mult, d)
            accs = {n: state[n][i] for n in self._acc_tree_names}
            if graw is None:
                new_p, new_accs = praw, accs
            else:
                new_p, new_accs = self._pure_one(
                    p, praw, graw.astype(d), accs, p_lr, t.astype(d)
                )
            new_ps.append(new_p)
            for n in self._acc_tree_names:
                new_state[n].append(new_accs[n])
        return tuple(new_ps), {n: tuple(v) for n, v in new_state.items()}


def _jit_rule(fn):
    """Compile an update rule once per shape/dtype; scalars ride as arrays."""
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# Update rules (pure; shared by eager step and jitted train steps)
# ---------------------------------------------------------------------------


@_jit_rule
def _sgd_rule(p, g, lr):
    return p - lr * g


@_jit_rule
def _momentum_rule(p, g, v, lr, mu, use_nesterov):
    v_new = mu * v + g
    p_new = jnp.where(
        use_nesterov, p - lr * (g + mu * v_new), p - lr * v_new
    )
    return p_new, v_new


@_jit_rule
def _adam_rule(p, g, m, v, lr, beta1, beta2, eps, t):
    m_new = beta1 * m + (1 - beta1) * g
    v_new = beta2 * v + (1 - beta2) * (g * g)
    mhat = m_new / (1 - beta1**t)
    vhat = v_new / (1 - beta2**t)
    return p - lr * mhat / (jnp.sqrt(vhat) + eps), m_new, v_new


@_jit_rule
def _adamw_rule(p, g, m, v, lr, beta1, beta2, eps, t, wd):
    m_new = beta1 * m + (1 - beta1) * g
    v_new = beta2 * v + (1 - beta2) * (g * g)
    mhat = m_new / (1 - beta1**t)
    vhat = v_new / (1 - beta2**t)
    return (
        p - lr * (mhat / (jnp.sqrt(vhat) + eps) + wd * p),
        m_new,
        v_new,
    )


@_jit_rule
def _adagrad_rule(p, g, G, lr, eps):
    G_new = G + g * g
    return p - lr * g / (jnp.sqrt(G_new) + eps), G_new


@_jit_rule
def _adadelta_rule(p, g, Eg, Ex, rho, eps):
    Eg_new = rho * Eg + (1 - rho) * g * g
    dx = -jnp.sqrt(Ex + eps) / jnp.sqrt(Eg_new + eps) * g
    Ex_new = rho * Ex + (1 - rho) * dx * dx
    return p + dx, Eg_new, Ex_new


@_jit_rule
def _rmsprop_rule(p, g, ms, mom, lr, rho, eps, momentum, centered, mg):
    ms_new = rho * ms + (1 - rho) * g * g
    denom = jnp.where(centered, ms_new - mg * mg, ms_new)
    mom_new = momentum * mom + lr * g / jnp.sqrt(denom + eps)
    return p - mom_new, ms_new, mom_new


@_jit_rule
def _adamax_rule(p, g, m, u, lr, beta1, beta2, eps, t):
    m_new = beta1 * m + (1 - beta1) * g
    u_new = jnp.maximum(beta2 * u, jnp.abs(g))
    return p - lr / (1 - beta1**t) * m_new / (u_new + eps), m_new, u_new


@_jit_rule
def _lars_rule(p, g, v, lr, mu, coeff, wd, eps):
    p_norm = jnp.sqrt(jnp.sum(p * p))
    g_norm = jnp.sqrt(jnp.sum(g * g))
    local_lr = jnp.where(
        (p_norm > 0) & (g_norm > 0),
        lr * coeff * p_norm / (g_norm + wd * p_norm + eps),
        lr,
    )
    v_new = mu * v + local_lr * (g + wd * p)
    return p - v_new, v_new


@_jit_rule
def _lamb_rule(p, g, m, v, lr, beta1, beta2, eps, t, wd):
    m_new = beta1 * m + (1 - beta1) * g
    v_new = beta2 * v + (1 - beta2) * (g * g)
    mhat = m_new / (1 - beta1**t)
    vhat = v_new / (1 - beta2**t)
    r = mhat / (jnp.sqrt(vhat) + eps) + wd * p
    p_norm = jnp.sqrt(jnp.sum(p * p))
    r_norm = jnp.sqrt(jnp.sum(r * r))
    trust = jnp.where(
        (p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0
    )
    return p - lr * trust * r, m_new, v_new


class SGD(Optimizer):
    """reference: optimizer.py SGDOptimizer / operators/optimizers/sgd_op."""

    def _apply_one(self, p, g, lr):
        p._data = _sgd_rule(p._data, g, jnp.asarray(lr, p._data.dtype))

    def _pure_one(self, p, p_raw, g_raw, accs, lr, t):
        return p_raw - lr * g_raw, accs


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _apply_one(self, p, g, lr):
        v = self._acc("velocity", p)
        p._data, v_new = _momentum_rule(
            p._data, g, v,
            jnp.asarray(lr, p._data.dtype),
            jnp.asarray(self._momentum, p._data.dtype),
            jnp.asarray(self._nesterov),
        )
        self._set_acc("velocity", p, v_new)

    _acc_tree_names = ("velocity",)

    def _pure_one(self, p, p_raw, g_raw, accs, lr, t):
        d = p_raw.dtype
        p_new, v_new = _momentum_rule(
            p_raw, g_raw, accs["velocity"], lr,
            jnp.asarray(self._momentum, d), jnp.asarray(self._nesterov),
        )
        return p_new, {"velocity": v_new}


class Adam(Optimizer):
    """reference: optimizer/adam.py over operators/optimizers/adam_op."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    # -- quantized moments (ISSUE 19) -----------------------------------
    # strategy.quantized_moments stores both moments as int8/fp8 payload
    # + per-block f32 scales (distributed/quantized_compute.py last-axis
    # layout): the compiled apply dequantizes to the update width, runs
    # the unchanged Adam rule, and requantizes — so moments never live
    # wide in HBM and the per-step state error is exactly ONE
    # quantize_dequantize round trip (the PR-10 error model). The scale
    # leaves ride the SAME accumulator machinery as extra acc names, so
    # gradient_merge's boundary select, ZeRO's pad/constrain, and
    # state_dict round trips all compose without special cases.
    _q_moments = None
    _Q_MOMENT_NAMES = ("moment1", "moment2")

    def quantize_moments(self, policy, block=128):
        """Arm narrow moment storage. Must run BEFORE any state is
        seeded (re-encoding live wide moments would silently change the
        trajectory mid-run — resume from a checkpoint instead)."""
        from ..distributed import quantized_comm as _qc

        pol = _qc.resolve_policy(policy, block, knob="quantized_moments")
        if pol is None:
            return None
        for nm in self._Q_MOMENT_NAMES:
            if self._accumulators.get(nm):
                raise RuntimeError(
                    "quantized_moments must be armed before the first "
                    "step: this optimizer already holds wide moment "
                    "state (arm at construction, or resume via "
                    "set_state_dict after arming)"
                )
        self._q_moments = pol
        self._acc_tree_names = (
            "moment1", "moment2", "moment1_scale", "moment2_scale"
        )
        return pol

    def _acc_init(self, name: str, p: Parameter):
        if self._q_moments is None:
            return super()._acc_init(name, p)
        from ..distributed import quantized_comm as _qc

        dt, bs = self._q_moments
        shp = p._data.shape
        if len(shp) == 0:
            # scalars have no axis to block over: wide payload + the 0-d
            # zero-scale sentinel moment_wide recognizes
            if name.endswith("_scale"):
                return jnp.zeros((), jnp.float32)
            return super()._acc_init(name, p)
        qdtype, _ = _qc._qparams(dt)
        d = int(shp[-1])
        eb = _qc._lastaxis_block(d, bs)
        if name.endswith("_scale"):
            arr = jnp.zeros(tuple(shp[:-1]) + (d // eb,), jnp.float32)
        else:
            arr = jnp.zeros(shp, qdtype)
        sh = getattr(p._data, "sharding", None)
        if sh is not None:
            if arr.shape == tuple(shp):
                arr = jax.device_put(arr, sh)
            else:
                # scale leaves are 1/block the bytes: replicate on the
                # param's mesh (same retrace-avoidance rationale as the
                # base seeding)
                from jax.sharding import NamedSharding, PartitionSpec

                if isinstance(sh, NamedSharding):
                    arr = jax.device_put(
                        arr, NamedSharding(sh.mesh, PartitionSpec())
                    )
        return arr

    def _q_wide(self, accs, d):
        from ..distributed import quantized_compute as _Q

        m = _Q.moment_wide(accs["moment1"], accs["moment1_scale"], d)
        # moment2 is stored in sqrt domain (see moment2_narrow): linear
        # int8 on v itself zero-rounds elements whose grad is ~16x below
        # the block max while moment1 still resolves them, and the
        # m / (sqrt(0) + eps) update then explodes by ~1/eps
        v = _Q.moment2_wide(accs["moment2"], accs["moment2_scale"], d)
        return m, v

    def _q_narrow(self, m_new, v_new):
        from ..distributed import quantized_compute as _Q

        dt, bs = self._q_moments
        mp, ms = _Q.moment_narrow(m_new, dt, bs)
        vp, vs = _Q.moment2_narrow(v_new, dt, bs)
        return {"moment1": mp, "moment2": vp,
                "moment1_scale": ms, "moment2_scale": vs}

    def _apply_one(self, p, g, lr):
        d = p._data.dtype
        if self._q_moments is not None:
            accs = {n: self._acc(n, p) for n in self._acc_tree_names}
            m, v = self._q_wide(accs, d)
        else:
            m = self._acc("moment1", p)
            v = self._acc("moment2", p)
        p._data, m_new, v_new = _adam_rule(
            p._data, g, m, v,
            jnp.asarray(lr, d), jnp.asarray(self._beta1, d),
            jnp.asarray(self._beta2, d), jnp.asarray(self._epsilon, d),
            jnp.asarray(self._step_count, d),
        )
        if self._q_moments is not None:
            for n, val in self._q_narrow(m_new, v_new).items():
                self._set_acc(n, p, val)
            return
        self._set_acc("moment1", p, m_new)
        self._set_acc("moment2", p, v_new)

    _acc_tree_names = ("moment1", "moment2")

    def _pure_one(self, p, p_raw, g_raw, accs, lr, t):
        d = p_raw.dtype
        if self._q_moments is not None:
            m, v = self._q_wide(accs, d)
        else:
            m, v = accs["moment1"], accs["moment2"]
        new_p, m_new, v_new = _adam_rule(
            p_raw, g_raw, m, v,
            lr, jnp.asarray(self._beta1, d), jnp.asarray(self._beta2, d),
            jnp.asarray(self._epsilon, d), t,
        )
        if self._q_moments is not None:
            return new_p, self._q_narrow(m_new, v_new)
        return new_p, {"moment1": m_new, "moment2": v_new}


class AdamW(Adam):
    """Decoupled weight decay (reference: optimizer/adamw.py). weight_decay
    multiplies the param directly instead of entering the moments."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        Optimizer.__init__(self, learning_rate, parameters, None, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._wd = float(weight_decay) if weight_decay else 0.0
        self._apply_decay_param_fun = apply_decay_param_fun

    def _apply_one(self, p, g, lr):
        wd = self._wd
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(p.name):
            wd = 0.0
        d = p._data.dtype
        if self._q_moments is not None:
            accs = {n: self._acc(n, p) for n in self._acc_tree_names}
            m, v = self._q_wide(accs, d)
        else:
            m = self._acc("moment1", p)
            v = self._acc("moment2", p)
        p._data, m_new, v_new = _adamw_rule(
            p._data, g, m, v,
            jnp.asarray(lr, d), jnp.asarray(self._beta1, d),
            jnp.asarray(self._beta2, d), jnp.asarray(self._epsilon, d),
            jnp.asarray(self._step_count, d), jnp.asarray(wd, d),
        )
        if self._q_moments is not None:
            for n, val in self._q_narrow(m_new, v_new).items():
                self._set_acc(n, p, val)
            return
        self._set_acc("moment1", p, m_new)
        self._set_acc("moment2", p, v_new)

    def _pure_one(self, p, p_raw, g_raw, accs, lr, t):
        wd = self._wd
        if (self._apply_decay_param_fun is not None
                and not self._apply_decay_param_fun(p.name)):
            wd = 0.0
        d = p_raw.dtype
        if self._q_moments is not None:
            m, v = self._q_wide(accs, d)
        else:
            m, v = accs["moment1"], accs["moment2"]
        new_p, m_new, v_new = _adamw_rule(
            p_raw, g_raw, m, v,
            lr, jnp.asarray(self._beta1, d), jnp.asarray(self._beta2, d),
            jnp.asarray(self._epsilon, d), t, jnp.asarray(wd, d),
        )
        if self._q_moments is not None:
            return new_p, self._q_narrow(m_new, v_new)
        return new_p, {"moment1": m_new, "moment2": v_new}


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _apply_one(self, p, g, lr):
        m = self._acc("moment", p)
        u = self._acc("inf_norm", p)
        d = p._data.dtype
        p._data, m_new, u_new = _adamax_rule(
            p._data, g, m, u,
            jnp.asarray(lr, d), jnp.asarray(self._beta1, d),
            jnp.asarray(self._beta2, d), jnp.asarray(self._epsilon, d),
            jnp.asarray(self._step_count, d),
        )
        self._set_acc("moment", p, m_new)
        self._set_acc("inf_norm", p, u_new)

    _acc_tree_names = ("moment", "inf_norm")

    def _pure_one(self, p, p_raw, g_raw, accs, lr, t):
        d = p_raw.dtype
        new_p, m_new, u_new = _adamax_rule(
            p_raw, g_raw, accs["moment"], accs["inf_norm"],
            lr, jnp.asarray(self._beta1, d), jnp.asarray(self._beta2, d),
            jnp.asarray(self._epsilon, d), t,
        )
        return new_p, {"moment": m_new, "inf_norm": u_new}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _apply_one(self, p, g, lr):
        G = self._acc(
            "moment", p, jnp.full_like(p._data, self._init_acc)
        )
        d = p._data.dtype
        p._data, G_new = _adagrad_rule(
            p._data, g, G, jnp.asarray(lr, d), jnp.asarray(self._epsilon, d)
        )
        self._set_acc("moment", p, G_new)

    _acc_tree_names = ("moment",)

    def _acc_init(self, name, p):
        return jnp.full_like(p._data, self._init_acc)

    def _pure_one(self, p, p_raw, g_raw, accs, lr, t):
        d = p_raw.dtype
        new_p, G_new = _adagrad_rule(
            p_raw, g_raw, accs["moment"], lr, jnp.asarray(self._epsilon, d)
        )
        return new_p, {"moment": G_new}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._rho = rho

    def _apply_one(self, p, g, lr):
        Eg = self._acc("avg_squared_grad", p)
        Ex = self._acc("avg_squared_update", p)
        d = p._data.dtype
        p._data, Eg_new, Ex_new = _adadelta_rule(
            p._data, g, Eg, Ex,
            jnp.asarray(self._rho, d), jnp.asarray(self._epsilon, d),
        )
        self._set_acc("avg_squared_grad", p, Eg_new)
        self._set_acc("avg_squared_update", p, Ex_new)

    _acc_tree_names = ("avg_squared_grad", "avg_squared_update")

    def _pure_one(self, p, p_raw, g_raw, accs, lr, t):
        d = p_raw.dtype
        new_p, Eg_new, Ex_new = _adadelta_rule(
            p_raw, g_raw, accs["avg_squared_grad"],
            accs["avg_squared_update"],
            jnp.asarray(self._rho, d), jnp.asarray(self._epsilon, d),
        )
        return new_p, {
            "avg_squared_grad": Eg_new,
            "avg_squared_update": Ex_new,
        }


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _apply_one(self, p, g, lr):
        ms = self._acc("mean_square", p)
        mom = self._acc("momentum", p)
        d = p._data.dtype
        mg = self._acc("mean_grad", p) if self._centered else jnp.zeros((), d)
        if self._centered:
            mg = self._rho * mg + (1 - self._rho) * g
            self._set_acc("mean_grad", p, mg)
        p._data, ms_new, mom_new = _rmsprop_rule(
            p._data, g, ms, mom,
            jnp.asarray(lr, d), jnp.asarray(self._rho, d),
            jnp.asarray(self._epsilon, d), jnp.asarray(self._momentum, d),
            jnp.asarray(self._centered), mg,
        )
        self._set_acc("mean_square", p, ms_new)
        self._set_acc("momentum", p, mom_new)

    _acc_tree_names = ("mean_square", "momentum", "mean_grad")

    def _pure_one(self, p, p_raw, g_raw, accs, lr, t):
        d = p_raw.dtype
        rho = jnp.asarray(self._rho, d)
        mg = accs["mean_grad"]
        if self._centered:
            mg = rho * mg + (1 - rho) * g_raw
        p_new, ms_new, mom_new = _rmsprop_rule(
            p_raw, g_raw, accs["mean_square"], accs["momentum"],
            lr, rho, jnp.asarray(self._epsilon, d),
            jnp.asarray(self._momentum, d),
            jnp.asarray(self._centered), mg,
        )
        return p_new, {
            "mean_square": ms_new, "momentum": mom_new, "mean_grad": mg,
        }


class Lamb(Optimizer):
    """reference: optimizer.py LambOptimizer over optimizers/lamb_op."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _apply_one(self, p, g, lr):
        wd = self._wd
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        d = p._data.dtype
        p._data, m_new, v_new = _lamb_rule(
            p._data, g, m, v,
            jnp.asarray(lr, d), jnp.asarray(self._beta1, d),
            jnp.asarray(self._beta2, d), jnp.asarray(self._epsilon, d),
            jnp.asarray(self._step_count, d), jnp.asarray(wd, d),
        )
        self._set_acc("moment1", p, m_new)
        self._set_acc("moment2", p, v_new)

    _acc_tree_names = ("moment1", "moment2")

    def _pure_one(self, p, p_raw, g_raw, accs, lr, t):
        wd = self._wd
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        d = p_raw.dtype
        new_p, m_new, v_new = _lamb_rule(
            p_raw, g_raw, accs["moment1"], accs["moment2"],
            lr, jnp.asarray(self._beta1, d), jnp.asarray(self._beta2, d),
            jnp.asarray(self._epsilon, d), t, jnp.asarray(wd, d),
        )
        return new_p, {"moment1": m_new, "moment2": v_new}


class Lars(Optimizer):
    """LARS momentum — layer-adaptive rate scaling for large-batch training.

    reference: paddle/fluid/operators/optimizers/lars_momentum_op.cu +
    fleet/meta_optimizers/lars_optimizer.py:19 (trust ratio
    ||p|| / (||g|| + wd*||p||) scales the lr per layer).
    """

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, epsilon=0.0, parameters=None,
                 grad_clip=None, exclude_from_weight_decay=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._momentum = momentum
        self._coeff = lars_coeff
        self._wd = lars_weight_decay
        self._epsilon = epsilon
        self._exclude = list(exclude_from_weight_decay or [])

    def _wd_for(self, p):
        name = p.name or ""
        if any(tag in name for tag in self._exclude):
            return 0.0
        return self._wd

    def _apply_one(self, p, g, lr):
        v = self._acc("velocity", p)
        d = p._data.dtype
        p._data, v_new = _lars_rule(
            p._data, g, v, jnp.asarray(lr, d),
            jnp.asarray(self._momentum, d), jnp.asarray(self._coeff, d),
            jnp.asarray(self._wd_for(p), d),
            jnp.asarray(self._epsilon or 1e-9, d),
        )
        self._set_acc("velocity", p, v_new)

    _acc_tree_names = ("velocity",)

    def _pure_one(self, p, p_raw, g_raw, accs, lr, t):
        d = p_raw.dtype
        new_p, v_new = _lars_rule(
            p_raw, g_raw, accs["velocity"], lr,
            jnp.asarray(self._momentum, d), jnp.asarray(self._coeff, d),
            jnp.asarray(self._wd_for(p), d),
            jnp.asarray(self._epsilon or 1e-9, d),
        )
        return new_p, {"velocity": v_new}


LarsMomentum = Lars
