"""paddle_tpu.optimizer (reference: python/paddle/optimizer/)."""
from . import lr  # noqa: F401
from .optimizer import (  # noqa: F401
    SGD,
    Adadelta,
    Adagrad,
    Adam,
    Adamax,
    AdamW,
    Lamb,
    Lars,
    LarsMomentum,
    Momentum,
    Optimizer,
    RMSProp,
)
from .extras import (  # noqa: F401
    ExponentialMovingAverage,
    LookaheadOptimizer,
    ModelAverage,
)
