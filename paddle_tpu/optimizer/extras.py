"""Optimizer wrappers: EMA, Lookahead, ModelAverage.

Reference: python/paddle/fluid/optimizer.py — ExponentialMovingAverage
(:3466, bias-corrected EMA with apply/restore), LookaheadOptimizer
(:5230, slow/fast params with k-step interpolation), ModelAverage
(:3157, sliding-window parameter averaging with apply/restore).

TPU-native: each maintains its extra state as jax arrays keyed per
parameter; the update math runs as (cached-jit) elementwise programs —
no program rewriting, usable around any eager or TrainStep loop.
"""
from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

import jax.numpy as jnp

from ..core.tensor import Parameter

__all__ = ["ExponentialMovingAverage", "LookaheadOptimizer", "ModelAverage"]


class ExponentialMovingAverage:
    """EMA_t = decay*EMA_{t-1} + (1-decay)*theta_t, applied with the
    1/(1-decay^t) bias correction (optimizer.py:3466). `thres_steps`
    scheduling: effective decay = min(decay, (1+t)/(10+t))."""

    def __init__(self, decay=0.999, thres_steps=None, parameters=None,
                 name=None):
        self._decay = float(decay)
        self._thres = thres_steps is not None
        self._params: List[Parameter] = list(parameters or [])
        self._ema: Dict[int, jnp.ndarray] = {}
        self._backup: Dict[int, jnp.ndarray] = {}
        self._t = 0
        # product of EFFECTIVE decays: the bias-correction divisor is
        # 1 - prod(d_i), which equals 1 - decay^t only without scheduling
        self._decay_prod = 1.0

    def _bind(self, parameters):
        if parameters is not None:
            self._params = list(parameters)
        if not self._params:
            raise ValueError("EMA has no parameters bound")

    def update(self, parameters=None):
        if parameters is not None or not self._params:
            self._bind(parameters)
        self._t += 1
        d = self._decay
        if self._thres:
            d = min(d, (1.0 + self._t) / (10.0 + self._t))
        self._decay_prod *= d
        for p in self._params:
            prev = self._ema.get(id(p))
            cur = p._data.astype(jnp.float32)
            self._ema[id(p)] = (
                cur * (1.0 - d) if prev is None
                else prev * d + cur * (1.0 - d)
            )

    def apply(self, need_restore=True):
        """Swap EMA weights in (bias-corrected); context-manager friendly."""
        if self._t == 0:
            raise RuntimeError("EMA.apply() before any update()")
        corr = 1.0 - self._decay_prod
        for p in self._params:
            self._backup[id(p)] = p._data
            p._data = (self._ema[id(p)] / corr).astype(p._data.dtype)
            p._node = None
        if need_restore:
            return self._restoring()
        return contextlib.nullcontext()

    @contextlib.contextmanager
    def _restoring(self):
        try:
            yield
        finally:
            self.restore()

    def restore(self):
        for p in self._params:
            if id(p) in self._backup:
                p._data = self._backup.pop(id(p))
                p._node = None


class LookaheadOptimizer:
    """slow += alpha * (fast - slow); fast = slow, every k inner steps
    (optimizer.py:5230)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError(f"alpha must be in [0, 1], got {alpha}")
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._slow: Dict[int, jnp.ndarray] = {}
        self._calls = 0

    def _params(self):
        return [p for p in self.inner_optimizer._get_params() if p.trainable]

    def step(self):
        # slow params anchor at the INITIAL weights (optimizer.py:5230
        # initializes slow_param = param before training starts)
        for p in self._params():
            if id(p) not in self._slow:
                self._slow[id(p)] = p._data
        self.inner_optimizer.step()
        self._calls += 1
        params = self._params()
        if self._calls % self.k == 0:
            a = self.alpha
            for p in params:
                slow = self._slow[id(p)]
                new_slow = slow + a * (p._data - slow)
                self._slow[id(p)] = new_slow
                p._data = new_slow
                p._node = None

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return None, None


class ModelAverage:
    """Sliding-window parameter average with apply()/restore()
    (optimizer.py:3157). Call accumulate() after each optimizer step."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._rate = float(average_window_rate)
        self._min_w = int(min_average_window)
        self._max_w = int(max_average_window)
        self._params: List[Parameter] = list(parameters or [])
        self._sum: Dict[int, jnp.ndarray] = {}
        self._backup: Dict[int, jnp.ndarray] = {}
        self._num_accumulates = 0
        self._num_updates = 0
        # the "old" accumulator pair of the reference's restart scheme:
        # when the window closes, current sums demote to old and restart
        self._old_sum: Dict[int, jnp.ndarray] = {}
        self._old_accumulates = 0

    def accumulate(self, parameters=None):
        if parameters is not None:
            self._params = list(parameters)
        self._num_updates += 1
        self._num_accumulates += 1
        for p in self._params:
            cur = p._data.astype(jnp.float32)
            self._sum[id(p)] = self._sum.get(id(p), 0.0) + cur
        window = min(self._max_w, int(self._num_updates * self._rate))
        if (self._num_accumulates >= self._min_w
                and self._num_accumulates >= window):
            self._old_sum = dict(self._sum)
            self._old_accumulates = self._num_accumulates
            self._sum = {}
            self._num_accumulates = 0

    step = accumulate

    def apply(self, need_restore=True):
        total = self._num_accumulates + self._old_accumulates
        if total == 0:
            raise RuntimeError("ModelAverage.apply() before accumulate()")
        for p in self._params:
            self._backup[id(p)] = p._data
            s = self._sum.get(id(p), 0.0) + self._old_sum.get(id(p), 0.0)
            p._data = (s / total).astype(p._data.dtype)
            p._node = None
        if need_restore:
            return self._restoring()
        return contextlib.nullcontext()

    @contextlib.contextmanager
    def _restoring(self):
        try:
            yield
        finally:
            self.restore()

    def restore(self):
        for p in self._params:
            if id(p) in self._backup:
                p._data = self._backup.pop(id(p))
                p._node = None
