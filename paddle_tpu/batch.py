"""paddle.batch (reference: python/paddle/batch.py:18) — wrap a sample
reader (a zero-arg generator factory) into a mini-batch reader."""
from __future__ import annotations

__all__ = ["batch"]


def batch(reader, batch_size, drop_last=False):
    if batch_size <= 0:
        raise ValueError(f"batch_size must be positive, got {batch_size}")

    def batch_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader
