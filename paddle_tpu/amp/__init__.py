"""Automatic mixed precision.

reference: python/paddle/amp/auto_cast.py:20 (auto_cast over
fluid/dygraph/amp/auto_cast.py:91 amp_guard), grad_scaler.py:20 (GradScaler
over loss_scaler.py:27 AmpScaler: scale :119, minimize :156), C++ white/
black op lists (paddle/fluid/imperative/amp_auto_cast.h:31), and the AMP
primitive ops check_finite_and_unscale / update_loss_scaling
(operators/amp/).

TPU-first: the default low-precision dtype is bfloat16 — same exponent
range as f32, so loss scaling is unnecessary for the default path (the
GradScaler degrades to a pass-through unless fp16 is requested, matching
how the reference's scaler behaves with use_dynamic_loss_scaling=False).
The white/black lists mirror the reference's: matmul/conv cast down (MXU
ops), reductions/softmax/norm stay f32.
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype
from ..core.tensor import Tensor

__all__ = ["auto_cast", "amp_guard", "GradScaler", "AmpScaler", "decorate"]

# op categories (imperative/amp_auto_cast.cc AmpOperators)
WHITE_LIST = {"matmul", "linear", "conv2d", "conv1d", "conv3d", "einsum",
              "bmm", "mm", "mv", "attention_scores", "attention_context",
              "flash_attention"}
# fused_layer_norm / fused_residual_layer_norm are deliberately on NEITHER
# list: the Pallas kernels take bf16 activations as-is and do their
# statistics in f32 internally — black-listing them would reintroduce the
# f32 HBM round trip they exist to remove (the dense "layer_norm" stays
# black-listed). fused_linear_cross_entropy likewise: its vocab-chunk
# matmuls accumulate f32 via preferred_element_type while the [N, d]
# hidden input stays in the compute dtype.
BLACK_LIST = {"softmax", "log_softmax", "cross_entropy", "mean", "sum",
              "layer_norm", "exp", "log", "logsumexp",
              "softmax_with_cross_entropy"}
# batch_norm is deliberately NOT black-listed: the functional keeps its
# stat accumulation in f32 internally while applying in the input dtype,
# so casting bf16 activations up before it would only double HBM traffic
# (round-5 perf work, tools/PERF.md)


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_state = _AmpState()


def is_enabled() -> bool:
    return _state.enabled


def amp_dtype():
    return _state.dtype


def should_cast_down(op_name: str) -> bool:
    if not _state.enabled:
        return False
    if op_name in _state.custom_black or op_name in BLACK_LIST:
        return False
    if _state.level == "O2":
        return True
    return op_name in WHITE_LIST or op_name in _state.custom_white


def _cast_floats(raws, d):
    return tuple(
        r.astype(d)
        if hasattr(r, "dtype")
        and jnp.issubdtype(r.dtype, jnp.floating)
        and r.dtype != d
        else r
        for r in raws
    )


def cast_if_amp(op_name: str, raws):
    """AutoCastInputs analog (tracer.cc:159): white-list ops cast float
    inputs down to the amp dtype; black-list ops cast up to f32; the rest
    pass through."""
    if not _state.enabled or op_name is None:
        return raws
    if op_name in _state.custom_black or op_name in BLACK_LIST:
        return _cast_floats(raws, jnp.float32)
    if should_cast_down(op_name):
        return _cast_floats(raws, _state.dtype)
    return raws


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """paddle.amp.auto_cast (auto_cast.py:20)."""
    prev = (_state.enabled, _state.dtype, _state.level,
            _state.custom_white, _state.custom_black)
    _state.enabled = bool(enable)
    _state.dtype = convert_dtype(dtype)
    _state.level = level
    _state.custom_white = set(custom_white_list or ())
    _state.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level,
         _state.custom_white, _state.custom_black) = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate: O2 casts model params to the amp dtype (master
    weights stay f32 inside the optimizer accumulators)."""
    if level == "O2":
        for m in models if isinstance(models, (list, tuple)) else [models]:
            m.to(dtype=dtype)
    if optimizers is None:
        return models
    return models, optimizers


def _unscale_rule(gs, s):
    out = tuple(g / s.astype(g.dtype) for g in gs)
    finite = jnp.all(jnp.stack([jnp.isfinite(g).all() for g in out]))
    return out, ~finite


_unscale_jitted = None


def _unscale_fused(grads, scale):
    """One compiled program: g/scale for every grad + a single fused
    finiteness reduction (cached per grad-shape structure by jax.jit)."""
    global _unscale_jitted
    if _unscale_jitted is None:
        import jax

        _unscale_jitted = jax.jit(_unscale_rule)
    return _unscale_jitted(grads, jnp.asarray(scale, jnp.float32))


class GradScaler:
    """Dynamic loss scaling (grad_scaler.py:20 / AmpScaler loss_scaler.py:27).

    With bf16 (TPU default) scaling is unnecessary: enable=True still works
    but becomes a no-op multiply by 1 unless init_loss_scaling != 1.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0**15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling and enable
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, loss: Tensor) -> Tensor:
        """AmpScaler.scale (loss_scaler.py:119)."""
        if not self._enable or self._scale == 1.0:
            return loss
        return loss * self._scale

    def unscale_(self, optimizer):
        """check_finite_and_unscale analog (operators/amp/
        check_finite_and_unscale_op.cc): divide grads by scale, flag
        non-finite — ONE fused program over all grads and a single
        device->host sync, like the reference's single kernel over the
        whole grad list (not one launch + sync per parameter)."""
        if not self._enable:
            return
        grads = [p.grad._data for p in optimizer._get_params()
                 if p.grad is not None]
        if not grads:
            self._found_inf = False
            return
        new_grads, found = _unscale_fused(tuple(grads), self._scale)
        it = iter(new_grads)
        for p in optimizer._get_params():
            if p.grad is not None:
                p.grad._data = next(it)
        self._found_inf = bool(found)

    def step(self, optimizer):
        """Skip the update on inf/nan; update the scale (AmpScaler.minimize
        loss_scaler.py:156 + update_loss_scaling op)."""
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._dynamic and self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            optimizer.step()
            self._good_steps += 1
            self._bad_steps = 0
            if self._dynamic and self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def update(self):
        pass  # folded into step()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        optimizer.clear_grad()

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every,
            "decr_every_n_nan_or_inf": self._decr_every,
            "good_steps": self._good_steps,
            "bad_steps": self._bad_steps,
        }

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)


AmpScaler = GradScaler
