"""Pipeline parallelism: stage partitioning + host-driven 1F1B schedule.

Reference analog (SURVEY.md §2.9 pipeline row):
  - stage partitioning ≙ `device_guard("gpu:N")` annotations consumed by
    `PipelineOptimizer._create_vars` program splitting
    (python/paddle/fluid/optimizer.py:3718,3801,4493) — here an explicit
    `PipelineLayer(layers, num_stages=...)` cut of a layer sequence;
  - cross-stage send_v2/recv_v2 ops ≙ `jax.device_put` of activations onto
    the next stage's submesh (ICI transfer compiled by PJRT);
  - the 1F1B microbatch loop of `SectionWorker::TrainFiles`
    (paddle/fluid/framework/section_worker.cc:34,51 — op-role-filtered
    micro-batch passes) ≙ a host-driven issue order over per-stage compiled
    programs: each stage keeps at most `num_stages - stage` microbatches in
    flight (warmup forwards, then alternate backward/forward, then drain);
  - DP-across-pipelines allreduce inserted by the fleet meta-optimizer
    (fleet/meta_optimizers/pipeline_optimizer.py:136,208–240) ≙ the `dp`
    axis of each stage submesh: batches are sharded over `dp`, parameters
    replicated, so XLA's partitioner emits the gradient all-reduce inside
    each stage's backward program.

TPU-first design: one process drives all stages (single-controller). Each
pipeline stage owns a submesh (the `pp` slice of the hybrid mesh, keeping
its `dp`/`sp`/`mp` axes); its forward and backward are separately jitted
programs placed there by input shardings. Backward recomputes the stage
forward under `jax.vjp` (activation recompute — only stage *inputs* are
kept per in-flight microbatch, the 1F1B memory bound). XLA dispatch is
async, so issuing in 1F1B order lets disjoint submeshes run concurrently.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import autograd as AG
from ..core import random as rnd
from ..core.tensor import Tensor
from ..jit.functional_call import _swapped, _trace_rng
from ..nn.layer import Layer
from . import comm

__all__ = ["PipelineLayer", "PipelineParallel"]


class PipelineLayer(Layer):
    """A sequential model cut into pipeline stages.

    `layers` is the full sequence of sublayers (the analog of the body a
    user would wrap in per-device `device_guard` regions,
    fluid/optimizer.py:3801); `num_stages` defaults to the hybrid mesh's
    pp degree at distribution time. `loss_fn(logits, *labels)` runs on the
    last stage. `seg_method`:
      - "uniform": equal layer counts per stage;
      - "param":   balance by parameter count (greedy prefix split).
    """

    def __init__(self, layers: Sequence[Layer], num_stages: Optional[int] = None,
                 loss_fn: Optional[Callable] = None, seg_method: str = "uniform"):
        super().__init__()
        from ..nn.layers.container import LayerList

        self.funcs = LayerList(list(layers))
        self.num_stages = num_stages
        self.loss_fn = loss_fn
        self.seg_method = seg_method

    # -- single-device semantics (also the parity reference in tests) -------
    def forward(self, x, *labels):
        out = x
        for lyr in self.funcs:
            out = lyr(out)
        if labels and self.loss_fn is not None:
            return self.loss_fn(out, *labels)
        return out

    def segment(self, num_stages: int) -> List[List[int]]:
        """Layer indices per stage."""
        n = len(self.funcs)
        if num_stages > n:
            raise ValueError(
                f"cannot cut {n} layers into {num_stages} pipeline stages"
            )
        if self.seg_method == "param":
            weights = [
                max(sum(int(np_.size) for np_ in
                        (p._data for p in lyr.parameters())), 1)
                for lyr in self.funcs
            ]
        elif self.seg_method == "uniform":
            weights = [1] * n
        else:
            raise ValueError(f"unknown seg_method '{self.seg_method}'")
        total = sum(weights)
        bounds = [0]
        acc, j = 0, 0
        for k in range(1, num_stages):
            target = total * k / num_stages
            # advance to the weight midpoint, leaving >=1 layer per
            # remaining stage and >=1 layer in this one
            while acc < target and j < n - (num_stages - k):
                acc += weights[j]
                j += 1
            if j <= bounds[-1]:
                j = bounds[-1] + 1
                acc = sum(weights[:j])
            bounds.append(j)
        bounds.append(n)
        return [list(range(bounds[s], bounds[s + 1]))
                for s in range(num_stages)]


def _f_then_b_order(num_stages: int, num_micro: int):
    """The F-then-B issue order (schedule_mode="F-then-B",
    distributed_strategy.proto pipeline_configs): every microbatch's
    forward completes before any backward — simpler, all M activations in
    flight (higher memory than 1F1B, the reference's default for small M)."""
    S, M = num_stages, num_micro
    fwd = [("F", s, m) for m in range(M) for s in range(S)]
    bwd = [("B", s, m) for m in range(M) for s in reversed(range(S))]
    return fwd + bwd


def _1f1b_order(num_stages: int, num_micro: int):
    """The 1F1B issue order: list of ("F"|"B", stage, microbatch).

    Per-stage policy of SectionWorker's schedule (section_worker.cc:51):
    stage s keeps at most `num_stages - s` microbatches in flight — it runs
    `num_stages - 1 - s` warmup forwards, then alternates backward/forward,
    then drains. Generated by discrete-clock simulation (one op per stage
    per tick, deeper stages first so cotangents flow without idle ticks).
    """
    S, M = num_stages, num_micro
    f_done = [0] * S
    b_done = [0] * S
    ops = []
    while any(b < M for b in b_done):
        progressed = False
        for s in reversed(range(S)):
            m = b_done[s]
            b_ready = (
                m < M
                and f_done[s] > m
                and (s == S - 1 or b_done[s + 1] > m)
            )
            fm = f_done[s]
            f_ready = (
                fm < M
                and (s == 0 or f_done[s - 1] > fm)
                and fm - b_done[s] < S - s  # in-flight bound
            )
            if b_ready:
                ops.append(("B", s, m))
                b_done[s] += 1
                progressed = True
            elif f_ready:
                ops.append(("F", s, fm))
                f_done[s] += 1
                progressed = True
        if not progressed:
            raise AssertionError("1F1B schedule deadlock (bug)")
    return ops


class _Stage:
    """One pipeline stage: its sublayer, parameters, submesh, and the two
    compiled programs (forward, backward-with-recompute)."""

    def __init__(self, module: Layer, mesh: Mesh, is_last: bool,
                 loss_fn: Optional[Callable]):
        self.module = module
        self.mesh = mesh
        self.is_last = is_last
        self.loss_fn = loss_fn
        self.p_objs = [p for p in module.parameters() if p.trainable]
        self.b_objs = list(dict(module.named_buffers()).values())
        # place state on this stage's submesh (TP specs keep their 'mp'
        # placement inside the submesh), and rebind tensor-parallel
        # sublayers' mesh so their forward sharding constraints target
        # THIS submesh rather than the job-wide hybrid mesh
        for lyr in module.sublayers(include_self=True):
            if isinstance(getattr(lyr, "mesh", None), Mesh):
                lyr.mesh = mesh
        for p in module.parameters():
            spec = getattr(p, "_tp_spec", None) or P()
            p._data = jax.device_put(p._data, NamedSharding(mesh, spec))
        for b in self.b_objs:
            b._data = jax.device_put(b._data, NamedSharding(mesh, P()))
        self.batch_sharding = NamedSharding(mesh, P(comm.dp_axes(mesh)))
        self._fwd = jax.jit(self._fwd_fn)
        self._bwd = jax.jit(self._bwd_fn)

    # pure stage forward: (params, buffers, x[, labels], key) -> out/loss
    def _apply(self, p_raws, b_raws, x, labels, key):
        with AG.trace_mode(), _trace_rng(key), \
                _swapped(self.p_objs + self.b_objs,
                         list(p_raws) + list(b_raws)):
            out = self.module(Tensor._wrap(x))
            if self.is_last and self.loss_fn is not None and labels:
                out = self.loss_fn(out, *[Tensor._wrap(l) for l in labels])
            out_raw = out._data if isinstance(out, Tensor) else out
            new_b = tuple(b._data for b in self.b_objs)
        return out_raw, new_b

    def _fwd_fn(self, p_raws, b_raws, x, labels, key):
        return self._apply(p_raws, b_raws, x, labels, key)

    def _bwd_fn(self, p_raws, b_raws, x, labels, key, gy):
        """Recompute forward, pull back gy -> (gparams, gx)."""
        def f(p, xx):
            return self._apply(p, b_raws, xx, labels, key)[0]

        _, vjp = jax.vjp(f, tuple(p_raws), x)
        gp, gx = vjp(gy)
        return gp, gx

    def forward(self, x, labels, key):
        p = tuple(q._data for q in self.p_objs)
        b = tuple(q._data for q in self.b_objs)
        out, new_b = self._fwd(p, b, x, labels, key)
        return out, (p, b), new_b

    def backward(self, saved, x, labels, key, gy):
        p, b = saved
        return self._bwd(p, b, x, labels, key, gy)


class PipelineParallel(Layer):
    """Drive a PipelineLayer over the hybrid mesh's pp axis.

    Built by `fleet.distributed_model` when `pp_degree > 1`; usage follows
    the fleet pipeline API::

        model = fleet.distributed_model(PipelineLayer(layers, loss_fn=...))
        opt = fleet.distributed_optimizer(opt)
        loss = model.train_batch([x, y], opt)

    `accumulate_steps` (strategy pipeline_configs) is the microbatch count
    (≙ distributed_strategy.proto:120 micro_batch).
    """

    def __init__(self, layer: PipelineLayer, mesh: Optional[Mesh] = None,
                 num_stages: Optional[int] = None,
                 accumulate_steps: int = 1, schedule_mode: str = "1F1B"):
        super().__init__()
        if schedule_mode not in ("1F1B", "F-then-B"):
            raise NotImplementedError(
                f"schedule_mode '{schedule_mode}': only '1F1B' and "
                "'F-then-B' are built (interleaved/virtual stages are not)"
            )
        self.schedule_mode = schedule_mode
        self.pipeline = layer
        mesh = mesh if mesh is not None else comm.hybrid_mesh()
        if mesh is None:
            raise RuntimeError(
                "PipelineParallel needs a hybrid mesh: call fleet.init with "
                "hybrid_configs pp_degree, or comm.init_hybrid_mesh(pp=N)"
            )
        self.mesh = mesh
        S = num_stages or layer.num_stages or mesh.shape["pp"]
        if mesh.shape["pp"] != S:
            raise ValueError(
                f"PipelineLayer wants {S} stages but the mesh pp axis is "
                f"{mesh.shape['pp']}"
            )
        self.num_stages = S
        self.accumulate_steps = int(accumulate_steps)
        from ..nn.layers.container import Sequential

        seg = layer.segment(S)
        self.stages: List[_Stage] = []
        devs = mesh.devices  # (dp, pp, sp, mp) / (dcn, ici, pp, sp, mp)
        hier = "ici" in mesh.axis_names
        for s in range(S):
            if hier:  # hierarchical dp keeps both levels in the submesh
                sub = Mesh(devs[:, :, s], ("dcn", "ici", "sp", "mp"))
            else:
                sub = Mesh(devs[:, s], ("dp", "sp", "mp"))
            mod = Sequential(*[layer.funcs[i] for i in seg[s]])
            self.stages.append(
                _Stage(mod, sub, is_last=(s == S - 1),
                       loss_fn=layer.loss_fn)
            )
        self._order_cache = {}

    def parameters(self, include_sublayers=True):
        return self.pipeline.parameters(include_sublayers)

    def forward(self, x, *labels):
        """Inference path: microbatch-free straight-through pass."""
        out = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        key = rnd.next_key()
        for s, st in enumerate(self.stages):
            out = jax.device_put(out, st.batch_sharding)
            out, _, new_b = st.forward(out, (), jax.random.fold_in(key, s))
            for bo, nb in zip(st.b_objs, new_b):
                bo._data = nb
        return Tensor._wrap(out)

    # -- the SectionWorker::TrainFiles analog -------------------------------
    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """One global batch: split into microbatches, run 1F1B, apply the
        optimizer once with microbatch-averaged gradients."""
        if scaler is not None:
            raise NotImplementedError(
                "GradScaler with pipeline: use bf16 (strategy.amp) instead"
            )
        if self.pipeline.loss_fn is None:
            raise ValueError(
                "train_batch needs PipelineLayer(..., loss_fn=...) — the "
                "last stage computes the loss"
            )
        if len(data) < 2:
            raise ValueError(
                "train_batch expects [inputs, *labels]; got no labels"
            )
        x, labels = data[0], tuple(data[1:])
        x = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        labels = tuple(
            l._data if isinstance(l, Tensor) else jnp.asarray(l)
            for l in labels
        )
        M = self.accumulate_steps
        S = self.num_stages
        if x.shape[0] % M != 0:
            raise ValueError(
                f"batch {x.shape[0]} not divisible by accumulate_steps {M}"
            )
        mb = x.shape[0] // M
        dp = comm.dp_size(self.mesh)
        if mb % dp != 0:
            raise ValueError(
                f"microbatch size {mb} (batch {x.shape[0]} / "
                f"accumulate_steps {M}) must be divisible by dp_degree {dp}"
            )
        first, last = self.stages[0], self.stages[-1]
        xs = [
            jax.device_put(x[i * mb:(i + 1) * mb], first.batch_sharding)
            for i in range(M)
        ]
        labs = [
            tuple(
                jax.device_put(l[i * mb:(i + 1) * mb], last.batch_sharding)
                for l in labels
            )
            for i in range(M)
        ]
        base_key = rnd.next_key()
        keys = [
            [jax.random.fold_in(base_key, s * M + m) for m in range(M)]
            for s in range(S)
        ]

        mode = self.schedule_mode
        if (S, M, mode) not in self._order_cache:
            gen = _1f1b_order if mode == "1F1B" else _f_then_b_order
            self._order_cache[(S, M, mode)] = gen(S, M)
        order = self._order_cache[(S, M, mode)]
        stage_in: List[dict] = [dict() for _ in range(S)]   # (m) -> x
        saved: List[dict] = [dict() for _ in range(S)]      # (m) -> (p, b)
        gout: List[dict] = [dict() for _ in range(S)]       # (m) -> cotangent
        gsum = [None] * S
        losses = []
        for m in range(M):
            stage_in[0][m] = xs[m]

        for op, s, m in order:
            st = self.stages[s]
            lab = labs[m] if st.is_last else ()
            if op == "F":
                xin = stage_in[s][m]
                out, sv, new_b = st.forward(xin, lab, keys[s][m])
                saved[s][m] = sv
                for bo, nb in zip(st.b_objs, new_b):
                    bo._data = nb
                if st.is_last:
                    losses.append(out)
                    gout[s][m] = jnp.ones_like(out)
                else:
                    stage_in[s + 1][m] = jax.device_put(
                        out, self.stages[s + 1].batch_sharding
                    )
            else:  # "B"
                xin = stage_in[s][m]
                gp, gx = st.backward(
                    saved[s].pop(m), xin, lab, keys[s][m], gout[s].pop(m)
                )
                if s > 0:
                    gout[s - 1][m] = jax.device_put(
                        gx, self.stages[s - 1].batch_sharding
                    )
                    del stage_in[s][m]
                gsum[s] = gp if gsum[s] is None else tuple(
                    a + b for a, b in zip(gsum[s], gp)
                )

        # -- optimizer: one update from microbatch-mean grads per stage ----
        # Routed through the (possibly fleet-wrapped) optimizer's
        # functional rule so sharding (ZeRO over each stage's dp axis) and
        # gradient_merge (k_steps across train_batch calls, on top of the
        # M-microbatch accumulation above) compose with pipeline — the
        # reference's hybrid of sharding_optimizer.py:33 `hybrid_dp` with
        # PipelineOptimizer. Each stage's update is ONE donated jitted
        # program on its submesh (not per-param eager dispatches).
        opt = optimizer
        is_wrapped = getattr(opt, "user_defined_strategy", None) is not None
        inner = getattr(opt, "_inner", opt)  # unwrap fleet decorator
        inner._step_count += 1
        lr = jnp.asarray(inner.get_lr(), jnp.float32)
        t = jnp.asarray(inner._step_count, jnp.float32)
        inv_m = 1.0 / M
        fopt = opt if is_wrapped else inner
        # snapshot every stage's state BEFORE any load: the wrapper's
        # gradient-merge counter is global, and loading stage s would
        # advance it under stage s+1's feet
        states = [fopt._functional_state(st.p_objs) for st in self.stages]
        if not hasattr(self, "_upd_jit"):
            self._upd_jit = {}
        results = []
        for s, st in enumerate(self.stages):
            if s not in self._upd_jit:
                def make(stage):
                    def update(p_raws, grads, state, lr, t):
                        grads = [g * inv_m for g in grads]
                        return fopt._functional_update(
                            stage.p_objs, p_raws, grads, state, lr, t
                        )
                    return jax.jit(update, donate_argnums=(0, 2))
                self._upd_jit[s] = make(st)
            if is_wrapped:
                fopt._constrain_mesh = st.mesh  # trace-time ZeRO target
            try:
                new_p, new_state = self._upd_jit[s](
                    [p._data for p in st.p_objs], list(gsum[s]),
                    states[s], lr, t,
                )
            finally:
                if is_wrapped:
                    fopt._constrain_mesh = None
            results.append((new_p, new_state))
        for st, (new_p, new_state) in zip(self.stages, results):
            fopt._load_functional_state(st.p_objs, new_state)
            for p, raw in zip(st.p_objs, new_p):
                p._data = raw
                p._node = None
                p.grad = None
        if lr_scheduler is not None:
            lr_scheduler.step()
        loss = sum(losses[1:], losses[0]) * inv_m
        return Tensor._wrap(loss, stop_gradient=True)
