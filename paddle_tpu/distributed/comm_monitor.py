"""Communication monitor: flight recorder, collective timeouts, desync
detection, and the monitored barrier.

The dominant multi-host failure mode on ICI pods is not a crashed process
but a *hung or mismatched collective*: one rank enters ``all_reduce`` while
a peer sits in ``barrier``, and every rank blocks forever with zero
diagnostics. The elastic watchdog (elastic.py) can only report "hung rank";
this module says *which collective, on which rank, with what shape*.

Reference analogs: the NCCL comm registry + TCP bootstrap layer
(platform/collective_helper.h:52, gen_comm_id_helper.cc) keyed every launch
by ring_id — here every eager collective (collective.py) records a per-rank,
per-group **sequence number + op fingerprint** into a bounded ring buffer
(the flight recorder), and the recorder is dumped to workerlog-adjacent
debug files on timeout, desync, or SIGTERM.

Pieces (all knobs documented in the README fault-tolerance table):

- **flight recorder** — ``PADDLE_COLL_RECORDER_SIZE`` (default 256) most
  recent collective records; ``dump_flight_recorder(reason)`` writes
  ``comm_dump.rank{N}.json`` into ``PADDLE_COLL_DEBUG_DIR`` (the elastic
  launcher points it at the workerlog dir).
- **timeout watchdog** — ``PADDLE_COLL_TIMEOUT`` seconds per eager
  collective (0 = off). A thread-based deadline fires while the main
  thread is stuck in the collective: it dumps the recorder, appends a
  machine-readable event line to ``PADDLE_COLL_EVENT_FILE`` (where the
  ElasticManager's reader picks it up for kill attribution), and then
  applies ``PADDLE_COLL_TIMEOUT_ACTION``: ``abort`` (default — exit with
  ``COLL_TIMEOUT_RC`` so the launcher recycles the rank) or ``dump``
  (diagnose only; for in-process tests and best-effort production runs).
  The deadline covers the whole eager call INCLUDING a first-use XLA
  compile, so set it well above worst-case compile time (minutes, like
  NCCL's default 10min timeout — it is a deadlock detector, not a
  latency SLO).
- **desync detection** — ranks exchange ``(seq, op-fingerprint)`` through
  ``PADDLE_COLL_SYNC_DIR`` (a launcher-shared directory) at every
  ``monitored_barrier`` and, when ``PADDLE_COLL_DESYNC_INTERVAL`` = K > 0,
  every K-th collective. A mismatch raises :class:`CollectiveDesyncError`
  naming BOTH call sites instead of deadlocking. The interval form
  assumes the SPMD contract the detector exists to police — every rank
  issues the same collective stream — so rank-divergent EXTRA traffic
  (subgroup collectives on some processes only) misaligns check rounds
  and reads as a desync/timeout; keep it off (the default) for such
  programs and rely on ``monitored_barrier`` at aligned points instead.
- **monitored barrier** — ``monitored_barrier(timeout)`` names the ranks
  that never arrived instead of blocking forever.

Pure stdlib on purpose: no jax import, so the monitor is usable from the
launcher side and from no-jax test children.
"""
from __future__ import annotations

import contextlib
import json
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque
from typing import Dict, List, Optional

__all__ = [
    "CommMonitor", "CollectiveTimeoutError", "CollectiveDesyncError",
    "monitor", "reset", "dump_flight_recorder", "read_events",
    "COLL_TIMEOUT_RC",
]

_TIMEOUT_ENV = "PADDLE_COLL_TIMEOUT"
_ACTION_ENV = "PADDLE_COLL_TIMEOUT_ACTION"
_RECORDER_ENV = "PADDLE_COLL_RECORDER_SIZE"
_DEBUG_DIR_ENV = "PADDLE_COLL_DEBUG_DIR"
_EVENT_ENV = "PADDLE_COLL_EVENT_FILE"
_SYNC_DIR_ENV = "PADDLE_COLL_SYNC_DIR"
_DESYNC_ENV = "PADDLE_COLL_DESYNC_INTERVAL"

#: exit code a rank reports when its own collective watchdog put it down
#: (distinct from elastic.HUNG_RC=98, which is the launcher-side verdict)
COLL_TIMEOUT_RC = 97


class CollectiveTimeoutError(RuntimeError):
    """A collective (or barrier arrival) exceeded its deadline."""


class CollectiveDesyncError(RuntimeError):
    """Two ranks issued different collectives at the same sequence point."""


class _Record:
    __slots__ = ("seq", "op", "gid", "axis", "nranks", "shape", "dtype",
                 "rank", "site", "t_start", "t_done", "status")

    def __init__(self, seq, op, gid, axis, nranks, shape, dtype, rank, site):
        self.seq = seq
        self.op = op
        self.gid = gid
        self.axis = axis
        self.nranks = nranks
        self.shape = shape
        self.dtype = dtype
        self.rank = rank
        self.site = site
        self.t_start = time.time()
        self.t_done = None
        self.status = "started"

    def fingerprint(self) -> str:
        return (f"{self.op}|g{self.gid}|n{self.nranks}|"
                f"{self.dtype}|{self.shape}")

    def describe(self) -> str:
        return (f"{self.op}(seq {self.seq}, group {self.gid}, "
                f"{self.dtype}{list(self.shape)}, {self.nranks} ranks, "
                f"site {self.site})")

    def to_json(self) -> dict:
        return {
            "seq": self.seq, "op": self.op, "group": self.gid,
            "axis": self.axis, "nranks": self.nranks,
            "shape": list(self.shape), "dtype": self.dtype,
            "rank": self.rank, "site": self.site, "status": self.status,
            "t_start": self.t_start, "t_done": self.t_done,
        }


def _caller_site() -> str:
    """First stack frame outside this package's distributed/ internals
    (and the contextmanager plumbing) — the user call site a desync
    diagnostic should name."""
    here = os.path.dirname(os.path.abspath(__file__))
    for frame in reversed(traceback.extract_stack(limit=24)[:-2]):
        fname = os.path.abspath(frame.filename)
        if os.path.dirname(fname) == here:
            continue
        if os.path.basename(fname) == "contextlib.py":
            continue
        return f"{frame.filename}:{frame.lineno}"
    return "<unknown>"


def _fault_point(site: str) -> None:
    """Route through utils.fault_injection when importable; the monitor
    itself stays stdlib-pure so no-jax children can load it standalone."""
    fi = sys.modules.get("paddle_tpu.utils.fault_injection") \
        or sys.modules.get("fault_injection")
    if fi is None:
        try:
            from ..utils import fault_injection as fi
        except ImportError:
            return
    fi.fault_point(site)


def _consume_desync_flag() -> bool:
    fi = sys.modules.get("paddle_tpu.utils.fault_injection") \
        or sys.modules.get("fault_injection")
    if fi is None or not hasattr(fi, "consume_flag"):
        return False
    return fi.consume_flag("desync")


def _bus():
    """The telemetry bus (observability/bus.py) when importable; None
    when this module was loaded standalone outside the package (no-jax
    launcher children) — events then fall back to the legacy-only
    inline write, preserving the stdlib-pure contract."""
    mod = sys.modules.get("paddle_tpu.observability.bus")
    if mod is not None:
        return mod
    try:
        from ..observability import bus as mod  # type: ignore

        return mod
    except ImportError:
        return None


class CommMonitor:
    """Per-process collective monitor (one per rank process).

    Constructor args exist for tests; production reads everything from the
    environment the elastic launcher populated.
    """

    def __init__(self, rank: Optional[int] = None,
                 world: Optional[int] = None,
                 sync_dir: Optional[str] = None,
                 timeout: Optional[float] = None,
                 recorder_size: Optional[int] = None,
                 action: Optional[str] = None):
        def _envf(name, default):
            raw = os.environ.get(name, "")
            return float(raw) if raw.strip() else default

        self.rank = rank if rank is not None else int(
            os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.world = world if world is not None else int(
            os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self.sync_dir = (sync_dir if sync_dir is not None
                         else os.environ.get(_SYNC_DIR_ENV))
        self.timeout = (timeout if timeout is not None
                        else _envf(_TIMEOUT_ENV, 0.0))
        self.action = action or os.environ.get(_ACTION_ENV, "abort")
        self.desync_interval = int(_envf(_DESYNC_ENV, 0.0))
        size = recorder_size if recorder_size is not None else int(
            _envf(_RECORDER_ENV, 256.0))
        self._ring: deque = deque(maxlen=max(size, 8))
        self._seq: Dict[int, int] = {}       # per-group sequence numbers
        self._n_records = 0
        self._barrier_round = 0
        self._desync_round = 0
        self._lock = threading.Lock()
        self._sigterm_installed = False

    # -- recording --------------------------------------------------------
    def record(self, op: str, gid: int, axis: str, nranks: int,
               shape=(), dtype: str = "", status: str = "started",
               ) -> _Record:
        with self._lock:
            seq = self._seq[gid] = self._seq.get(gid, 0) + 1
            rec = _Record(seq, op, gid, axis, nranks, tuple(shape),
                          str(dtype), self.rank, _caller_site())
            rec.status = status
            self._ring.append(rec)
            self._n_records += 1
            n = self._n_records
        if (self.desync_interval > 0 and n % self.desync_interval == 0
                and status == "started"):
            self.check_desync()
        return rec

    @contextlib.contextmanager
    def watch(self, op: str, gid: int, axis: str, nranks: int,
              shape=(), dtype: str = "", timeout: Optional[float] = None):
        """Record one eager collective and arm its timeout deadline.

        The timer thread fires while the caller is stuck inside the
        collective — the only vantage point that can still produce a
        diagnostic when the main thread is wedged in the runtime."""
        self._maybe_install_sigterm_dump()
        rec = self.record(op, gid, axis, nranks, shape, dtype)
        deadline = self.timeout if timeout is None else timeout
        timer = None
        if deadline and deadline > 0:
            timer = threading.Timer(deadline, self._on_timeout,
                                    (rec, deadline))
            timer.daemon = True
            timer.start()
        try:
            _fault_point("coll")      # coll:hang / coll:fail / coll:kill
            if _consume_desync_flag():
                # injected desync: this rank's fingerprint mutates as if
                # it had issued a different op — peers see the mismatch
                rec.op = f"{op}[desync-injected]"
            yield rec
        except BaseException:
            rec.status = "failed"
            rec.t_done = time.time()
            raise
        finally:
            if timer is not None:
                timer.cancel()
            if rec.status == "started":
                rec.status = "done"
                rec.t_done = time.time()

    # -- timeout path -----------------------------------------------------
    def _on_timeout(self, rec: _Record, deadline: float) -> None:
        if rec.status != "started":
            return  # raced with completion
        rec.status = "timeout"
        msg = (f"collective timeout: rank {self.rank} stalled "
               f">{deadline:g}s in {rec.describe()}")
        path = self.dump_flight_recorder("timeout")
        self._write_event("coll_timeout", rec, extra={
            "timeout_s": deadline, "dump": path})
        print(f"paddle_tpu.comm_monitor: {msg}"
              + (f"; flight recorder dumped to {path}" if path else ""),
              file=sys.stderr, flush=True)
        if self.action == "abort":
            # the rank is wedged in the runtime; exiting is the only way
            # to hand control back to the launcher, which attributes the
            # kill from the event line written above
            os._exit(COLL_TIMEOUT_RC)

    # -- flight recorder dump ---------------------------------------------
    def snapshot(self) -> List[dict]:
        with self._lock:
            return [r.to_json() for r in self._ring]

    def dump_flight_recorder(self, reason: str) -> Optional[str]:
        """Write the ring buffer to PADDLE_COLL_DEBUG_DIR (the launcher
        points it at the workerlog dir). Returns the path, or None when
        no destination is configured or nothing was recorded."""
        records = self.snapshot()
        if not records:
            return None
        dump_dir = os.environ.get(_DEBUG_DIR_ENV)
        if not dump_dir:
            return None
        try:
            os.makedirs(dump_dir, exist_ok=True)
            path = os.path.join(dump_dir, f"comm_dump.rank{self.rank}.json")
            tmp = path + f".tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({
                    "rank": self.rank, "world": self.world,
                    "reason": reason, "time": time.time(),
                    "pid": os.getpid(), "records": records,
                }, f, indent=1)
            os.replace(tmp, path)
            return path
        except OSError:
            return None  # diagnostics must never take the trainer down

    def _write_event(self, kind: str, rec: Optional[_Record],
                     extra: Optional[dict] = None) -> None:
        payload: dict = {}
        if rec is not None:
            payload.update(rec.to_json())
            payload["describe"] = rec.describe()
        if extra:
            payload.update(extra)
        bus = _bus()
        if bus is not None:
            # unified-schema row on the per-rank bus stream + the legacy
            # flat row on PADDLE_COLL_EVENT_FILE (kill-attribution reader)
            bus.emit(kind, payload, rank=self.rank, legacy_env=_EVENT_ENV)
            return
        path = os.environ.get(_EVENT_ENV)
        if not path:
            return
        row = {"event": kind, "rank": self.rank, "time": time.time()}
        row.update(payload)
        try:
            with open(path, "a") as f:
                f.write(json.dumps(row) + "\n")
        except OSError:
            pass

    # -- SIGTERM dump -----------------------------------------------------
    def _maybe_install_sigterm_dump(self) -> None:
        """Dump on preemption notice when nothing else owns SIGTERM.
        Trainers using install_preempt_notice get the dump through that
        hook instead (elastic.py chains it); this covers bare scripts."""
        if self._sigterm_installed:
            return
        if threading.current_thread() is not threading.main_thread():
            return  # keep trying: a later main-thread collective installs
        self._sigterm_installed = True
        try:
            current = signal.getsignal(signal.SIGTERM)
        except (ValueError, OSError):
            return
        if current not in (signal.SIG_DFL, None):
            return  # somebody owns SIGTERM; they chain the dump themselves

        def _handler(signum, frame):
            self.dump_flight_recorder("sigterm")
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

        try:
            signal.signal(signal.SIGTERM, _handler)
        except (ValueError, OSError):
            pass

    # -- desync detection -------------------------------------------------
    def _exchange(self, subdir: str, rnd: int, payload: dict,
                  timeout: float) -> Dict[int, dict]:
        """Publish this rank's payload for round `rnd` and collect every
        peer's. Raises CollectiveTimeoutError naming the missing ranks."""
        assert self.sync_dir
        d = os.path.join(self.sync_dir, subdir)
        os.makedirs(d, exist_ok=True)
        mine = os.path.join(d, f"r{rnd}.rank{self.rank}")
        tmp = mine + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, mine)
        deadline = time.monotonic() + timeout
        out: Dict[int, dict] = {}
        while True:
            missing = []
            for peer in range(self.world):
                if peer in out:
                    continue
                p = os.path.join(d, f"r{rnd}.rank{peer}")
                try:
                    with open(p) as f:
                        out[peer] = json.load(f)
                except (OSError, ValueError):
                    missing.append(peer)
            if not missing:
                # a rank only publishes round K after completing K-1, so
                # everyone seeing round `rnd` implies round rnd-2 readers
                # are done — prune it to bound the dir (long jobs would
                # otherwise accumulate world files per round forever)
                if rnd >= 2:
                    for peer in range(self.world):
                        try:
                            os.unlink(
                                os.path.join(d, f"r{rnd - 2}.rank{peer}"))
                        except OSError:
                            pass
                return out
            if time.monotonic() > deadline:
                raise CollectiveTimeoutError(
                    f"{subdir} round {rnd}: rank {self.rank} waited "
                    f"{timeout:g}s; missing ranks {missing} "
                    f"(arrived: {sorted(out)})")
            time.sleep(0.02)

    #: how many trailing flight-recorder entries each rank publishes for
    #: the desync diff — enough to localize the first divergent call
    DESYNC_TAIL = 32

    def check_desync(self, timeout: Optional[float] = None) -> None:
        """Exchange the (seq, op-fingerprint) tail of the flight recorder
        with every peer and raise a diagnostic naming the two mismatched
        call sites on divergence. Entries are matched per (group, seq):
        the same sequence slot filled by DIFFERENT collectives on two
        ranks is exactly the mismatched-collective deadlock this detector
        exists for. No-op when there is nothing to exchange through
        (single rank or no launcher-shared sync dir)."""
        if self.world <= 1 or not self.sync_dir:
            return
        with self._lock:
            rnd = self._desync_round
            self._desync_round += 1
            tail = [
                {"gid": r.gid, "seq": r.seq, "op": r.op,
                 "fingerprint": r.fingerprint(), "site": r.site}
                for r in list(self._ring)[-self.DESYNC_TAIL:]
            ]
        payload = {"rank": self.rank, "tail": tail}
        t = timeout if timeout is not None else max(self.timeout, 30.0)
        try:
            peers = self._exchange("desync", rnd, payload, t)
        except CollectiveTimeoutError:
            self.dump_flight_recorder("desync-timeout")
            raise
        base_rank = min(peers)
        base = {(e["gid"], e["seq"]): e for e in peers[base_rank]["tail"]}
        for r in sorted(peers):
            if r == base_rank:
                continue
            for e in peers[r]["tail"]:
                b = base.get((e["gid"], e["seq"]))
                if b is None or b["fingerprint"] == e["fingerprint"]:
                    continue
                err = CollectiveDesyncError(
                    "collective desync detected at group "
                    f"{e['gid']} seq {e['seq']}: rank {base_rank} issued "
                    f"{b['op']} ({b['fingerprint']}) from {b['site']}, "
                    f"but rank {r} issued {e['op']} "
                    f"({e['fingerprint']}) from {e['site']}")
                rec = _Record(e["seq"], "desync_check", e["gid"], "",
                              self.world, (), "", self.rank,
                              _caller_site())
                rec.status = "desync"
                self._write_event("coll_desync", rec, extra={
                    "detail": str(err),
                    "site_a": b["site"], "site_b": e["site"],
                    "op_a": b["op"], "op_b": e["op"],
                    "rank_a": base_rank, "rank_b": r,
                })
                self.dump_flight_recorder("desync")
                raise err

    # -- monitored barrier ------------------------------------------------
    def barrier_rendezvous(self, timeout: float) -> None:
        """Cross-process half of monitored_barrier: every rank checks in
        through the sync dir; a deadline names the ranks that never
        arrived (instead of blocking forever), then fingerprints are
        cross-checked for desync."""
        if self.world <= 1 or not self.sync_dir:
            return
        with self._lock:
            rnd = self._barrier_round
            self._barrier_round += 1
        try:
            self._exchange("barrier", rnd, {"rank": self.rank}, timeout)
        except CollectiveTimeoutError as e:
            rec = _Record(rnd, "monitored_barrier", -1, "", self.world,
                          (), "", self.rank, _caller_site())
            rec.status = "timeout"
            self._write_event("barrier_timeout", rec,
                              extra={"detail": str(e)})
            self.dump_flight_recorder("barrier-timeout")
            raise
        self.check_desync(timeout=timeout)


# ---------------------------------------------------------------------------
# process-global instance
# ---------------------------------------------------------------------------

_active: Optional[CommMonitor] = None
_lock = threading.Lock()


def monitor() -> CommMonitor:
    global _active
    if _active is None:
        with _lock:
            if _active is None:
                _active = CommMonitor()
    return _active


def reset() -> None:
    """Drop the process-global monitor (tests re-arm between cases)."""
    global _active
    _active = None


def dump_flight_recorder(reason: str = "manual") -> Optional[str]:
    """Module-level convenience for signal/teardown hooks: dump the
    active monitor's ring buffer (no-op when nothing was recorded)."""
    if _active is None:
        return None
    return _active.dump_flight_recorder(reason)


def read_events(path: str) -> List[dict]:
    """Parse a PADDLE_COLL_EVENT_FILE (one JSON object per line). The
    launcher-side reader — tolerant of torn last lines."""
    out: List[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return out
