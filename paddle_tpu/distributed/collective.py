"""Collective communication API.

Reference: python/paddle/distributed/collective.py:101–457 (all_reduce /
all_gather / reduce / broadcast / scatter / barrier over ring_id'd NCCL
comms; kernels in paddle/fluid/operators/collective/, e.g.
c_allreduce_op.h:123–158 → ncclAllReduce).

TPU-native: each collective is an XLA op over a named mesh axis. Two modes,
one API:
  * eager — operands follow the per-rank convention (leading axis = rank,
    sharded over the group axis; comm.shard_rank_axis). The call jits a
    shard_map once per (shape, dtype, op, group) — the analog of cached
    per-comm NCCL launches — and swaps the tensor's storage in place.
  * spmd  — inside a shard_map region (comm.spmd_region), operands are the
    per-rank values themselves and the call lowers directly to
    lax.psum/all_gather/ppermute; XLA fuses and schedules the collective
    with the surrounding computation (the `use_calc_stream` semantics are
    the default — there are no separate comm streams to sync).
"""
from __future__ import annotations

import functools
from typing import List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from . import comm
from . import comm_monitor as _cm
from .comm import Group


class ReduceOp:
    """reference: collective.py ReduceOp (SUM/MAX/MIN/PROD + AVG)."""

    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


def _group(group) -> Group:
    if group is None:
        return comm._default_group()
    if isinstance(group, int):
        g = comm.get_group(group)
        if g is None:
            raise ValueError(f"no group with id {group}")
        return g
    return group


def _raw(tensor):
    return tensor._data if isinstance(tensor, Tensor) else jnp.asarray(tensor)


def _psum_like(x, axis: str, op: int):
    if op == ReduceOp.SUM:
        return jax.lax.psum(x, axis)
    if op == ReduceOp.MAX:
        return jax.lax.pmax(x, axis)
    if op == ReduceOp.MIN:
        return jax.lax.pmin(x, axis)
    if op == ReduceOp.AVG:
        return jax.lax.pmean(x, axis)
    if op == ReduceOp.PROD:
        g = jax.lax.all_gather(x, axis)
        return jnp.prod(g, axis=0)
    raise ValueError(f"unknown ReduceOp {op}")


@functools.lru_cache(maxsize=None)
def _allreduce_prog(gid: int, op: int):
    g = comm.get_group(gid)
    ax = g.axis_name
    return jax.jit(comm.shard_map(
        lambda x: _psum_like(x, ax, op),
        g.mesh, in_specs=P(ax), out_specs=P(ax),
    ))


@functools.lru_cache(maxsize=None)
def _reduce_prog(gid: int, op: int, dst: int):
    g = comm.get_group(gid)
    ax = g.axis_name

    def f(x):
        r = _psum_like(x, ax, op)
        i = jax.lax.axis_index(ax)
        return jnp.where(i == dst, r, x)

    return jax.jit(comm.shard_map(f, g.mesh, in_specs=P(ax),
                                  out_specs=P(ax)))


@functools.lru_cache(maxsize=None)
def _allgather_prog(gid: int):
    g = comm.get_group(gid)
    ax = g.axis_name
    # per-rank slice [1, ...] -> every rank holds the full stack
    return jax.jit(comm.shard_map(
        lambda x: jax.lax.all_gather(x, ax, axis=0, tiled=True),
        g.mesh, in_specs=P(ax), out_specs=P(),
    ))


@functools.lru_cache(maxsize=None)
def _broadcast_prog(gid: int, src: int):
    g = comm.get_group(gid)
    ax = g.axis_name

    def f(x):
        full = jax.lax.all_gather(x, ax, axis=0, tiled=True)
        return jax.lax.dynamic_slice_in_dim(full, src, 1, 0)

    return jax.jit(comm.shard_map(f, g.mesh, in_specs=P(ax),
                                  out_specs=P(ax)))


@functools.lru_cache(maxsize=None)
def _alltoall_prog(gid: int):
    g = comm.get_group(gid)
    ax = g.axis_name
    from jax.sharding import NamedSharding

    # out[s] stays rank-stacked along its (new) leading axis; sharding the
    # transposed stack's second axis keeps every parts[s] slice laid out
    # over the group, so the exchange compiles to one all-to-all.
    return jax.jit(
        lambda A: jnp.swapaxes(A, 0, 1),
        out_shardings=NamedSharding(g.mesh, P(None, ax)),
    )


@functools.lru_cache(maxsize=None)
def _reduce_scatter_prog(gid: int, op: int):
    g = comm.get_group(gid)
    ax = g.axis_name
    if op == ReduceOp.SUM:
        fn = lambda x: jax.lax.psum_scatter(  # noqa: E731
            x, ax, scatter_dimension=1, tiled=True
        )
    else:
        def fn(x):
            r = _psum_like(x, ax, op)  # [1, nranks*chunk]
            i = jax.lax.axis_index(ax)
            chunk = r.shape[1] // g.nranks
            return jax.lax.dynamic_slice_in_dim(r, i * chunk, chunk, 1)
    return jax.jit(comm.shard_map(fn, g.mesh, in_specs=P(ax),
                                  out_specs=P(ax)))


# ---------------------------------------------------------------------------
# Monitoring seam: every collective call reports (op, group, shape, dtype)
# to the flight recorder; eager calls additionally run under the
# PADDLE_COLL_TIMEOUT watchdog (comm_monitor.py).
# ---------------------------------------------------------------------------


def _meta(x):
    raw = getattr(x, "_data", x)  # Tensor, jax/numpy array, or None
    if raw is None or isinstance(raw, (list, tuple)):
        return (), ""
    return tuple(getattr(raw, "shape", ())), str(getattr(raw, "dtype", ""))


def _watched(op_name: str, g: Group, x):
    shape, dtype = _meta(x)
    return _cm.monitor().watch(op_name, g.id, g.axis_name, g.nranks,
                               shape=shape, dtype=dtype)


def _record_spmd(op_name: str, g: Group, x):
    # inside a shard_map trace there is no execution to deadline — the
    # collective runs when XLA schedules it — but the call still takes a
    # sequence number so desync checks see the full op stream
    shape, dtype = _meta(x)
    _cm.monitor().record(op_name, g.id, g.axis_name, g.nranks,
                         shape=shape, dtype=dtype, status="spmd")


# ---------------------------------------------------------------------------
# Public API (paddle.distributed.*)
# ---------------------------------------------------------------------------


def all_reduce(tensor, op: int = ReduceOp.SUM, group=None,
               sync_op: bool = True, use_calc_stream: bool = True):
    """collective.py:101 all_reduce. In-place; every rank sees the result."""
    g = _group(group)
    if comm.in_spmd_region(g.axis_name):
        from ..core import autograd as AG

        _record_spmd("all_reduce", g, tensor)
        out = AG.apply(
            lambda x: _psum_like(x, g.axis_name, op), (_as_t(tensor),),
            name="c_allreduce",
        )
        return _write_back(tensor, out)
    t = _as_t(tensor)
    with _watched("all_reduce", g, t):
        t._data = _allreduce_prog(g.id, op)(_ranked(t, g))
    t._node = None
    return t


def reduce(tensor, dst: int = 0, op: int = ReduceOp.SUM, group=None,
           sync_op: bool = True, use_calc_stream: bool = True):
    """collective.py reduce: only dst's slice carries the result."""
    g = _group(group)
    if comm.in_spmd_region(g.axis_name):
        from ..core import autograd as AG

        _record_spmd("reduce", g, tensor)

        def f(x):
            r = _psum_like(x, g.axis_name, op)
            i = jax.lax.axis_index(g.axis_name)
            return jnp.where(i == dst, r, x)

        return _write_back(tensor, AG.apply(f, (_as_t(tensor),),
                                            name="c_reduce"))
    t = _as_t(tensor)
    with _watched("reduce", g, t):
        t._data = _reduce_prog(g.id, op, dst)(_ranked(t, g))
    t._node = None
    return t


def all_gather(tensor_list: Optional[List], tensor=None, group=None,
               sync_op: bool = True, use_calc_stream: bool = True):
    """collective.py all_gather. Eager: per-rank stack in, list of nranks
    tensors out (appended to tensor_list). spmd: returns gathered array."""
    g = _group(group)
    if tensor is None:  # all_gather(x) shorthand
        tensor, tensor_list = tensor_list, None
    if comm.in_spmd_region(g.axis_name):
        from ..core import autograd as AG

        _record_spmd("all_gather", g, tensor)
        out = AG.apply(
            lambda x: jax.lax.all_gather(x, g.axis_name, axis=0, tiled=False),
            (_as_t(tensor),), name="c_allgather",
        )
        if tensor_list is not None:
            tensor_list.extend(out[i] for i in range(g.nranks))
        return out
    t = _as_t(tensor)
    with _watched("all_gather", g, t):
        full = _allgather_prog(g.id)(_ranked(t, g))
    parts = [
        Tensor._wrap(jax.lax.index_in_dim(full, i, 0, keepdims=False))
        for i in range(g.nranks)
    ]
    if tensor_list is not None:
        tensor_list.extend(parts)
    return parts


def broadcast(tensor, src: int = 0, group=None, sync_op: bool = True,
              use_calc_stream: bool = True):
    """collective.py broadcast: every rank gets src's value."""
    g = _group(group)
    if comm.in_spmd_region(g.axis_name):
        from ..core import autograd as AG

        _record_spmd("broadcast", g, tensor)

        def f(x):
            # O(size) select+psum, not an O(nranks*size) all_gather;
            # psum promotes bool, so restore the caller's dtype
            i = jax.lax.axis_index(g.axis_name)
            contrib = jnp.where(i == src, x, jnp.zeros_like(x))
            return jax.lax.psum(contrib, g.axis_name).astype(x.dtype)

        return _write_back(tensor, AG.apply(f, (_as_t(tensor),),
                                            name="c_broadcast"))
    t = _as_t(tensor)
    with _watched("broadcast", g, t):
        t._data = _broadcast_prog(g.id, src)(_ranked(t, g))
    t._node = None
    return t


def reduce_scatter(tensor, tensor_or_tensor_list=None, op: int = ReduceOp.SUM,
                   group=None, sync_op: bool = True):
    """Each rank receives its chunk of the reduction. Eager convention:
    input [nranks, nranks*chunk] per-rank-stacked; output [nranks, chunk]."""
    g = _group(group)
    src = tensor_or_tensor_list if tensor_or_tensor_list is not None else tensor
    if comm.in_spmd_region(g.axis_name):
        from ..core import autograd as AG

        _record_spmd("reduce_scatter", g, src)

        def f(x):
            if op == ReduceOp.SUM:
                return jax.lax.psum_scatter(
                    x, g.axis_name, scatter_dimension=0, tiled=True
                )
            r = _psum_like(x, g.axis_name, op)
            i = jax.lax.axis_index(g.axis_name)
            chunk = r.shape[0] // g.nranks
            return jax.lax.dynamic_slice_in_dim(r, i * chunk, chunk, 0)

        return _write_back(src, AG.apply(f, (_as_t(src),),
                                         name="c_reducescatter"))
    t = _as_t(src)
    with _watched("reduce_scatter", g, t):
        out_raw = _reduce_scatter_prog(g.id, op)(_ranked(t, g))
    out = Tensor._wrap(out_raw)
    if isinstance(tensor, Tensor) and tensor is not src:
        tensor._data = out_raw
        tensor._node = None
        return tensor
    t._data = out_raw
    t._node = None
    return t


def scatter(tensor, tensor_list=None, src: int = 0, group=None,
            sync_op: bool = True, use_calc_stream: bool = True):
    """collective.py scatter: rank r receives the r-th chunk held at src.

    spmd region: only src's stacked value is read (broadcast-select +
    per-rank chunk pick), so `src` carries its full meaning. Eager
    single-controller: one process owns the single copy of tensor_list,
    so every logical src holds identical data and the stacked layout
    already places chunk r on device r — `src` is semantically inert
    THERE (not dropped: there is nothing rank-distinct to choose)."""
    g = _group(group)
    if comm.in_spmd_region(g.axis_name):
        from ..core import autograd as AG

        _record_spmd("scatter", g, tensor)
        stacked_in = tensor_list if tensor_list is not None else tensor
        if isinstance(stacked_in, (list, tuple)):
            raws = tuple(_as_t(t) for t in stacked_in)

            def f(*xs):
                x = jnp.stack(xs, axis=0)
                i = jax.lax.axis_index(g.axis_name)
                xb = jax.lax.psum(
                    jnp.where(i == src, x, jnp.zeros_like(x)), g.axis_name
                ).astype(x.dtype)
                return xb[i]

            return _write_back(tensor, AG.apply(f, raws, name="c_scatter"))

        def f(x):
            i = jax.lax.axis_index(g.axis_name)
            xb = jax.lax.psum(
                jnp.where(i == src, x, jnp.zeros_like(x)), g.axis_name
            ).astype(x.dtype)
            return xb[i]

        return _write_back(tensor, AG.apply(f, (_as_t(stacked_in),),
                                            name="c_scatter"))
    if tensor_list is not None:
        stacked = jnp.stack([_raw(t) for t in tensor_list], axis=0)
    else:
        stacked = _raw(tensor)
    t = _as_t(tensor)
    with _watched("scatter", g, t):
        t._data = comm.shard_rank_axis(stacked, g)
    t._node = None
    return t


def alltoall(in_tensor_list, out_tensor_list=None, group=None,
             sync_op: bool = True):
    """Each rank scatters its list and gathers one item from every rank."""
    g = _group(group)
    if comm.in_spmd_region(g.axis_name):
        from ..core import autograd as AG

        _record_spmd("alltoall", g, in_tensor_list)
        return AG.apply(
            lambda x: jax.lax.all_to_all(x, g.axis_name, split_axis=0,
                                         concat_axis=0, tiled=True),
            (_as_t(in_tensor_list),), name="c_alltoall",
        )
    # Eager single-controller: with A = stack(in_list) (A[s, r] = rank r's
    # item destined to rank s), rank r's received list is out_r[s] =
    # A[r, s], i.e. the stacked output is swapaxes(A, 0, 1). ONE jitted
    # transpose+reshard program — XLA emits the actual all-to-all when the
    # swapped layout lands back on the rank axis.
    if isinstance(in_tensor_list, (list, tuple)):
        A = jnp.stack([_raw(t) for t in in_tensor_list], axis=0)
    else:
        A = _raw(in_tensor_list)
    with _cm.monitor().watch("alltoall", g.id, g.axis_name, g.nranks,
                             shape=tuple(A.shape), dtype=str(A.dtype)):
        B = _alltoall_prog(g.id)(comm.shard_rank_axis(A, g))
    parts = [Tensor._wrap(B[s]) for s in range(g.nranks)]
    if out_tensor_list is not None:
        out_tensor_list.extend(parts)
    return parts


def barrier(group=None):
    """collective ops barrier (operators/collective/barrier_op)."""
    g = _group(group)
    if comm.in_spmd_region(g.axis_name):
        _record_spmd("barrier", g, None)
        return
    x = comm.shard_rank_axis(jnp.zeros((g.nranks, 1), jnp.int32), g)
    with _cm.monitor().watch("barrier", g.id, g.axis_name, g.nranks,
                             shape=(g.nranks, 1), dtype="int32"):
        jax.block_until_ready(_allreduce_prog(g.id, ReduceOp.SUM)(x))


def wait(tensor, group=None, use_calc_stream=True):
    """collective.py wait: block until the tensor's pending work is done.
    XLA has no separate comm stream to synchronize against — dispatch is
    async-by-value — so this is block_until_ready on the backing array
    (the calc/comm stream distinction collapses under PJRT)."""
    raw = getattr(tensor, "_data", tensor)
    jax.block_until_ready(raw)
    return tensor


def monitored_barrier(group=None, timeout: Optional[float] = None):
    """Barrier that NAMES the missing ranks instead of deadlocking
    (torch.distributed.monitored_barrier analog, built on the file-based
    rendezvous the elastic launcher shares between its local ranks).

    Phase 1 — cross-process: every trainer process checks in through
    PADDLE_COLL_SYNC_DIR; ranks absent at the deadline are named in the
    raised :class:`~.comm_monitor.CollectiveTimeoutError`, and the
    (seq, op-fingerprint) exchange raises
    :class:`~.comm_monitor.CollectiveDesyncError` naming both mismatched
    call sites when the op streams diverged. Trainer-process ranks are
    orthogonal to device subgroups in the single-controller model, so
    phase 1 runs only for the job-wide default group — a subgroup
    barrier must not wait for processes that never joined it. Phase 2 —
    on-device barrier over the group's mesh axis, under the
    PADDLE_COLL_TIMEOUT watchdog.

    `timeout` defaults to PADDLE_COLL_TIMEOUT, else 300s for the
    cross-process wait."""
    mon = _cm.monitor()
    t = timeout
    if t is None:
        t = mon.timeout if mon.timeout > 0 else 300.0
    g = _group(group)
    if comm.in_spmd_region(g.axis_name):
        # inside a shard_map trace: no execution to monitor (and blocking
        # file I/O at trace time would be wrong) — record like barrier()
        _record_spmd("monitored_barrier", g, None)
        return
    if g.id == 0:
        mon.barrier_rendezvous(t)
    x = comm.shard_rank_axis(jnp.zeros((g.nranks, 1), jnp.int32), g)
    with mon.watch("monitored_barrier", g.id, g.axis_name, g.nranks,
                   shape=(g.nranks, 1), dtype="int32", timeout=t):
        jax.block_until_ready(_allreduce_prog(g.id, ReduceOp.SUM)(x))


def _as_t(x) -> Tensor:
    return x if isinstance(x, Tensor) else Tensor(x)


def _write_back(orig, out: Tensor) -> Tensor:
    """Honor the paddle in-place collective contract in spmd regions: the
    caller's tensor must carry the result (they may keep using `orig`)."""
    if isinstance(orig, Tensor) and orig is not out:
        orig._data = out._data
        orig._node = out._node
        orig._out_idx = out._out_idx
    return orig if isinstance(orig, Tensor) else out


def _ranked(t: Tensor, g: Group):
    raw = t._data
    if raw.ndim == 0 or raw.shape[0] != g.nranks:
        raise ValueError(
            f"eager collective over group of {g.nranks} ranks expects the "
            f"per-rank convention: leading axis of length {g.nranks} "
            f"(got shape {tuple(raw.shape)}). Stack per-rank values with "
            "paddle_tpu.distributed.shard_rank_axis, or call inside an "
            "spmd region."
        )
    return comm.shard_rank_axis(raw, g)
