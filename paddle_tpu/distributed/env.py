"""Distributed environment discovery.

reference: python/paddle/distributed/parallel.py:143-147 — env-var cluster
discovery (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS).
On TPU the real topology comes from the runtime (jax.process_index/count for
multi-host; device mesh axes for in-host parallelism); the PADDLE_* env vars
are honored as overrides so reference launch scripts keep working.
"""
from __future__ import annotations

import os


def rank() -> int:
    if "PADDLE_TRAINER_ID" in os.environ:
        return int(os.environ["PADDLE_TRAINER_ID"])
    import jax

    return jax.process_index()


def world_size() -> int:
    if "PADDLE_TRAINERS_NUM" in os.environ:
        return int(os.environ["PADDLE_TRAINERS_NUM"])
    import jax

    return jax.process_count()


def get_rank() -> int:
    return rank()


def get_world_size() -> int:
    return world_size()
