"""Block-scaled quantization for the comm plane (ISSUE 10):
symmetric per-block int8 / fp8-e4m3 quantize/dequantize primitives, the
quantized dcn-hop allreduce built on them, and the quantized KV-cache
layout serving reuses.

EQuARX lineage ("Efficient Quantized AllReduce in XLA", PAPERS.md): the
slow inter-node (dcn) hop of a hierarchical grad reduction moves MOST of
the bytes and tolerates narrow payloads — per-block scales recover the
dynamic range a single tensor-wide scale loses on long-tailed grads.
Everything here is a PURE function of arrays (no custom VJP, no state):
the primitives sit AFTER value_and_grad in the step dataflow (grad comm)
or in inference-only paths (KV cache), so autodiff never traverses them,
and jit/shard_map trace them like any other jnp code.

Two forms of the grad-comm policy consume these primitives:

* ``quantized_allreduce(g, axis)`` — the WIRE-TRUE exchange, callable
  inside a shard_map region MANUAL over ``axis`` (the PR 6
  ``dcn_value_and_grad`` seam): each dcn group quantizes its local
  (already ici-reduced — GSPMD owns the fast full-width inner hop)
  partial grad, all-gathers payloads + per-block scales over the axis
  (int8/fp8 bytes plus a 1/block-sized f32 side channel on the wire),
  dequantizes each peer's contribution and reduces in f32 — the f32
  master apply then sees the mean of the per-group block-quantized
  values. The reduction itself never happens in the narrow dtype.

* ``quantize_dequantize(g)`` — the BOUNDARY round trip for programs with
  no explicit dcn seam (flat-dp meshes / eager steps), the same contract
  as the bf16 ``fp16_allreduce`` policy: the grad value entering the f32
  master update is exactly a block-quantized-width number (one pass
  through the quantizer — the error model of the quantized wire),
  while the reduction XLA emits stays wherever the compiler put it.
"""
from __future__ import annotations

from collections import namedtuple

import jax
import jax.numpy as jnp

__all__ = [
    "SUPPORTED", "fp8_dtype", "resolve_policy", "quantize_blockwise",
    "dequantize_blockwise", "quantize_dequantize", "quantized_allreduce",
    "quantized_pmean", "quantize_lastaxis", "dequantize_lastaxis",
    "QuantKV", "kv_quant_policy", "kv_zero", "wire_bytes",
    "grad_comm_info",
]

#: grad-comm policy dtypes DistributedStrategy.quantized_allreduce accepts
SUPPORTED = ("int8", "fp8")

#: symmetric int8 range: +-127 (the -128 code is never emitted, keeping
#: the quantizer symmetric so sign(x) == sign(q))
_INT8_QMAX = 127.0
#: largest finite float8_e4m3fn value
_FP8_QMAX = 448.0


def fp8_dtype():
    """jnp.float8_e4m3fn where this jax has it, else None."""
    return getattr(jnp, "float8_e4m3fn", None)


def resolve_policy(value, block=128, *, knob="quantized_allreduce"):
    """Validate a strategy (quantized_allreduce, quantized_allreduce_block)
    pair -> ("int8"|"fp8", block) or None. Loud on unknown dtypes and on
    fp8 without the dtype in this jax (silently training at a different
    width than asked is the one failure mode a comm policy must not
    have). ``knob`` names the strategy field / env var in the raise, so
    the round-19 compute knobs (quantized_matmul, quantized_moments,
    PADDLE_Q_MATMUL) share this resolver verbatim."""
    if value is None or value is False or value == "":
        return None
    v = str(value).strip().lower()
    if v not in SUPPORTED:
        raise ValueError(
            f"{knob}={value!r}: supported policies are "
            f"{SUPPORTED} (or None to disable)"
        )
    if v == "fp8" and fp8_dtype() is None:
        raise NotImplementedError(
            f"{knob}='fp8' needs jnp.float8_e4m3fn, which "
            "this jax does not provide; use 'int8'"
        )
    b = int(block)
    if b <= 0:
        raise ValueError(
            f"{knob}_block={block} must be a positive "
            "block width"
        )
    return v, b


def _qparams(dtype: str):
    if dtype == "int8":
        return jnp.int8, _INT8_QMAX
    if dtype == "fp8":
        f8 = fp8_dtype()
        if f8 is None:
            raise NotImplementedError("no float8_e4m3fn in this jax")
        return f8, _FP8_QMAX
    raise ValueError(f"unknown quantization dtype {dtype!r}")


def _encode(x32, scale, qdtype, qmax):
    """Scale-then-narrow one block layout (x32 f32, scale broadcastable).
    int8 rounds-to-nearest and clips; fp8 relies on the cast (the scale
    maps the block amax onto the largest finite e4m3 value, so nothing
    saturates)."""
    safe = jnp.where(scale > 0, scale, 1.0)
    y = x32 / safe
    if qdtype == jnp.int8:
        return jnp.clip(jnp.round(y), -qmax, qmax).astype(jnp.int8)
    return y.astype(qdtype)


def quantize_blockwise(x, dtype: str = "int8", block: int = 128):
    """x (any shape) -> (payload [nb, block] narrow, scales [nb] f32).

    The array is flattened and zero-padded to a block multiple; each
    128-wide (``block``) run gets one symmetric scale amax/qmax. Zero
    blocks encode as zero payload with zero scale (dequantizes to exact
    zeros)."""
    qdtype, qmax = _qparams(dtype)
    flat = jnp.ravel(x).astype(jnp.float32)
    n = flat.shape[0]
    nb = -(-n // block)
    flat = jnp.pad(flat, (0, nb * block - n))
    xb = flat.reshape(nb, block)
    scales = jnp.max(jnp.abs(xb), axis=1) / qmax
    payload = _encode(xb, scales[:, None], qdtype, qmax)
    return payload, scales.astype(jnp.float32)


def dequantize_blockwise(payload, scales, shape, out_dtype=jnp.float32):
    """Inverse of :func:`quantize_blockwise` back onto ``shape``."""
    flat = payload.astype(jnp.float32) * scales[:, None].astype(jnp.float32)
    n = 1
    for d in shape:
        n *= int(d)
    return flat.reshape(-1)[:n].reshape(shape).astype(out_dtype)


def quantize_dequantize(x, dtype: str = "int8", block: int = 128):
    """The boundary round trip: x passes the block quantizer once and
    comes back at its own dtype — the grad-comm width policy for
    programs whose reduction has no explicit dcn seam (the bf16
    ``_comm_cast`` contract at int8/fp8 width)."""
    p, s = quantize_blockwise(x, dtype, block)
    return dequantize_blockwise(p, s, x.shape, x.dtype)


def quantized_allreduce(x, axis: str, *, dtype: str = "int8",
                        block: int = 128, mean: bool = True):
    """Block-quantized allreduce over the named mesh axis — call inside
    a shard_map region MANUAL over ``axis`` (e.g. the async-dcn grad
    body). Exchanges per-block scales alongside the narrow payload and
    applies the reduction against an f32 master:

      local quantize -> all_gather(payload, scales) over ``axis`` ->
      per-peer f32 dequantize -> f32 sum (mean) -> cast to x.dtype.

    With an all-gather the wire moves (axis_size x) the quantized bytes
    — for the small dcn degrees this hop targets (2-8 pods) that is the
    one-shot EQuARX variant; the payload is 1/4 (int8 vs f32) plus a
    1/block scale side channel, so the hop's bytes drop ~3.8x at
    block=128."""
    payload, scales = quantize_blockwise(x, dtype, block)
    all_p = jax.lax.all_gather(payload, axis)   # [n, nb, block]
    all_s = jax.lax.all_gather(scales, axis)    # [n, nb]
    contrib = all_p.astype(jnp.float32) * all_s[..., None]
    total = jnp.sum(contrib, axis=0)            # f32 master accumulate
    if mean:
        total = total / all_p.shape[0]
    n = x.size
    return total.reshape(-1)[:n].reshape(x.shape).astype(x.dtype)


def quantized_pmean(x, axis: str, *, dtype: str = "int8",
                    block: int = 128):
    """The quantized hop's form for PARTIAL-manual shard_map regions
    (manual over ``axis``, GSPMD auto over ici/mp — the
    ``dcn_value_and_grad`` seam): per-shard block quantize-dequantize,
    then a full-width pmean.

    Why not :func:`quantized_allreduce` there: this XLA's SPMD
    partitioner admits only all-reduce collectives inside manual
    SUBGROUPS — ``all_gather`` and ``ppermute`` both trip the
    ``IsManualSubgroup`` check (spmd_partitioner.cc:512) when other mesh
    axes stay auto, so the narrow-payload exchange cannot lower in the
    partial-auto region. This form keeps the quantized exchange's
    NUMERICS — each dcn group's contribution passes the symmetric
    per-block quantizer BEFORE the reduction and the f32 master
    accumulates the group values (the EQuARX error model: n independent
    per-group quantization errors averaged, NOT one post-reduction
    round trip) — and keeps the per-grad definition-point placement, so
    the overlap schedule is unchanged. The wire-byte win is what the
    ``grad_comm`` telemetry prices and what ``quantized_allreduce``
    realizes wherever a full-manual region is available."""
    q = quantize_dequantize(x, dtype, block)
    return jax.lax.pmean(q, axis)


# ---------------------------------------------------------------------------
# last-axis block layout (the KV-cache form)
# ---------------------------------------------------------------------------


def _lastaxis_block(d: int, block: int) -> int:
    """Effective block width along a length-d last axis: the requested
    width when it tiles, else the whole row (one scale per row — a head
    dim of 64 under block=128 gets per-row scales, which is exactly the
    per-token-per-head scaling a KV cache wants)."""
    return block if (block > 0 and d % block == 0) else d


def quantize_lastaxis(x, dtype: str = "int8", block: int = 128):
    """x [..., D] -> (payload [..., D] narrow, scales [..., D/bs] f32),
    blocks along the LAST axis so a [B, H, cap, Dh] KV buffer keeps its
    shape (in-place decode writes stay one dynamic_update_slice) and the
    scales ride a parallel [B, H, cap, nb] buffer."""
    qdtype, qmax = _qparams(dtype)
    d = int(x.shape[-1])
    bs = _lastaxis_block(d, block)
    xr = x.astype(jnp.float32).reshape(x.shape[:-1] + (d // bs, bs))
    scales = jnp.max(jnp.abs(xr), axis=-1) / qmax
    payload = _encode(xr, scales[..., None], qdtype, qmax)
    return payload.reshape(x.shape), scales.astype(jnp.float32)


def dequantize_lastaxis(payload, scales, out_dtype=jnp.float32):
    """Inverse of :func:`quantize_lastaxis`."""
    d = int(payload.shape[-1])
    nb = int(scales.shape[-1])
    pr = payload.astype(jnp.float32).reshape(
        payload.shape[:-1] + (nb, d // nb))
    out = pr * scales[..., None].astype(jnp.float32)
    return out.reshape(payload.shape).astype(out_dtype)


#: quantized K or V cache buffer: `q` holds the narrow payload at the
#: full [B, H, cap, Dh] cache shape, `scale` the per-block f32 scales
#: [B, H, cap, Dh/bs]. A namedtuple, so it is a pytree — DecodeStep
#: donates/pins it leaf-wise exactly like the f32 Cache entries, and the
#: engine's CacheInsert splice tree_maps over both leaves by batch dim.
QuantKV = namedtuple("QuantKV", ["q", "scale"])


def kv_quant_policy(dtype):
    """Resolve a ``gen_cache(dtype=)`` request (plus the
    ``PADDLE_SERVE_KV_QUANT`` env default when no dtype is passed) into
    "int8" | "fp8" | None. A non-policy value (a real array dtype like
    bf16, or unset) returns None — the caller builds the plain
    full-width cache from it."""
    import os

    v = dtype
    if v is None:
        env = os.environ.get("PADDLE_SERVE_KV_QUANT", "").strip().lower()
        if not env or env in ("0", "off", "false", "none"):
            return None
        if env not in SUPPORTED:
            # the env knob takes ONLY policy names — a typo must not
            # silently serve at full width
            raise ValueError(
                f"PADDLE_SERVE_KV_QUANT={env!r}: supported values are "
                f"{SUPPORTED} (or 0/off)"
            )
        v = env
    if isinstance(v, str) and v.lower() in SUPPORTED:
        v = v.lower()
        if v == "fp8" and fp8_dtype() is None:
            raise NotImplementedError(
                "PADDLE_SERVE_KV_QUANT/gen_cache dtype 'fp8' needs "
                "jnp.float8_e4m3fn, which this jax does not provide; "
                "use 'int8'"
            )
        return v
    return None


def kv_zero(shape, dtype: str = "int8", block: int = 128):
    """Zero-filled (payload, scales) raw arrays for a fresh quantized
    KV-cache buffer of ``shape`` [B, H, cap, Dh] (zero scales dequantize
    to exact zeros, matching the f32 cache's zero fill)."""
    qdtype, _ = _qparams(dtype)
    d = int(shape[-1])
    bs = _lastaxis_block(d, block)
    return (jnp.zeros(shape, qdtype),
            jnp.zeros(tuple(shape[:-1]) + (d // bs,), jnp.float32))


# ---------------------------------------------------------------------------
# byte accounting (observability: bytes-on-wire, all static ints)
# ---------------------------------------------------------------------------


def wire_bytes(n_elems: int, dtype, block: int = 128) -> int:
    """Bytes one grad-comm hop moves for ``n_elems`` gradient elements
    under the named width policy: quantized payload (1 byte/elem for
    int8 and fp8-e4m3) plus the f32 per-block scale side channel;
    full-width dtypes have no side channel. Static-shape arithmetic —
    zero device reads."""
    if dtype in SUPPORTED:
        nb = -(-int(n_elems) // int(block))
        return int(n_elems) + 4 * nb
    itemsize = {"float32": 4, "bfloat16": 2, "float16": 2}.get(
        str(dtype), 4)
    return int(n_elems) * itemsize


def grad_comm_info(n_elems: int, policy, *, fp16_allreduce=False) -> dict:
    """The static ``grad_comm`` telemetry record: grad-comm dtype and
    actual bytes-on-wire per step (payload + scales) next to the f32
    baseline. ``policy`` is a resolve_policy() pair or None."""
    if policy is not None:
        dtype, block = policy
    else:
        dtype, block = ("bfloat16" if fp16_allreduce else "float32"), 0
    wire = wire_bytes(n_elems, dtype, block or 128)
    f32 = 4 * int(n_elems)
    return {
        "dtype": dtype,
        "block": int(block),
        "grad_elems": int(n_elems),
        "bytes_on_wire": int(wire),
        "bytes_f32": int(f32),
        "reduction_x": round(f32 / wire, 2) if wire else 1.0,
    }
