"""Train–serve co-tenancy: the fleet controller (ISSUE 16 tentpole).

Rounds 11–15 built every ingredient of one pod running both planes:
ElasticStep reshards training at a step boundary (PR 11), the router
drains a serving host live with zero token loss (PR 14), and the fleet
monitor sees SLO pressure as it builds — queue depth, TTFT digests,
``router_admit`` rejection rate (PRs 12–14). This module closes the
loop: a control process that LENDS training chips to a serving spike
and RECLAIMS them when it passes, so two over-provisioned planes become
one pod that degrades gracefully instead of shedding traffic — the
runtime-reconfigurability shape Flex-TPU argues for in hardware
(PAPERS.md), applied at the fleet level.

The state machine::

        sustained pressure >= PADDLE_CTL_PRESSURE
        for PADDLE_CTL_SUSTAIN_N windows, cooldown elapsed,
        lent < PADDLE_CTL_LEND_BUDGET
    TRAIN+SERVE ───────────────────────────────────────▶ LENT
        ◀───────────────────────────────────────
        pressure <= PADDLE_CTL_RELEASE
        for PADDLE_CTL_COOLDOWN_N windows, cooldown elapsed

- **pressure** per control window is
  ``max(reject_frac, queue_frac)``: the fraction of admissions the
  router REJECTED this window (from the monitor's cumulative
  ``router_metrics`` counters, differenced) and the total queue depth
  relative to the fleet's admission bound. The first window after a
  (re)start only seeds the baselines — a restart can never mistake a
  lifetime of counters for one hot window.
- **hysteresis**: separate lend/release thresholds with a dead band
  between them, a sustain requirement on each side, a cooldown of
  ``PADDLE_CTL_COOLDOWN_N`` windows between ANY two transitions, and a
  concurrent-lend budget — an oscillating load (the ``ctl:flap`` fault)
  cannot flap the mesh faster than one transition per cooldown window;
  blocked decisions are counted as ``suppressed``.
- **actuation** is injected, not owned: ``lend(ranks, sample)`` /
  ``reclaim(ranks, sample)`` callbacks. The in-process co-tenant wires
  the real ones — ``ElasticStep.notify_departure`` (the PR-11 depart
  path, verbatim) + ``InferenceEngine.expand_slots`` +
  ``Router.register_capacity`` for a lend; drain → ``retire_slots`` →
  ``notify_return`` for the reclaim. With no callbacks the controller
  is a DRYRUN: it decides and journals, moving nothing — the launcher
  embedding (``PADDLE_CTL=dryrun``) runs this way so the incident
  chain names the decision a human would have made.
- **crash safety**: every transition is journaled to the launcher bus
  stream as ``ctl_lend``/``ctl_reclaim`` rows with ``phase: begin`` →
  actuate → ``phase: commit``. On restart ownership is re-derived by
  replaying the journal — committed lends minus committed reclaims —
  never from guesswork; a trailing ``begin`` without its ``commit``
  (death mid-lend, the ``ctl:die`` fault) is resolved by the optional
  ``probe`` callback against the planes themselves, else conservatively
  journaled as ``ctl_abort`` and ignored. A controller death therefore
  leaves both planes running and a restarted controller consistent.

Runs EMBEDDED in the elastic launcher (``distributed/elastic.py``
starts it at rank −1 next to the monitor thread when
``PADDLE_CTL != off``) or STANDALONE::

    python -m paddle_tpu.distributed.fleet_controller --obs_dir <dir>

Stdlib-pure and standalone-loadable (no jax, no package imports) like
``observability/monitor.py`` — safe on a login node.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Set

__all__ = ["CtlConfig", "LendPolicy", "FleetController",
           "pressure_default", "sustain_n_default", "release_default",
           "cooldown_n_default", "lend_budget_default",
           "window_s_default", "main"]

SCHEMA_VERSION = 1  # mirrors bus.SCHEMA_VERSION (stdlib-pure on purpose)

_PRESSURE_ENV = "PADDLE_CTL_PRESSURE"
_SUSTAIN_ENV = "PADDLE_CTL_SUSTAIN_N"
_RELEASE_ENV = "PADDLE_CTL_RELEASE"
_COOLDOWN_ENV = "PADDLE_CTL_COOLDOWN_N"
_BUDGET_ENV = "PADDLE_CTL_LEND_BUDGET"
_WINDOW_S_ENV = "PADDLE_CTL_WINDOW_S"

#: journal kinds this module writes (tools/timeline.py renders the
#: begin→commit pairs as duration slices on the controller track)
_JOURNAL_KINDS = ("ctl_lend", "ctl_reclaim", "ctl_abort", "ctl_recover")

_FALLBACK_WRITE_LOCK = threading.Lock()


def _envf(name: str, default: float) -> float:
    try:
        raw = os.environ.get(name, "").strip()
        return float(raw) if raw else default
    except ValueError:
        return default


def pressure_default() -> float:
    """``PADDLE_CTL_PRESSURE`` — serving pressure at or above which a
    window counts as hot (default 0.5: half the admission attempts
    rejected, or the queue half full fleet-wide)."""
    return _envf(_PRESSURE_ENV, 0.5)


def sustain_n_default() -> int:
    """``PADDLE_CTL_SUSTAIN_N`` — consecutive hot windows before a lend
    fires (default 3; one hot sample is noise, not a spike)."""
    return max(int(_envf(_SUSTAIN_ENV, 3)), 1)


def release_default() -> float:
    """``PADDLE_CTL_RELEASE`` — pressure at or below which a window
    counts as calm (default 0.05). The gap to ``PADDLE_CTL_PRESSURE``
    is the hysteresis dead band: windows between the two reset BOTH
    streaks and can never trigger a transition."""
    return _envf(_RELEASE_ENV, 0.05)


def cooldown_n_default() -> int:
    """``PADDLE_CTL_COOLDOWN_N`` — consecutive calm windows before a
    reclaim, AND the minimum windows between any two transitions
    (default 5) — the anti-flap floor."""
    return max(int(_envf(_COOLDOWN_ENV, 5)), 1)


def lend_budget_default() -> int:
    """``PADDLE_CTL_LEND_BUDGET`` — dp rows that may be lent to serving
    concurrently (default 1; training never silently shrinks to
    nothing)."""
    return max(int(_envf(_BUDGET_ENV, 1)), 1)


def window_s_default() -> float:
    """``PADDLE_CTL_WINDOW_S`` — seconds per control window
    (default 1)."""
    return max(_envf(_WINDOW_S_ENV, 1.0), 0.01)


def _consume_ctl_events() -> List:
    """Drain armed ``ctl:*`` fault events (utils/fault_injection.py).
    Package import first; standalone loads find the injector under the
    names the test helpers register it as."""
    fi = None
    try:
        from ..utils import fault_injection as fi  # type: ignore
    except ImportError:
        for name in ("fault_injection", "_pdtpu_fault"):
            fi = sys.modules.get(name)
            if fi is not None:
                break
    if fi is None:
        return []
    try:
        return list(fi.consume_ctl_events())
    except Exception:  # noqa: BLE001 — fault plumbing never kills control
        return []


def _launcher_write_lock():
    """The telemetry bus's append lock when the package is importable
    (the embedded controller shares its process — and launcher file —
    with bus.emit and the monitor); module-local fallback otherwise."""
    try:
        from ..observability import bus as _bus

        return _bus._lock
    except Exception:  # noqa: BLE001 — standalone load, no package
        return _FALLBACK_WRITE_LOCK


def _read_rows(path: str) -> List[dict]:
    """Every complete JSON row in one stream file (torn-line tolerant,
    like bus.read_stream — local copy so standalone loads need no
    package)."""
    rows: List[dict] = []
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return rows
    for line in data.splitlines():
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "kind" in rec:
            rows.append(rec)
    return rows


class CtlConfig:
    """Resolved controller knobs (env defaults, ctor overrides)."""

    __slots__ = ("pressure", "sustain_n", "release", "cooldown_n",
                 "lend_budget", "window_s")

    def __init__(self, pressure: Optional[float] = None,
                 sustain_n: Optional[int] = None,
                 release: Optional[float] = None,
                 cooldown_n: Optional[int] = None,
                 lend_budget: Optional[int] = None,
                 window_s: Optional[float] = None):
        self.pressure = (pressure_default() if pressure is None
                         else float(pressure))
        self.sustain_n = (sustain_n_default() if sustain_n is None
                          else max(int(sustain_n), 1))
        self.release = (release_default() if release is None
                        else float(release))
        self.cooldown_n = (cooldown_n_default() if cooldown_n is None
                           else max(int(cooldown_n), 1))
        self.lend_budget = (lend_budget_default() if lend_budget is None
                            else max(int(lend_budget), 1))
        self.window_s = (window_s_default() if window_s is None
                         else max(float(window_s), 0.01))
        if self.release >= self.pressure:
            raise ValueError(
                f"hysteresis requires release < pressure, got "
                f"{self.release} >= {self.pressure}")


class LendPolicy:
    """The pure hysteresis state machine — no I/O, no clock, one
    :meth:`observe` per control window. Deterministic and unit-testable
    apart from everything that moves chips."""

    __slots__ = ("cfg", "hot", "calm", "since", "windows", "suppressed")

    def __init__(self, cfg: CtlConfig):
        self.cfg = cfg
        self.hot = 0            # consecutive windows at/above pressure
        self.calm = 0           # consecutive windows at/below release
        self.since = cfg.cooldown_n  # windows since last transition
        self.windows = 0
        self.suppressed = 0     # decisions blocked by cooldown/budget

    def observe(self, pressure: float, lent: int) -> Optional[str]:
        """Fold one window's pressure in; returns ``"lend"``,
        ``"reclaim"``, or None. ``lent`` is the number of rows
        currently lent (the budget check and the reclaim precondition
        — ownership lives in the journal, not here)."""
        self.windows += 1
        self.since += 1
        if pressure >= self.cfg.pressure:
            self.hot += 1
            self.calm = 0
        elif pressure <= self.cfg.release:
            self.calm += 1
            self.hot = 0
        else:  # the dead band: neither streak survives it
            self.hot = 0
            self.calm = 0
        if self.hot >= self.cfg.sustain_n:
            if lent >= self.cfg.lend_budget:
                return None  # budget-capped steady state, not a flap
            if self.since <= self.cfg.cooldown_n:
                self.suppressed += 1
                return None
            self.hot = 0
            self.since = 0
            return "lend"
        if self.calm >= self.cfg.cooldown_n and lent > 0:
            if self.since <= self.cfg.cooldown_n:
                self.suppressed += 1
                return None
            self.calm = 0
            self.since = 0
            return "reclaim"
        return None


class FleetController:
    """Consume the monitor's serving aggregates, decide, journal,
    actuate.

    ``monitor`` is a live ``FleetMonitor`` to share (the embedded
    launcher mode — the manager already tails the streams); pass None
    with ``own_monitor_factory`` (or use the CLI) to tail standalone.
    ``lend`` / ``reclaim`` are ``fn(ranks, sample)`` actuation
    callbacks; both None = dryrun. ``probe`` is the restart
    reconciliation callback: ``probe(pending) -> bool`` asks the planes
    whether a journaled ``begin`` without its ``commit`` actually
    happened. ``die_hook`` exists for tests — the default really does
    ``os.kill(os.getpid(), sig)`` when a ``ctl:die`` fault fires."""

    def __init__(self, obs_dir: str, *,
                 monitor=None,
                 config: Optional[CtlConfig] = None,
                 donor_ranks: Optional[List[int]] = None,
                 lend: Optional[Callable] = None,
                 reclaim: Optional[Callable] = None,
                 probe: Optional[Callable] = None,
                 emit: bool = True,
                 die_hook: Optional[Callable] = None):
        self.obs_dir = obs_dir
        self.monitor = monitor
        self.cfg = config or CtlConfig()
        self.policy = LendPolicy(self.cfg)
        self.donor_ranks = sorted(donor_ranks or [])
        self.lend_fn = lend
        self.reclaim_fn = reclaim
        self.emit = bool(emit)
        self.die_hook = die_hook or (
            lambda sig: os.kill(os.getpid(), sig))
        self._write_lock = _launcher_write_lock()
        self.lent: Set[int] = set()
        self.seq = 0
        self.windows = 0
        self.transitions: List[dict] = []
        self._base: Optional[tuple] = None
        self._flap_left = 0
        self._flap_tick = 0
        self._die_armed = False
        self._die_sig = signal.SIGKILL
        self._recover(probe)

    # -- journal ----------------------------------------------------------
    def _write_row(self, kind: str, payload: dict) -> None:
        """Append one launcher-stream (rank −1) bus row directly — like
        the monitor, the journal must land in the obs dir even when
        this process has no PADDLE_OBS_DIR exported."""
        if not self.emit:
            return
        row = {"v": SCHEMA_VERSION, "kind": kind, "step": None,
               "time": time.time(), "rank": -1, "payload": payload}
        try:
            path = os.path.join(self.obs_dir, "telemetry.launcher.jsonl")
            with self._write_lock, open(path, "a") as f:
                f.write(json.dumps(row, default=str) + "\n")
                f.flush()
                os.fsync(f.fileno())  # the crash-safety contract: a
                # ``begin`` row must survive the SIGKILL it precedes
        except (OSError, TypeError, ValueError):
            pass  # journaling must never take the control loop down

    def _recover(self, probe: Optional[Callable]) -> None:
        """Re-derive ownership by replaying the journal: committed
        lends minus committed reclaims = lent rows; a trailing begin
        without commit is reconciled via ``probe`` or aborted. Never
        guesswork — a controller that cannot read its journal starts
        owning nothing."""
        path = os.path.join(self.obs_dir, "telemetry.launcher.jsonl")
        lent: Set[int] = set()
        pending: Optional[dict] = None
        max_seq = 0
        rows = 0
        for row in _read_rows(path):
            kind = row.get("kind")
            if kind not in ("ctl_lend", "ctl_reclaim", "ctl_abort"):
                continue
            p = row.get("payload") or {}
            if not isinstance(p, dict):
                continue
            rows += 1
            seq = p.get("seq")
            if isinstance(seq, int):
                max_seq = max(max_seq, seq)
            if kind == "ctl_abort":
                if pending is not None and pending["seq"] == seq:
                    pending = None
                continue
            verb = "lend" if kind == "ctl_lend" else "reclaim"
            ranks = [r for r in (p.get("ranks") or [])
                     if isinstance(r, int)]
            if p.get("phase") == "begin":
                pending = {"verb": verb, "seq": seq, "ranks": ranks}
            elif p.get("phase") == "commit":
                if verb == "lend":
                    lent.update(ranks)
                else:
                    lent.difference_update(ranks)
                if pending is not None and pending["seq"] == seq:
                    pending = None
        self.lent = lent
        self.seq = max_seq
        if pending is not None:
            committed = False
            if probe is not None:
                try:
                    committed = bool(probe(dict(pending)))
                except Exception:  # noqa: BLE001 — a broken probe is a "no"
                    committed = False
            if committed:
                # the planes say the half-journaled transition landed:
                # write the commit the dead controller never got to
                if pending["verb"] == "lend":
                    self.lent.update(pending["ranks"])
                else:
                    self.lent.difference_update(pending["ranks"])
                self._write_row(f"ctl_{pending['verb']}", {
                    "phase": "commit", "seq": pending["seq"],
                    "ranks": pending["ranks"], "recovered": True,
                    "lent": sorted(self.lent)})
            else:
                self._write_row("ctl_abort", {
                    "verb": pending["verb"], "seq": pending["seq"],
                    "ranks": pending["ranks"],
                    "reason": "recovered begin without commit"})
        if rows:
            self._write_row("ctl_recover", {
                "lent": sorted(self.lent), "rows": rows,
                "seq": self.seq,
                "pending": None if pending is None else pending["verb"]})
            print(f"paddle_tpu.ctl: recovered from journal — "
                  f"lent {sorted(self.lent)}, seq {self.seq}"
                  + (f", reconciled pending {pending['verb']}"
                     if pending is not None else ""),
                  file=sys.stderr, flush=True)

    # -- pressure ---------------------------------------------------------
    def _sample(self) -> Dict:
        """One window's pressure sample from the monitor's cumulative
        serving aggregates (differenced against the previous window)."""
        s = self.monitor.serving_sample() if self.monitor is not None \
            else {}
        adm = int(s.get("admitted") or 0)
        rej = int(s.get("rejected") or 0)
        first = self._base is None
        base = self._base or (adm, rej)
        d_adm, d_rej = adm - base[0], rej - base[1]
        self._base = (adm, rej)
        reject_frac = d_rej / float(max(d_adm + d_rej, 1))
        qd = int(s.get("queue_depth") or 0)
        aq = s.get("admit_queue")
        hosts = int(s.get("hosts") or 1)
        cap = aq * max(hosts, 1) if isinstance(aq, (int, float)) and \
            aq > 0 else None
        queue_frac = min(qd / cap, 1.0) if cap else 0.0
        # the first window only seeds the baselines: a restarted
        # controller must not read a lifetime of counters as one spike
        pressure = 0.0 if first else max(reject_frac, queue_frac)
        return {
            "pressure": pressure,
            "reject_frac": round(reject_frac, 4),
            "queue_frac": round(queue_frac, 4),
            "d_admitted": d_adm, "d_rejected": d_rej,
            "queue_depth": qd,
            "train_step_ms": s.get("train_step_ms"),
        }

    # -- the control window -----------------------------------------------
    def window(self) -> Optional[dict]:
        """One control window: drain faults, sample pressure, decide,
        and (on a decision) journal + actuate. Returns the transition
        record, or None on a quiet window."""
        for action, arg in _consume_ctl_events():
            if action == "flap":
                self._flap_left = int(arg) if arg else 32
                self._flap_tick = 0
            elif action == "die":
                self._die_armed = True
                self._die_sig = int(arg) if arg else signal.SIGKILL
        samp = self._sample()
        if self._flap_left > 0:
            # synthetic square wave: runs of sustain-length hot windows
            # alternating with calm ones — each run WOULD trigger a
            # transition were the cooldown not in the way
            half = self.cfg.sustain_n
            samp["pressure"] = (1.0 if (self._flap_tick // half) % 2 == 0
                                else 0.0)
            samp["flap"] = True
            self._flap_tick += 1
            self._flap_left -= 1
        self.windows += 1
        decision = self.policy.observe(samp["pressure"], len(self.lent))
        if decision is None:
            return None
        return self._transition(decision, samp)

    def _transition(self, verb: str, samp: dict) -> Optional[dict]:
        if verb == "lend":
            avail = [r for r in self.donor_ranks if r not in self.lent]
            if not avail:
                return None  # nothing left to lend (no donors wired)
            ranks = [max(avail)]  # highest dp row first, the PR-11 order
        else:
            if not self.lent:
                return None
            ranks = [max(self.lent)]
        self.seq += 1
        seq = self.seq
        kind = f"ctl_{verb}"
        t0 = time.time()
        base = {"seq": seq, "ranks": ranks,
                "pressure": round(samp["pressure"], 4),
                "lent": sorted(self.lent)}
        self._write_row(kind, dict(base, phase="begin",
                                   sample={k: samp[k] for k in
                                           ("reject_frac", "queue_frac",
                                            "queue_depth")
                                           if k in samp}))
        if self._die_armed:
            # ctl:die aims HERE — after the begin row is durable,
            # before actuation/commit: the journal-recovery path's prey
            self._die_armed = False
            print(f"fault_injection: ctl:die firing sig="
                  f"{int(self._die_sig)} mid-{verb} seq {seq}",
                  file=sys.stderr, flush=True)
            self.die_hook(self._die_sig)
        fn = self.lend_fn if verb == "lend" else self.reclaim_fn
        try:
            if fn is not None:
                fn(ranks, samp)
        except Exception as e:  # noqa: BLE001 — actuation failed: abort,
            # ownership unchanged (the journal shows begin→abort, both
            # planes keep running on their pre-transition shapes)
            self._write_row("ctl_abort", {
                "verb": verb, "seq": seq, "ranks": ranks,
                "reason": repr(e)[:200]})
            print(f"paddle_tpu.ctl: {verb} seq {seq} aborted: {e!r}",
                  file=sys.stderr, flush=True)
            return None
        if verb == "lend":
            self.lent.update(ranks)
        else:
            self.lent.difference_update(ranks)
        dur_ms = (time.time() - t0) * 1000.0
        self._write_row(kind, dict(base, phase="commit",
                                   lent=sorted(self.lent),
                                   dur_ms=round(dur_ms, 3)))
        rec = {"verb": verb, "seq": seq, "ranks": ranks,
               "pressure": samp["pressure"], "dur_ms": dur_ms,
               "lent": sorted(self.lent), "dryrun": fn is None}
        self.transitions.append(rec)
        print(f"paddle_tpu.ctl: {verb} seq {seq} ranks {ranks} "
              f"(pressure {samp['pressure']:.2f}, "
              f"{dur_ms:.1f}ms{', dryrun' if fn is None else ''}) — "
              f"lent now {sorted(self.lent)}",
              file=sys.stderr, flush=True)
        return rec

    def run(self, max_seconds: Optional[float] = None,
            stop: Optional[threading.Event] = None) -> int:
        """Window loop for the standalone/embedded modes; returns the
        number of transitions driven."""
        t0 = time.monotonic()
        while True:
            if self.monitor is not None:
                try:
                    self.monitor.poll()
                except Exception:  # noqa: BLE001 — keep controlling
                    pass
            self.window()
            if max_seconds is not None and \
                    time.monotonic() - t0 >= max_seconds:
                return len(self.transitions)
            if stop is not None:
                if stop.wait(self.cfg.window_s):
                    return len(self.transitions)
            else:
                time.sleep(self.cfg.window_s)


# ---------------------------------------------------------------------------
# standalone CLI
# ---------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.fleet_controller",
        description="train–serve co-tenancy controller over an "
                    "observability dir (standalone = dryrun: decisions "
                    "are journaled, nothing moves)")
    ap.add_argument("--obs_dir", required=True,
                    help="PADDLE_OBS_DIR of the running job")
    ap.add_argument("--window_s", type=float, default=None,
                    help="seconds per control window (default "
                         "$PADDLE_CTL_WINDOW_S or 1)")
    ap.add_argument("--donors", default="",
                    help="comma-separated dp ranks eligible to lend "
                         "(default: none — decisions log as "
                         "unactionable)")
    ap.add_argument("--max_seconds", type=float, default=None,
                    help="exit after this long (default: run until ^C)")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.obs_dir):
        print(f"ctl: {args.obs_dir} is not a directory", file=sys.stderr)
        return 2
    try:
        from ..observability.monitor import FleetMonitor
    except ImportError:  # standalone module load: tail-only fallback
        FleetMonitor = None
    mon = None
    if FleetMonitor is not None:
        mon = FleetMonitor(args.obs_dir, emit=False)
    donors = [int(r) for r in args.donors.split(",") if r.strip()]
    ctl = FleetController(
        args.obs_dir, monitor=mon,
        config=CtlConfig(window_s=args.window_s),
        donor_ranks=donors)
    try:
        n = ctl.run(max_seconds=args.max_seconds)
    except KeyboardInterrupt:
        n = len(ctl.transitions)
    print(f"ctl: {ctl.windows} window(s), {n} transition(s), "
          f"lent {sorted(ctl.lent)}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
