"""Train–serve co-tenancy: the fleet controller (ISSUE 16 tentpole).

Rounds 11–15 built every ingredient of one pod running both planes:
ElasticStep reshards training at a step boundary (PR 11), the router
drains a serving host live with zero token loss (PR 14), and the fleet
monitor sees SLO pressure as it builds — queue depth, TTFT digests,
``router_admit`` rejection rate (PRs 12–14). This module closes the
loop: a control process that LENDS training chips to a serving spike
and RECLAIMS them when it passes, so two over-provisioned planes become
one pod that degrades gracefully instead of shedding traffic — the
runtime-reconfigurability shape Flex-TPU argues for in hardware
(PAPERS.md), applied at the fleet level.

The state machine::

        sustained pressure >= PADDLE_CTL_PRESSURE
        for PADDLE_CTL_SUSTAIN_N windows, cooldown elapsed,
        lent < PADDLE_CTL_LEND_BUDGET
    TRAIN+SERVE ───────────────────────────────────────▶ LENT
        ◀───────────────────────────────────────
        pressure <= PADDLE_CTL_RELEASE
        for PADDLE_CTL_COOLDOWN_N windows, cooldown elapsed

- **pressure** per control window is
  ``max(reject_frac, queue_frac)``: the fraction of admissions the
  router REJECTED this window (from the monitor's cumulative
  ``router_metrics`` counters, differenced) and the total queue depth
  relative to the fleet's admission bound. The first window after a
  (re)start only seeds the baselines — a restart can never mistake a
  lifetime of counters for one hot window.
- **hysteresis**: separate lend/release thresholds with a dead band
  between them, a sustain requirement on each side, a cooldown of
  ``PADDLE_CTL_COOLDOWN_N`` windows between ANY two transitions, and a
  concurrent-lend budget — an oscillating load (the ``ctl:flap`` fault)
  cannot flap the mesh faster than one transition per cooldown window;
  blocked decisions are counted as ``suppressed``.
- **actuation** is injected, not owned: ``lend(ranks, sample)`` /
  ``reclaim(ranks, sample)`` callbacks. The in-process co-tenant wires
  the real ones — ``ElasticStep.notify_departure`` (the PR-11 depart
  path, verbatim) + ``InferenceEngine.expand_slots`` +
  ``Router.register_capacity`` for a lend; drain → ``retire_slots`` →
  ``notify_return`` for the reclaim. With no callbacks the controller
  is a DRYRUN: it decides and journals, moving nothing — the launcher
  embedding (``PADDLE_CTL=dryrun``) runs this way so the incident
  chain names the decision a human would have made.
- **crash safety**: every transition is journaled to the launcher bus
  stream as ``ctl_lend``/``ctl_reclaim`` rows with ``phase: begin`` →
  actuate → ``phase: commit``. On restart ownership is re-derived by
  replaying the journal — committed lends minus committed reclaims —
  never from guesswork; a trailing ``begin`` without its ``commit``
  (death mid-lend, the ``ctl:die`` fault) is resolved by the optional
  ``probe`` callback against the planes themselves, else conservatively
  journaled as ``ctl_abort`` and ignored. A controller death therefore
  leaves both planes running and a restarted controller consistent.

Runs EMBEDDED in the elastic launcher (``distributed/elastic.py``
starts it at rank −1 next to the monitor thread when
``PADDLE_CTL != off``) or STANDALONE::

    python -m paddle_tpu.distributed.fleet_controller --obs_dir <dir>

The LIVE lend plane (ISSUE 20) replaces the single lend/reclaim
callback pair with a journaled PHASE LADDER driven per transition::

    lend:    depart → deliver → join
    reclaim: drain  → leave   → rejoin

Each phase is its own fsync'd ``ctl_phase`` begin→commit pair nested
inside the outer ``ctl_lend``/``ctl_reclaim`` begin→commit envelope:
**depart** retires the dp row from the training mesh (the PR-11
reshard notice — survivors continue without relaunch), **deliver**
loads serving weights onto the lent rank via the PR-18
``load_quantized`` resident-checkpoint path (deadline-bounded),
**join** registers the rank as a serving worker and admits traffic
into it (``Router.register_capacity``); the reclaim ladder is the
reverse (PR-14 live drain / PR-16 KV migration, then the PR-11 return
notice — one ledger-attributed recompile). Recovery is
probe-or-rollback per the journal: a SIGKILL between ANY begin/commit
pair (the ``ctl:lend_crash:nth[:phase]`` fault) restarts into
``_recover``, which probes the planes for the half-journaled
transition and either writes the commit it proves or rolls the
completed phases back — never a half-lent chip. Actuation stays
injected (:class:`PhaseActuators`); with none wired the controller is
the same journal-only dryrun as before, emitting no ``ctl_phase``
rows at all.

Stdlib-pure and standalone-loadable (no jax, no package imports) like
``observability/monitor.py`` — safe on a login node.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Set

__all__ = ["CtlConfig", "LendPolicy", "FleetController", "PhaseActuators",
           "LEND_PHASES", "RECLAIM_PHASES",
           "pressure_default", "sustain_n_default", "release_default",
           "cooldown_n_default", "lend_budget_default",
           "window_s_default", "predict_default", "predict_n_default",
           "main"]

SCHEMA_VERSION = 1  # mirrors bus.SCHEMA_VERSION (stdlib-pure on purpose)

_PRESSURE_ENV = "PADDLE_CTL_PRESSURE"
_SUSTAIN_ENV = "PADDLE_CTL_SUSTAIN_N"
_RELEASE_ENV = "PADDLE_CTL_RELEASE"
_BUDGET_ENV = "PADDLE_CTL_LEND_BUDGET"
_WINDOW_S_ENV = "PADDLE_CTL_WINDOW_S"
_COOLDOWN_ENV = "PADDLE_CTL_COOLDOWN_N"
_PREDICT_ENV = "PADDLE_CTL_PREDICT"
_PREDICT_N_ENV = "PADDLE_CTL_PREDICT_N"

#: journal kinds this module writes (tools/timeline.py renders the
#: begin→commit pairs as duration slices on the controller track);
#: ``ctl_phase`` rows (ISSUE 20) appear only when live PhaseActuators
#: are wired — a dryrun journal is byte-compatible with round 16
_JOURNAL_KINDS = ("ctl_lend", "ctl_reclaim", "ctl_abort", "ctl_recover",
                  "ctl_phase")

#: the live-lend phase ladders (ISSUE 20) — mirror
#: utils/fault_injection.LEND_PHASES/RECLAIM_PHASES (both modules must
#: stay standalone-loadable, so neither imports the other's copy)
LEND_PHASES = ("depart", "deliver", "join")
RECLAIM_PHASES = ("drain", "leave", "rejoin")

_FALLBACK_WRITE_LOCK = threading.Lock()


def _envf(name: str, default: float) -> float:
    try:
        raw = os.environ.get(name, "").strip()
        return float(raw) if raw else default
    except ValueError:
        return default


def pressure_default() -> float:
    """``PADDLE_CTL_PRESSURE`` — serving pressure at or above which a
    window counts as hot (default 0.5: half the admission attempts
    rejected, or the queue half full fleet-wide)."""
    return _envf(_PRESSURE_ENV, 0.5)


def sustain_n_default() -> int:
    """``PADDLE_CTL_SUSTAIN_N`` — consecutive hot windows before a lend
    fires (default 3; one hot sample is noise, not a spike)."""
    return max(int(_envf(_SUSTAIN_ENV, 3)), 1)


def release_default() -> float:
    """``PADDLE_CTL_RELEASE`` — pressure at or below which a window
    counts as calm (default 0.05). The gap to ``PADDLE_CTL_PRESSURE``
    is the hysteresis dead band: windows between the two reset BOTH
    streaks and can never trigger a transition."""
    return _envf(_RELEASE_ENV, 0.05)


def cooldown_n_default() -> int:
    """``PADDLE_CTL_COOLDOWN_N`` — consecutive calm windows before a
    reclaim, AND the minimum windows between any two transitions
    (default 5) — the anti-flap floor."""
    return max(int(_envf(_COOLDOWN_ENV, 5)), 1)


def lend_budget_default() -> int:
    """``PADDLE_CTL_LEND_BUDGET`` — dp rows that may be lent to serving
    concurrently (default 1; training never silently shrinks to
    nothing)."""
    return max(int(_envf(_BUDGET_ENV, 1)), 1)


def window_s_default() -> float:
    """``PADDLE_CTL_WINDOW_S`` — seconds per control window
    (default 1)."""
    return max(_envf(_WINDOW_S_ENV, 1.0), 0.01)


def predict_default() -> bool:
    """``PADDLE_CTL_PREDICT`` — fold the TTFT digest TREND into the
    pressure signal so the controller lends *before* the SLO burns
    (default off; ``1``/``on``/``true`` enables)."""
    return os.environ.get(_PREDICT_ENV, "").strip().lower() in \
        ("1", "on", "true", "yes")


def predict_n_default() -> int:
    """``PADDLE_CTL_PREDICT_N`` — trailing control windows the TTFT
    p50/p99 slope is fit over, and the horizon it is projected forward
    (default 4; minimum 2 — a slope needs two points)."""
    return max(int(_envf(_PREDICT_N_ENV, 4)), 2)


def _consume_ctl_events() -> List:
    """Drain armed ``ctl:*`` fault events (utils/fault_injection.py).
    Package import first; standalone loads find the injector under the
    names the test helpers register it as."""
    fi = None
    try:
        from ..utils import fault_injection as fi  # type: ignore
    except ImportError:
        for name in ("fault_injection", "_pdtpu_fault"):
            fi = sys.modules.get(name)
            if fi is not None:
                break
    if fi is None:
        return []
    try:
        return list(fi.consume_ctl_events())
    except Exception:  # noqa: BLE001 — fault plumbing never kills control
        return []


def _launcher_write_lock():
    """The telemetry bus's append lock when the package is importable
    (the embedded controller shares its process — and launcher file —
    with bus.emit and the monitor); module-local fallback otherwise."""
    try:
        from ..observability import bus as _bus

        return _bus._lock
    except Exception:  # noqa: BLE001 — standalone load, no package
        return _FALLBACK_WRITE_LOCK


def _read_rows(path: str) -> List[dict]:
    """Every complete JSON row in one stream file (torn-line tolerant,
    like bus.read_stream — local copy so standalone loads need no
    package)."""
    rows: List[dict] = []
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return rows
    for line in data.splitlines():
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "kind" in rec:
            rows.append(rec)
    return rows


class CtlConfig:
    """Resolved controller knobs (env defaults, ctor overrides)."""

    __slots__ = ("pressure", "sustain_n", "release", "cooldown_n",
                 "lend_budget", "window_s", "predict", "predict_n")

    def __init__(self, pressure: Optional[float] = None,
                 sustain_n: Optional[int] = None,
                 release: Optional[float] = None,
                 cooldown_n: Optional[int] = None,
                 lend_budget: Optional[int] = None,
                 window_s: Optional[float] = None,
                 predict: Optional[bool] = None,
                 predict_n: Optional[int] = None):
        self.pressure = (pressure_default() if pressure is None
                         else float(pressure))
        self.sustain_n = (sustain_n_default() if sustain_n is None
                          else max(int(sustain_n), 1))
        self.release = (release_default() if release is None
                        else float(release))
        self.cooldown_n = (cooldown_n_default() if cooldown_n is None
                           else max(int(cooldown_n), 1))
        self.lend_budget = (lend_budget_default() if lend_budget is None
                            else max(int(lend_budget), 1))
        self.window_s = (window_s_default() if window_s is None
                         else max(float(window_s), 0.01))
        self.predict = (predict_default() if predict is None
                        else bool(predict))
        self.predict_n = (predict_n_default() if predict_n is None
                          else max(int(predict_n), 2))
        if self.release >= self.pressure:
            raise ValueError(
                f"hysteresis requires release < pressure, got "
                f"{self.release} >= {self.pressure}")


class LendPolicy:
    """The pure hysteresis state machine — no I/O, no clock, one
    :meth:`observe` per control window. Deterministic and unit-testable
    apart from everything that moves chips."""

    __slots__ = ("cfg", "hot", "calm", "since", "windows", "suppressed")

    def __init__(self, cfg: CtlConfig):
        self.cfg = cfg
        self.hot = 0            # consecutive windows at/above pressure
        self.calm = 0           # consecutive windows at/below release
        self.since = cfg.cooldown_n  # windows since last transition
        self.windows = 0
        self.suppressed = 0     # decisions blocked by cooldown/budget

    def observe(self, pressure: float, lent: int) -> Optional[str]:
        """Fold one window's pressure in; returns ``"lend"``,
        ``"reclaim"``, or None. ``lent`` is the number of rows
        currently lent (the budget check and the reclaim precondition
        — ownership lives in the journal, not here)."""
        self.windows += 1
        self.since += 1
        if pressure >= self.cfg.pressure:
            self.hot += 1
            self.calm = 0
        elif pressure <= self.cfg.release:
            self.calm += 1
            self.hot = 0
        else:  # the dead band: neither streak survives it
            self.hot = 0
            self.calm = 0
        if self.hot >= self.cfg.sustain_n:
            if lent >= self.cfg.lend_budget:
                return None  # budget-capped steady state, not a flap
            if self.since <= self.cfg.cooldown_n:
                self.suppressed += 1
                return None
            self.hot = 0
            self.since = 0
            return "lend"
        if self.calm >= self.cfg.cooldown_n and lent > 0:
            if self.since <= self.cfg.cooldown_n:
                self.suppressed += 1
                return None
            self.calm = 0
            self.since = 0
            return "reclaim"
        return None


class PhaseActuators:
    """The live lend plane's verbs (ISSUE 20), injected per phase.

    Each phase callable has signature ``fn(rank, sample)`` and runs
    between that phase's journal ``begin`` and ``commit`` rows —
    raising aborts the whole transition (completed phases are rolled
    back). ``probe(rank) -> bool`` answers "is this rank currently
    serving on loan?" against the planes themselves — the restart
    reconciliation oracle AND the per-row budget gate (a second lend
    fires only while every already-lent row probes as serving).
    ``rollback(verb, stage, completed, ranks)`` undoes the named
    completed phases (in reverse) after a mid-ladder failure or a
    recovered crash; exceptions from it are swallowed — rollback is
    best-effort convergence, the journal is the authority. A phase
    left None is a committed no-op (tests wire subsets)."""

    __slots__ = ("depart", "deliver", "join", "drain", "leave",
                 "rejoin", "probe", "rollback")

    def __init__(self, depart: Optional[Callable] = None,
                 deliver: Optional[Callable] = None,
                 join: Optional[Callable] = None,
                 drain: Optional[Callable] = None,
                 leave: Optional[Callable] = None,
                 rejoin: Optional[Callable] = None,
                 probe: Optional[Callable] = None,
                 rollback: Optional[Callable] = None):
        self.depart = depart
        self.deliver = deliver
        self.join = join
        self.drain = drain
        self.leave = leave
        self.rejoin = rejoin
        self.probe = probe
        self.rollback = rollback

    def stage_fn(self, stage: str) -> Optional[Callable]:
        if stage not in LEND_PHASES + RECLAIM_PHASES:
            raise ValueError(f"unknown lend phase {stage!r}")
        return getattr(self, stage)


class FleetController:
    """Consume the monitor's serving aggregates, decide, journal,
    actuate.

    ``monitor`` is a live ``FleetMonitor`` to share (the embedded
    launcher mode — the manager already tails the streams); pass None
    with ``own_monitor_factory`` (or use the CLI) to tail standalone.
    ``lend`` / ``reclaim`` are ``fn(ranks, sample)`` actuation
    callbacks; both None = dryrun. ``actuators`` (ISSUE 20) supersedes
    them with the live :class:`PhaseActuators` ladder — each transition
    then runs depart→deliver→join (lend) or drain→leave→rejoin
    (reclaim) as journaled ``ctl_phase`` begin→commit pairs. ``probe``
    is the restart reconciliation callback: ``probe(pending) -> bool``
    asks the planes whether a journaled ``begin`` without its
    ``commit`` actually happened (default: derived from
    ``actuators.probe`` when present). ``die_hook`` exists for tests —
    the default really does ``os.kill(os.getpid(), sig)`` when a
    ``ctl:die`` / ``ctl:lend_crash`` fault fires."""

    def __init__(self, obs_dir: str, *,
                 monitor=None,
                 config: Optional[CtlConfig] = None,
                 donor_ranks: Optional[List[int]] = None,
                 lend: Optional[Callable] = None,
                 reclaim: Optional[Callable] = None,
                 actuators: Optional[PhaseActuators] = None,
                 probe: Optional[Callable] = None,
                 emit: bool = True,
                 die_hook: Optional[Callable] = None):
        self.obs_dir = obs_dir
        self.monitor = monitor
        self.cfg = config or CtlConfig()
        self.policy = LendPolicy(self.cfg)
        self.donor_ranks = sorted(donor_ranks or [])
        self.lend_fn = lend
        self.reclaim_fn = reclaim
        self.actuators = actuators
        if actuators is not None and (lend is not None
                                      or reclaim is not None):
            raise ValueError(
                "wire either the legacy lend/reclaim callbacks or the "
                "live PhaseActuators ladder, not both")
        self.emit = bool(emit)
        self.die_hook = die_hook or (
            lambda sig: os.kill(os.getpid(), sig))
        self._write_lock = _launcher_write_lock()
        self.lent: Set[int] = set()
        #: lend commit order — reclaim pops the LAST lent row (LIFO),
        #: reconstructed from the journal on restart
        self.lent_order: List[int] = []
        self.seq = 0
        self.windows = 0
        self.transitions: List[dict] = []
        self.deferred_lends = 0  # budget said yes, probe said not yet
        self._base: Optional[tuple] = None
        self._ttft_trail: List[tuple] = []  # (p50, p99) per window
        self._flap_left = 0
        self._flap_tick = 0
        self._die_armed = False
        self._die_sig = signal.SIGKILL
        self._crash_armed = False
        self._crash_phase: Optional[str] = None
        self._recover(probe)

    # -- journal ----------------------------------------------------------
    def _write_row(self, kind: str, payload: dict) -> None:
        """Append one launcher-stream (rank −1) bus row directly — like
        the monitor, the journal must land in the obs dir even when
        this process has no PADDLE_OBS_DIR exported."""
        if not self.emit:
            return
        row = {"v": SCHEMA_VERSION, "kind": kind, "step": None,
               "time": time.time(), "rank": -1, "payload": payload}
        try:
            path = os.path.join(self.obs_dir, "telemetry.launcher.jsonl")
            with self._write_lock, open(path, "a") as f:
                f.write(json.dumps(row, default=str) + "\n")
                f.flush()
                os.fsync(f.fileno())  # the crash-safety contract: a
                # ``begin`` row must survive the SIGKILL it precedes
        except (OSError, TypeError, ValueError):
            pass  # journaling must never take the control loop down

    def _recover(self, probe: Optional[Callable]) -> None:
        """Re-derive ownership by replaying the journal: committed
        lends minus committed reclaims = lent rows (commit ORDER
        reconstructs the LIFO reclaim stack); a trailing begin without
        commit is reconciled via ``probe`` — or rolled back, phase by
        completed phase, through ``actuators.rollback``. Never
        guesswork — a controller that cannot read its journal starts
        owning nothing."""
        path = os.path.join(self.obs_dir, "telemetry.launcher.jsonl")
        lent: Set[int] = set()
        order: List[int] = []
        pending: Optional[dict] = None
        max_seq = 0
        rows = 0
        for row in _read_rows(path):
            kind = row.get("kind")
            if kind not in ("ctl_lend", "ctl_reclaim", "ctl_abort",
                            "ctl_phase"):
                continue
            p = row.get("payload") or {}
            if not isinstance(p, dict):
                continue
            rows += 1
            seq = p.get("seq")
            if isinstance(seq, int):
                max_seq = max(max_seq, seq)
            if kind == "ctl_phase":
                # per-stage ladder rows: fold into the pending outer
                # transition so reconciliation knows how far it got
                if pending is not None and pending["seq"] == seq:
                    if p.get("phase") == "commit":
                        pending["stages"].append(p.get("stage"))
                    else:
                        pending["stage_open"] = p.get("stage")
                continue
            if kind == "ctl_abort":
                if pending is not None and pending["seq"] == seq:
                    pending = None
                continue
            verb = "lend" if kind == "ctl_lend" else "reclaim"
            ranks = [r for r in (p.get("ranks") or [])
                     if isinstance(r, int)]
            if p.get("phase") == "begin":
                pending = {"verb": verb, "seq": seq, "ranks": ranks,
                           "stages": [], "stage_open": None}
            elif p.get("phase") == "commit":
                if verb == "lend":
                    lent.update(ranks)
                    order.extend(r for r in ranks if r not in order)
                else:
                    lent.difference_update(ranks)
                    order = [r for r in order if r not in ranks]
                if pending is not None and pending["seq"] == seq:
                    pending = None
        self.lent = lent
        self.lent_order = [r for r in order if r in lent]
        self.seq = max_seq
        if pending is not None:
            committed = False
            if probe is None and self.actuators is not None \
                    and self.actuators.probe is not None:
                probe = self._pending_probe
            if probe is not None:
                try:
                    committed = bool(probe(dict(pending)))
                except Exception:  # noqa: BLE001 — a broken probe is a "no"
                    committed = False
            if committed:
                # the planes say the half-journaled transition landed:
                # write the commit the dead controller never got to
                if pending["verb"] == "lend":
                    self.lent.update(pending["ranks"])
                    self.lent_order.extend(
                        r for r in pending["ranks"]
                        if r not in self.lent_order)
                else:
                    self.lent.difference_update(pending["ranks"])
                    self.lent_order = [r for r in self.lent_order
                                       if r not in pending["ranks"]]
                self._write_row(f"ctl_{pending['verb']}", {
                    "phase": "commit", "seq": pending["seq"],
                    "ranks": pending["ranks"], "recovered": True,
                    "lent": sorted(self.lent)})
            else:
                # roll the half-done ladder back to the pre-transition
                # ownership the journal still records — the stage that
                # died mid-flight counts as touched and is undone too
                touched = list(pending["stages"])
                if pending["stage_open"] is not None \
                        and pending["stage_open"] not in touched:
                    touched.append(pending["stage_open"])
                self._rollback(pending["verb"],
                               pending.get("stage_open"),
                               touched, pending["ranks"])
                self._write_row("ctl_abort", {
                    "verb": pending["verb"], "seq": pending["seq"],
                    "ranks": pending["ranks"],
                    "stage": pending.get("stage_open"),
                    "rolled_back": touched,
                    "reason": "recovered begin without commit"})
        if rows:
            self._write_row("ctl_recover", {
                "lent": sorted(self.lent), "rows": rows,
                "seq": self.seq, "order": list(self.lent_order),
                "pending": None if pending is None else pending["verb"]})
            print(f"paddle_tpu.ctl: recovered from journal — "
                  f"lent {sorted(self.lent)}, seq {self.seq}"
                  + (f", reconciled pending {pending['verb']}"
                     if pending is not None else ""),
                  file=sys.stderr, flush=True)

    def _pending_probe(self, pending: dict) -> bool:
        """Default reconciliation when only the per-rank
        ``actuators.probe`` is wired: a lend landed iff every rank now
        probes as serving; a reclaim landed iff none still does."""
        checks = [bool(self.actuators.probe(r)) for r in pending["ranks"]]
        return (all(checks) if pending["verb"] == "lend"
                else not any(checks))

    def _rollback(self, verb: str, stage: Optional[str],
                  completed: List[str], ranks: List[int]) -> None:
        """Best-effort physical undo of a failed/recovered ladder —
        the journal already records the authoritative ownership; this
        just converges the planes to it."""
        act = self.actuators
        if act is None or act.rollback is None:
            return
        try:
            act.rollback(verb, stage, list(completed), list(ranks))
        except Exception as e:  # noqa: BLE001 — rollback is advisory
            print(f"paddle_tpu.ctl: rollback of {verb} "
                  f"{completed} failed: {e!r}", file=sys.stderr,
                  flush=True)

    # -- pressure ---------------------------------------------------------
    def _sample(self) -> Dict:
        """One window's pressure sample from the monitor's cumulative
        serving aggregates (differenced against the previous window)."""
        s = self.monitor.serving_sample() if self.monitor is not None \
            else {}
        adm = int(s.get("admitted") or 0)
        rej = int(s.get("rejected") or 0)
        first = self._base is None
        base = self._base or (adm, rej)
        d_adm, d_rej = adm - base[0], rej - base[1]
        self._base = (adm, rej)
        reject_frac = d_rej / float(max(d_adm + d_rej, 1))
        qd = int(s.get("queue_depth") or 0)
        aq = s.get("admit_queue")
        hosts = int(s.get("hosts") or 1)
        cap = aq * max(hosts, 1) if isinstance(aq, (int, float)) and \
            aq > 0 else None
        queue_frac = min(qd / cap, 1.0) if cap else 0.0
        # the first window only seeds the baselines: a restarted
        # controller must not read a lifetime of counters as one spike
        pressure = 0.0 if first else max(reject_frac, queue_frac)
        samp = {
            "pressure": pressure,
            "reject_frac": round(reject_frac, 4),
            "queue_frac": round(queue_frac, 4),
            "d_admitted": d_adm, "d_rejected": d_rej,
            "queue_depth": qd,
            "train_step_ms": s.get("train_step_ms"),
        }
        if self.cfg.predict and not first:
            pred = self._predict(s.get("ttft_p50_ms"),
                                 s.get("ttft_p99_ms"))
            if pred is not None:
                samp["predicted"] = round(pred, 4)
                samp["ttft_p99_ms"] = s.get("ttft_p99_ms")
                # the fold is a MAX: prediction can only raise pressure
                # toward a lend, never mask a measured burn — and the
                # dead band / sustain / cooldown see one number, so
                # hysteresis semantics are unchanged
                samp["pressure"] = max(pressure, pred)
        return samp

    def _predict(self, p50, p99) -> Optional[float]:
        """Satellite: pressure the TTFT trend PROJECTS, before the
        rejections start. Least-squares slope of the fleet p99 digest
        over the last ``predict_n`` windows, extrapolated ``predict_n``
        windows forward; the predicted pressure is the projected
        FRACTIONAL growth over that horizon, clipped to [0, 1] — a
        latency on track to double within the horizon saturates to
        1.0, flat or improving trends contribute 0."""
        if not isinstance(p99, (int, float)) or p99 <= 0:
            return None
        self._ttft_trail.append(
            (float(p50) if isinstance(p50, (int, float)) else 0.0,
             float(p99)))
        n = self.cfg.predict_n
        if len(self._ttft_trail) > n:
            self._ttft_trail = self._ttft_trail[-n:]
        if len(self._ttft_trail) < n:
            return None
        ys = [y for _, y in self._ttft_trail]
        xm = (n - 1) / 2.0
        ym = sum(ys) / n
        den = sum((i - xm) ** 2 for i in range(n))
        slope = sum((i - xm) * (y - ym)
                    for i, y in enumerate(ys)) / den
        last = ys[-1]
        projected = last + slope * n
        return max(0.0, min((projected - last) / max(last, 1e-9), 1.0))

    # -- the control window -----------------------------------------------
    def window(self) -> Optional[dict]:
        """One control window: drain faults, sample pressure, decide,
        and (on a decision) journal + actuate. Returns the transition
        record, or None on a quiet window."""
        for action, arg in _consume_ctl_events():
            if action == "flap":
                self._flap_left = int(arg) if arg else 32
                self._flap_tick = 0
            elif action == "die":
                self._die_armed = True
                self._die_sig = int(arg) if arg else signal.SIGKILL
            elif action == "lend_crash":
                # phase-targeted die: fires between the named phase's
                # begin and commit rows (no phase named = the first
                # phase of the next transition)
                self._crash_armed = True
                self._crash_phase = arg if isinstance(arg, str) else None
        samp = self._sample()
        if self._flap_left > 0:
            # synthetic square wave: runs of sustain-length hot windows
            # alternating with calm ones — each run WOULD trigger a
            # transition were the cooldown not in the way
            half = self.cfg.sustain_n
            samp["pressure"] = (1.0 if (self._flap_tick // half) % 2 == 0
                                else 0.0)
            samp["flap"] = True
            self._flap_tick += 1
            self._flap_left -= 1
        self.windows += 1
        decision = self.policy.observe(samp["pressure"], len(self.lent))
        if decision is None:
            return None
        return self._transition(decision, samp)

    def _transition(self, verb: str, samp: dict) -> Optional[dict]:
        if verb == "lend":
            avail = [r for r in self.donor_ranks if r not in self.lent]
            if not avail:
                return None  # nothing left to lend (no donors wired)
            if self.actuators is not None \
                    and self.actuators.probe is not None:
                # per-row budget (ISSUE 20): a second row leaves
                # training only while every already-lent row is
                # COMMITTED AND SERVING per the planes themselves — a
                # row still mid-delivery defers the decision, it does
                # not stack a second in-flight migration
                try:
                    settled = all(bool(self.actuators.probe(r))
                                  for r in self.lent)
                except Exception:  # noqa: BLE001 — broken probe = not settled
                    settled = False
                if not settled:
                    self.deferred_lends += 1
                    return None
            ranks = [max(avail)]  # highest dp row first, the PR-11 order
        else:
            if not self.lent:
                return None
            # LIFO (ISSUE 20): the most recently lent row returns
            # first — nested lends unwind like a stack, so training's
            # mesh shrinks and regrows through the same shapes
            ranks = [self.lent_order[-1]] if self.lent_order \
                else [max(self.lent)]
        self.seq += 1
        seq = self.seq
        kind = f"ctl_{verb}"
        t0 = time.time()
        base = {"seq": seq, "ranks": ranks,
                "pressure": round(samp["pressure"], 4),
                "lent": sorted(self.lent)}
        self._write_row(kind, dict(base, phase="begin",
                                   sample={k: samp[k] for k in
                                           ("reject_frac", "queue_frac",
                                            "queue_depth", "predicted")
                                           if k in samp}))
        if self._die_armed:
            # ctl:die aims HERE — after the begin row is durable,
            # before actuation/commit: the journal-recovery path's prey
            self._die_armed = False
            print(f"fault_injection: ctl:die firing sig="
                  f"{int(self._die_sig)} mid-{verb} seq {seq}",
                  file=sys.stderr, flush=True)
            self.die_hook(self._die_sig)
        if self.actuators is not None:
            if not self._run_ladder(verb, seq, ranks, samp):
                return None
            live = True
        else:
            fn = self.lend_fn if verb == "lend" else self.reclaim_fn
            live = fn is not None
            try:
                if fn is not None:
                    fn(ranks, samp)
            except Exception as e:  # noqa: BLE001 — actuation failed:
                # abort, ownership unchanged (the journal shows
                # begin→abort, both planes keep running on their
                # pre-transition shapes)
                self._write_row("ctl_abort", {
                    "verb": verb, "seq": seq, "ranks": ranks,
                    "reason": repr(e)[:200]})
                print(f"paddle_tpu.ctl: {verb} seq {seq} aborted: {e!r}",
                      file=sys.stderr, flush=True)
                return None
        if verb == "lend":
            self.lent.update(ranks)
            self.lent_order.extend(r for r in ranks
                                   if r not in self.lent_order)
        else:
            self.lent.difference_update(ranks)
            self.lent_order = [r for r in self.lent_order
                               if r not in ranks]
        dur_ms = (time.time() - t0) * 1000.0
        self._write_row(kind, dict(base, phase="commit",
                                   lent=sorted(self.lent),
                                   dur_ms=round(dur_ms, 3)))
        rec = {"verb": verb, "seq": seq, "ranks": ranks,
               "pressure": samp["pressure"], "dur_ms": dur_ms,
               "lent": sorted(self.lent), "dryrun": not live}
        self.transitions.append(rec)
        print(f"paddle_tpu.ctl: {verb} seq {seq} ranks {ranks} "
              f"(pressure {samp['pressure']:.2f}, "
              f"{dur_ms:.1f}ms{'' if live else ', dryrun'}) — "
              f"lent now {sorted(self.lent)}",
              file=sys.stderr, flush=True)
        return rec

    def _run_ladder(self, verb: str, seq: int, ranks: List[int],
                    samp: dict) -> bool:
        """Drive one live transition through its phase ladder: every
        stage is a ``ctl_phase`` begin → actuate → commit triple, each
        row fsync'd BEFORE the next action, so a SIGKILL anywhere
        leaves a journal from which :meth:`_recover` can reconstruct
        exactly how far the migration got. Returns False (after
        rollback + ``ctl_abort``) when a stage raises."""
        stages = LEND_PHASES if verb == "lend" else RECLAIM_PHASES
        completed: List[str] = []
        for stage in stages:
            self._write_row("ctl_phase", {
                "seq": seq, "verb": verb, "stage": stage,
                "phase": "begin", "ranks": ranks})
            if self._crash_armed and self._crash_phase in (None, stage):
                # ctl:lend_crash aims HERE — the stage's begin row is
                # durable, its commit will never be written: the
                # phase-ladder recovery matrix's prey
                self._crash_armed = False
                print(f"fault_injection: ctl:lend_crash firing "
                      f"mid-{stage} ({verb} seq {seq})",
                      file=sys.stderr, flush=True)
                self.die_hook(signal.SIGKILL)
            t0 = time.time()
            fn = self.actuators.stage_fn(stage)
            try:
                if fn is not None:
                    fn(ranks[0], samp)
            except Exception as e:  # noqa: BLE001 — mid-ladder failure:
                # undo what committed (this stage counts as touched),
                # journal the abort with the stage name, ownership
                # unchanged
                self._rollback(verb, stage, completed + [stage], ranks)
                self._write_row("ctl_abort", {
                    "verb": verb, "seq": seq, "ranks": ranks,
                    "stage": stage, "rolled_back": completed + [stage],
                    "reason": repr(e)[:200]})
                print(f"paddle_tpu.ctl: {verb} seq {seq} aborted at "
                      f"{stage}: {e!r}", file=sys.stderr, flush=True)
                return False
            self._write_row("ctl_phase", {
                "seq": seq, "verb": verb, "stage": stage,
                "phase": "commit", "ranks": ranks,
                "dur_ms": round((time.time() - t0) * 1000.0, 3)})
            completed.append(stage)
        return True

    def force_reclaim(self, rank: int, reason: str) -> Optional[dict]:
        """Out-of-band reclaim (ISSUE 20): the lent worker DIED while
        serving, so there is nothing to drain and no ladder to run —
        journal the ownership change (begin→commit, ``forced``) so the
        row is back on the training plane's books before anything else
        happens. Router failover re-homes the dead worker's in-flight
        requests; the process death itself then takes the training
        plane's standard rank-loss path."""
        if rank not in self.lent:
            return None
        self.seq += 1
        seq = self.seq
        base = {"seq": seq, "ranks": [rank], "forced": True,
                "reason": str(reason)[:200], "lent": sorted(self.lent)}
        self._write_row("ctl_reclaim", dict(base, phase="begin"))
        self.lent.discard(rank)
        self.lent_order = [r for r in self.lent_order if r != rank]
        self._write_row("ctl_reclaim", dict(base, phase="commit",
                                            lent=sorted(self.lent),
                                            dur_ms=0.0))
        rec = {"verb": "reclaim", "seq": seq, "ranks": [rank],
               "pressure": None, "dur_ms": 0.0, "forced": True,
               "lent": sorted(self.lent), "dryrun": False}
        self.transitions.append(rec)
        print(f"paddle_tpu.ctl: FORCED reclaim seq {seq} rank {rank} "
              f"({reason}) — lent now {sorted(self.lent)}",
              file=sys.stderr, flush=True)
        return rec

    def run(self, max_seconds: Optional[float] = None,
            stop: Optional[threading.Event] = None) -> int:
        """Window loop for the standalone/embedded modes; returns the
        number of transitions driven."""
        t0 = time.monotonic()
        while True:
            if self.monitor is not None:
                try:
                    self.monitor.poll()
                except Exception:  # noqa: BLE001 — keep controlling
                    pass
            self.window()
            if max_seconds is not None and \
                    time.monotonic() - t0 >= max_seconds:
                return len(self.transitions)
            if stop is not None:
                if stop.wait(self.cfg.window_s):
                    return len(self.transitions)
            else:
                time.sleep(self.cfg.window_s)


# ---------------------------------------------------------------------------
# standalone CLI
# ---------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.fleet_controller",
        description="train–serve co-tenancy controller over an "
                    "observability dir (standalone = dryrun: decisions "
                    "are journaled, nothing moves)")
    ap.add_argument("--obs_dir", required=True,
                    help="PADDLE_OBS_DIR of the running job")
    ap.add_argument("--window_s", type=float, default=None,
                    help="seconds per control window (default "
                         "$PADDLE_CTL_WINDOW_S or 1)")
    ap.add_argument("--donors", default="",
                    help="comma-separated dp ranks eligible to lend "
                         "(default: none — decisions log as "
                         "unactionable)")
    ap.add_argument("--max_seconds", type=float, default=None,
                    help="exit after this long (default: run until ^C)")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.obs_dir):
        print(f"ctl: {args.obs_dir} is not a directory", file=sys.stderr)
        return 2
    try:
        from ..observability.monitor import FleetMonitor
    except ImportError:  # standalone module load: tail-only fallback
        FleetMonitor = None
    mon = None
    if FleetMonitor is not None:
        mon = FleetMonitor(args.obs_dir, emit=False)
    donors = [int(r) for r in args.donors.split(",") if r.strip()]
    ctl = FleetController(
        args.obs_dir, monitor=mon,
        config=CtlConfig(window_s=args.window_s),
        donor_ranks=donors)
    try:
        n = ctl.run(max_seconds=args.max_seconds)
    except KeyboardInterrupt:
        n = len(ctl.transitions)
    print(f"ctl: {ctl.windows} window(s), {n} transition(s), "
          f"lent {sorted(ctl.lent)}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
