"""Quantization plane round 2 (ISSUE 19): block-scaled COMPUTE on the
PR-10 wire primitives — quantized matmuls for the TP linears, int8
optimizer moments, and the pre-quantized weight form serving loads
straight from an int8 checkpoint.

Three legs, all built on ``distributed/quantized_comm.py``'s symmetric
per-block quantizer (same dtypes — int8 / fp8-e4m3 — same error model):

* **Quantized matmul.** Weights carry an int8/fp8 payload at the full
  [in, out] shape plus f32 per-block scales along the CONTRACTION axis
  (one scale per 128-row block per output column — the layout a
  row-streamed MXU pass wants). :func:`quantized_matmul` dequantizes
  in-graph and lets XLA fuse the widen into the matmul operand load: HBM
  traffic is the narrow payload + the 1/block scale side channel, the
  accumulate stays f32/bf16. Two routes arm it at the ``F.linear`` seam
  (`nn/functional/common.py` — the single chokepoint every Linear /
  ColumnParallelLinear / RowParallelLinear / ParallelMHA projection
  funnels through):

  - a weight that was LOADED narrow (``_q_scale`` set by
    :func:`quantize_layer` or an int8 checkpoint) always routes — the
    serving path, no wide copy ever exists; and
  - a wide weight under an armed policy (``strategy.quantized_matmul``
    via :func:`matmul_scope`, or the ``PADDLE_Q_MATMUL`` env default)
    routes through :func:`qat_matmul` — a fake-quant forward with a
    custom VJP (straight-through estimator to the wide master weight),
    so TrainStep's value_and_grad trains THROUGH the quantizer.

  With the policy unset and no narrow weights the seam falls through to
  the exact pre-PR ``jnp.matmul`` lines — off-switch bitwise identical.

* **Quantized moments** (:func:`moment_narrow` / :func:`moment_wide`):
  the last-axis block layout from the KV cache reused for Adam/AdamW
  moment accumulators — `optimizer/optimizer.py` dequantizes, updates in
  f32, and requantizes inside the compiled apply, so the moments never
  live wide in HBM (the round-trip error per step is exactly one pass
  through ``quantize_dequantize`` — the PR-10 error model).

* **Byte attribution** (:func:`q_matmul_info`, :func:`moment_bytes_info`)
  — static-shape arithmetic for the observability plane, zero device
  reads, same shape as ``grad_comm_info``.
"""
from __future__ import annotations

import contextlib
import os
from functools import partial

import jax
import jax.numpy as jnp

from . import quantized_comm as qc

__all__ = [
    "resolve_matmul", "matmul_policy", "matmul_scope",
    "quantize_weight", "dequantize_weight", "quantized_matmul",
    "qat_matmul", "moment_narrow", "moment_wide",
    "quantize_layer", "iter_quantizable",
    "q_matmul_info", "moment_bytes_info",
]

#: default contraction-axis block width (documented in README; matches
#: the wire plane's quantized_allreduce_block default)
DEFAULT_BLOCK = 128


def resolve_matmul(value, block=DEFAULT_BLOCK):
    """strategy.quantized_matmul -> ("int8"|"fp8", block) or None, loud
    on typos and on fp8 without float8_e4m3fn (same contract as the wire
    knob — silently computing at a different width than asked is the
    failure mode a compute policy must not have)."""
    return qc.resolve_policy(value, block, knob="quantized_matmul")


# -- scope/env policy (what F.linear consults) ------------------------------

#: innermost wins: TrainStep pushes the strategy policy around its traced
#: forward; the env var is the ambient default (eager + decode tracing)
_SCOPE = []


@contextlib.contextmanager
def matmul_scope(policy):
    """Arm (or force off, with None) the quantized-matmul route for the
    dynamic extent — ``policy`` is a resolved (dtype, block) pair."""
    _SCOPE.append(policy)
    try:
        yield
    finally:
        _SCOPE.pop()


def matmul_policy():
    """The policy F.linear consults per call: innermost scope override,
    else PADDLE_Q_MATMUL (loud on typos), else None."""
    if _SCOPE:
        return _SCOPE[-1]
    env = os.environ.get("PADDLE_Q_MATMUL", "").strip().lower()
    if not env or env in ("0", "off", "false", "none"):
        return None
    return qc.resolve_policy(env, knob="PADDLE_Q_MATMUL")


# -- the weight block layout ------------------------------------------------


def quantize_weight(w, dtype: str = "int8", block: int = DEFAULT_BLOCK):
    """w [in, out] -> (payload [in, out] narrow, scales [in/bs, out] f32)
    with symmetric per-block scales along the CONTRACTION axis (axis 0).
    A block spans `bs` input rows of ONE output column, so each output
    element's accumulation crosses scale groups only at block
    boundaries; `bs` falls back to the whole axis when ``block`` does
    not tile it (per-column scales — same degradation rule as the KV
    layout)."""
    qdtype, qmax = qc._qparams(dtype)
    i, o = int(w.shape[0]), int(w.shape[1])
    bs = qc._lastaxis_block(i, block)
    wr = w.astype(jnp.float32).reshape(i // bs, bs, o)
    scales = jnp.max(jnp.abs(wr), axis=1) / qmax          # [nb, o]
    payload = qc._encode(wr, scales[:, None, :], qdtype, qmax)
    return payload.reshape(i, o), scales.astype(jnp.float32)


def dequantize_weight(payload, scales, out_dtype=jnp.float32):
    """Inverse of :func:`quantize_weight` (payload [in, out] narrow,
    scales [nb, out] f32) -> wide [in, out] at ``out_dtype``."""
    i, o = int(payload.shape[0]), int(payload.shape[1])
    nb = int(scales.shape[0])
    pr = payload.astype(jnp.float32).reshape(nb, i // nb, o)
    out = pr * scales[:, None, :].astype(jnp.float32)
    return out.reshape(i, o).astype(out_dtype)


def quantized_matmul(x, w_q, scales):
    """x [..., in] @ dequant(w_q, scales) — the serving-path matmul over
    a pre-quantized weight. The dequant is IN-GRAPH so XLA fuses the
    widen into the matmul's operand load: what streams from HBM is the
    narrow payload + f32 scales, the accumulate runs at x's width."""
    out_dtype = (x.dtype if jnp.issubdtype(x.dtype, jnp.floating)
                 else jnp.float32)
    return jnp.matmul(x, dequantize_weight(w_q, scales, out_dtype))


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def qat_matmul(x, w, dtype: str = "int8", block: int = DEFAULT_BLOCK):
    """Fake-quant matmul over a WIDE master weight: the forward computes
    against the block-quantized weight (exactly what a narrow deployment
    will run), the backward is a straight-through estimator — dx uses
    the same quantized weight the forward saw (consistent
    linearization), dw flows full-width to the wide master so the
    optimizer keeps accumulating fine updates smaller than one
    quantization step."""
    wq, ws = quantize_weight(w, dtype, block)
    return jnp.matmul(x, dequantize_weight(wq, ws, w.dtype))


def _qat_fwd(x, w, dtype, block):
    wq, ws = quantize_weight(w, dtype, block)
    wdq = dequantize_weight(wq, ws, w.dtype)
    return jnp.matmul(x, wdq), (x, wdq)


def _qat_bwd(dtype, block, res, g):
    x, wdq = res
    dx = jnp.matmul(g, wdq.T).astype(x.dtype)
    xf = x.reshape(-1, x.shape[-1]).astype(jnp.float32)
    gf = g.reshape(-1, g.shape[-1]).astype(jnp.float32)
    dw = jnp.matmul(xf.T, gf).astype(wdq.dtype)
    return dx, dw


qat_matmul.defvjp(_qat_fwd, _qat_bwd)


# -- optimizer-moment layout ------------------------------------------------


def moment_narrow(m, dtype: str = "int8", block: int = DEFAULT_BLOCK):
    """f32 moment -> (payload, scales) in the last-axis block layout.
    0-d moments stay wide (a scalar has no axis to block over): the
    payload IS the f32 value and the scale is a 0-d zero sentinel
    :func:`moment_wide` recognizes."""
    if m.ndim == 0:
        return m.astype(jnp.float32), jnp.zeros((), jnp.float32)
    return qc.quantize_lastaxis(m, dtype, block)


def moment_wide(payload, scales, out_dtype=jnp.float32):
    """Inverse of :func:`moment_narrow`."""
    if payload.ndim == 0 or scales.ndim == 0:
        return payload.astype(out_dtype)
    return qc.dequantize_lastaxis(payload, scales, out_dtype)


def moment2_narrow(v, dtype: str = "int8", block: int = DEFAULT_BLOCK):
    """Second-moment narrow form: quantize sqrt(v), not v. Linear int8
    on v itself is structurally broken for Adam — v scales as g**2, so
    an element 16x below its block max already rounds to ZERO payload
    while the matching first-moment element (scaling as g) survives,
    and m / (sqrt(0) + eps) explodes the update by ~1/eps. In the sqrt
    domain both moments scale as g and cross the rounding threshold at
    the same relative magnitude."""
    return moment_narrow(jnp.sqrt(jnp.maximum(v, 0.0)), dtype, block)


def moment2_wide(payload, scales, out_dtype=jnp.float32):
    """Inverse of :func:`moment2_narrow`, with a half-step denominator
    floor: an element whose sqrt(v) rounded to zero payload had a true
    value somewhere in [0, scale/2), so reconstructing it as scale/2
    (instead of 0) keeps the update's denominator within HALF ONE
    QUANTIZATION STEP of the truth — the same per-element bound as the
    quantize_dequantize error model — while removing the 1/eps blowup
    for elements the narrow form cannot resolve. Zero-scale blocks
    (moments never touched) stay exactly zero."""
    if payload.ndim == 0 or scales.ndim == 0:
        u = payload.astype(jnp.float32)
        return (u * u).astype(out_dtype)
    d = int(payload.shape[-1])
    nb = int(scales.shape[-1])
    sc = scales[..., None].astype(jnp.float32)
    ur = payload.astype(jnp.float32).reshape(
        payload.shape[:-1] + (nb, d // nb)) * sc
    ur = jnp.maximum(ur, 0.5 * sc)
    u = ur.reshape(payload.shape)
    return (u * u).astype(out_dtype)


# -- the pre-quantized layer form (what int8 checkpoints load into) ---------

#: buffer name the per-weight scale table registers under on the OWNING
#: layer (non-persistable: it rides named_buffers into the compiled
#: decode step but never shadows the wide weight in a state_dict)
SCALE_BUFFER = "weight_q_scale"


def _linear_classes():
    from .. import nn
    from .meta_parallel import ColumnParallelLinear, RowParallelLinear

    return (nn.Linear, ColumnParallelLinear, RowParallelLinear)


def iter_quantizable(layer):
    """Yield (param_name, sublayer, weight) for every matmul weight the
    narrow form covers: 2-D floating `weight` params owned by
    Linear/ColumnParallelLinear/RowParallelLinear. Embedding tables and
    norm params stay wide (their access pattern is gather/elementwise,
    not an MXU contraction)."""
    classes = _linear_classes()
    for lname, sub in layer.named_sublayers(include_self=True):
        if not isinstance(sub, classes):
            continue
        w = sub._parameters.get("weight")
        if w is None or w.ndim != 2:
            continue
        if (not jnp.issubdtype(w.dtype, jnp.floating)
                and getattr(w, "_q_scale", None) is None):
            # int8 payloads fail the floating check but ARE eligible
            # when already narrow (re-save / reload of a quantized model)
            continue
        yield (f"{lname}.weight" if lname else "weight"), sub, w


def attach_quantized(sub, w, payload, scales):
    """Install a narrow (payload, scales) pair onto ``sub``'s weight
    in place: the param's raw becomes the payload (same shape, narrow
    dtype) and the scales ride a non-persistable buffer — so the
    compiled decode step threads both from HBM automatically (params +
    named_buffers are its donated inputs) and `F.linear` routes through
    :func:`quantized_matmul` on sight of ``_q_scale``."""
    from ..core.tensor import Tensor

    sc = Tensor._wrap(scales, stop_gradient=True)
    sub.register_buffer(SCALE_BUFFER, sc, persistable=False)
    w._data = payload
    w._q_scale = sc
    return sc


def quantize_layer(layer, dtype: str = "int8", block: int = DEFAULT_BLOCK):
    """Narrow every eligible linear weight of ``layer`` IN PLACE (the
    serving form: int8/fp8 payload resident, f32 scales alongside) and
    return the byte ledger::

        {"dtype", "block", "quantized": [param names],
         "bytes_payload", "bytes_scales", "bytes_wide_f32"}

    Already-narrow weights are skipped (idempotent), so a checkpoint
    load followed by an engine expand re-accounts without re-encoding.
    """
    pol = qc.resolve_policy(dtype, block, knob="quantized_matmul")
    if pol is None:
        raise ValueError("quantize_layer needs an explicit 'int8'/'fp8'")
    dt, bs = pol
    names, b_payload, b_scales, b_wide = [], 0, 0, 0
    for pname, sub, w in iter_quantizable(layer):
        if getattr(w, "_q_scale", None) is not None:
            continue
        payload, scales = quantize_weight(w._data, dt, bs)
        attach_quantized(sub, w, payload, scales)
        names.append(pname)
        b_payload += payload.size
        b_scales += 4 * scales.size
        b_wide += 4 * payload.size
    return {
        "dtype": dt, "block": bs, "quantized": names,
        "bytes_payload": int(b_payload), "bytes_scales": int(b_scales),
        "bytes_wide_f32": int(b_wide),
    }


# -- byte attribution (static ints, ledger/metrics shape) -------------------


def q_matmul_info(n_elems: int, policy) -> dict:
    """The static ``q_matmul`` telemetry record: resident matmul-weight
    bytes under the policy (payload + scale side channel, the
    ``wire_bytes`` arithmetic) next to the bf16 deployment baseline.
    ``policy`` is a resolve_matmul() pair or None."""
    n = int(n_elems)
    if policy is not None:
        dtype, block = policy
        resident = qc.wire_bytes(n, dtype, block)
    else:
        dtype, block = "bfloat16", 0
        resident = 2 * n
    bf16 = 2 * n
    return {
        "dtype": dtype, "block": int(block), "weight_elems": n,
        "bytes_resident": int(resident), "bytes_bf16": int(bf16),
        "reduction_x": round(bf16 / resident, 2) if resident else 1.0,
    }


def moment_bytes_info(n_elems: int, policy) -> dict:
    """The static ``moment_bytes`` record: HBM resident bytes for the
    TWO Adam moments under quantized_moments vs the f32 baseline (the
    flat-count block estimate — per-row blocking rounds each trailing
    axis up, a <1% correction the telemetry ignores)."""
    n = int(n_elems)
    if policy is not None:
        dtype, block = policy
        per_moment = qc.wire_bytes(n, dtype, block)
    else:
        dtype, block = "float32", 0
        per_moment = 4 * n
    f32 = 8 * n
    resident = 2 * per_moment
    return {
        "dtype": dtype, "block": int(block), "moment_elems": n,
        "bytes_resident": int(resident), "bytes_f32": int(f32),
        "reduction_x": round(f32 / resident, 2) if resident else 1.0,
    }
