"""Tensor (model) parallel layers.

Reference: python/paddle/distributed/collective.py:492 (`_parallel_linear`),
:526 (`_parallel_embedding`), :566 (`split`) — weight-partitioned layers over
a model-parallel NCCL ring with explicit c_allreduce/c_allgather calls.
Tests: column_parallel_linear_api.py / row_parallel_linear_api.py /
parallel_embedding_api.py.

TPU-native: a partitioned weight is ONE logical parameter laid out sharded
over the 'mp' mesh axis (each device stores 1/mp of it in HBM). The forward
is the plain dense computation; XLA's sharding propagation inserts the
all-reduce / all-gather exactly where the reference calls them explicitly,
and fuses them with the matmuls. `gather_output` / `input_is_parallel`
become output/input sharding constraints.
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import autograd as AG
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn.initializer import XavierNormal
from ..nn.layer import Layer
from . import comm


def _constrain(x: Tensor, mesh, spec) -> Tensor:
    """Differentiable sharding constraint, usable eager and in-trace."""
    sh = NamedSharding(mesh, spec)
    return AG.apply(
        lambda r: jax.lax.with_sharding_constraint(r, sh), (x,),
        name="sharding_constraint",
    )


def _shard_param(p, mesh, spec):
    p._data = jax.device_put(p._data, NamedSharding(mesh, spec))
    p._tp_spec = spec  # consumed by fleet.distributed_model layout pass
    return p


class ColumnParallelLinear(Layer):
    """Weight column-partitioned linear (collective.py:492, axis=1 path).

    W: [in, out] sharded P(None, 'mp'); per-device block [in, out/mp].
    gather_output=True replicates the output (reference: c_concat-style
    allgather); False leaves it sharded on the feature axis for a following
    RowParallelLinear.
    """

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, bias_attr=None,
                 name=None):
        super().__init__()
        self.mesh = comm.mp_mesh()
        mp = self.mesh.shape["mp"]
        if out_features % mp != 0:
            raise ValueError(
                f"out_features={out_features} not divisible by mp={mp}"
            )
        self._in = in_features
        self._out = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=XavierNormal(),
        )
        _shard_param(self.weight, self.mesh, P(None, "mp"))
        if has_bias and bias_attr is not False:
            self.bias = self.create_parameter(
                shape=[out_features], attr=bias_attr, is_bias=True
            )
            _shard_param(self.bias, self.mesh, P("mp"))
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            return _constrain(out, self.mesh, P())
        return _constrain(out, self.mesh, P(*([None] * (out.ndim - 1) + ["mp"])))


class RowParallelLinear(Layer):
    """Weight row-partitioned linear (collective.py:492, axis=0 path).

    W: [in, out] sharded P('mp', None). With input_is_parallel the incoming
    activation is already sharded on its feature axis (from a
    gather_output=False column layer); the matmul's contraction produces
    the partial sums whose all-reduce (reference: explicit c_allreduce_sum)
    XLA inserts via propagation. Output replicated.
    """

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, bias_attr=None,
                 name=None):
        super().__init__()
        self.mesh = comm.mp_mesh()
        mp = self.mesh.shape["mp"]
        if in_features % mp != 0:
            raise ValueError(
                f"in_features={in_features} not divisible by mp={mp}"
            )
        self._in = in_features
        self._out = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=XavierNormal(),
        )
        _shard_param(self.weight, self.mesh, P("mp", None))
        if has_bias and bias_attr is not False:
            self.bias = self.create_parameter(
                shape=[out_features], attr=bias_attr, is_bias=True
            )
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            x = _constrain(
                x, self.mesh, P(*([None] * (x.ndim - 1) + ["mp"]))
            )
        out = F.linear(x, self.weight, self.bias)
        return _constrain(out, self.mesh, P())


class VocabParallelEmbedding(Layer):
    """Vocab-partitioned embedding (collective.py:526 _parallel_embedding).

    Weight [vocab, dim] sharded P('mp', None): each device stores a vocab
    slice; the gather of looked-up rows (reference: masked local lookup +
    c_allreduce_sum) is XLA's gather over the sharded operand.
    """

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 name=None):
        super().__init__()
        self.mesh = comm.mp_mesh()
        mp = self.mesh.shape["mp"]
        if num_embeddings % mp != 0:
            raise ValueError(
                f"num_embeddings={num_embeddings} not divisible by mp={mp}"
            )
        self._num = num_embeddings
        self._dim = embedding_dim
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=XavierNormal(),
        )
        _shard_param(self.weight, self.mesh, P("mp", None))

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return _constrain(out, self.mesh, P())


def split(x, size, operation: str, axis: int = 0, num_partitions: Optional[int] = None,
          gather_out: bool = True, weight_attr=None, bias_attr=None,
          name=None):
    """paddle.distributed.split (collective.py:566): build-and-apply a
    model-parallel layer. size=(in,out) for 'linear' (axis=0 row-, axis=1
    column-parallel), (vocab,dim) for 'embedding'. Creates fresh parameters
    per call — construct the *ParallelLinear layers directly inside models.
    """
    if operation == "linear":
        if axis == 1:
            layer = ColumnParallelLinear(
                size[0], size[1], weight_attr=weight_attr,
                bias_attr=bias_attr, gather_output=gather_out,
            )
        elif axis == 0:
            layer = RowParallelLinear(
                size[0], size[1], weight_attr=weight_attr,
                bias_attr=bias_attr, input_is_parallel=not gather_out,
            )
        else:
            raise ValueError("split(linear) axis must be 0 or 1")
    elif operation == "embedding":
        layer = VocabParallelEmbedding(
            size[0], size[1], weight_attr=weight_attr
        )
    else:
        raise ValueError(f"unknown split operation {operation!r}")
    return layer(x)
