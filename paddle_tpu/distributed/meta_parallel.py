"""Tensor (model) parallel layers.

Reference: python/paddle/distributed/collective.py:492 (`_parallel_linear`),
:526 (`_parallel_embedding`), :566 (`split`) — weight-partitioned layers over
a model-parallel NCCL ring with explicit c_allreduce/c_allgather calls.
Tests: column_parallel_linear_api.py / row_parallel_linear_api.py /
parallel_embedding_api.py.

TPU-native: a partitioned weight is ONE logical parameter laid out sharded
over the 'mp' mesh axis (each device stores 1/mp of it in HBM). The forward
is the plain dense computation; XLA's sharding propagation inserts the
all-reduce / all-gather exactly where the reference calls them explicitly,
and fuses them with the matmuls. `gather_output` / `input_is_parallel`
become output/input sharding constraints.
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import autograd as AG
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn.initializer import XavierNormal
from ..nn.layer import Layer
from . import comm


def _constrain(x: Tensor, mesh, spec) -> Tensor:
    """Differentiable sharding constraint, usable eager and in-trace."""
    sh = NamedSharding(mesh, spec)
    return AG.apply(
        lambda r: jax.lax.with_sharding_constraint(r, sh), (x,),
        name="sharding_constraint",
    )


def _shard_param(p, mesh, spec):
    p._data = jax.device_put(p._data, NamedSharding(mesh, spec))
    p._tp_spec = spec  # consumed by fleet.distributed_model layout pass
    return p


def _overlap_plan(mesh, x, weight=None):
    """(mp, row_spec_elem) when PADDLE_TP_OVERLAP routes this layer's
    matmul through the collective-matmul ring (distributed/overlap.py),
    else None (the GSPMD sharding-propagation form). Declines when the
    weight takes a quantized-matmul route (ISSUE 19: pre-quantized
    payload or armed PADDLE_Q_MATMUL/strategy policy) — the narrow form
    goes through the F.linear seam; hand-fusing the dequant into the
    ring chunks is future work."""
    from . import overlap as _ov

    if not _ov.tp_overlap_enabled():
        return None
    if weight is not None:
        from . import quantized_compute as _qcp

        if (getattr(weight, "_q_scale", None) is not None
                or _qcp.matmul_policy() is not None):
            return None
    rows = 1
    for s in x.shape[:-1]:
        rows *= int(s)
    return _ov.row_overlap_plan(mesh, rows)


class ColumnParallelLinear(Layer):
    """Weight column-partitioned linear (collective.py:492, axis=1 path).

    W: [in, out] sharded P(None, 'mp'); per-device block [in, out/mp].
    gather_output=True replicates the output (reference: c_concat-style
    allgather); False leaves it sharded on the feature axis for a following
    RowParallelLinear.
    """

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, bias_attr=None,
                 name=None):
        super().__init__()
        self.mesh = comm.mp_mesh()
        mp = self.mesh.shape["mp"]
        if out_features % mp != 0:
            raise ValueError(
                f"out_features={out_features} not divisible by mp={mp}"
            )
        self._in = in_features
        self._out = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=XavierNormal(),
        )
        _shard_param(self.weight, self.mesh, P(None, "mp"))
        if has_bias and bias_attr is not False:
            self.bias = self.create_parameter(
                shape=[out_features], attr=bias_attr, is_bias=True
            )
            _shard_param(self.bias, self.mesh, P("mp"))
        else:
            self.bias = None

    def forward(self, x):
        if self.gather_output:
            plan = _overlap_plan(self.mesh, x, self.weight)
            if plan is not None:
                # pipelined output gather: per-row-chunk local matmuls,
                # each chunk's all-gather issued while the next computes
                from . import overlap as _ov

                mp, row_ax = plan
                args = (x, self.weight) + (
                    (self.bias,) if self.bias is not None else ()
                )
                return AG.apply(
                    lambda xr, wr, *br: _ov.column_gather_overlap(
                        xr, wr, br[0] if br else None, self.mesh, mp,
                        row_ax,
                    ),
                    args, name="column_gather_overlap",
                )
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            return _constrain(out, self.mesh, P())
        return _constrain(out, self.mesh, P(*([None] * (out.ndim - 1) + ["mp"])))


class RowParallelLinear(Layer):
    """Weight row-partitioned linear (collective.py:492, axis=0 path).

    W: [in, out] sharded P('mp', None). With input_is_parallel the incoming
    activation is already sharded on its feature axis (from a
    gather_output=False column layer); the matmul's contraction produces
    the partial sums whose all-reduce (reference: explicit c_allreduce_sum)
    XLA inserts via propagation. Output replicated.
    """

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, bias_attr=None,
                 name=None):
        super().__init__()
        self.mesh = comm.mp_mesh()
        mp = self.mesh.shape["mp"]
        if in_features % mp != 0:
            raise ValueError(
                f"in_features={in_features} not divisible by mp={mp}"
            )
        self._in = in_features
        self._out = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=XavierNormal(),
        )
        _shard_param(self.weight, self.mesh, P("mp", None))
        if has_bias and bias_attr is not False:
            self.bias = self.create_parameter(
                shape=[out_features], attr=bias_attr, is_bias=True
            )
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            x = _constrain(
                x, self.mesh, P(*([None] * (x.ndim - 1) + ["mp"]))
            )
        plan = _overlap_plan(self.mesh, x, self.weight)
        if plan is not None:
            # the contraction's psum decomposed into per-chunk ppermute
            # ring steps interleaved with the matmul chunks (collective
            # matmul): each ppermute overlaps the next chunk's MXU work
            from . import overlap as _ov

            mp, row_ax = plan
            args = (x, self.weight) + (
                (self.bias,) if self.bias is not None else ()
            )
            return AG.apply(
                lambda xr, wr, *br: _ov.row_parallel_overlap(
                    xr, wr, br[0] if br else None, self.mesh, mp, row_ax
                ),
                args, name="row_parallel_overlap",
            )
        out = F.linear(x, self.weight, self.bias)
        return _constrain(out, self.mesh, P())


class VocabParallelEmbedding(Layer):
    """Vocab-partitioned embedding (collective.py:526 _parallel_embedding).

    Weight [vocab, dim] sharded P('mp', None): each device stores a vocab
    slice; the gather of looked-up rows (reference: masked local lookup +
    c_allreduce_sum) is XLA's gather over the sharded operand.
    """

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 name=None):
        super().__init__()
        self.mesh = comm.mp_mesh()
        mp = self.mesh.shape["mp"]
        if num_embeddings % mp != 0:
            raise ValueError(
                f"num_embeddings={num_embeddings} not divisible by mp={mp}"
            )
        self._num = num_embeddings
        self._dim = embedding_dim
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=XavierNormal(),
        )
        _shard_param(self.weight, self.mesh, P("mp", None))

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return _constrain(out, self.mesh, P())


class ParallelMultiHeadAttention(Layer):
    """Megatron-style tensor-parallel self-attention.

    Reference lineage: the fused qkv + head-partitioned attention the
    reference reaches via `paddle.distributed.split` compositions
    (collective.py:492) and its Megatron ERNIE/GPT configs — heads are
    split over the 'mp' axis: the qkv projection is column-parallel
    (gather_output=False keeps [B, T, 3D] feature-sharded), each mp shard
    computes attention for its own heads locally (zero comm in the
    softmax), and the output projection is row-parallel, whose contraction
    all-reduce XLA inserts from sharding propagation.
    """

    def __init__(self, embed_dim, num_heads, dropout=0.0, causal=True,
                 weight_attr=None, bias_attr=None,
                 use_flash_attention=None):
        super().__init__()
        self.mesh = comm.mp_mesh()
        mp = self.mesh.shape["mp"]
        if embed_dim % num_heads != 0:
            raise ValueError("embed_dim must divide into num_heads")
        if num_heads % mp != 0:
            raise ValueError(
                f"num_heads={num_heads} not divisible by mp={mp}"
            )
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.causal = causal
        self.dropout = dropout
        # softmax(QK^T)V core routing (ISSUE 4 flash-by-default):
        #   None  -> AUTO: the Pallas flash kernel whenever the
        #            functional.attention policy allows (causal,
        #            dropout-free, TPU; PADDLE_FLASH_DEFAULT=0 escape
        #            hatch) — dense fallback otherwise;
        #   True  -> force the kernel (requires dropout == 0: flash
        #            never materializes the attention probabilities);
        #   False -> force the dense materialized-score path.
        if use_flash_attention and dropout:
            raise ValueError(
                "use_flash_attention requires dropout=0.0: the flash "
                "kernel never materializes the attention probabilities"
            )
        self.use_flash_attention = use_flash_attention
        self.qkv = ColumnParallelLinear(
            embed_dim, 3 * embed_dim, weight_attr=weight_attr,
            bias_attr=bias_attr, gather_output=False,
        )
        self.out_proj = RowParallelLinear(
            embed_dim, embed_dim, weight_attr=weight_attr,
            bias_attr=bias_attr, input_is_parallel=True,
        )

    def gen_cache(self, batch_size, max_length, dtype=None,
                  block_size=None, pool_blocks=None):
        """Static-capacity decode cache (ISSUE 9): zero [B, H, cap, Dh]
        K/V buffers in the same MultiHeadAttention.Cache namedtuple the
        single-chip layer uses, laid out with heads sharded over 'mp'
        (matching the attention compute) when the mesh is real — the
        compiled DecodeStep then updates each shard's slice in place.

        Round 13: ``block_size`` / ``PADDLE_SERVE_BLOCK_SIZE`` switches
        to the PAGED layout (`serving.paged_kv.PagedKV`): the
        [P, H, bs, Dh] block pool shards its heads over 'mp' exactly
        like the contiguous buffer; the pool dim is slot-agnostic (any
        block can belong to any slot), so it does NOT shard over the
        dp axes — a dp job replicates the pool and dp slots index it
        through their (replicated) tables, which is correct, just not
        dp-elastic in HBM (the multi-host router scales hosts, not
        per-host pools)."""
        import jax.numpy as jnp

        from ..nn.layers.transformer import MultiHeadAttention
        from ..serving import paged_kv as pk

        H, dh = self.num_heads, self.head_dim
        from . import quantized_comm as qc

        kvq = qc.kv_quant_policy(dtype)
        dt = dtype or self._dtype  # follow the layer dtype (bf16 models
        #                            get bf16 caches, like the 1-chip MHA)
        shape = (int(batch_size), H, int(max_length), dh)
        mp = int(self.mesh.shape["mp"])
        # batch shards over the data-parallel axes when divisible (dp
        # slots each store/decode only their shard — dp actually scales
        # serving memory + throughput), heads over mp; indivisible dims
        # stay replicated, which is correct but redundant
        bax = comm.dp_axes(self.mesh)
        baxes = (bax,) if isinstance(bax, str) else tuple(bax)
        bdeg = 1
        for a in baxes:
            if a in self.mesh.shape:
                bdeg *= int(self.mesh.shape[a])
        bspec = None
        if bdeg > 1 and int(batch_size) % bdeg == 0:
            bspec = baxes[0] if len(baxes) == 1 else tuple(baxes)
        spec = P(bspec, "mp" if (mp > 1 and H % mp == 0) else None,
                 None, None)

        def place(z, s=None):
            if self.mesh.size > 1:
                # the scale buffer's leading dims match the payload's,
                # so one spec lays out both
                z = jax.device_put(
                    z, NamedSharding(self.mesh, spec if s is None else s))
            # _wrap, not Tensor(): the ctor's dtype inference would
            # np.asarray the buffer — a device read per cache allocation
            return Tensor._wrap(z)

        bs_pg = (int(block_size) if block_size is not None
                 else pk.block_size_default())
        if bs_pg > 0:
            # paged pool [P, H, bs, Dh]: heads over 'mp' (axis 1, like
            # the contiguous buffer); pool dim + tables replicated
            pspec = P(None, "mp" if (mp > 1 and H % mp == 0) else None,
                      None, None)
            pdt = None if kvq is not None else dt

            def paged_buf():
                raw = pk.paged_zero(
                    int(batch_size), H, int(max_length), dh,
                    block=bs_pg, pool_blocks=pool_blocks, dtype=pdt,
                    quant=kvq,
                )
                kv = (qc.QuantKV(place(raw.kv.q, pspec),
                                 place(raw.kv.scale, pspec))
                      if kvq is not None else place(raw.kv, pspec))
                return pk.PagedKV(kv, place(raw.table, P()))

            return MultiHeadAttention.Cache(paged_buf(), paged_buf())

        if kvq is not None:
            # int8/fp8 block-scaled KV cache (ISSUE 10): payload +
            # per-row-block scales shard identically (batch over dp,
            # heads over mp); decode writes quantize, reads dequantize
            def qkv_buf():
                p, s = qc.kv_zero(shape, kvq)
                return qc.QuantKV(place(p), place(s))

            return MultiHeadAttention.Cache(qkv_buf(), qkv_buf())
        out = [place(jnp.zeros(shape, dt)) for _ in range(2)]
        return MultiHeadAttention.Cache(out[0], out[1])

    def forward(self, x, cache=None, pos=None):
        from .. import ops

        B, T = x.shape[0], x.shape[1]
        H, dh = self.num_heads, self.head_dim
        qkv = self.qkv(x)  # [B, T, 3D] sharded on the feature axis
        # heads axis inherits the mp sharding (3D = 3*H*dh, H-major)
        qkv = qkv.reshape([B, T, 3, H, dh]).transpose([2, 0, 3, 1, 4])
        qkv = _constrain(qkv, self.mesh, P(None, None, "mp", None, None))
        q, k, v = qkv[0], qkv[1], qkv[2]  # [B, H, T, dh]
        from ..nn.functional import attention as attn_route

        if cache is not None:
            # static-capacity decode-append: write this step's K/V rows
            # at per-slot `pos`, attend position-masked over the full
            # capacity. Plain XLA ops throughout, so GSPMD partitions
            # them over (dp -> batch, mp -> heads) exactly like the
            # training path — no shard_map seam needed (a traced pos
            # cannot feed the flash kernel's static q_offset anyway).
            if pos is None:
                raise ValueError(
                    "cache decoding needs `pos` (per-slot write "
                    "positions [B] int32)"
                )
            from ..nn.layers.transformer import MultiHeadAttention

            k = attn_route.cache_update(cache.k, k, pos)
            v = attn_route.cache_update(cache.v, v, pos)
            new_cache = MultiHeadAttention.Cache(k, v)
            ctx = attn_route.cached_attention(
                q, k, v, pos, scale=dh ** -0.5
            )
            ctx = ctx.transpose([0, 2, 1, 3]).reshape([B, T, H * dh])
            ctx = _constrain(ctx, self.mesh, P(None, None, "mp"))
            return self.out_proj(ctx), new_cache

        route_flash = self.use_flash_attention
        plan = None
        if route_flash is None:  # AUTO: the flash-by-default policy
            # self.mesh is the job-wide hybrid mesh — or, inside a
            # pipeline stage, the rebound pp-free submesh — so the
            # policy routes on the axes that partition THIS program
            plan = attn_route.flash_plan(
                T, T, causal=self.causal,
                dropout_active=bool(self.dropout) and self.training,
                mesh=self.mesh, batch=B, heads=H,
            )
            route_flash = plan is not None
        elif route_flash:
            # FORCED flash still needs the shard plan: when the seam
            # declines (PADDLE_FLASH_SHARD=0, a mesh the seam cannot
            # cover, the async-dcn manual region) the dense form below
            # composes — a bare pallas_call inside a multi-device GSPMD
            # program has no partition rule and would fail to compile
            p = attn_route._shard_plan(self.mesh, int(B), int(H))
            if p is False:
                route_flash = False
            else:
                plan = ("plain",) if p is None else ("sharded",) + p
        if route_flash:
            ctx = attn_route.flash_core_routed(
                q, k, v, mesh=self.mesh, causal=self.causal, plan=plan
            )
            ctx = ctx.transpose([0, 2, 1, 3]).reshape([B, T, H * dh])
            ctx = _constrain(ctx, self.mesh, P(None, None, "mp"))
            return self.out_proj(ctx)
        scores = ops.matmul(q, k, transpose_y=True) * (dh ** -0.5)
        if self.causal:
            import numpy as np

            mask = np.triu(
                np.full((T, T), -1e9, dtype=np.float32), k=1
            )
            scores = scores + Tensor._wrap(
                jax.numpy.asarray(mask), stop_gradient=True
            )
        attn = F.softmax(scores, axis=-1)
        if self.dropout:
            attn = F.dropout(attn, p=self.dropout, training=self.training)
        ctx = ops.matmul(attn, v)  # [B, H, T, dh], heads sharded
        ctx = ctx.transpose([0, 2, 1, 3]).reshape([B, T, H * dh])
        ctx = _constrain(ctx, self.mesh, P(None, None, "mp"))
        return self.out_proj(ctx)


class ParallelGPTBlock(Layer):
    """Pre-LN GPT decoder block with tensor-parallel attention + MLP —
    the unit the BASELINE GPT-3 configs stack inside pipeline stages."""

    def __init__(self, d_model, num_heads, dim_feedforward=None,
                 dropout=0.0, causal=True, use_flash_attention=None):
        super().__init__()
        from ..nn.layers.norm import LayerNorm

        ffn = dim_feedforward or 4 * d_model
        self._d_model = d_model
        self.ln1 = LayerNorm(d_model)
        self.attn = ParallelMultiHeadAttention(
            d_model, num_heads, dropout=dropout, causal=causal,
            use_flash_attention=use_flash_attention,
        )
        self.ln2 = LayerNorm(d_model)
        # the block's program mesh, shared with its LN layers so the
        # fused-LN routing targets the same device set as the attention
        # routing — pipeline _Stage rebinds every Mesh-valued `.mesh`
        # (this one, the LNs', the TP layers') to its pp-free submesh
        self.mesh = self.attn.mesh
        self.ln1.mesh = self.mesh
        self.ln2.mesh = self.mesh
        self.fc1 = ColumnParallelLinear(d_model, ffn, gather_output=False)
        self.fc2 = RowParallelLinear(ffn, d_model, input_is_parallel=True)
        self.dropout = dropout

    def forward(self, x, cache=None, pos=None, adapter=None):
        if cache is not None:
            a, new_cache = self.attn(self.ln1(x), cache=cache, pos=pos)
        else:
            a, new_cache = self.attn(self.ln1(x)), None
        # residual-add + LN fused in one Pallas pass on TPU (the sum is
        # formed once; both the residual stream and its normalization
        # come back) — dense x+LN fallback elsewhere
        h, n2 = F.fused_residual_layer_norm(
            x, a, [self._d_model],
            self.ln2.weight, self.ln2.bias, self.ln2._epsilon,
            mesh=self.mesh,
        )
        m_in = self.fc1(n2)
        if adapter is not None and "adapter_A" in self._buffers:
            # per-slot LoRA delta on the fc1 projection (ISSUE 18
            # adapter fleets): rows gathered from the resident stacks
            # by the traced [B] id vector — one program serves every
            # adapter mix; row 0 is zeros, so id 0 adds exact zeros
            m_in = m_in + self._adapter_delta(n2, adapter)
        m = F.gelu(m_in)
        if self.dropout:
            m = F.dropout(m, p=self.dropout, training=self.training)
        out = h + self.fc2(m)
        return out if new_cache is None else (out, new_cache)

    def _adapter_delta(self, x, ids):
        """``scale * B[a] @ (A[a] @ x)`` with ``a`` the per-row adapter
        id: two batched low-rank einsums over rows gathered in-graph
        from the stacked buffers. ``B`` is sharded on the ffn axis like
        the ``fc1`` weight, so the delta lands feature-sharded exactly
        where ``fc1``'s output does."""
        scale = self._adapter_scale

        def d(xr, ar, br, ir):
            import jax.numpy as jnp

            xf = xr.astype(jnp.float32)
            a = ar[ir].astype(jnp.float32)   # [B, r, d]
            b = br[ir].astype(jnp.float32)   # [B, ffn, r]
            u = jnp.einsum("btd,brd->btr", xf, a)
            out = jnp.einsum("btr,bfr->btf", u, b)
            return (scale * out).astype(xr.dtype)

        out = AG.apply(
            d, (x, self.adapter_A, self.adapter_B, ids),
            name="adapter_delta")
        return _constrain(out, self.mesh, P(None, None, "mp"))

    def gen_cache(self, batch_size, max_length, dtype=None,
                  block_size=None, pool_blocks=None):
        return self.attn.gen_cache(batch_size, max_length, dtype,
                                   block_size=block_size,
                                   pool_blocks=pool_blocks)


def split(x, size, operation: str, axis: int = 0, num_partitions: Optional[int] = None,
          gather_out: bool = True, weight_attr=None, bias_attr=None,
          name=None):
    """paddle.distributed.split (collective.py:566): build-and-apply a
    model-parallel layer. size=(in,out) for 'linear' (axis=0 row-, axis=1
    column-parallel), (vocab,dim) for 'embedding'. Creates fresh parameters
    per call — construct the *ParallelLinear layers directly inside models.
    """
    if operation == "linear":
        if axis == 1:
            layer = ColumnParallelLinear(
                size[0], size[1], weight_attr=weight_attr,
                bias_attr=bias_attr, gather_output=gather_out,
            )
        elif axis == 0:
            layer = RowParallelLinear(
                size[0], size[1], weight_attr=weight_attr,
                bias_attr=bias_attr, input_is_parallel=not gather_out,
            )
        else:
            raise ValueError("split(linear) axis must be 0 or 1")
    elif operation == "embedding":
        layer = VocabParallelEmbedding(
            size[0], size[1], weight_attr=weight_attr
        )
    else:
        raise ValueError(f"unknown split operation {operation!r}")
    return layer(x)
