"""paddle.distributed.spawn — in-Python multiprocess launcher.

Reference: python/paddle/distributed/spawn.py:276 — start nprocs python
processes running `func(*args)` with the cluster env injected, join, and
re-raise the first failure.

TPU note: one jax process per HOST; nprocs>1 is the CPU-backend testing
path (each child pins JAX_PLATFORM_NAME=cpu unless told otherwise). Env
is injected before `func` runs; lazily-imported jax in the child then
picks up the coordinator settings.
"""
from __future__ import annotations

import multiprocessing as mp
import os
from typing import Tuple

from .launch import build_cluster_env

__all__ = ["spawn"]


def _worker(func, args, env):
    os.environ.update(env)
    func(*args)


def spawn(func, args: Tuple = (), nprocs: int = 1, join: bool = True,
          daemon: bool = False, backend: str = None, start_port: int = 6170,
          **options):
    """spawn.py:276 parity. Returns the process list when join=False."""
    ctx = mp.get_context("spawn")
    envs = build_cluster_env(nprocs, start_port=start_port)
    procs = []
    for env in envs:
        if backend:
            env["JAX_PLATFORM_NAME"] = backend
        p = ctx.Process(target=_worker, args=(func, args, env),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if not join:
        return procs
    # polling watch loop (launch_utils.py teardown semantics): the first
    # failing rank tears the job down, so a sibling blocked on a dead
    # coordinator cannot hang the launcher forever
    import time

    failed = None
    while True:
        all_done = True
        for rank, p in enumerate(procs):
            if p.is_alive():
                all_done = False
            elif p.exitcode != 0 and failed is None:
                failed = (rank, p.exitcode)
        if failed is not None or all_done:
            break
        time.sleep(0.2)
    if failed is not None:
        for p in procs:
            if p.is_alive():
                p.terminate()
        for p in procs:
            p.join(timeout=10)
        raise RuntimeError(
            f"spawned rank {failed[0]} exited with code {failed[1]}"
        )
    return procs
