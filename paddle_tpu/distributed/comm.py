"""Communication core: device mesh, groups, and the collective engine.

Reference analog (SURVEY.md §2.9 / §5 backend table):
  - ring_id-keyed NCCL communicators (platform/collective_helper.h:52,72;
    gen_comm_id_helper.cc TCP bootstrap) ≙ named axes of a
    `jax.sharding.Mesh` over ICI — a Group here IS a mesh axis; there are no
    streams or comm-id exchanges because XLA compiles collectives into the
    program and the PJRT runtime owns topology discovery.
  - multi-host bootstrap (`init_parallel_env`, distributed/parallel.py:57 +
    c_gen_nccl_id/c_comm_init ops) ≙ `jax.distributed.initialize`
    (coordinator service) + the global device list.

Single-controller SPMD model: one Python process drives all devices. A
"per-rank value" is a global array whose leading axis is the rank axis,
sharded over the group's mesh axis (`shard_rank_axis`). Collectives are
shard_map'd XLA ops jitted once per (shape, dtype, op); inside an spmd
region (shard_map trace entered via this module) they lower directly to
`lax.psum`/`all_gather`/`ppermute` on the axis name.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _shard_map


# The mesh-axis classification every hot-path router shares (round 7):
# these axes shard the BATCH/row dims of an operand ('dp' flat
# data-parallel, or the hierarchical 'dcn' x 'ici' pair); 'mp' shards
# heads/features; anything else ('pp' pipeline stages, 'sp' ring
# attention's sequence axis) belongs to its own schedule and makes the
# shard_map seams decline. One constant so the three routing policies
# (attention.shard_factoring, norm._ln_row_factoring,
# overlap.row_overlap_plan) cannot drift.
DP_AXES = ("dp", "dcn", "ici")


def partitioning_axes(mesh) -> tuple:
    """The mesh axes that actually partition a program: every axis with
    size > 1, in mesh order (size-1 axes partition nothing and must
    never veto a routing decision)."""
    return tuple(a for a in mesh.axis_names if int(mesh.shape[a]) > 1)


def shard_map(f, mesh, in_specs, out_specs, auto=None):
    """The repo-wide shard_map wrapper (replication checking off — bodies
    use explicit collectives). `auto` names mesh axes left to GSPMD
    inside the body (partial-manual regions: the async-dcn grad
    reduction is manual over 'dcn', auto over ici/mp/...)."""
    kw = {} if auto is None else {"auto": frozenset(auto)}
    try:
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False, **kw,
        )
    except TypeError as e:
        if kw and "auto" in str(e):
            # distinct failure from the check_vma/check_rep rename: this
            # jax's shard_map has no partial-auto support at all
            raise NotImplementedError(
                "this jax's shard_map does not accept `auto` (partial-"
                "manual regions) — async_dcn_allreduce needs a jax with "
                "partial-auto shard_map"
            ) from e
        # pre-0.9 jax: the flag was called check_rep
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False, **kw,
        )


class Group:
    """A communicator: a set of devices bound to one mesh axis.

    The ring_id/NCCLComm analog (collective_helper.h:52) — but declarative:
    holding a Group means collectives over its axis name compile to ICI
    collectives among exactly these devices.
    """

    _counter = 0

    def __init__(self, devices: Sequence, axis_name: Optional[str] = None,
                 gid: Optional[int] = None, ranks: Optional[List[int]] = None):
        self.devices = list(devices)
        self.nranks = len(self.devices)
        self.id = Group._counter if gid is None else gid
        Group._counter += 1
        self.axis_name = axis_name or f"g{self.id}"
        self.ranks = list(ranks) if ranks is not None else list(
            range(self.nranks)
        )
        self.mesh = Mesh(
            np.array(self.devices).reshape(self.nranks), (self.axis_name,)
        )

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def __repr__(self):
        return (f"Group(id={self.id}, nranks={self.nranks}, "
                f"axis='{self.axis_name}')")


class _CommState(threading.local):
    def __init__(self):
        self.default_group: Optional[Group] = None
        self.groups: Dict[int, Group] = {}
        self.spmd_axes: Tuple[str, ...] = ()  # inside shard_map regions
        self.hybrid_mesh: Optional[Mesh] = None


_state = _CommState()
_jax_dist_initialized = False


def _ensure_init() -> Group:
    if _state.default_group is None:
        init_parallel_env()
    return _state.default_group


def _probe_endpoint(endpoint: str, timeout: float = 1.0) -> bool:
    """Cheap TCP reachability check of a host:port (the coordinator)."""
    import socket

    host, _, port = endpoint.rpartition(":")
    try:
        with socket.create_connection((host, int(port)), timeout):
            return True
    except (OSError, ValueError):
        return False


def _rdv_diagnose(coordinator: str, num: int, pid: int) -> str:
    """Attribution for a failed rendezvous: coordinator reachability plus
    which ranks never checked in through the launcher's shared sync dir."""
    import os

    parts = [
        f"rendezvous failed: rank {pid}/{num}, coordinator {coordinator} "
        f"tcp-{'reachable' if _probe_endpoint(coordinator) else 'UNREACHABLE'}"
    ]
    sync_dir = os.environ.get("PADDLE_COLL_SYNC_DIR")
    if sync_dir:
        d = os.path.join(sync_dir, "rdv")
        missing = [r for r in range(num)
                   if not os.path.exists(os.path.join(d, f"rank{r}"))]
        if missing:
            parts.append(f"ranks that never reached rendezvous: {missing}")
        else:
            parts.append(
                "all ranks checked in — suspect coordinator service or "
                "network between hosts, not a missing rank")
    return "; ".join(parts)


def _rendezvous_with_retry(init_fn, coordinator: str, num: int, pid: int,
                           deadline: Optional[float] = None,
                           backoff_base: Optional[float] = None,
                           backoff_cap: float = 15.0,
                           sleep=None) -> None:
    """Run `init_fn(remaining_seconds)` (jax.distributed.initialize) with
    exponential backoff + jitter under an overall PADDLE_RDV_DEADLINE.

    Mirrors the reference's TCP comm-id exchange retry loop
    (gen_comm_id_helper.cc retries connect with a bounded budget) — a
    slow-to-start peer must not fail the job, but a truly absent one must
    fail it LOUDLY with attribution instead of hanging forever."""
    import os
    import random
    import sys
    import time

    def _envf(name, default):
        raw = os.environ.get(name, "")
        return float(raw) if raw.strip() else default

    deadline = deadline if deadline is not None else _envf(
        "PADDLE_RDV_DEADLINE", 300.0)
    base = backoff_base if backoff_base is not None else _envf(
        "PADDLE_RDV_BACKOFF", 1.0)
    sleep = sleep or time.sleep
    sync_dir = os.environ.get("PADDLE_COLL_SYNC_DIR")
    if sync_dir:
        # check in BEFORE attempting: peers diagnosing a failure see who
        # ever made it this far (unreachable-rank attribution)
        try:
            d = os.path.join(sync_dir, "rdv")
            os.makedirs(d, exist_ok=True)
            with open(os.path.join(d, f"rank{pid}"), "w") as f:
                f.write(str(time.time()))
        except OSError:
            pass
    t_end = time.monotonic() + deadline
    attempt = 0
    while True:
        remaining = t_end - time.monotonic()
        try:
            if remaining <= 0:
                raise TimeoutError(
                    f"rendezvous deadline {deadline:g}s exhausted")
            init_fn(remaining)
            return
        except Exception as e:
            attempt += 1
            delay = min(base * (2.0 ** (attempt - 1)), backoff_cap)
            delay *= 0.5 + random.random()  # ±50% jitter: no stampedes
            if remaining <= 0 or time.monotonic() + delay >= t_end:
                raise RuntimeError(
                    _rdv_diagnose(coordinator, num, pid)
                    + f" (after {attempt} attempt(s), {deadline:g}s "
                      f"deadline; last error: {e})"
                ) from e
            print(
                f"paddle_tpu.rendezvous: attempt {attempt} failed ({e}); "
                f"retrying in {delay:.1f}s", file=sys.stderr, flush=True)
            sleep(delay)


def init_parallel_env(backend: Optional[str] = None) -> "ParallelEnv":
    """Bootstrap distributed state (reference: parallel.py:57
    init_parallel_env → NCCLParallelContext::Init + TCP comm-id exchange).

    TPU-native: multi-host rendezvous is jax.distributed (coordinator env:
    COORDINATOR_ADDRESS / PADDLE_TRAINER_ENDPOINTS honored); the default
    group spans every device in the job over axis 'dp'. The coordinator
    connection retries with exponential backoff + jitter under an overall
    PADDLE_RDV_DEADLINE and fails with unreachable-rank attribution
    (:func:`_rendezvous_with_retry`).
    """
    import os

    global _jax_dist_initialized

    def _dist_client_active():
        # must not touch jax.process_count() here: that initializes the
        # XLA backend, after which jax.distributed.initialize refuses to
        # run. The distributed client state is the pre-backend signal.
        try:
            from jax._src import distributed as _jd

            return _jd.global_state.client is not None
        except Exception:
            return False

    if (int(os.environ.get("PADDLE_TRAINERS_NUM", "1")) > 1
            and os.environ.get("PADDLE_TRAINER_ENDPOINTS")
            and not _jax_dist_initialized
            and not _dist_client_active()):
        # Multi-host launch: endpoints list ≙ coordinator bootstrap
        # (gen_comm_id_helper.cc:284 SendBroadCastCommID analog). Failures
        # propagate: a typo'd coordinator address must NOT degrade to
        # silent single-host training.
        coordinator = os.environ["PADDLE_TRAINER_ENDPOINTS"].split(",")[0]
        if ":" not in coordinator:
            raise ValueError(
                "PADDLE_TRAINER_ENDPOINTS entries must be host:port, got "
                f"{coordinator!r}"
            )
        num = int(os.environ["PADDLE_TRAINERS_NUM"])
        pid = int(os.environ.get("PADDLE_TRAINER_ID", "0"))

        def _init(remaining):
            try:
                try:
                    jax.distributed.initialize(
                        coordinator_address=coordinator,
                        num_processes=num, process_id=pid,
                        initialization_timeout=max(int(remaining), 1),
                    )
                except TypeError:  # older jax: no initialization_timeout
                    jax.distributed.initialize(
                        coordinator_address=coordinator,
                        num_processes=num, process_id=pid,
                    )
            except Exception:
                try:  # leave no half-initialized client behind a retry
                    jax.distributed.shutdown()
                except Exception:
                    pass
                raise

        _rendezvous_with_retry(_init, coordinator, num, pid)
        _jax_dist_initialized = True
    if _state.default_group is None:
        devs = jax.devices()
        _state.default_group = Group(devs, axis_name="dp", gid=0)
        _state.groups[0] = _state.default_group
    return ParallelEnv()


def is_initialized() -> bool:
    return _state.default_group is not None


def get_group(gid: int = 0) -> Optional[Group]:
    return _state.groups.get(gid)


def _default_group() -> Group:
    return _ensure_init()


def new_group(ranks: Optional[List[int]] = None, backend: Optional[str] = None,
              axis_name: Optional[str] = None) -> Group:
    """Create a communicator over a device subset (collective.py new_group)."""
    world = _ensure_init()
    if ranks is None:
        ranks = list(range(world.nranks))
    devs = [world.devices[r] for r in ranks]
    g = Group(devs, axis_name=axis_name, ranks=ranks)
    _state.groups[g.id] = g
    return g


class ParallelEnv:
    """Env facade (reference: fluid/dygraph/parallel.py ParallelEnv)."""

    @property
    def rank(self) -> int:
        import os

        if "PADDLE_TRAINER_ID" in os.environ:
            return int(os.environ["PADDLE_TRAINER_ID"])
        return jax.process_index()

    @property
    def world_size(self) -> int:
        g = _state.default_group
        return g.nranks if g is not None else len(jax.devices())

    @property
    def nranks(self) -> int:
        return self.world_size

    @property
    def local_rank(self) -> int:
        return self.rank

    @property
    def dev_id(self) -> int:
        return self.rank

    @property
    def device_id(self) -> int:
        return self.rank


def get_rank() -> int:
    """Trainer rank (reference parallel.py get_rank: PADDLE_TRAINER_ID or
    the process index)."""
    return ParallelEnv().rank


def get_world_size() -> int:
    """Number of TRAINER PROCESSES (reference get_world_size semantics —
    PADDLE_TRAINERS_NUM / process count), distinct from
    ParallelEnv().world_size which counts mesh devices in the
    single-controller model."""
    import os

    if "PADDLE_TRAINERS_NUM" in os.environ:
        return int(os.environ["PADDLE_TRAINERS_NUM"])
    return jax.process_count()


# ---------------------------------------------------------------------------
# spmd region tracking: inside a shard_map'd program, collectives lower to
# bare lax ops on the axis name instead of launching their own shard_map.
# ---------------------------------------------------------------------------


class _SpmdRegion:
    def __init__(self, axes: Tuple[str, ...]):
        self.axes = axes

    def __enter__(self):
        self._prev = _state.spmd_axes
        _state.spmd_axes = self._prev + self.axes
        return self

    def __exit__(self, *exc):
        _state.spmd_axes = self._prev


def spmd_region(*axes: str) -> _SpmdRegion:
    """Mark that code runs inside a shard_map over `axes` (used by
    DataParallel/pipeline/ring-attention internals and user rank programs)."""
    return _SpmdRegion(tuple(axes))


def in_spmd_region(axis_name: Optional[str] = None) -> bool:
    if axis_name is None:
        return bool(_state.spmd_axes)
    return axis_name in _state.spmd_axes


# ---------------------------------------------------------------------------
# Hybrid topology: one mesh, axes = parallelism dimensions
# ---------------------------------------------------------------------------


def init_hybrid_mesh(dp: int = 1, mp: int = 1, pp: int = 1,
                     sp: int = 1, dp_inner: int = 1) -> Mesh:
    """Build the job-wide hybrid mesh (dp, pp, sp, mp axes; mp innermost for
    ICI locality — model-parallel collectives are the latency-critical ones).

    The analog of the reference's per-strategy comm-ring construction
    (fleet meta_optimizers/common.py CollectiveHelper ring setup): here ONE
    declaration; each strategy consumes its axis by sharding on it.

    `dp_inner > 1` factors the dp axis into TWO mesh axes ('dcn' outer x
    'ici' inner, dp = dcn * dp_inner) — the two-level topology behind
    DistributedStrategy.hierarchical_allreduce: anything sharded or
    reduced over data-parallel uses the axis PAIR, so GSPMD emits the
    grad reduction as reduce-scatter/all-reduce over the fast inner
    (intra-pod ICI) axis composed with the slow outer (cross-pod DCN)
    axis, instead of one flat ring spanning both fabrics (the reference's
    hierarchical_allreduce inter/exter NCCL ring split,
    fleet meta_optimizers/common.py)."""
    _ensure_init()
    devs = jax.devices()
    need = dp * mp * pp * sp
    if len(devs) < need:
        raise ValueError(
            f"hybrid topology dp={dp} x pp={pp} x sp={sp} x mp={mp} needs "
            f"{need} devices, have {len(devs)}"
        )
    if dp_inner > 1:
        if dp % dp_inner:
            raise ValueError(
                f"hierarchical dp: dp={dp} not divisible by "
                f"dp_inner={dp_inner}"
            )
        arr = np.array(devs[:need]).reshape(
            dp // dp_inner, dp_inner, pp, sp, mp
        )
        mesh = Mesh(arr, ("dcn", "ici", "pp", "sp", "mp"))
    else:
        arr = np.array(devs[:need]).reshape(dp, pp, sp, mp)
        mesh = Mesh(arr, ("dp", "pp", "sp", "mp"))
    _state.hybrid_mesh = mesh
    return mesh


def hybrid_mesh() -> Optional[Mesh]:
    return _state.hybrid_mesh


def set_hybrid_mesh(mesh: Optional[Mesh]) -> Optional[Mesh]:
    """Swap the job-wide hybrid mesh in place (the elastic-reshard seam:
    survivors re-factor onto a smaller/larger device set mid-job —
    distributed/resharding.py). Returns the previous mesh."""
    prev = _state.hybrid_mesh
    _state.hybrid_mesh = mesh
    return prev


def rebuild_world(devices: Sequence) -> Group:
    """Re-point the default communicator ('dp' axis, group id 0) at
    exactly `devices` — the comm-group half of an elastic reshard: after
    rank departure/arrival the eager collectives and DataParallel input
    sharding must span the SURVIVORS, not the spawn-time world."""
    g = Group(list(devices), axis_name="dp", gid=0)
    _state.default_group = g
    _state.groups[0] = g
    return g


def dp_axes(mesh: Optional[Mesh] = None):
    """The mesh axis (or axis pair) data-parallel work shards over:
    'dp' on a flat mesh, ('dcn', 'ici') on a hierarchical one. The tuple
    drops straight into a PartitionSpec element."""
    m = mesh if mesh is not None else _state.hybrid_mesh
    if m is not None and "ici" in m.axis_names:
        return ("dcn", "ici")
    return "dp"


def dp_size(mesh: Optional[Mesh] = None) -> int:
    """Total data-parallel degree of the mesh (product over dp axes)."""
    m = mesh if mesh is not None else _state.hybrid_mesh
    if m is None:
        return 1
    ax = dp_axes(m)
    if isinstance(ax, tuple):
        n = 1
        for a in ax:
            n *= m.shape[a]
        return int(n)
    return int(m.shape[ax]) if ax in m.shape else 1


def mp_mesh() -> Mesh:
    """Mesh tensor-parallel params shard over ('mp' axis of the hybrid
    mesh). Declared by fleet.init(strategy with hybrid_configs mp_degree)
    or comm.init_hybrid_mesh."""
    if _state.hybrid_mesh is None:
        raise RuntimeError(
            "model-parallel layers need a hybrid mesh: call "
            "fleet.init(strategy=DistributedStrategy with "
            "hybrid_configs={'mp_degree': N}) or "
            "distributed.comm.init_hybrid_mesh(mp=N) first"
        )
    return _state.hybrid_mesh


# ---------------------------------------------------------------------------
# Sharding helpers
# ---------------------------------------------------------------------------


def shard_rank_axis(raw, group: Optional[Group] = None):
    """Lay a [nranks, ...] array out with one leading-axis slice per device
    of the group — the canonical 'per-rank value' layout."""
    g = group or _ensure_init()
    return jax.device_put(raw, NamedSharding(g.mesh, P(g.axis_name)))


def replicate(raw, group: Optional[Group] = None):
    g = group or _ensure_init()
    return jax.device_put(raw, NamedSharding(g.mesh, P()))
