"""Data-parallel training.

Reference: python/paddle/fluid/dygraph/parallel.py:322 (`DataParallel`) +
the C++ Reducer (paddle/fluid/imperative/reducer.cc:374–718): bucketed
grad-allreduce hooks over NCCL rings.

TPU-native design: there is no reducer. Parameters are laid out replicated
over the mesh and the batch is sharded on the 'dp' axis, so the loss is the
global loss and XLA's sharding propagation inserts (and fuses/buckets — the
all-reduce combiner subsumes `last_comm_group_size_MB`) the gradient
all-reduce wherever the program needs it: per-op in eager mode, one fused
program in the jit/TrainStep path. N-device training is numerically the
single-device program on the global batch.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..nn.layer import Layer
from . import comm


def shard_batch(x, mesh, axis_name="dp") -> Tensor:
    """Lay a global batch out sharded over `axis_name` on its leading dim —
    the one input-sharding helper every data-parallel surface uses. On a
    hierarchical mesh (hierarchical_allreduce: dp factored into dcn x ici)
    'dp' resolves to the axis pair."""
    if axis_name == "dp" and "dp" not in mesh.axis_names:
        axis_name = comm.dp_axes(mesh)
    raw = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor._wrap(
        jax.device_put(raw, NamedSharding(mesh, P(axis_name))),
        stop_gradient=True,
    )


class DataParallel(Layer):
    """Wrap a Layer for data-parallel training (parallel.py:322 parity).

    Usage matches the reference::

        dist.init_parallel_env()
        model = paddle.DataParallel(model)
        out = model(dp_model.shard_input(x))   # or any dp-sharded batch

    `scale_loss` / `no_sync` are kept for script parity: loss scaling is
    identity (the global mean already divides by the global batch) and
    no_sync is a no-op marker (grad comm is part of the compiled program,
    deferred accumulation comes from the gradient-merge strategy instead).
    """

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size_MB=25,
                 last_comm_buffer_size_MB=1, find_unused_parameters=False,
                 group: Optional[comm.Group] = None):
        # comm_buffer_size_MB / last_comm_buffer_size_MB: accepted for
        # script parity, deliberately unused — grad-comm bucketing is
        # XLA's all-reduce combiner (the Reducer group-size knobs have no
        # seam here). find_unused_parameters likewise: TrainStep's jaxpr
        # usage analysis subsumes it (unused params get no update).
        super().__init__()
        self._layers = layers
        self.group = group or comm._default_group()
        self.replicate_state()

    def replicate_state(self):
        """Lay every param/buffer out replicated over the group mesh — the
        broadcast-from-rank-0 step of reference init (parallel.py
        sync_params_buffers)."""
        sharding = NamedSharding(self.group.mesh, P())
        for p in self._layers.parameters():
            p._data = jax.device_put(p._data, sharding)
        for b in self._layers.buffers():
            b._data = jax.device_put(b._data, sharding)

    def shard_input(self, x):
        """Shard a global batch on the dp axis (leading dim)."""
        return shard_batch(x, self.group.mesh, self.group.axis_name)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return loss

    def no_sync(self):
        import contextlib

        return contextlib.nullcontext()

    # state passthrough: checkpoints are of the wrapped model
    def state_dict(self, destination=None, include_sublayers=True, prefix=""):
        return self._layers.state_dict(destination, include_sublayers, prefix)

    def set_state_dict(self, state_dict, use_structured_name=True):
        out = self._layers.set_state_dict(state_dict, use_structured_name)
        self.replicate_state()
        return out

    set_dict = set_state_dict
    load_dict = set_state_dict
