"""Comm/compute overlap for the multi-device hot path (ISSUE 6):

1. Collective-matmul decomposition for the tensor-parallel linear layers
   ("Overlap communication with computation in collective matmuls" /
   MLPerf-on-TPU-pods lineage). The row-parallel contraction's psum is
   split into per-chunk `ppermute` ring steps interleaved with the
   matmul chunks: at ring step s each device computes its partial for
   one output-row chunk and adds the accumulator arriving from its ring
   neighbor — the partial matmul for step s+1 has no data dependency on
   the incoming accumulator, so XLA's async collectives overlap each
   ppermute with the next chunk's MXU work instead of serializing one
   monolithic all-reduce after the full matmul. The column-parallel
   gather is pipelined the same way: per-row-chunk local matmuls with
   each chunk's all-gather issued while the next chunk computes.
   Enabled by `PADDLE_TP_OVERLAP=1` (default off: the r6 GSPMD
   sharding-propagation form stays the default until the overlap win is
   measured on a pod — bench.py's dp x mp pair tracks it).

2. Async DCN-hop gradient reduction ("EQuARX" motivation: the dcn hop
   is the slow, overlappable piece). The r6 hierarchical mesh leaves the
   WHOLE grad reduction to GSPMD, which (via the all-reduce combiner)
   tends to batch it after the full backward. Here the step's
   value_and_grad runs inside a `shard_map` that is MANUAL over 'dcn'
   and auto over every other axis: within a dcn group, GSPMD still owns
   the fast ici/mp collectives, while the inter-group (cross-pod) hop is
   an EXPLICIT per-gradient `lax.pmean` placed at each grad's definition
   point in the backward dataflow — so the slow collective for layer N's
   grads can start the moment layer N's backward finishes, behind the
   remaining layers' compute, and the combiner cannot sink it to the
   end. Enabled by `DistributedStrategy.async_dcn_allreduce` (requires
   `hierarchical_allreduce`). Numerically identical to the implicit
   form WHEN the loss is a fixed-divisor batch mean (the default
   `cross_entropy`/`mse_loss` reduction): an equal-sized-group mean of
   means IS the global mean (parity gated in
   tests/test_sharded_hot_path.py). A loss that is NOT such a mean —
   `reduction='sum'`, or a masked mean whose denominator (e.g. live
   token count) varies per dcn group — composes differently: the
   per-group losses are pmean'd, so a sum-reduced loss comes out
   scaled by 1/dcn and a variable-denominator mean is biased toward
   small-denominator groups. Keep the default batch-mean reduction (or
   any per-element loss whose divisor is the same on every dcn shard)
   under this flag.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import comm

__all__ = [
    "tp_overlap_enabled", "in_manual_dcn", "row_parallel_overlap",
    "column_gather_overlap", "dcn_value_and_grad",
]


def tp_overlap_enabled() -> bool:
    v = os.environ.get("PADDLE_TP_OVERLAP", "0").strip().lower()
    return v not in ("", "0", "false", "off")


# True while dcn_value_and_grad traces its manual-over-'dcn' body. The
# hot-path routers (attention._shard_plan, norm._fused_ln_route,
# row_overlap_plan) consult it and decline: opening a NESTED shard_map
# whose specs mention the already-manual 'dcn' axis is ill-formed, so
# inside the async-dcn region the model composes through its dense /
# implicit-GSPMD forms (routing is a trace-time Python decision, which
# is exactly when this flag is set).
_MANUAL_DCN = False


def in_manual_dcn() -> bool:
    return _MANUAL_DCN


def _dp_row_axes(mesh, rows, chunks):
    """Row-shard spec element for the overlap region: the dp axes when
    the flattened row count tiles (rows % dp == 0 and the local rows
    still split into `chunks`); None when the mesh has no size>1 dp axis
    (rows replicated is exact — there is no dp redundancy); False when
    dp axes exist but the rows don't tile over them — the caller must
    DECLINE, because a shard_map with rows unsharded would all-gather
    the dp-sharded activation onto every dp replica and recompute the
    full matmul dp times, regressing below the un-overlapped form."""
    axes = tuple(
        a for a in comm.DP_AXES
        if a in mesh.shape and int(mesh.shape[a]) > 1
    )
    if not axes:
        return None
    deg = 1
    for a in axes:
        deg *= int(mesh.shape[a])
    if rows % deg or (rows // deg) % chunks:
        return False
    return axes[0] if len(axes) == 1 else axes


def row_overlap_plan(mesh, rows):
    """Eligibility for the overlapped TP matmuls: returns
    (mp, row_spec_elem) or None when the shapes don't chunk (mp must be
    >1 and the per-device rows must split into mp ring chunks)."""
    if in_manual_dcn():
        return None  # no nested shard_map inside the async-dcn region
    if mesh is None or "mp" not in mesh.shape:
        return None
    mp = int(mesh.shape["mp"])
    if mp <= 1:
        return None
    for ax in comm.partitioning_axes(mesh):
        # pp/sp carry stage-/sequence-LOCAL activations: a shard_map
        # over the job-wide mesh would assert replication that does not
        # hold (pipeline stages that rebind a pp-free submesh pass it)
        if ax not in comm.DP_AXES + ("mp",):
            return None
    row_ax = _dp_row_axes(mesh, rows, mp)
    if row_ax is False:
        return None  # dp-sharded rows that don't tile: decline
    local_rows = rows
    if row_ax is not None:
        for a in (row_ax if isinstance(row_ax, tuple) else (row_ax,)):
            local_rows //= int(mesh.shape[a])
    if local_rows % mp:
        return None
    return mp, row_ax


def _row_ring_body(xl, wl, bl, *, n, axis):
    """Per-device body: xl [Rl, in/mp], wl [in/mp, out], bl [out]|None.
    Reduce-scatter ring over row chunks + chunk all-gather:

    step s: device d computes its partial for chunk c = (d - s) mod n,
    adds the accumulator ppermuted in from d-1 (which carries the
    partials of devices d-s..d-1 for the same chunk), and passes it on.
    After n-1 steps device d owns the fully-reduced chunk (d+1) mod n;
    the all-gather + roll reassembles row order. The partial matmul of
    step s+1 does not read the incoming accumulator, so the ppermute
    overlaps with it.
    """
    Rl, _ = xl.shape
    chunk = Rl // n
    idx = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    xr = xl.reshape(n, chunk, xl.shape[1])
    acc = None
    for s in range(n):
        c = (idx - s) % n
        xs = jnp.take(xr, c, axis=0)  # [chunk, in/mp]
        part = jax.lax.dot_general(
            xs, wl, (((1,), (0,)), ((), ())),
            preferred_element_type=xs.dtype,
        )
        acc = part if acc is None else acc + part
        if s < n - 1:
            acc = jax.lax.ppermute(acc, axis, perm)
    g = jax.lax.all_gather(acc, axis)       # [n, chunk, out]
    g = jnp.roll(g, 1, axis=0)              # slot c now holds chunk c
    out = g.reshape(Rl, -1)
    if bl is not None:
        out = out + bl
    return out


def row_parallel_overlap(x, w, b, mesh, mp, row_ax, axis="mp"):
    """RowParallelLinear forward with the psum decomposed into the
    overlap ring: x [..., in] (feature axis sharded over mp — or
    replicated, shard_map slices it), w [in, out] row-sharded, b [out]
    replicated (added once after the reduction). Output replicated over
    mp, rows sharded over `row_ax` when the shapes tile."""
    from .. import profiler as _prof

    shape = x.shape[:-1] + (w.shape[-1],)
    x2d = x.reshape(-1, x.shape[-1])
    with _prof.device_annotation("tp_overlap::row_ring"):
        if b is None:
            body = functools.partial(
                lambda xl, wl, **kw: _row_ring_body(xl, wl, None, **kw),
                n=mp, axis=axis,
            )
            out = comm.shard_map(
                body, mesh,
                in_specs=(P(row_ax, axis), P(axis, None)),
                out_specs=P(row_ax, None),
            )(x2d, w)
        else:
            body = functools.partial(_row_ring_body, n=mp, axis=axis)
            out = comm.shard_map(
                body, mesh,
                in_specs=(P(row_ax, axis), P(axis, None), P()),
                out_specs=P(row_ax, None),
            )(x2d, w, b)
    return out.reshape(shape)


def _col_pipeline_body(xl, wl, bl, *, n, axis):
    """Per-device body: xl [Rl, in] (full features), wl [in, out/mp],
    bl [out/mp]|None. The output gather is pipelined per row chunk:
    chunk c's all-gather is issued as soon as its local matmul is done,
    while chunk c+1 computes."""
    Rl, _ = xl.shape
    chunk = Rl // n
    outs = []
    for c in range(n):
        xs = jax.lax.dynamic_slice_in_dim(xl, c * chunk, chunk, 0)
        part = jax.lax.dot_general(
            xs, wl, (((1,), (0,)), ((), ())),
            preferred_element_type=xs.dtype,
        )
        if bl is not None:
            part = part + bl
        g = jax.lax.all_gather(part, axis)  # [n, chunk, out/mp]
        outs.append(jnp.moveaxis(g, 0, 1).reshape(chunk, -1))
    return jnp.concatenate(outs, axis=0)


def column_gather_overlap(x, w, b, mesh, mp, row_ax, axis="mp"):
    """ColumnParallelLinear (gather_output=True) forward with the output
    all-gather pipelined behind per-chunk matmuls. w [in, out]
    column-sharded, b [out] sharded over mp."""
    from .. import profiler as _prof

    shape = x.shape[:-1] + (w.shape[-1],)
    x2d = x.reshape(-1, x.shape[-1])
    with _prof.device_annotation("tp_overlap::column_gather"):
        if b is None:
            body = functools.partial(
                lambda xl, wl, **kw: _col_pipeline_body(xl, wl, None, **kw),
                n=mp, axis=axis,
            )
            out = comm.shard_map(
                body, mesh,
                in_specs=(P(row_ax, None), P(None, axis)),
                out_specs=P(row_ax, None),
            )(x2d, w)
        else:
            body = functools.partial(_col_pipeline_body, n=mp, axis=axis)
            out = comm.shard_map(
                body, mesh,
                in_specs=(P(row_ax, None), P(None, axis), P(axis)),
                out_specs=P(row_ax, None),
            )(x2d, w, b)
    return out.reshape(shape)


# ---------------------------------------------------------------------------
# async DCN-hop gradient reduction
# ---------------------------------------------------------------------------


def dcn_value_and_grad(loss_of, mesh, p_raws, key, in_raws, label_raws,
                       quant=None):
    """value_and_grad of the training loss with the inter-node ('dcn')
    gradient reduction explicit and per-grad (manual over 'dcn', GSPMD
    auto over every other axis). `loss_of(p_tuple, b_raws, key, in_raws,
    label_raws) -> (loss, aux)` is TrainStep._loss_of; buffers must be
    empty (batch-statistic layers would change numerics per dcn group).

    Returns (loss, grads): loss is the global mean (a mean of the
    equal-sized per-group means), grads are the globally-reduced grads —
    numerically the implicit-GSPMD values PROVIDED the loss is a
    fixed-divisor batch mean (see module docstring: sum-reduced or
    variable-denominator losses scale/bias under the per-group pmean),
    with each grad's dcn pmean placed at its definition point in the
    backward dataflow.

    ``quant`` — a quantized_comm.resolve_policy pair ("int8"|"fp8",
    block) — swaps each grad's dcn pmean for the block-scaled
    ``quantized_pmean`` (ISSUE 10): the ici hop inside each dcn group
    stays full-width under GSPMD; each group's contribution to the slow
    inter-node exchange passes the symmetric per-block quantizer before
    the f32-master reduction (the EQuARX error model; see
    quantized_comm.quantized_pmean for why the narrow-payload
    ``quantized_allreduce`` form cannot lower in this partial-manual
    region). The per-grad placement is unchanged, so the quantized hop
    inherits the same overlap-behind-backward schedule. The loss scalar
    stays full-width.
    """
    dcn = int(mesh.shape["dcn"])
    for r in tuple(in_raws) + tuple(label_raws):
        if r.ndim == 0 or r.shape[0] % dcn:
            raise ValueError(
                "async_dcn_allreduce: every input/label needs a leading "
                f"batch dim divisible by the dcn degree {dcn}; got shape "
                f"{tuple(r.shape)}"
            )
    auto = frozenset(a for a in mesh.axis_names if a != "dcn")
    if quant is None:
        reduce_grad = lambda g: jax.lax.pmean(g, "dcn")
    else:
        # quantized_pmean, not quantized_allreduce: this region is
        # PARTIAL-manual (GSPMD auto over ici/mp) and this XLA admits
        # only all-reduce collectives in manual subgroups — see the
        # quantized_comm.quantized_pmean docstring for the trade
        from . import quantized_comm as _qc

        q_dtype, q_block = quant
        reduce_grad = lambda g: _qc.quantized_pmean(
            g, "dcn", dtype=q_dtype, block=q_block
        )

    def body(p, k, ins, lbls):
        global _MANUAL_DCN
        if k is not None:
            # decorrelate dropout/noise across dcn groups (the implicit
            # form draws one global mask; parity holds when no RNG is
            # consumed, i.e. the deterministic training step — an
            # RNG-consuming model gets per-group masks: a valid but
            # DIFFERENT sample, documented in README/strategy)
            k = jax.random.fold_in(k, jax.lax.axis_index("dcn"))
        _MANUAL_DCN = True  # routers decline nested shard_map seams
        try:
            (loss, _aux), grads = jax.value_and_grad(
                lambda pt: loss_of(pt, (), k, ins, lbls), has_aux=True
            )(p)
        finally:
            _MANUAL_DCN = False
        # the explicit dcn hop, one collective PER GRAD at the grad's
        # own position in the dataflow — schedulable behind the rest of
        # backward, un-combinable into a tail collective (full-width
        # pmean, or the block-quantized exchange under the policy)
        grads = tuple(
            g if g is None else reduce_grad(g) for g in grads
        )
        return jax.lax.pmean(loss, "dcn"), grads

    from .. import profiler as _prof

    p_specs = jax.tree_util.tree_map(lambda _: P(), tuple(p_raws))
    in_specs_ins = tuple(P("dcn") for _ in in_raws)
    in_specs_lbls = tuple(P("dcn") for _ in label_raws)
    with _prof.device_annotation("TrainStep::async_dcn"):
        if key is None:
            f = comm.shard_map(
                lambda p, ins, lbls: body(p, None, ins, lbls), mesh,
                in_specs=(p_specs, in_specs_ins, in_specs_lbls),
                out_specs=(P(), p_specs),
                auto=auto,
            )
            return f(tuple(p_raws), tuple(in_raws), tuple(label_raws))
        f = comm.shard_map(
            body, mesh,
            in_specs=(p_specs, P(), in_specs_ins, in_specs_lbls),
            out_specs=(P(), p_specs),
            auto=auto,
        )
        loss, grads = f(tuple(p_raws), key, tuple(in_raws),
                        tuple(label_raws))
        return loss, grads
