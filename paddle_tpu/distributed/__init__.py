"""paddle_tpu.distributed — Fleet-style distributed API (SURVEY.md §2.9).

Stage 4-6 build-out; env discovery lands first so io.DistributedBatchSampler
works standalone.
"""
from . import env  # noqa: F401
from .env import get_rank, get_world_size  # noqa: F401
