"""paddle_tpu.distributed — the distributed API (SURVEY.md §2.9, L10).

Reference surface: python/paddle/distributed/__init__.py (collectives,
init_parallel_env, ParallelEnv, DataParallel re-export, fleet, spawn).
TPU-native core: one device mesh + named-axis XLA collectives (comm.py)
instead of ring-id'd NCCL communicators; see comm.py / collective.py /
parallel.py docstrings for the mapping.
"""
from .comm import (  # noqa: F401
    Group,
    get_rank,
    get_world_size,
    ParallelEnv,
    get_group,
    init_parallel_env,
    is_initialized,
    new_group,
    replicate,
    shard_rank_axis,
    spmd_region,
    in_spmd_region,
)
from .collective import (  # noqa: F401
    ReduceOp,
    all_gather,
    all_reduce,
    alltoall,
    barrier,
    broadcast,
    monitored_barrier,
    reduce,
    reduce_scatter,
    scatter,
    wait,
)
from . import comm_monitor  # noqa: F401  (flight recorder, CommMonitor)
from .parallel import DataParallel  # noqa: F401
from .pipeline import PipelineLayer, PipelineParallel  # noqa: F401
from .spawn import spawn  # noqa: F401
from . import elastic  # noqa: F401  (ElasticManager, heartbeat)
from . import resharding  # noqa: F401  (ElasticStep, plan_refactoring)
# NOTE: .launch is deliberately not imported here — it is the
# `python -m paddle_tpu.distributed.launch` entry point, and importing it
# eagerly would trip runpy's re-execution warning.
from . import fleet  # noqa: F401
from .meta_parallel import (  # noqa: F401
    ColumnParallelLinear,
    ParallelGPTBlock,
    ParallelMultiHeadAttention,
    RowParallelLinear,
    VocabParallelEmbedding,
    split,
)
