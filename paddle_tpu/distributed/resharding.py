"""Elastic mesh resharding — survive rank loss without a relaunch
round trip (ISSUE 11).

PR 1's elastic runtime treats every rank loss the same way: kill the
world, respawn every rank, reload the last host checkpoint — minutes of
lost pod time per preemption. Flex-TPU's runtime-reconfigurable-dataflow
idea (PAPERS.md), lifted to the framework level, says the recovery path
for a *covered* loss should be a device-to-device reshard among
survivors instead:

- **planner** (:func:`plan_refactoring`): given the surviving rank set,
  pick a new dcn x ici (or flat dp) factoring of the mesh. Model axes
  (mp/pp/sp) keep their degree — their shards are replicated across dp
  rows, so a lost device retires its whole dp row and the planner keeps
  only intact rows (hierarchical meshes balance to the smallest
  surviving ici group: dcn2 x ici4 minus one device -> dcn2 x ici3).
- **coverage** (:func:`leaf_coverage`): a reshard is only sound when
  every shard of every state leaf still has a surviving replica. Plain
  data-parallel state (params/moments replicated over dp) is always
  covered; ZeRO-sharded state is NOT — the departed rank held the only
  copy of its slice — so those jobs take the host-checkpoint fallback,
  exactly like a dp=1 loss.
- **executor** (``TrainStep.rebind_mesh``): params, optimizer state,
  guard counters and the fp16 scaler move with ``jax.device_put`` onto
  the new mesh — an XLA device-to-device transfer program, no host
  filesystem on the happy path — and the step re-jits once (bounded
  recompile, attributed by the recompile ledger).
- **control plane** (:class:`ElasticStep` here;
  ``ElasticManager.reshard`` launcher-side): departure/arrival notices
  are consumed at a STEP BOUNDARY (the guard's async cadence makes the
  step object the natural drain point); the policy knob
  ``strategy.elastic_reshard`` selects off / ``"shrink"`` /
  ``"shrink_expand"``, with quorum and global-batch semantics in
  ``strategy.elastic_reshard_configs``.

Every reshard emits a ``reshard`` row on the telemetry bus (trigger,
survivor set, old/new factoring, bytes moved, wall seconds);
``tools/timeline.py`` renders them as duration slices.
"""
from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

__all__ = [
    "ReshardError", "RankLostError", "CoverageError", "MeshPlan",
    "plan_refactoring", "leaf_coverage", "coverage_report", "ElasticStep",
    "install_reshard_notice", "read_launcher_notices", "factoring_str",
]

_NOTICE_ENV = "PADDLE_RESHARD_NOTICE_FILE"

#: mesh axes that carry data-parallel rows (shrinkable); everything else
#: is a model axis whose degree the planner must preserve
_DP_AXES = ("dp", "dcn", "ici")


class ReshardError(RuntimeError):
    """Base of the reshard control plane's failures."""


class RankLostError(ReshardError):
    """Rank loss that cannot be absorbed in-job (policy off, quorum
    lost, or no surviving dp row) — the caller hands the job back to the
    elastic launcher's relaunch path."""


class CoverageError(ReshardError):
    """Survivors cannot reconstruct the state (a departed rank held the
    only replica of some shard) and no host-checkpoint fallback is
    registered."""


def factoring_str(dims: Dict[str, int]) -> str:
    """'dp4' / 'dcn2xici4' / 'dcn2xici3xmp2' — size-1 model axes are
    elided, dp axes always print (a shrink to dp1 must be visible)."""
    parts = [f"{a}{n}" for a, n in dims.items()
             if a in _DP_AXES or n > 1]
    return "x".join(parts) if parts else "dp1"


class MeshPlan:
    """One planned re-factoring: the survivor mesh plus bookkeeping the
    control plane and telemetry need."""

    __slots__ = ("old_mesh", "new_mesh", "old_dims", "new_dims",
                 "lost_ranks", "survivor_ranks", "dropped_ranks")

    def __init__(self, old_mesh, new_mesh, old_dims, new_dims,
                 lost_ranks, survivor_ranks, dropped_ranks):
        self.old_mesh = old_mesh
        self.new_mesh = new_mesh
        self.old_dims = old_dims      # {axis: size} of the base mesh
        self.new_dims = new_dims
        self.lost_ranks = lost_ranks          # sorted flat base ranks
        self.survivor_ranks = survivor_ranks  # ranks the new mesh uses
        self.dropped_ranks = dropped_ranks    # alive but unused (ici
        #                                       balancing remainder)

    def describe(self) -> str:
        s = (f"{factoring_str(self.old_dims)} -> "
             f"{factoring_str(self.new_dims)}")
        if self.dropped_ranks:
            s += f" (idling intact ranks {self.dropped_ranks})"
        return s


def plan_refactoring(base_mesh, lost_ranks: Sequence[int]) -> MeshPlan:
    """Factor the surviving devices of `base_mesh` into a new mesh.

    `lost_ranks` are flat indices into ``base_mesh.devices.flatten()``
    (row-major — the same order ranks are spawned in). A lost device
    retires its whole dp row: the row's mp/pp/sp peers hold shards that
    are only replicated ACROSS dp rows, so a partial row cannot compute.
    Raises :class:`RankLostError` when no complete dp row survives.
    """
    axes = list(base_mesh.axis_names)
    sizes = {a: int(base_mesh.shape[a]) for a in axes}
    dp_axes = [a for a in axes if a in _DP_AXES]
    model_axes = [a for a in axes if a not in _DP_AXES]
    if axes[:len(dp_axes)] != dp_axes:
        raise ReshardError(
            f"unsupported mesh layout {axes}: dp axes must lead "
            "(init_hybrid_mesh order)")
    devs = np.asarray(base_mesh.devices)
    n = devs.size
    lost = sorted(set(int(r) for r in lost_ranks))
    for r in lost:
        if not 0 <= r < n:
            raise ReshardError(f"lost rank {r} out of range for a "
                               f"{n}-device mesh")
    row_len = 1
    for a in model_axes:
        row_len *= sizes[a]
    n_rows = n // row_len
    lost_rows = {r // row_len for r in lost}
    row_ranks = [list(range(i * row_len, (i + 1) * row_len))
                 for i in range(n_rows)]

    new_dims = dict(sizes)
    keep_rows: List[int] = []
    dropped: List[int] = []
    if len(dp_axes) == 2:  # hierarchical dcn x ici
        ici = sizes[dp_axes[1]]
        groups = []
        for g in range(sizes[dp_axes[0]]):
            intact = [g * ici + j for j in range(ici)
                      if (g * ici + j) not in lost_rows]
            if intact:
                groups.append(intact)
        if not groups:
            raise RankLostError(
                "no intact dp row survives — world lost, fall back to "
                "the relaunch path")
        ici_new = min(len(g) for g in groups)
        for g in groups:
            keep_rows.extend(g[:ici_new])
            for row in g[ici_new:]:
                dropped.extend(row_ranks[row])
        new_dims[dp_axes[0]] = len(groups)
        new_dims[dp_axes[1]] = ici_new
    elif len(dp_axes) == 1:
        keep_rows = [i for i in range(n_rows) if i not in lost_rows]
        if not keep_rows:
            raise RankLostError(
                "no intact dp row survives — world lost, fall back to "
                "the relaunch path")
        new_dims[dp_axes[0]] = len(keep_rows)
    else:
        raise ReshardError(
            f"mesh {axes} has no dp axis to shrink — elastic resharding "
            "needs a data-parallel dimension")

    new_devs = np.stack([devs.reshape(n_rows, row_len)[i]
                         for i in keep_rows])
    shape = [new_dims[a] for a in axes]
    from jax.sharding import Mesh

    new_mesh = Mesh(new_devs.reshape(shape), tuple(axes))
    survivors = sorted(r for i in keep_rows for r in row_ranks[i])
    return MeshPlan(base_mesh, new_mesh, sizes, new_dims, lost,
                    survivors, sorted(dropped))


# ---------------------------------------------------------------------------
# coverage: can the survivors reconstruct every byte?
# ---------------------------------------------------------------------------


def leaf_coverage(arr, lost_devices: Set) -> bool:
    """True when every shard of `arr` has at least one replica on a
    device OUTSIDE `lost_devices` (jax arrays are global: the sharding's
    device->index map names who holds what)."""
    sharding = getattr(arr, "sharding", None)
    if sharding is None:
        return True  # host value — trivially covered
    try:
        imap = sharding.devices_indices_map(arr.shape)
    except Exception:  # noqa: BLE001 — exotic shardings: assume covered
        return True
    holders: Dict[tuple, Set] = {}
    for dev, idx in imap.items():
        key = tuple(
            (s.start or 0,
             s.stop if s.stop is not None else dim)
            for s, dim in zip(idx, arr.shape)
        ) if idx else ()
        holders.setdefault(key, set()).add(dev)
    return all(hs - lost_devices for hs in holders.values())


def coverage_report(leaves: Dict[str, object],
                    lost_devices: Set) -> List[str]:
    """Names of the leaves the survivors can NOT reconstruct."""
    return [name for name, arr in leaves.items()
            if not leaf_coverage(arr, lost_devices)]


def relayout_tree(tree, target_sharding):
    """Re-place every array leaf of ``tree`` onto ``target_sharding``
    with one ``device_put`` per leaf — the reshard transition's
    re-layout primitive factored out for reuse. The PR-11 reshard moves
    whole training states between meshes this way; the KV migration
    plane (ISSUE 17) moves a request's gathered cache blocks onto the
    survivor pool's placement with the same call before the compiled
    splice, so an in-process migration is device-to-device (XLA picks
    direct transfer when source and destination share a backend) rather
    than a host bounce per leaf. ``target_sharding`` may be a Sharding
    or a bare Device; None leaves pass through."""
    import jax

    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, target_sharding)
        if a is not None else a, tree)


# ---------------------------------------------------------------------------
# launcher notice channel (the SIGTERM-notice pattern from PR 1)
# ---------------------------------------------------------------------------

_notice_flag = threading.Event()


def install_reshard_notice() -> None:
    """Install the SIGUSR1 handler the elastic launcher pokes after
    writing a reshard notice (``PADDLE_RESHARD_NOTICE_FILE``). The
    handler only sets a flag — the notice is consumed at the next step
    boundary by :meth:`ElasticStep._poll_notices`. No-op off the main
    thread (the poller reads the file regardless; the signal just makes
    pickup prompt).

    Installation touches ``<notice_file>.armed``: the launcher sends
    SIGUSR1 ONLY once that marker exists — before the handler is armed
    the default SIGUSR1 disposition would TERMINATE a child still deep
    in imports/first-compile (a departure one second into the job),
    turning a survivable rank loss into a world loss."""
    if threading.current_thread() is not threading.main_thread():
        return

    def _handler(signum, frame):
        _notice_flag.set()

    try:
        signal.signal(signal.SIGUSR1, _handler)
    except (ValueError, OSError, AttributeError):
        return
    path = os.environ.get(_NOTICE_ENV)
    if path:
        try:
            with open(path + ".armed", "w"):
                pass
        except OSError:
            pass


def read_launcher_notices(offset: int = 0) -> Tuple[List[dict], int]:
    """Parse notice rows appended to ``PADDLE_RESHARD_NOTICE_FILE``
    past `offset`; returns (rows, new_offset). Torn last lines are left
    for the next poll."""
    path = os.environ.get(_NOTICE_ENV)
    if not path or not os.path.exists(path):
        return [], offset
    rows: List[dict] = []
    try:
        with open(path) as f:
            f.seek(offset)
            chunk = f.read()
    except OSError:
        return [], offset
    consumed = 0
    for line in chunk.splitlines(keepends=True):
        if not line.endswith("\n"):
            break  # torn write: retry next poll
        consumed += len(line)
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if isinstance(row, dict) and row.get("event") in (
                "depart", "return", "lend", "reclaim"):
            # "lend"/"reclaim" (ISSUE 20) are ROLE-carrying depart/
            # return rows from the live lend plane: survivors fold them
            # into the mesh like any departure; the NAMED rank reads
            # its new job off the same row (ElasticStep.role_events)
            rows.append(row)
    return rows, offset + consumed


# ---------------------------------------------------------------------------
# the control plane: a reshard-aware step wrapper
# ---------------------------------------------------------------------------


class ElasticStep:
    """Wrap a compiled ``jit.TrainStep`` with the elastic-reshard
    control plane::

        estep = resharding.ElasticStep(TrainStep(model, loss_fn, opt))
        for x, y in loader:
            loss = estep(estep.shard_input(x), estep.shard_input(y))

    Departure/arrival notices — from the ``rank`` fault-injection site,
    the launcher's notice file (SIGUSR1 + ``PADDLE_RESHARD_NOTICE_FILE``)
    or the :meth:`notify_departure`/:meth:`notify_return` API — are
    consumed at the next call, i.e. at a step boundary: the wrapped
    step's in-flight work has drained by construction (its guard reads
    ride an async cadence; the reshard syncs the pending prefetch before
    moving anything).

    Policy comes from ``strategy.elastic_reshard`` on the optimizer's
    strategy (constructor args override): ``None``/"off" re-raises every
    departure as :class:`RankLostError` (PR-1 relaunch semantics),
    ``"shrink"`` absorbs covered departures, ``"shrink_expand"`` also
    re-absorbs returning ranks back toward the original factoring.
    """

    def __init__(self, step, policy: Optional[str] = None,
                 quorum: Optional[float] = None,
                 batch: Optional[str] = None, fallback=None):
        from . import comm

        self.step = step
        strategy = getattr(step.opt, "user_defined_strategy", None)
        cfg = (dict(strategy.elastic_reshard_configs)
               if strategy is not None else {})
        if policy is None and strategy is not None:
            policy = strategy.elastic_reshard
        self.policy = (policy or "off").lower()
        if self.policy not in ("off", "shrink", "shrink_expand"):
            raise ValueError(
                f"elastic_reshard={self.policy!r}: want off|shrink|"
                "shrink_expand")
        self.quorum = float(quorum if quorum is not None
                            else cfg.get("quorum", 0.5))
        self.batch = str(batch if batch is not None
                         else cfg.get("batch", "rescale"))
        if self.batch not in ("rescale", "shrink"):
            raise ValueError(
                f"elastic_reshard batch={self.batch!r}: want "
                "rescale|shrink")
        self._fallback = fallback
        mesh = comm.hybrid_mesh()
        if mesh is None:
            group = getattr(getattr(step, "model", None), "group", None)
            mesh = group.mesh if group is not None \
                else comm._default_group().mesh
        self._base_mesh = mesh
        self._base_devices = list(np.asarray(mesh.devices).reshape(-1))
        self._had_hybrid = comm.hybrid_mesh() is not None
        self.mesh = mesh
        self._lost: Set[int] = set()
        self._queued: List[Tuple[str, Optional[int]]] = []
        #: live-lend role notices naming THIS rank (ISSUE 20): dicts
        #: like ``{"role": "serve", "ckpt": ..., "event": "lend"}``
        #: appended in arrival order — the training loop drains them
        #: via :meth:`pending_role` and switches jobs at the same step
        #: boundary the survivors reshard at
        self.role_events: List[dict] = []
        self._self_rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._notice_offset = 0
        self._per_rank_batch: Optional[int] = None
        self.reshards = 0
        if os.environ.get(_NOTICE_ENV):
            # launched under a reshard-aware ElasticManager: arm the
            # SIGUSR1 prompt-pickup handler before the first poke
            install_reshard_notice()

    # -- public notice API -------------------------------------------------
    def notify_departure(self, ranks) -> None:
        """Queue a departure notice (consumed at the next step
        boundary). `ranks` are flat indices into the ORIGINAL mesh."""
        for r in np.atleast_1d(ranks):
            self._queued.append(("depart", int(r)))

    def notify_return(self, ranks) -> None:
        for r in np.atleast_1d(ranks):
            self._queued.append(("return", int(r)))

    def pending_role(self):
        """Pop the oldest live-lend role notice addressed to THIS rank
        (``{"role": "serve"|"train", ...}``), or None. A "serve" role
        means the launcher lent this rank to the serving plane: the
        training loop should stop stepping, load serving weights (the
        row's ``ckpt`` names the PR-18 ``load_quantized`` artifact) and
        run the worker; "train" is the reclaim — rejoin the gang."""
        return self.role_events.pop(0) if self.role_events else None

    @property
    def live_ranks(self) -> List[int]:
        return [r for r in range(len(self._base_devices))
                if r not in self._lost]

    def dp_size(self) -> int:
        from . import comm

        return comm.dp_size(self.mesh) if len(self.mesh.axis_names) > 1 \
            else int(self.mesh.size)

    # -- input sharding (global-batch semantics) ---------------------------
    def shard_input(self, x):
        """Shard a global batch over the CURRENT mesh. Under
        ``batch="rescale"`` the global batch is preserved (the per-rank
        share grows after a shrink; divisibility asserted). Under
        ``batch="shrink"`` the fed batch is trimmed to the original
        per-rank share x the current dp — a smaller global batch."""
        from ..core.tensor import Tensor
        from .parallel import shard_batch

        raw = x._data if isinstance(x, Tensor) else np.asarray(x)
        dp = self.dp_size()
        if self._per_rank_batch is None:
            if raw.shape[0] % dp:
                raise ValueError(
                    f"global batch {raw.shape[0]} does not divide the "
                    f"dp degree {dp}")
            self._per_rank_batch = raw.shape[0] // dp
        if self.batch == "shrink":
            want = self._per_rank_batch * dp
            if raw.shape[0] > want:
                raw = raw[:want]
        if raw.shape[0] % dp:
            if self.batch == "rescale":
                raise ValueError(
                    f"elastic_reshard batch='rescale' preserves the "
                    f"global batch, but {raw.shape[0]} does not divide "
                    f"the post-reshard dp degree {dp}; feed a divisible "
                    f"global batch or use batch='shrink'")
            raise ValueError(
                f"batch of {raw.shape[0]} rows does not divide the "
                f"current dp degree {dp} (elastic_reshard "
                f"batch='shrink' trims to {self._per_rank_batch} rows "
                f"per rank; feed at least that many per live rank)")
        return shard_batch(raw, self.mesh)

    # -- the step-boundary hook --------------------------------------------
    def __call__(self, inputs, labels=None):
        n = self.reshards
        self._poll_notices()
        if self.reshards != n:
            # the caller sharded this batch BEFORE the notice landed —
            # re-lay it out on the post-reshard mesh (and re-apply the
            # batch policy: a "shrink" job trims to the new global batch)
            inputs = self._reshard_batch(inputs)
            labels = self._reshard_batch(labels)
        return self.step(inputs, labels)

    def _reshard_batch(self, xs):
        if xs is None:
            return None
        single = not isinstance(xs, (list, tuple))
        out = [self.shard_input(x) for x in ([xs] if single else xs)]
        return out[0] if single else type(xs)(out)

    def _poll_notices(self) -> None:
        from ..utils import fault_injection as _FI

        events = [(a, r, "fault") for a, r in _FI.consume_rank_events()]
        if self._queued:
            events.extend((a, r, "api") for a, r in self._queued)
            self._queued = []
        if _notice_flag.is_set() or os.environ.get(_NOTICE_ENV):
            _notice_flag.clear()
            rows, self._notice_offset = read_launcher_notices(
                self._notice_offset)
            for row in rows:
                ev = row["event"]
                if ev in ("lend", "reclaim"):
                    # live lend plane (ISSUE 20): mesh-wise a lend IS a
                    # departure and a reclaim IS a return; the named
                    # rank additionally learns its new job
                    ranks = [int(r) for r in row.get("ranks", [])]
                    if self._self_rank in ranks:
                        self.role_events.append(dict(
                            row, role=("serve" if ev == "lend"
                                       else "train")))
                    ev = "depart" if ev == "lend" else "return"
                    events.extend((ev, r, "launcher") for r in ranks)
                    continue
                events.extend((ev, int(r), "launcher")
                              for r in row.get("ranks", []))
        if not events:
            return
        # fold the events into the lost set IN ORDER (a return followed
        # by a depart of the same rank nets out to "still lost" — batch
        # processing by kind would resurrect it), then make at most ONE
        # transition to the net state
        net_lost = set(self._lost)
        n = len(self._base_devices)
        trigger = "api"
        first = True
        for action, rank, src in events:
            if rank is None:
                live = [r for r in range(n) if r not in net_lost]
                rank = max(live) if action == "depart" and live \
                    else (max(net_lost) if net_lost else None)
            if rank is None:
                continue
            if action == "depart":
                net_lost.add(int(rank))
            elif self.policy == "shrink_expand":
                net_lost.discard(int(rank))
            if first:
                trigger = src
                first = False
        added = net_lost - self._lost
        if added:
            self._handle_departure(sorted(net_lost), sorted(added),
                                   trigger=trigger)
        elif net_lost != self._lost:
            self._handle_return(sorted(net_lost), trigger=trigger)

    # -- state-leaf inventory ----------------------------------------------
    def _state_leaves(self) -> Dict[str, object]:
        step = self.step
        leaves: Dict[str, object] = {}
        for i, p in enumerate(step._p_objs):
            leaves[f"param:{p.name or i}"] = p._data
        for name, b in zip(step._b_names, step._b_objs):
            leaves[f"buffer:{name}"] = b._data
        inner = getattr(step.opt, "_inner", step.opt)
        names = {id(p): (p.name or str(i))
                 for i, p in enumerate(step._p_objs)}
        for acc, store in getattr(inner, "_accumulators", {}).items():
            if isinstance(store, dict):
                for pid, v in store.items():
                    leaves[f"opt:{names.get(pid, pid)}.{acc}"] = v
        for i, v in enumerate(step._scaler_state or ()):
            leaves[f"scaler:{i}"] = v
        if step._guard is not None and len(step._guard_state):
            leaves["guard:state"] = step._guard_state
        return leaves

    @staticmethod
    def _bytes_of(leaves: Dict[str, object]) -> int:
        total = 0
        for v in leaves.values():
            total += int(getattr(v, "nbytes", 0) or 0)
        return total

    # -- the reshard transitions -------------------------------------------
    def _handle_departure(self, net_lost: List[int], newly: List[int],
                          trigger: str) -> None:
        n = len(self._base_devices)
        if self.policy == "off":
            raise RankLostError(
                f"rank(s) {newly} departed and "
                "strategy.elastic_reshard is off — rank loss is a job "
                "failure (elastic relaunch path)")
        if (n - len(net_lost)) / n < self.quorum:
            raise RankLostError(
                f"quorum lost: {n - len(net_lost)}/{n} survivors < "
                f"quorum {self.quorum} — world loss, relaunch path")
        plan = plan_refactoring(self._base_mesh, net_lost)
        lost_devices = {self._base_devices[r] for r in newly}
        leaves = self._state_leaves()
        uncovered = coverage_report(leaves, lost_devices)
        self._transition(plan, trigger, uncovered, leaves, lost=net_lost)

    def _handle_return(self, net_lost: List[int], trigger: str) -> None:
        plan = plan_refactoring(self._base_mesh, net_lost)
        leaves = self._state_leaves()
        # expansion is always covered: all state lives on survivors,
        # which remain members of the grown mesh
        self._transition(plan, trigger, [], leaves, lost=net_lost)

    def _transition(self, plan: MeshPlan, trigger: str,
                    uncovered: List[str], leaves: Dict[str, object],
                    lost: List[int]) -> None:
        from . import comm
        from ..observability import bus as _bus

        import jax

        step = self.step
        cur_dims = {a: int(self.mesh.shape[a])
                    for a in self.mesh.axis_names}
        t0 = time.perf_counter()
        # drain: the step boundary is the barrier — sync the guard's
        # in-flight async prefetch and the dispatched device work
        if step._guard is not None:
            step._guard._sync_pending()
        try:
            jax.block_until_ready([p._data for p in step._p_objs])
        except Exception:  # noqa: BLE001 — drain stays best-effort
            pass
        fallback_used = False
        if uncovered:
            if self._fallback is None and not self._has_rescue_target():
                raise CoverageError(
                    f"survivors cannot cover {len(uncovered)} state "
                    f"leaf/leaves (e.g. {uncovered[:3]}) and no "
                    "host-checkpoint fallback is registered — pass "
                    "fallback= or iterate a TrainEpochRange")
            fallback_used = True
        bytes_moved = self._bytes_of(leaves)
        if self._had_hybrid:
            comm.set_hybrid_mesh(plan.new_mesh)
            from .fleet.base import fleet as _fleet

            if _fleet._hcg is not None:  # topology accessor follows
                _fleet._hcg.mesh = plan.new_mesh
        comm.rebuild_world(list(
            np.asarray(plan.new_mesh.devices).reshape(-1)))
        model = getattr(step, "model", None)
        group = getattr(model, "group", None)
        if group is not None:  # DataParallel wrapper follows the world
            model.group = comm._default_group()
        step.rebind_mesh(plan.new_mesh)
        if fallback_used:
            # the uncoverable shards are gone: reload the last host
            # checkpoint INTO the new layout (the one filesystem read
            # this subsystem is built to avoid on the happy path)
            if self._fallback is not None:
                self._fallback()
            else:
                from ..utils import train_guard as _TG

                _TG._rescue_target().restore()
            step.rebind_mesh(plan.new_mesh)  # re-place restored values
        self._lost = set(lost)
        self.mesh = plan.new_mesh
        self.reshards += 1
        wall = time.perf_counter() - t0
        payload = {
            "trigger": trigger,
            "lost": lost,
            "survivors": plan.survivor_ranks,
            "dropped": plan.dropped_ranks,
            "old": factoring_str(cur_dims),
            "new": factoring_str(plan.new_dims),
            "covered": not uncovered,
            "fallback": fallback_used,
            "uncovered": uncovered[:8],
            "bytes_moved": bytes_moved,
            "wall_s": round(wall, 4),
        }
        _bus.emit("reshard", payload)
        import sys

        print(f"paddle_tpu.resharding: {factoring_str(cur_dims)} -> "
              f"{factoring_str(plan.new_dims)} "
              f"({'fallback' if fallback_used else 'device-to-device'}, "
              f"{bytes_moved / 1e6:.3f} MB state, {wall:.2f}s)",
              file=sys.stderr, flush=True)

    @staticmethod
    def _has_rescue_target() -> bool:
        from ..utils import train_guard as _TG

        return _TG._rescue_target() is not None
