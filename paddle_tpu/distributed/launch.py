"""Distributed launch runner.

Reference: python/paddle/distributed/fleet/launch.py — launch() :334,
launch_collective :208 (build Cluster from env/args, spawn one subprocess
per device with PADDLE_TRAINER_ID / PADDLE_TRAINER_ENDPOINTS /
FLAGS_selected_gpus, then a watch loop that tears the job down when any
proc dies — launch_utils.py:996-1118 TrainerProc management).

TPU-native: one process PER HOST (not per chip — a jax process owns all
its local chips), `PADDLE_TRAINER_ENDPOINTS`'s first entry doubling as
the jax.distributed coordinator address (the gen_comm_id TCP-bootstrap
analog). `--nproc_per_node > 1` exists for CPU-backend testing where each
proc simulates a host.

Usage::

    python -m paddle_tpu.distributed.launch --nproc_per_node=2 \
        [--ips=h1,h2] [--start_port=6170] train.py [args...]
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import Dict, List

from .elastic import ElasticManager

__all__ = ["launch", "build_cluster_env", "main"]


def build_cluster_env(nproc: int, ips: str = "127.0.0.1",
                      start_port: int = 6170,
                      base_env: Dict[str, str] = None) -> List[Dict[str, str]]:
    """Per-rank environment blocks (launch_utils.py get_cluster analog).

    Endpoints are host:port pairs, rank-major across hosts; rank 0's
    endpoint is the coordinator address.
    """
    if nproc < 1:
        raise ValueError(f"nproc must be >= 1, got {nproc}")
    hosts = [h.strip() for h in ips.split(",") if h.strip()]
    if not hosts:
        raise ValueError(f"no hosts parsed from ips={ips!r}")
    endpoints = []
    for host in hosts:
        for p in range(nproc):
            endpoints.append(f"{host}:{start_port + p}")
    envs = []
    for rank, ep in enumerate(endpoints):
        env = dict(base_env if base_env is not None else os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(len(endpoints)),
            "PADDLE_CURRENT_ENDPOINT": ep,
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        })
        envs.append(env)
    return envs


def launch(script: str, script_args: List[str], nproc_per_node: int = 1,
           ips: str = "127.0.0.1", start_port: int = 6170,
           backend: str = None, node_rank: int = None,
           elastic_retries: int = 0, watchdog_timeout: float = None,
           log_dir: str = None, coll_timeout: float = None,
           reshard: str = None, reshard_quorum: float = None,
           monitor: bool = None, ctl: str = None) -> int:
    """Spawn THIS node's ranks and babysit them (launch_collective :208).

    `node_rank` selects which host of `ips` this invocation is (default
    env PADDLE_NODE_RANK, else 0); only that host's ranks spawn here —
    remote hosts run the same command with their own node_rank. Returns
    the first non-zero exit code (0 on full success); on any failure the
    remaining ranks are terminated (the watch-loop teardown).

    Fault tolerance is delegated to :class:`~.elastic.ElasticManager`:

    - `elastic_retries` > 0 relaunches the WHOLE job after a failure
      (budgeted per PADDLE_ELASTIC_WINDOW, exponential backoff with
      jitter) — scripts resume from their auto-checkpoint
      (incubate.checkpoint.TrainEpochRange) so a preempted/crashed rank
      costs at most the epochs since the last snapshot. Children see
      the attempt index in PADDLE_LAUNCH_ATTEMPT.
    - `watchdog_timeout` (or PADDLE_WATCHDOG_TIMEOUT) > 0 kills ranks
      whose PADDLE_HEARTBEAT_FILE goes stale that many seconds — a hung
      rank counts as a failure and consumes a restart.
    - `log_dir` (or PADDLE_LOG_DIR) captures each rank's output to
      `workerlog.N` (launch_utils.py behavior).
    - SIGTERM to the launcher is forwarded to every rank (the
      preemption notice); no relaunch follows.
    - `coll_timeout` (or PADDLE_COLL_TIMEOUT in the ranks' env) arms the
      per-collective watchdog (distributed/comm_monitor.py): a rank
      wedged in a collective dumps its flight recorder, writes a
      machine-readable event, and exits; the manager's relaunch log
      attributes the kill to the named collective instead of a generic
      hang. The manager always exports PADDLE_COLL_EVENT_FILE,
      PADDLE_COLL_SYNC_DIR (monitored_barrier / desync exchange), and
      PADDLE_COLL_DEBUG_DIR (dumps land next to the workerlogs).
    - `reshard` (or PADDLE_RESHARD_MODE) = "shrink"/"shrink_expand"
      turns a quorum-holding rank loss into an IN-JOB event: the dead
      rank retires, survivors get a reshard notice
      (PADDLE_RESHARD_NOTICE_FILE + SIGUSR1, consumed by
      distributed/resharding.ElasticStep at a step boundary) and keep
      training on a re-factored mesh — no teardown, no checkpoint
      round trip. `reshard_quorum` (or PADDLE_RESHARD_QUORUM, default
      0.5) is the minimum surviving fraction; below it the loss is a
      world loss and the relaunch path above applies.
    - `monitor` (or PADDLE_MON, default on) embeds the live fleet
      monitor (observability/monitor.py) in the manager whenever an
      observability dir exists (`log_dir` or PADDLE_OBS_DIR): per-rank
      stream tailing, straggler ranking, percentile digests, and
      `incident` rows correlating co-occurring failures across ranks —
      flushed before launch() returns.
    - `ctl` (or PADDLE_CTL, default off) = "dryrun" embeds the
      train-serve co-tenancy controller (distributed/fleet_controller.py)
      next to the monitor: the hysteresis state machine samples the
      monitor's serving aggregates every control window and journals
      lend/reclaim decisions (ctl_lend/ctl_reclaim rows, crash
      recoverable) to the launcher bus stream — without actuating, since
      the training step and serving engine live in the children.
      "live" (ISSUE 20) additionally wires the phase-ladder actuators:
      a committed lend really walks the chosen dp row through
      depart → deliver → join (and a reclaim through
      drain → leave → rejoin), each phase its own crash-recoverable
      journal pair; requires reshard != "off" (the depart/rejoin
      phases ride the reshard notice channel).
    """
    if node_rank is None:
        node_rank = int(os.environ.get("PADDLE_NODE_RANK", "0"))
    hosts = [h.strip() for h in ips.split(",") if h.strip()]
    if not 0 <= node_rank < len(hosts):
        raise ValueError(
            f"node_rank {node_rank} out of range for {len(hosts)} hosts"
        )
    envs = build_cluster_env(nproc_per_node, ips=ips, start_port=start_port)
    lo = node_rank * nproc_per_node
    envs = envs[lo:lo + nproc_per_node]
    mgr = ElasticManager(
        script, list(script_args), envs, backend=backend,
        max_restarts=int(elastic_retries),
        watchdog_timeout=watchdog_timeout, log_dir=log_dir,
        coll_timeout=coll_timeout, reshard=reshard,
        reshard_quorum=reshard_quorum, monitor=monitor,
        controller=ctl,
    )
    return mgr.run()


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="spawn per-host training processes (fleet launch analog)",
    )
    parser.add_argument("--nproc_per_node", type=int, default=1)
    parser.add_argument("--node_rank", type=int, default=None,
                        help="index of this host in --ips "
                             "(default: $PADDLE_NODE_RANK or 0)")
    parser.add_argument("--ips", type=str, default="127.0.0.1")
    parser.add_argument("--start_port", type=int,
                        default=int(os.environ.get("PADDLE_PORT", 6170)))
    parser.add_argument("--backend", type=str, default=None,
                        help="force a jax backend in children (e.g. cpu)")
    parser.add_argument("--elastic_retries", type=int, default=0,
                        help="relaunch the whole job up to N times per "
                             "rolling PADDLE_ELASTIC_WINDOW after a "
                             "failure (auto-checkpoint resumes)")
    parser.add_argument("--watchdog_timeout", type=float, default=None,
                        help="seconds without a rank heartbeat before the "
                             "watchdog recycles it (default: "
                             "$PADDLE_WATCHDOG_TIMEOUT, 0 = off)")
    parser.add_argument("--log_dir", type=str, default=None,
                        help="capture each rank's output to "
                             "<log_dir>/workerlog.N (default: "
                             "$PADDLE_LOG_DIR, unset = inherit stdio)")
    parser.add_argument("--coll_timeout", type=float, default=None,
                        help="per-collective deadline in seconds for the "
                             "ranks' comm monitor (default: children's "
                             "$PADDLE_COLL_TIMEOUT, 0 = off); a stalled "
                             "collective dumps the flight recorder and "
                             "recycles the rank with attribution")
    parser.add_argument("--reshard", type=str, default=None,
                        choices=("off", "shrink", "shrink_expand"),
                        help="turn a quorum-holding rank loss into an "
                             "in-job reshard notice instead of a world "
                             "relaunch (default: $PADDLE_RESHARD_MODE "
                             "or off)")
    parser.add_argument("--reshard_quorum", type=float, default=None,
                        help="minimum surviving fraction for an in-job "
                             "reshard (default: $PADDLE_RESHARD_QUORUM "
                             "or 0.5)")
    parser.add_argument("--monitor", type=str, default=None,
                        choices=("on", "off"),
                        help="embed the live fleet monitor when an "
                             "observability dir exists (default: "
                             "$PADDLE_MON or on)")
    parser.add_argument("--ctl", type=str, default=None,
                        choices=("off", "dryrun", "live"),
                        help="embed the co-tenancy fleet controller "
                             "(dryrun journals only; live drives the "
                             "lend phase ladder against the children; "
                             "default: $PADDLE_CTL or off)")
    parser.add_argument("script", type=str)
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    rc = launch(
        args.script, args.script_args, nproc_per_node=args.nproc_per_node,
        ips=args.ips, start_port=args.start_port, backend=args.backend,
        node_rank=args.node_rank, elastic_retries=args.elastic_retries,
        watchdog_timeout=args.watchdog_timeout, log_dir=args.log_dir,
        coll_timeout=args.coll_timeout, reshard=args.reshard,
        reshard_quorum=args.reshard_quorum,
        monitor=(None if args.monitor is None
                 else args.monitor == "on"),
        ctl=args.ctl,
    )
    sys.exit(rc)


if __name__ == "__main__":
    main()
