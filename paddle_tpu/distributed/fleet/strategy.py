"""DistributedStrategy — the single distributed-config surface.

Reference: python/paddle/distributed/fleet/base/distributed_strategy.py:104
(protobuf-backed, distributed_strategy.proto:122) with per-feature bool +
`*_configs` dict pairs, prototxt save/load (:145,:163).

TPU-native: a plain config object (SURVEY.md §5 config tiers — dataclass
configs). Feature flags select sharding/transform passes applied by
fleet.distributed_model / distributed_optimizer over the one hybrid mesh;
fields that configure NCCL ring mechanics (nccl_comm_num, fuse sizes) are
accepted for script parity and ignored — XLA's collective combiner owns
bucketing.
"""
from __future__ import annotations

import copy
import json


_DEFAULTS = {
    # feature flags + configs (reference field names)
    "amp": False,
    "amp_configs": {
        "init_loss_scaling": 32768.0,
        "incr_every_n_steps": 1000,
        "decr_every_n_nan_or_inf": 2,
        "incr_ratio": 2.0,
        "decr_ratio": 0.5,
        "use_dynamic_loss_scaling": True,
        "custom_white_list": [],
        "custom_black_list": [],
        "use_pure_fp16": False,
        "use_bf16": True,  # TPU-first default
    },
    "recompute": False,
    "recompute_configs": {"checkpoints": []},
    "sharding": False,
    "sharding_configs": {
        "sharding_degree": 8, "stage": 1, "fuse_broadcast_MB": 32.0,
        "hybrid_dp": False,
    },
    "pipeline": False,
    "pipeline_configs": {
        "micro_batch_size": 1, "accumulate_steps": 1, "schedule_mode": "1F1B",
    },
    "tensor_parallel": False,
    "tensor_parallel_configs": {"tensor_parallel_degree": 1},
    "gradient_merge": False,
    "gradient_merge_configs": {"k_steps": 1, "avg": True},
    "fp16_allreduce": False,
    "localsgd": False,
    "localsgd_configs": {"k_steps": 1, "begin_step": 1},
    "lamb": False,
    "lamb_configs": {"lamb_weight_decay": 0.01, "exclude_from_weight_decay": []},
    "lars": False,
    "lars_configs": {
        "lars_coeff": 0.001, "lars_weight_decay": 0.0005,
        "epsilon": 0.0, "exclude_from_weight_decay": [],
    },
    "hybrid_configs": {
        "dp_degree": 1, "mp_degree": 1, "pp_degree": 1, "sp_degree": 1,
    },
    # two-level grad reduction (reference: hierarchical_allreduce +
    # hierarchical_allreduce_inter_nranks inter/exter NCCL ring split).
    # TPU-native: fleet.init factors the dp mesh axis into dcn x ici
    # (inter_nranks = the fast inner degree; 0 = auto dp//2), and every
    # dp-sharded spec/reduction uses the axis pair — GSPMD then emits the
    # reduction per level instead of one flat ring across both fabrics.
    "hierarchical_allreduce": False,
    "hierarchical_allreduce_inter_nranks": 0,
    # async cross-pod grad reduction (EQuARX lineage: the dcn hop is the
    # slow, overlappable piece). The compiled TrainStep runs its
    # value_and_grad manual over the 'dcn' mesh axis (GSPMD keeps the
    # fast ici/mp collectives) with an explicit per-grad pmean at each
    # grad's definition point in the backward dataflow — the inter-node
    # reduction for layer N starts when layer N's backward finishes,
    # behind the remaining layers' compute, instead of being combined
    # into one tail collective. Requires hierarchical_allreduce (the
    # dcn x ici mesh factoring). Numerically identical to the implicit
    # form for deterministic steps whose loss is a fixed-divisor batch
    # MEAN (an equal-group mean of means IS the global mean — but a
    # reduction='sum' loss comes out scaled 1/dcn, and a masked mean
    # with per-group denominators is biased: keep the default mean
    # reduction under this flag); RNG-consuming models (dropout) draw
    # decorrelated per-dcn-group masks — a valid but different sample. The
    # Pallas/TP-overlap seams decline inside the manual-over-dcn
    # backward region (nested shard_map over a manual axis is
    # ill-formed): the model composes through its dense forms there.
    "async_dcn_allreduce": False,
    # block-scaled quantized grad allreduce (EQuARX, PAPERS.md):
    # "int8" | "fp8" narrows the grad-comm payload with symmetric
    # per-block (quantized_allreduce_block-wide) scales exchanged
    # alongside it, f32 master apply (distributed/quantized_comm.py).
    # Composed with hierarchical_allreduce the policy quantizes ONLY the
    # slow dcn hop — the step routes through the manual-over-'dcn' seam
    # (dcn_value_and_grad) where each grad's inter-node exchange is an
    # explicit quantized collective (ici stays full-width under GSPMD),
    # inheriting that seam's constraints (buffer-free model, no fp16
    # dynamic loss scaling, fixed-divisor batch-mean loss). On a flat dp
    # mesh / eager steps the policy is the boundary round trip at the
    # comm seam (the fp16_allreduce contract at int8/fp8 width). One
    # width policy at a time: combining with fp16_allreduce raises.
    "quantized_allreduce": None,
    "quantized_allreduce_block": 128,
    # quantization plane round 2 (ISSUE 19) — COMPUTE-side widths on the
    # same block-scaled primitives. quantized_matmul = "int8" | "fp8"
    # arms the fake-quant matmul route at the F.linear seam for the
    # compiled TrainStep's forward (QAT: forward sees the block-quantized
    # weight, backward is straight-through to the wide master —
    # distributed/quantized_compute.py); PADDLE_Q_MATMUL is the ambient
    # env twin for eager/serving. quantized_moments = "int8" | "fp8"
    # stores Adam/AdamW moments as narrow payload + per-block f32 scales
    # (dequant-update-requant inside the compiled apply; Adam-family
    # only, raises with fp16_allreduce — two lossy width policies on the
    # same grad->moment path compound). Both default off; with both off
    # every step is bitwise identical to pre-round-19 behavior.
    "quantized_matmul": None,
    "quantized_moments": None,
    # dgc (top-k sparsified allreduce) is DEPRECATED on TPU: setting it
    # routes to quantized_allreduce="int8" with a warning — the
    # TPU-native bandwidth-reduction analog (SURVEY §5; VERDICT row 33)
    "dgc": False,
    # elastic mesh resharding (ISSUE 11): how the job reacts when a rank
    # departs mid-training. None/"off" keeps the PR-1 semantics (rank
    # loss = job failure; the elastic launcher relaunches the world from
    # the last checkpoint). "shrink" turns a covered departure into an
    # in-job event: survivors re-factor the dcn x ici mesh, move
    # params/optimizer state/scaler/guard counters device-to-device
    # (distributed/resharding.py — no host filesystem on the happy
    # path), rebuild the compiled step on the smaller mesh, and resume.
    # "shrink_expand" additionally re-absorbs returning ranks back to
    # the original factoring. `elastic_reshard_configs`:
    #   quorum — minimum surviving fraction for an in-job reshard; below
    #            it the event is a world loss (relaunch path);
    #   batch  — "rescale": the caller keeps feeding the SAME global
    #            batch (per-rank batch grows; global-batch-preserving —
    #            must stay divisible by the new dp, asserted), or
    #            "shrink": ElasticStep trims each fed batch to the old
    #            per-rank share x the new dp (smaller global batch).
    "elastic_reshard": None,
    "elastic_reshard_configs": {"quorum": 0.5, "batch": "rescale"},
    "a_sync": False,
    # parity-accepted, no-op on TPU (XLA owns comm fusion/scheduling)
    "fuse_all_reduce_ops": True,
    "fuse_grad_size_in_MB": 32,
    "nccl_comm_num": 1,
    "find_unused_parameters": False,
    "without_graph_optimization": False,
    "last_comm_group_size_MB": 1,
}


class DistributedStrategy:
    """reference: distributed_strategy.py:104."""

    def __init__(self):
        self.__dict__["_conf"] = copy.deepcopy(_DEFAULTS)

    def __getattr__(self, name):
        conf = self.__dict__["_conf"]
        if name in conf:
            return conf[name]
        raise AttributeError(name)

    def __setattr__(self, name, value):
        conf = self.__dict__["_conf"]
        if name not in conf:
            raise AttributeError(
                f"DistributedStrategy has no field '{name}' "
                f"(known: {sorted(conf)})"
            )
        if name.endswith("_configs"):
            if not isinstance(value, dict):
                raise TypeError(f"{name} expects a dict")
            known = set(_DEFAULTS[name])
            unknown = set(value) - known
            if unknown:
                # check_configs_key analog (distributed_strategy.py) —
                # typos must not silently disable a parallelism mode
                raise ValueError(
                    f"unknown key(s) {sorted(unknown)} for {name}; "
                    f"known: {sorted(known)}"
                )
            merged = dict(conf[name])
            merged.update(value)
            conf[name] = merged
        else:
            conf[name] = value

    def to_dict(self):
        return copy.deepcopy(self._conf)

    # prototxt-shaped round trip (reference :145 save_to_prototxt /
    # :163 load_from_prototxt) — json here, same contract.
    def save_to_prototxt(self, output: str):
        with open(output, "w") as f:
            json.dump(self._conf, f, indent=2, sort_keys=True)

    def load_from_prototxt(self, pb_file: str):
        with open(pb_file) as f:
            loaded = json.load(f)
        for k, v in loaded.items():
            if k not in self._conf:
                continue
            if k.endswith("_configs") and isinstance(v, dict):
                merged = dict(self._conf[k])
                merged.update(v)  # partial files keep defaults for the rest
                self._conf[k] = merged
            else:
                self._conf[k] = v

    def __repr__(self):
        on = [k for k, v in self._conf.items()
              if isinstance(v, bool) and v and k != "fuse_all_reduce_ops"]
        return f"DistributedStrategy(enabled={on})"
