"""LocalSGD: per-worker local updates + periodic parameter averaging.

Reference: fleet/meta_optimizers/localsgd_optimizer.py:23 (LocalSGD) — each
worker steps independently and every `k_steps` the workers average their
parameters (c_allreduce_sum / nranks), replacing the per-step gradient
all-reduce (:194 builds the averaging comm block).

TPU-native: divergent per-worker parameters are a leading `dp` axis on
every param/state leaf, sharded over the mesh's dp axis; ONE compiled
shard_map program runs the local forward/backward/update per worker slice
and a `lax.pmean` over 'dp', selected by a traced `sync` flag, implements
the periodic averaging. The host never materializes per-worker copies.

Reached through the standard hot path: `jit.TrainStep(model, loss, opt)`
delegates here when `opt.user_defined_strategy.localsgd` is on.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core import autograd as AG
from ...core.tensor import Tensor
from ...jit.functional_call import _swapped
from ...nn.layer import Layer
from ...utils import train_guard as _TG
from .. import comm


def _as_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


class LocalSGDStep:
    """Compiled LocalSGD train step (localsgd_optimizer.py:23 analog).

    `optimizer` may be the fleet wrapper; only its inner pure update rule
    is used (LocalSGD owns the comm schedule). Parameters diverge across
    the dp axis between syncs; `model.state_dict()` is wrapped at
    construction to call `sync_to_model()` first, so checkpoints always
    see the averaged weights.
    """

    def __init__(self, model: Layer, loss_fn: Callable, optimizer, *,
                 k_steps: int = 1, begin_step: int = 1,
                 grad_post_hook: Callable = None):
        mesh = comm.hybrid_mesh()
        if mesh is not None and any(
            mesh.shape[a] != 1 for a in ("mp", "pp", "sp")
        ):
            raise NotImplementedError(
                "localsgd composes with pure data parallelism only"
            )
        group = comm._default_group()
        self.mesh = group.mesh
        self.axis = group.axis_name
        self.dp = group.nranks
        self.model = model
        self.loss_fn = loss_fn
        self.opt = optimizer
        self._inner = getattr(optimizer, "_inner", optimizer)
        self.k_steps = int(k_steps)
        self.begin_step = int(begin_step)
        self._grad_post_hook = grad_post_hook
        self._p_objs = [p for p in self._inner._get_params() if p.trainable]
        b_named = dict(model.named_buffers())
        self._b_objs = list(b_named.values())
        stack = lambda r: jax.device_put(
            jnp.broadcast_to(r[None], (self.dp,) + r.shape),
            NamedSharding(self.mesh, P(self.axis)),
        )
        self._stk_p = [stack(p._data) for p in self._p_objs]
        self._stk_b = [stack(b._data) for b in self._b_objs]
        state = self._inner._functional_state(self._p_objs)
        self._stk_state = {
            name: tuple(stack(v) for v in vals)
            for name, vals in state.items()
        }
        # numerical guard (utils/train_guard.py): same sentinel as
        # TrainStep, computed per worker slice through the shared
        # process_grads seam and combined with a lax.pmin so every
        # replica skips (or applies) the step together — a desynced
        # skip would make the next pmean average healthy params with
        # stale ones
        self._guard_mode = _TG.guard_mode()
        self._guard = (_TG.TrainGuard(mode=self._guard_mode, model=model)
                       if self._guard_mode != "off" else None)
        self._guard_state = ()
        if self._guard is not None:
            self._guard._on_rollback = self._after_rollback
            # replicated on the dp mesh: a single-device carry among
            # mesh-placed operands would retrace the step on call 2
            self._guard_state = jax.device_put(
                _TG.init_guard_state(), NamedSharding(self.mesh, P()))
        # sync is STATIC (host-known): two cached compilations, and the
        # non-sync program contains NO collective at all — the whole point
        # of LocalSGD's reduced communication. The recompile ledger
        # (observability/ledger.py) records both expected compiles —
        # anything past two is a real miss worth a bus row.
        from ...observability import ledger as _ledger

        self._jitted = _ledger.instrument(
            jax.jit(self._step_fn, static_argnums=8),
            label="LocalSGDStep",
        )
        self._n_steps = 0
        self._dirty = False
        # checkpoint consumers must see averaged weights: state_dict pulls
        # the replicas back into the Layer first
        orig_state_dict = model.state_dict

        def _synced_state_dict(*a, **kw):
            self.sync_to_model()
            return orig_state_dict(*a, **kw)

        model.state_dict = _synced_state_dict

    # -- the pure spmd program ----------------------------------------------
    def _step_fn(self, stk_p, stk_state, stk_b, in_raws, label_raws, lr, t,
                 guard_state, sync):
        spec_of = lambda tree: jax.tree_util.tree_map(
            lambda _: P(self.axis), tree
        )
        f = comm.shard_map(
            lambda p, st, b, i, l, lr_, t_: self._worker(
                p, st, b, i, l, lr_, t_, sync
            ),
            self.mesh,
            in_specs=(
                spec_of(stk_p), spec_of(stk_state), spec_of(stk_b),
                spec_of(list(in_raws)), spec_of(list(label_raws)),
                P(), P(),
            ),
            out_specs=(
                P(), spec_of(stk_p), spec_of(stk_state), spec_of(stk_b),
                (P(), P(), P()),
            ),
        )
        loss, new_p, new_st, new_b, health = f(
            stk_p, stk_state, stk_b, list(in_raws), list(label_raws),
            lr, t)
        if self._guard is not None:
            ok, bits, gnorm = health
            guard_state, ok_apply = _TG.update_guard_state(
                guard_state, ok, bits, gnorm, loss
            )
            # the gnorm-spike verdict (ok_apply) needs the EWMA state,
            # which lives out here — mask the STACKED outputs against
            # the stacked inputs so a finite grad-norm explosion is
            # still a no-op before it applies, same as TrainStep
            # (nonfinite steps were already masked in-worker; for them
            # this select is an identity)
            new_p = _TG.mask_step(ok_apply, new_p, list(stk_p))
            new_st = _TG.mask_step(ok_apply, new_st, stk_state)
            new_b = _TG.mask_step(ok_apply, new_b, list(stk_b))
        return loss, new_p, new_st, new_b, guard_state

    def _worker(self, p_stk, st_stk, b_stk, ins, labels, lr, t, sync):
        p_loc = [q[0] for q in p_stk]
        b_loc = [q[0] for q in b_stk]
        st_loc = jax.tree_util.tree_map(lambda v: v[0], st_stk)

        def loss_of(p_tuple):
            with AG.trace_mode(), comm.spmd_region(self.axis), \
                    _swapped(self._p_objs + self._b_objs,
                             list(p_tuple) + b_loc):
                outs = self.model(*[Tensor._wrap(r) for r in ins])
                loss = self.loss_fn(
                    outs, *[Tensor._wrap(r) for r in labels]
                )
                loss_raw = loss._data if isinstance(loss, Tensor) else loss
                new_b = tuple(b._data for b in self._b_objs)
            return loss_raw, new_b

        (loss, new_b), grads = jax.value_and_grad(
            loss_of, has_aux=True
        )(tuple(p_loc))
        from ...jit.train_step import process_grads

        grads = process_grads(
            self._inner, self._p_objs, p_loc, list(grads),
            self._grad_post_hook,
        )
        new_p, new_st = self._inner._functional_update(
            self._p_objs, p_loc, grads, st_loc, lr, t
        )
        if self._guard is not None:
            # per-worker sentinel, job-wide verdict: ANY worker tripping
            # skips the step on EVERY worker (pmin), so the replicas
            # stay element-wise comparable for the next pmean
            ok, bits, gnorm = _TG.grad_health(loss, grads, new_p)
            ok = jax.lax.pmin(ok.astype(jnp.int32), self.axis) == 1
            bits = jax.lax.pmax(bits, self.axis)
            gnorm = jax.lax.pmax(gnorm, self.axis)
            health = (ok, bits, gnorm)
        else:
            ok = None
            health = (jnp.asarray(True), jnp.asarray(0.0, jnp.float32),
                      jnp.asarray(0.0, jnp.float32))
        # the periodic c_allreduce_sum/nranks of params (:194); `sync` is
        # static, so non-sync steps compile with no collective at all
        if sync:
            new_p = [jax.lax.pmean(v, self.axis) for v in new_p]
            new_b = [jax.lax.pmean(v, self.axis) for v in new_b]
        if ok is not None:
            # mask AFTER the sync average: a skipped step must skip the
            # whole step INCLUDING the comm — even over bitwise-equal
            # replicas a pmean costs an ulp (sequential f32
            # accumulation), which would break the no-op guarantee; the
            # deferred average simply runs at the next healthy sync
            new_p = _TG.mask_step(ok, list(new_p), p_loc)
            new_st = _TG.mask_step(ok, new_st, st_loc)
            new_b = _TG.mask_step(ok, list(new_b), b_loc)
        loss_mean = jax.lax.pmean(loss, self.axis)
        return (
            loss_mean,
            [v[None] for v in new_p],
            jax.tree_util.tree_map(lambda v: v[None], new_st),
            [v[None] for v in new_b],
            health,
        )

    # -- eager entry ---------------------------------------------------------
    def __call__(self, inputs, labels=None):
        in_raws = tuple(
            x._data if isinstance(x, Tensor) else jnp.asarray(x)
            for x in _as_list(inputs)
        )
        label_raws = tuple(
            y._data if isinstance(y, Tensor) else jnp.asarray(y)
            for y in _as_list(labels)
        )
        opt = self._inner
        opt._step_count += 1
        t = opt._step_count
        sync = t >= self.begin_step and t % self.k_steps == 0
        if self._guard is not None:
            self._guard.capture(None, in_raws, label_raws)
        from ... import profiler as _prof
        from ...observability import bus as _bus

        self._n_steps += 1
        _bus.set_step(self._n_steps)
        _prof.step_boundary(self._n_steps)
        (loss, self._stk_p, self._stk_state, self._stk_b,
         self._guard_state) = self._jitted(
            self._stk_p, self._stk_state, self._stk_b,
            in_raws, label_raws,
            jnp.asarray(opt.get_lr(), jnp.float32),
            jnp.asarray(t, jnp.float32),
            self._guard_state,
            bool(sync),
        )
        self._dirty = True
        if self._guard is not None:
            # on rollback the _on_rollback hook (-> _after_rollback)
            # restacks the replicas and re-seeds the guard carry
            self._guard.observe(self._guard_state)
        return Tensor._wrap(loss, stop_gradient=True)

    def flops_per_step(self):
        """Cost-analysis FLOPs are not derived for the LocalSGD program
        (two cached compilations, stacked-replica operands) — report
        None rather than a wrong number."""
        return None

    def _after_rollback(self):
        """Guard rollback hook: the checkpoint restored the LAYER's
        params; rebuild the per-worker replicas and guard carry."""
        self._restack()
        self._guard_state = jax.device_put(
            self._guard.restored_device_state(),
            NamedSharding(self.mesh, P()))

    def _restack(self):
        """Re-broadcast the Layer's (restored) params/buffers/opt state
        into the per-worker stacked replicas."""
        stack = lambda r: jax.device_put(
            jnp.broadcast_to(r[None], (self.dp,) + r.shape),
            NamedSharding(self.mesh, P(self.axis)),
        )
        self._stk_p = [stack(p._data) for p in self._p_objs]
        self._stk_b = [stack(b._data) for b in self._b_objs]
        state = self._inner._functional_state(self._p_objs)
        self._stk_state = {
            name: tuple(stack(v) for v in vals)
            for name, vals in state.items()
        }
        self._dirty = False

    def sync_to_model(self):
        """Average the per-worker replicas back into the Layer's params
        (what a checkpoint/state_dict consumer must see)."""
        if not self._dirty:
            return
        for p, stk in zip(self._p_objs, self._stk_p):
            p._data = jnp.mean(stk, axis=0).astype(stk.dtype)
            p._node = None
            p.grad = None
        for b, stk in zip(self._b_objs, self._stk_b):
            b._data = jnp.mean(stk, axis=0).astype(stk.dtype)
        state = {
            name: tuple(
                jnp.mean(v, axis=0).astype(v.dtype) for v in vals
            )
            for name, vals in self._stk_state.items()
        }
        self._inner._load_functional_state(self._p_objs, state)
        self._dirty = False
