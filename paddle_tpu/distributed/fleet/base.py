"""Fleet: the unified distributed-training facade.

Reference: python/paddle/distributed/fleet/base/fleet_base.py — `fleet.init`
(:130), `distributed_model` (:598 docs region), `distributed_optimizer`
(:598), `minimize` (:1070) composing meta-optimizers picked by
StrategyCompiler over DistributedStrategy; topology via role_maker.

TPU-native: init declares the hybrid mesh (axes dp/pp/sp/mp) from
strategy.hybrid_configs; distributed_model lays parameters out on it
(tensor-parallel params keep their 'mp' sharding, the rest replicate);
distributed_optimizer wraps the user optimizer with the strategy so the
fused TrainStep / minimize path applies sharding (ZeRO), gradient merge,
etc. as sharding specs and step transforms — program rewriting passes are
not needed because XLA partitions the one traced program.
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from ...nn.layer import Layer
from .. import comm
from ..parallel import DataParallel
from .strategy import DistributedStrategy


class HybridCommunicateGroup:
    """Topology accessors (reference: fleet/base/topology.py
    HybridCommunicateGroup in the fleet lineage; 2.0's equivalent info
    lives in role_maker + meta-optimizer ring setup)."""

    def __init__(self, mesh):
        self.mesh = mesh

    def _size(self, axis):
        return self.mesh.shape[axis] if self.mesh is not None else 1

    def get_data_parallel_world_size(self):
        return self._size("dp")

    def get_model_parallel_world_size(self):
        return self._size("mp")

    def get_pipe_parallel_world_size(self):
        return self._size("pp")

    def get_sequence_parallel_world_size(self):
        return self._size("sp")

    # single-controller SPMD: the driving process is logical rank 0 of
    # every axis; per-device ranks exist only inside compiled programs.
    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def topology(self):
        return {k: v for k, v in self.mesh.shape.items()}


class _DistributedOptimizer:
    """Strategy-carrying optimizer wrapper (fleet_base.py:598
    distributed_optimizer / :1070 minimize)."""

    def __init__(self, optimizer, strategy: DistributedStrategy):
        self._inner = optimizer
        self.user_defined_strategy = strategy

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def step(self):
        return self._inner.step()

    def clear_grad(self):
        return self._inner.clear_grad()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        return self._inner.minimize(loss, startup_program, parameters,
                                    no_grad_set)


class Fleet:
    def __init__(self):
        self._is_initialized = False
        self._strategy: Optional[DistributedStrategy] = None
        self._hcg: Optional[HybridCommunicateGroup] = None

    # -- lifecycle -----------------------------------------------------------
    def init(self, role_maker=None, is_collective=True, strategy=None):
        """fleet_base.py:130. Collective mode only (PS is out of the TPU
        north star, SURVEY.md §2.9)."""
        if not is_collective:
            raise NotImplementedError(
                "parameter-server mode is out of scope on TPU; "
                "use is_collective=True"
            )
        comm.init_parallel_env()
        self._strategy = strategy or DistributedStrategy()
        hc = self._strategy.hybrid_configs
        dp, mp = int(hc["dp_degree"]), int(hc["mp_degree"])
        pp, sp = int(hc["pp_degree"]), int(hc["sp_degree"])
        if self._strategy.tensor_parallel and mp == 1:
            mp = int(
                self._strategy.tensor_parallel_configs[
                    "tensor_parallel_degree"]
            )
        ndev = len(jax.devices())
        if dp == 1 and ndev % (mp * pp * sp) == 0:
            # dp fills whatever the other degrees leave (reference fleet
            # infers dp from world size; explicit dp_degree overrides)
            dp = ndev // (mp * pp * sp)
        if dp * mp * pp * sp != ndev:
            raise ValueError(
                f"hybrid topology dp={dp} x pp={pp} x sp={sp} x mp={mp} = "
                f"{dp * mp * pp * sp} does not cover the {ndev} devices of "
                "this job; set hybrid_configs degrees whose product (with "
                "dp inferred when left at 1) equals the device count"
            )
        mesh = comm.init_hybrid_mesh(dp=dp, mp=mp, pp=pp, sp=sp)
        self._hcg = HybridCommunicateGroup(mesh)
        self._is_initialized = True
        return self

    @property
    def is_initialized(self):
        return self._is_initialized

    def _require_init(self):
        if not self._is_initialized:
            raise RuntimeError("call fleet.init() first")

    # -- role/topology info (fleet_base.py worker API) -----------------------
    def worker_index(self):
        return comm.ParallelEnv().rank

    def worker_num(self):
        import jax as _jax

        return _jax.process_count()

    def is_first_worker(self):
        return self.worker_index() == 0

    def is_worker(self):
        return True

    def is_server(self):
        return False

    def worker_endpoints(self, to_string=False):
        import os

        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
        eps = [e for e in eps if e]
        return ",".join(eps) if to_string else eps

    def barrier_worker(self):
        from .. import collective

        collective.barrier()

    def stop_worker(self):
        return None

    def get_hybrid_communicate_group(self):
        self._require_init()
        return self._hcg

    # -- the model/optimizer decorators --------------------------------------
    def distributed_model(self, model: Layer):
        """Lay the model out on the hybrid mesh (fleet_base.py
        distributed_model ≙ DataParallel wrap; here also the TP layout
        pass): tensor-parallel params keep their 'mp' spec, everything else
        replicates; inputs shard over 'dp' via .shard_input."""
        self._require_init()
        mesh = self._hcg.mesh
        for p in model.parameters():
            spec = getattr(p, "_tp_spec", None)
            if spec is not None:
                p._data = jax.device_put(p._data, NamedSharding(mesh, spec))
            else:
                p._data = jax.device_put(p._data, NamedSharding(mesh, P()))
        for b in model.buffers():
            b._data = jax.device_put(b._data, NamedSharding(mesh, P()))
        outer = self

        class _FleetModel(Layer):
            def __init__(self, inner):
                super().__init__()
                self._layers = inner

            def forward(self, *a, **kw):
                return self._layers(*a, **kw)

            def shard_input(self, x):
                raw = x._data if isinstance(x, Tensor) else None
                if raw is None:
                    import jax.numpy as jnp

                    raw = jnp.asarray(x)
                sharded = jax.device_put(
                    raw, NamedSharding(outer._hcg.mesh, P("dp"))
                )
                return Tensor._wrap(sharded, stop_gradient=True)

            def state_dict(self, destination=None, include_sublayers=True,
                           prefix=""):
                return self._layers.state_dict(
                    destination, include_sublayers, prefix
                )

            def set_state_dict(self, state_dict, use_structured_name=True):
                return self._layers.set_state_dict(
                    state_dict, use_structured_name
                )

        if isinstance(model, DataParallel):
            return model
        return _FleetModel(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        self._require_init()
        if strategy is not None:
            self._strategy = strategy
        return _DistributedOptimizer(optimizer, self._strategy)


fleet = Fleet()
