"""Fleet: the unified distributed-training facade.

Reference: python/paddle/distributed/fleet/base/fleet_base.py — `fleet.init`
(:130), `distributed_model` (:598 docs region), `distributed_optimizer`
(:598), `minimize` (:1070) composing meta-optimizers picked by
StrategyCompiler over DistributedStrategy; topology via role_maker.

TPU-native: init declares the hybrid mesh (axes dp/pp/sp/mp) from
strategy.hybrid_configs; distributed_model lays parameters out on it
(tensor-parallel params keep their 'mp' sharding, the rest replicate);
distributed_optimizer wraps the user optimizer with the strategy so the
fused TrainStep / minimize path applies sharding (ZeRO), gradient merge,
etc. as sharding specs and step transforms — program rewriting passes are
not needed because XLA partitions the one traced program.
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from ...nn.layer import Layer
from .. import comm
from ..parallel import DataParallel
from .strategy import DistributedStrategy


class HybridCommunicateGroup:
    """Topology accessors (reference: fleet/base/topology.py
    HybridCommunicateGroup in the fleet lineage; 2.0's equivalent info
    lives in role_maker + meta-optimizer ring setup)."""

    def __init__(self, mesh):
        self.mesh = mesh

    def _size(self, axis):
        if self.mesh is None:
            return 1
        if axis == "dp":  # flat axis or the hierarchical dcn x ici pair
            return comm.dp_size(self.mesh)
        return self.mesh.shape[axis]

    def get_data_parallel_world_size(self):
        return self._size("dp")

    def get_model_parallel_world_size(self):
        return self._size("mp")

    def get_pipe_parallel_world_size(self):
        return self._size("pp")

    def get_sequence_parallel_world_size(self):
        return self._size("sp")

    # single-controller SPMD: the driving process is logical rank 0 of
    # every axis; per-device ranks exist only inside compiled programs.
    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def topology(self):
        return {k: v for k, v in self.mesh.shape.items()}


class _DistributedOptimizer:
    """Strategy-composing optimizer wrapper (fleet_base.py:598
    distributed_optimizer / :1070 minimize + the meta-optimizer chain).

    Where the reference rewrites the program per strategy
    (sharding_optimizer.py:33 prunes non-owned states and inserts
    broadcast/allreduce; fluid/optimizer.py:5402 GradientMerge builds a
    cond-guarded update block), here each strategy composes into the pure
    update that the fused TrainStep traces:
      * sharding (ZeRO): optimizer-state (stage>=1), grad (stage>=2) and
        param (stage 3) leaves get sharding constraints over the 'dp' axis
        — XLA partitions storage and inserts the gather on use.
      * gradient_merge: a grad-accumulator buffer + counter ride in the
        functional state; the inner update applies every k-th step under
        jnp.where selection.
    The eager step() path honors gradient_merge by skipping inner.step()
    on non-boundary steps (grads keep accumulating on .grad).
    """

    def __init__(self, optimizer, strategy: DistributedStrategy):
        object.__setattr__(self, "_inner", optimizer)
        object.__setattr__(self, "user_defined_strategy", strategy)
        object.__setattr__(self, "_gm_calls", 0)
        # set by jit.TrainStep when it routes the quantized grad comm
        # through the explicit manual-over-'dcn' exchange — the boundary
        # round trip here must then stand down (quantizing twice would
        # double the error the parity gates budget for once)
        object.__setattr__(self, "_quant_explicit", False)

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_inner"), name)

    def __setattr__(self, name, value):
        if name in ("_inner", "user_defined_strategy", "_gm_calls",
                    "_quant_explicit"):
            object.__setattr__(self, name, value)
        else:
            setattr(self._inner, name, value)  # e.g. _step_count, _lr

    # -- strategy pieces -----------------------------------------------------
    @property
    def _gm_k(self) -> int:
        s = self.user_defined_strategy
        return int(s.gradient_merge_configs["k_steps"]) if s.gradient_merge \
            else 1

    @property
    def _gm_avg(self) -> bool:
        return bool(self.user_defined_strategy.gradient_merge_configs["avg"])

    def _zero_constrain(self, x, pad=False):
        """Shard a state leaf over dp on the FIRST dp-divisible axis.

        Leaves with no dp-divisible axis (e.g. a [30522, 12] embedding on
        dp=8) are handled per ``pad``: storage leaves (``pad=True`` —
        optimizer state at stage>=1, params at stage 3) are PADDED on
        their largest axis to the next shard multiple and sharded evenly
        (the pad-to-divisible of the reference's sharding/shard.py owner
        assignment, done in the framework because this XLA silently
        *drops* uneven sharding constraints — probed in
        test_sharding_gm); transient leaves (grads) keep the best-effort
        uneven constraint, which a GSPMD that supports it may honor.
        Scalars and tiny leaves (< one tile) stay replicated —
        distributing <1KiB costs more in collective latency than it
        saves."""
        mesh = getattr(self, "_constrain_mesh", None) or comm.hybrid_mesh()
        if mesh is None:
            return x
        dp = comm.dp_size(mesh)
        dp_ax = comm.dp_axes(mesh)  # 'dp', or ('dcn','ici') hierarchical

        def constrain(v, axis):
            spec = P(*(
                [None] * axis + [dp_ax] + [None] * (v.ndim - axis - 1)
            ))
            return jax.lax.with_sharding_constraint(
                v, NamedSharding(mesh, spec)
            )

        for axis in range(x.ndim):
            if x.shape[axis] % dp == 0 and x.shape[axis] > 0:
                return constrain(x, axis)
        if x.ndim > 0 and x.size >= 1024:
            axis = int(max(range(x.ndim), key=lambda a: x.shape[a]))
            if pad:
                import jax.numpy as jnp

                target = -(-x.shape[axis] // dp) * dp
                widths = [(0, target - x.shape[a]) if a == axis else (0, 0)
                          for a in range(x.ndim)]
                return constrain(jnp.pad(x, widths), axis)
            return constrain(x, axis)
        return x

    # -- ZeRO pad-to-shard-multiple storage (ISSUE 11 satellite) ----------
    # A leaf with NO dp-divisible axis cannot be stored evenly sharded,
    # and this XLA silently drops uneven sharding constraints — so such
    # leaves were silently replicated (the stage3-odd-embedding tier-1
    # failure). Storage is now padded to the shard multiple on the
    # largest axis; math unpads at the use site (TrainStep unpads params
    # before the forward "gather"; _functional_update unpads state to the
    # grad shapes). Checkpoints stay at LOGICAL shapes: Tensor.numpy()
    # slices the pad off and set_value re-pads (core/tensor.py).

    def _leaf_pad_plan(self, p):
        """(axis, logical_extent, padded_extent) for a param whose state
        (and, at stage 3, the param itself) needs padded storage under
        the current mesh — None when an even sharding exists (or no mesh,
        or the leaf is too small to distribute)."""
        mesh = getattr(self, "_constrain_mesh", None) or comm.hybrid_mesh()
        if mesh is None or comm.dp_size(mesh) <= 1:
            return None
        dp = comm.dp_size(mesh)
        shape = list(p._data.shape)
        zp = getattr(p, "_zero_pad", None)
        if zp is not None:
            shape[zp[0]] = zp[1]  # logical extent of the padded axis
        if any(d % dp == 0 and d > 0 for d in shape):
            return None
        size = 1
        for d in shape:
            size *= d
        if not shape or size < 1024:
            return None
        axis = int(max(range(len(shape)), key=lambda a: shape[a]))
        return axis, shape[axis], -(-shape[axis] // dp) * dp

    def _dp_sharding(self, mesh, ndim, axis):
        dp_ax = comm.dp_axes(mesh)
        spec = P(*([None] * axis + [dp_ax] + [None] * (ndim - axis - 1)))
        return NamedSharding(mesh, spec)

    def _apply_zero_padding(self, params):
        """Stage 3: pad each uneven param's storage to the shard multiple
        and lay it out dp-sharded EAGERLY (stable jit signature from call
        one; the in-graph constraint keeps it sharded). Marks the param
        with ``_zero_pad = (axis, logical_extent)`` — the contract every
        unpad site (forward gather, numpy()/set_value, reshard) reads.

        Known limitation (documented in the README): the padded physical
        shape lives in ``p._data``, so EAGER forward of such a leaf
        between compiled steps sees the padded extent (embedding row
        lookups tolerate it; a shape-coupled op like a matmul does not).
        Stage-3 training runs through the compiled step, which unpads at
        the gather; eager evaluation should go through a checkpoint
        round-trip (state_dict exports logical shapes) or a model built
        without stage-3 sharding."""
        if self._sharding_stage < 3:
            return
        mesh = getattr(self, "_constrain_mesh", None) or comm.hybrid_mesh()
        if mesh is None:
            return
        import jax.numpy as jnp

        for p in params:
            plan = self._leaf_pad_plan(p)
            if plan is None or getattr(p, "_zero_pad", None) is not None:
                continue
            axis, logical, target = plan
            widths = [(0, target - logical) if a == axis else (0, 0)
                      for a in range(p._data.ndim)]
            p._data = jax.device_put(
                jnp.pad(p._data, widths),
                self._dp_sharding(mesh, p._data.ndim, axis))
            p._zero_pad = (axis, logical)

    def _strip_zero_padding(self, params):
        """Unpad padded storage back to logical shapes (the reshard seam:
        the pad multiple depends on dp, which is about to change — the
        next step/seed re-pads for the new mesh). Keyed off the RECORDED
        padding (param ``_zero_pad`` / a state leaf wider than the
        param's logical shape), never off a freshly computed plan: the
        caller may already have swapped the mesh, under which the old
        pad can look unnecessary and would be silently left in place."""
        for p in params:
            zp = getattr(p, "_zero_pad", None)
            shape = list(p._data.shape)
            if zp is not None:
                shape[zp[0]] = zp[1]
            for store in self._inner._accumulators.values():
                v = store.get(id(p)) if isinstance(store, dict) else None
                if v is None or not hasattr(v, "ndim") \
                        or v.ndim != len(shape):
                    continue
                if tuple(v.shape) != tuple(shape) and all(
                        a >= b for a, b in zip(v.shape, shape)):
                    store[id(p)] = self._unpad_to(v, shape)
            if zp is not None:
                p._data = self._unpad_to(p._data, shape)
                del p._zero_pad

    @staticmethod
    def _unpad_to(v, ref_shape):
        """Slice a (possibly padded) state leaf down to the update's
        reference shape (identity when shapes already match)."""
        if tuple(v.shape) == tuple(ref_shape):
            return v
        return v[tuple(slice(0, d) for d in ref_shape)]

    @property
    def _sharding_stage(self) -> int:
        s = self.user_defined_strategy
        return int(s.sharding_configs["stage"]) if s.sharding else 0

    def _comm_cast(self, g):
        """strategy.fp16_allreduce as a grad-COMM DTYPE policy
        (fp16_allreduce_optimizer.py:18: cast grads to half around the
        explicit NCCL all-reduce, fp32 master apply). On TPU the dp
        reduction is emitted by XLA inside the compiled step and its wire
        dtype follows the tensor dtype at the reduction point, and bf16
        is the chip-native half type — so the policy is a bf16 round
        trip at the optimizer's comm boundary: the grad value entering
        the f32 master update is exactly a bf16-width number (what a
        bf16 all-reduce would have delivered), halving grad-comm bytes
        wherever the boundary is a real wire. Non-f32 grads (already
        half, or int) pass through untouched."""
        import jax.numpy as jnp

        if g.dtype != jnp.float32:
            return g
        return g.astype(jnp.bfloat16).astype(jnp.float32)

    @property
    def _fp16_allreduce(self) -> bool:
        return bool(self.user_defined_strategy.fp16_allreduce)

    @property
    def _quant_policy(self):
        """strategy.quantized_allreduce as a validated ("int8"|"fp8",
        block) pair, or None."""
        from .. import quantized_comm as qc

        s = self.user_defined_strategy
        return qc.resolve_policy(
            s.quantized_allreduce, s.quantized_allreduce_block
        )

    def _quant_cast(self, g):
        """strategy.quantized_allreduce at the grad-comm boundary (same
        seam and contract as the bf16 _comm_cast, at block-quantized
        width): the grad value entering the f32 master update has passed
        the symmetric per-block quantizer exactly once — the error model
        of the quantized wire. Used when no explicit dcn exchange owns
        the policy (flat-dp mesh / eager steps); TrainStep sets
        _quant_explicit when the manual-over-'dcn' quantized allreduce
        is the one doing the narrowing."""
        import jax.numpy as jnp

        from .. import quantized_comm as qc

        if g.dtype != jnp.float32:
            return g
        dtype, block = self._quant_policy
        return qc.quantize_dequantize(g, dtype=dtype, block=block)

    def _comm_width_cast(self):
        """The active grad-comm width policy's cast fn, or None (one
        policy at a time — distributed_optimizer rejects combining
        fp16_allreduce with quantized_allreduce)."""
        if self._fp16_allreduce:
            return self._comm_cast
        if self._quant_policy is not None and not self._quant_explicit:
            return self._quant_cast
        return None

    # -- functional path hooks (consumed by jit.TrainStep) -------------------
    def _pad_seed_state(self, params, state):
        """Pad-seed: uneven state leaves enter the program already padded
        + dp-sharded, so the jit signature is stable from call one (a
        logical-shaped leaf appears after set_state_dict or a reshard
        stripped the pads — re-pad here)."""
        if self._sharding_stage < 1:
            return state
        mesh = getattr(self, "_constrain_mesh", None) or comm.hybrid_mesh()
        if mesh is None:
            return state
        import jax.numpy as jnp

        for name, vals in state.items():
            if not (isinstance(vals, tuple) and len(vals) == len(params)):
                continue
            store = self._inner._accumulators.get(name)
            out = []
            for p, v in zip(params, vals):
                plan = self._leaf_pad_plan(p)
                if plan is not None and v.ndim == p._data.ndim \
                        and v.shape[plan[0]] == plan[1] \
                        and plan[1] != plan[2]:
                    axis, logical, target = plan
                    widths = [(0, target - logical) if a == axis
                              else (0, 0) for a in range(v.ndim)]
                    v = jax.device_put(
                        jnp.pad(v, widths),
                        self._dp_sharding(mesh, v.ndim, axis))
                    if isinstance(store, dict):
                        store[id(p)] = v
                out.append(v)
            state[name] = tuple(out)
        return state

    def _functional_state(self, params):
        state = self._inner._functional_state(params)
        if self._gm_k > 1:
            import jax.numpy as jnp

            if "@gm_buf" not in self._inner._accumulators:
                self._inner._accumulators["@gm_buf"] = {}
            buf_store = self._inner._accumulators["@gm_buf"]
            bufs = []
            for p in params:
                if id(p) not in buf_store:
                    z = jnp.zeros_like(p._data)
                    sh = getattr(p._data, "sharding", None)
                    if sh is not None:  # match param placement (no retrace)
                        z = jax.device_put(z, sh)
                    buf_store[id(p)] = z
                bufs.append(buf_store[id(p)])
            state["@gm_buf"] = tuple(bufs)
            state["@gm_cnt"] = jnp.asarray(self._gm_calls, jnp.int32)
        return self._pad_seed_state(params, state)

    def _load_functional_state(self, params, state):
        state = dict(state)
        if "@gm_buf" in state:
            buf_store = self._inner._accumulators.setdefault("@gm_buf", {})
            for p, v in zip(params, state.pop("@gm_buf")):
                buf_store[id(p)] = v
            self._gm_calls = int(state.pop("@gm_cnt"))
            # TrainStep's opt._step_count counts micro-steps; the inner
            # optimizer's public count is applied updates
            self._inner._step_count = self._gm_calls // self._gm_k
        self._inner._load_functional_state(params, state)

    def _functional_update(self, params, p_raws, g_raws, state, lr, t):
        import jax.numpy as jnp

        stage = self._sharding_stage
        k = self._gm_k
        state = dict(state)
        gm_buf = state.pop("@gm_buf", None)
        gm_cnt = state.pop("@gm_cnt", None)
        if stage >= 1:
            # padded-storage leaves come down to the update's reference
            # shapes (the traced p_raws — themselves padded at stage 3,
            # where the whole update runs in padded space: pad rows carry
            # g=0/m=0/v=0, so every elementwise rule is exact there)
            refs = [r.shape for r in p_raws]
            state = {
                name: tuple(self._unpad_to(v, r)
                            for v, r in zip(vals, refs))
                if isinstance(vals, tuple) and len(vals) == len(p_raws)
                else vals
                for name, vals in state.items()
            }
            if gm_buf is not None:
                gm_buf = [self._unpad_to(b, r)
                          for b, r in zip(gm_buf, refs)]

        width_cast = self._comm_width_cast()
        if width_cast is not None:
            g_raws = [g if g is None else width_cast(g) for g in g_raws]

        if stage >= 2:
            g_raws = [g if g is None else self._zero_constrain(g)
                      for g in g_raws]

        if k > 1:
            new_buf = [
                b if g is None else b + g for b, g in zip(gm_buf, g_raws)
            ]
            boundary = (gm_cnt + 1) % k == 0
            scale = 1.0 / k if self._gm_avg else 1.0
            merged = [
                None if g is None else (b * scale).astype(b.dtype)
                for g, b in zip(g_raws, new_buf)
            ]
            # inner step count = APPLIED updates, not micro-steps, so
            # Adam-family bias correction matches the eager path (which
            # calls inner.step() only at boundaries)
            t_inner = ((gm_cnt + 1) // k).astype(t.dtype)
            new_p, new_state = self._inner._functional_update(
                params, p_raws, merged, state, lr, t_inner
            )
            # select: params/state advance only at the boundary; the buffer
            # resets there (cond-guarded block analog, optimizer.py:5402)
            new_p = tuple(
                jnp.where(boundary, np_, p_)
                for np_, p_ in zip(new_p, p_raws)
            )
            new_state = {
                name: tuple(
                    jnp.where(boundary, nv, ov)
                    for nv, ov in zip(new_state[name], state[name])
                )
                for name in new_state
            }
            new_buf = [
                jnp.where(boundary, jnp.zeros_like(b), b) for b in new_buf
            ]
            new_state["@gm_buf"] = tuple(new_buf)
            new_state["@gm_cnt"] = gm_cnt + 1
        else:
            new_p, new_state = self._inner._functional_update(
                params, p_raws, g_raws, state, lr, t
            )

        if stage >= 1:
            new_state = {
                name: tuple(self._zero_constrain(v, pad=True) for v in vals)
                if isinstance(vals, tuple) else vals  # @gm_cnt scalar rides
                for name, vals in new_state.items()
            }
        if stage >= 3:
            new_p = tuple(self._zero_constrain(v, pad=True) for v in new_p)
        return new_p, new_state

    def state_dict(self):
        """Padded ZeRO storage exports at LOGICAL shapes (the checkpoint
        contract — a snapshot must restore into any sharding config)."""
        out = self._inner.state_dict()
        params = self._inner._get_params()
        name_of = {(p.name or f"param_{i}"): p
                   for i, p in enumerate(params)}
        for key, val in list(out.items()):
            pname, _, _acc = key.rpartition(".")
            p = name_of.get(pname)
            if p is None or not isinstance(val, Tensor):
                continue
            shape = list(p._data.shape)
            zp = getattr(p, "_zero_pad", None)
            if zp is not None:
                shape[zp[0]] = zp[1]
            if val._data.ndim == len(shape) \
                    and tuple(val._data.shape) != tuple(shape) \
                    and all(a >= b for a, b in zip(val._data.shape, shape)):
                out[key] = Tensor._wrap(self._unpad_to(val._data, shape))
        return out

    # -- eager path ----------------------------------------------------------
    def _comm_cast_grads(self, cast):
        for p in self._inner._get_params():
            if p.grad is not None:
                p.grad._data = cast(p.grad._data)

    def step(self):
        k = self._gm_k
        width_cast = self._comm_width_cast()
        if k > 1:
            self._gm_calls += 1
            if self._gm_calls % k != 0:
                return  # keep accumulating on .grad (paddle dygraph accum)
            if self._gm_avg:
                for p in self._inner._get_params():
                    if p.grad is not None:
                        p.grad._data = p.grad._data / k
            # ONE width round trip (bf16 or block-quantized) on the
            # merged grad at the apply boundary — casting every
            # micro-step would re-quantize the running sum k times and
            # compound the error
            if width_cast is not None:
                self._comm_cast_grads(width_cast)
            out = self._inner.step()
            self._inner.clear_grad()
            return out
        if width_cast is not None:
            self._comm_cast_grads(width_cast)
        return self._inner.step()

    def clear_grad(self):
        if self._gm_k > 1 and self._gm_calls % self._gm_k != 0:
            return  # mid-merge: grads must survive across steps
        return self._inner.clear_grad()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        if parameters is not None:
            self._inner._parameter_list = list(parameters)
        # dygraph reference semantics (see Optimizer.minimize): apply
        # grads the user's own backward produced for this loss; run
        # backward only in the minimize-only idiom
        if not getattr(loss, "_backward_ran", False):
            loss.backward()
        self.step()
        return None, None


class Fleet:
    def __init__(self):
        self._is_initialized = False
        self._strategy: Optional[DistributedStrategy] = None
        self._hcg: Optional[HybridCommunicateGroup] = None

    # -- lifecycle -----------------------------------------------------------
    def init(self, role_maker=None, is_collective=True, strategy=None):
        """fleet_base.py:130. Collective mode only (PS is out of the TPU
        north star, SURVEY.md §2.9)."""
        if not is_collective:
            raise NotImplementedError(
                "parameter-server mode is out of scope on TPU; "
                "use is_collective=True"
            )
        comm.init_parallel_env()
        self._strategy = strategy or DistributedStrategy()
        hc = self._strategy.hybrid_configs
        dp, mp = int(hc["dp_degree"]), int(hc["mp_degree"])
        pp, sp = int(hc["pp_degree"]), int(hc["sp_degree"])
        if self._strategy.tensor_parallel and mp == 1:
            mp = int(
                self._strategy.tensor_parallel_configs[
                    "tensor_parallel_degree"]
            )
        ndev = len(jax.devices())
        if dp == 1 and ndev % (mp * pp * sp) == 0:
            # dp fills whatever the other degrees leave (reference fleet
            # infers dp from world size; explicit dp_degree overrides)
            dp = ndev // (mp * pp * sp)
        if dp * mp * pp * sp != ndev:
            raise ValueError(
                f"hybrid topology dp={dp} x pp={pp} x sp={sp} x mp={mp} = "
                f"{dp * mp * pp * sp} does not cover the {ndev} devices of "
                "this job; set hybrid_configs degrees whose product (with "
                "dp inferred when left at 1) equals the device count"
            )
        ici = 1
        if self._strategy.hierarchical_allreduce and dp > 1:
            ici = int(
                self._strategy.hierarchical_allreduce_inter_nranks
            )
            if ici <= 0:
                # auto: the largest proper divisor of dp — two REAL
                # levels (the reference defaults inter_nranks to the
                # 8-GPU node size; here the inner degree is a topology
                # choice the operator pins explicitly when the dcn/ici
                # boundary differs). A prime dp has no two-level
                # factoring: fail loudly rather than silently flat.
                ici = next(
                    (d for d in range(dp // 2, 1, -1) if dp % d == 0), 0
                )
                if ici < 2:
                    raise ValueError(
                        f"hierarchical_allreduce: dp_degree={dp} has no "
                        "two-level factoring (prime or 2); set "
                        "hierarchical_allreduce_inter_nranks explicitly "
                        "or disable the flag"
                    )
            if dp % ici:
                raise ValueError(
                    f"hierarchical_allreduce_inter_nranks={ici} must "
                    f"divide dp_degree={dp}"
                )
        mesh = comm.init_hybrid_mesh(dp=dp, mp=mp, pp=pp, sp=sp,
                                     dp_inner=ici)
        self._hcg = HybridCommunicateGroup(mesh)
        self._is_initialized = True
        return self

    @property
    def is_initialized(self):
        return self._is_initialized

    def _require_init(self):
        if not self._is_initialized:
            raise RuntimeError("call fleet.init() first")

    # -- role/topology info (fleet_base.py worker API) -----------------------
    def worker_index(self):
        return comm.ParallelEnv().rank

    def worker_num(self):
        import jax as _jax

        return _jax.process_count()

    def is_first_worker(self):
        return self.worker_index() == 0

    def is_worker(self):
        return True

    def is_server(self):
        return False

    def worker_endpoints(self, to_string=False):
        import os

        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
        eps = [e for e in eps if e]
        return ",".join(eps) if to_string else eps

    def barrier_worker(self):
        from .. import collective

        collective.barrier()

    def stop_worker(self):
        return None

    def get_hybrid_communicate_group(self):
        self._require_init()
        return self._hcg

    # -- the model/optimizer decorators --------------------------------------
    def distributed_model(self, model: Layer):
        """Lay the model out on the hybrid mesh (fleet_base.py
        distributed_model ≙ DataParallel wrap; here also the TP layout
        pass): tensor-parallel params keep their 'mp' spec, everything else
        replicates; inputs shard over 'dp' via .shard_input."""
        self._require_init()
        mesh = self._hcg.mesh
        from ..pipeline import PipelineLayer, PipelineParallel

        if isinstance(model, PipelineLayer):
            if mesh.shape["pp"] == 1:
                raise ValueError(
                    "PipelineLayer needs hybrid_configs pp_degree > 1"
                )
            return PipelineParallel(
                model, mesh=mesh,
                accumulate_steps=int(
                    self._strategy.pipeline_configs["accumulate_steps"]
                ),
                schedule_mode=str(
                    self._strategy.pipeline_configs.get(
                        "schedule_mode", "1F1B"
                    )
                ),
            )
        if mesh.shape["pp"] > 1:
            raise ValueError(
                "pp_degree > 1 requires the model to be a "
                "distributed.PipelineLayer (stage partition; the "
                "device_guard analog)"
            )
        for p in model.parameters():
            spec = getattr(p, "_tp_spec", None)
            if spec is not None:
                p._data = jax.device_put(p._data, NamedSharding(mesh, spec))
            else:
                p._data = jax.device_put(p._data, NamedSharding(mesh, P()))
        for b in model.buffers():
            b._data = jax.device_put(b._data, NamedSharding(mesh, P()))
        outer = self

        class _FleetModel(Layer):
            def __init__(self, inner):
                super().__init__()
                self._layers = inner

            def forward(self, *a, **kw):
                return self._layers(*a, **kw)

            def shard_input(self, x):
                from ..parallel import shard_batch

                return shard_batch(x, outer._hcg.mesh, "dp")

            def state_dict(self, destination=None, include_sublayers=True,
                           prefix=""):
                return self._layers.state_dict(
                    destination, include_sublayers, prefix
                )

            def set_state_dict(self, state_dict, use_structured_name=True):
                return self._layers.set_state_dict(
                    state_dict, use_structured_name
                )

        if isinstance(model, DataParallel):
            return model
        return _FleetModel(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        """Compose the strategy into the optimizer (fleet_base.py:598 +
        the meta-optimizer chain :1150-1181). Every flag is either real —
        it changes the update/step — or raises; nothing is silently
        dropped (strategy_compiler.py:171 behavior, made loud)."""
        self._require_init()
        if strategy is not None:
            self._strategy = strategy
        s = self._strategy
        if s.dgc and s.fp16_allreduce:
            # don't route-then-blame: the user set dgc + fp16_allreduce,
            # not quantized_allreduce — name the actual conflict
            raise ValueError(
                "dgc routes to the quantized_allreduce grad-comm width "
                "policy, which cannot combine with fp16_allreduce — "
                "drop one of dgc/fp16_allreduce"
            )
        if s.dgc:
            # VERDICT row 33, the last loud-raise strategy: DGC's top-k
            # sparsified allreduce has no TPU-native form (a sparse
            # exchange has no GSPMD lowering), but its goal — grad-comm
            # bytes — is exactly what the block-scaled quantized
            # allreduce delivers, so the flag routes there (SURVEY §5)
            import warnings

            warnings.warn(
                "strategy.dgc (top-k sparsified allreduce) is deprecated "
                "on TPU: routing to the block-scaled quantized allreduce "
                "policy (strategy.quantized_allreduce='int8'), the "
                "TPU-native bandwidth-reduction analog",
                DeprecationWarning, stacklevel=2,
            )
            if not s.quantized_allreduce:
                s.quantized_allreduce = "int8"
        if s.quantized_allreduce:
            from .. import quantized_comm as _qc

            _qc.resolve_policy(          # loud on typos / missing fp8
                s.quantized_allreduce, s.quantized_allreduce_block
            )
            if s.fp16_allreduce:
                raise ValueError(
                    "fp16_allreduce and quantized_allreduce are both "
                    "grad-comm width policies — enable one, not both"
                )
        if s.a_sync:
            raise NotImplementedError(
                "a_sync is parameter-server mode — out of the TPU scope"
            )
        if s.sharding and s.sharding_configs["hybrid_dp"]:
            raise NotImplementedError(
                "sharding hybrid_dp (sharding groups x dp groups) is not "
                "built; state shards over the FULL dp axis here "
                "(equivalent to sharding_degree == dp_degree)"
            )
        if s.quantized_matmul:
            # compute-width twin of the wire knob (ISSUE 19): resolving
            # here is the loud typo/fp8 gate; the policy itself reaches
            # the F.linear seam through TrainStep's matmul_scope
            from .. import quantized_compute as _qcp

            _qcp.resolve_matmul(s.quantized_matmul)
        from ...optimizer import Adam, AdamW, Lamb, Lars, Momentum

        if s.lamb:
            # LambOptimizer meta (_can_apply: inner must be Adam-family,
            # fleet/meta_optimizers/lamb_optimizer.py:20)
            if not isinstance(optimizer, (Adam, AdamW)):
                raise ValueError(
                    "strategy.lamb swaps an Adam/AdamW inner optimizer for "
                    f"Lamb; got {type(optimizer).__name__}"
                )
            cfg = s.lamb_configs
            excl = list(cfg["exclude_from_weight_decay"])
            optimizer = Lamb(
                learning_rate=optimizer._lr,
                lamb_weight_decay=float(cfg["lamb_weight_decay"]),
                beta1=optimizer._beta1, beta2=optimizer._beta2,
                parameters=optimizer._parameter_list,
                grad_clip=optimizer._grad_clip,
                exclude_from_weight_decay_fn=(
                    (lambda p: any(tag in (p.name or "") for tag in excl))
                    if excl else None
                ),
            )
        elif s.lars:
            # lars_optimizer.py:19 (_can_apply: inner must be Momentum)
            if not isinstance(optimizer, Momentum):
                raise ValueError(
                    "strategy.lars swaps a Momentum inner optimizer for "
                    f"Lars; got {type(optimizer).__name__}"
                )
            cfg = s.lars_configs
            optimizer = Lars(
                learning_rate=optimizer._lr,
                momentum=optimizer._momentum,
                lars_coeff=float(cfg["lars_coeff"]),
                lars_weight_decay=float(cfg["lars_weight_decay"]),
                epsilon=float(cfg["epsilon"]),
                parameters=optimizer._parameter_list,
                grad_clip=optimizer._grad_clip,
                exclude_from_weight_decay=list(
                    cfg["exclude_from_weight_decay"]
                ),
            )
        if s.quantized_moments:
            # AFTER the lamb/lars swaps so a Lamb-swapped inner fails the
            # family check loudly instead of silently training wide
            if s.fp16_allreduce:
                raise ValueError(
                    "quantized_moments cannot combine with "
                    "fp16_allreduce: the grad would pass two lossy width "
                    "policies back to back on the grad->moment path "
                    "(bf16 comm round trip, then the int8 moment "
                    "round trip), compounding beyond the documented "
                    "single-pass quantize_dequantize error bound — use "
                    "quantized_allreduce for narrow comm instead"
                )
            if not isinstance(optimizer, (Adam, AdamW)):
                raise ValueError(
                    "strategy.quantized_moments stores Adam-family "
                    "moment1/moment2 state narrow; got "
                    f"{type(optimizer).__name__}"
                )
            optimizer.quantize_moments(s.quantized_moments)
        return _DistributedOptimizer(optimizer, self._strategy)


fleet = Fleet()
