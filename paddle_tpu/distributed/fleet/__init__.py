"""paddle_tpu.distributed.fleet (reference:
python/paddle/distributed/fleet/__init__.py — the module object itself acts
as the fleet singleton: fleet.init, fleet.distributed_model, ...)."""
from .base import Fleet, HybridCommunicateGroup, fleet as _fleet
from .strategy import DistributedStrategy

# module-level singleton surface, matching `from paddle.distributed import
# fleet; fleet.init(...)`
init = _fleet.init
worker_index = _fleet.worker_index
worker_num = _fleet.worker_num
is_first_worker = _fleet.is_first_worker
is_worker = _fleet.is_worker
is_server = _fleet.is_server
worker_endpoints = _fleet.worker_endpoints
barrier_worker = _fleet.barrier_worker
stop_worker = _fleet.stop_worker
distributed_model = _fleet.distributed_model
distributed_optimizer = _fleet.distributed_optimizer
get_hybrid_communicate_group = _fleet.get_hybrid_communicate_group

__all__ = [
    "DistributedStrategy", "Fleet", "HybridCommunicateGroup", "init",
    "worker_index", "worker_num", "is_first_worker", "is_worker",
    "is_server", "worker_endpoints", "barrier_worker", "stop_worker",
    "distributed_model", "distributed_optimizer",
    "get_hybrid_communicate_group",
]
