"""Elastic runtime: heartbeat watchdog, restart budget, log capture.

Reference: launch_utils.py:996-1118 — `TrainerProc` bookkeeping, the
`watch_local_trainers` poll loop, `workerlog.N` per-rank log files,
`terminate_local_procs` SIGTERM→grace→SIGKILL teardown — plus
`distributed/fleet/elastic/manager.py`'s ElasticManager (hung-worker
watchdog + bounded relaunch).

TPU-native additions over the reference watch loop:

- **heartbeats**: each rank gets `PADDLE_HEARTBEAT_FILE`; the trainer
  (TrainEpochRange per epoch, hapi `TerminateOnPreempt` per batch, or
  anything calling :func:`heartbeat`) touches it. A rank whose file goes
  stale for `PADDLE_WATCHDOG_TIMEOUT` seconds is *hung* (deadlocked
  collective, wedged host) — the reference only notices exits, so a hung
  rank stalls the pod forever.
- **escalation**: hung/failed ranks get SIGTERM, a
  `PADDLE_WATCHDOG_GRACE`-second window to snapshot, then SIGKILL.
- **restart budget**: at most `max_restarts` relaunches per
  `PADDLE_ELASTIC_WINDOW`-second rolling window, with exponential
  backoff (base `PADDLE_ELASTIC_BACKOFF`, cap 30s, ±50% jitter) so a
  crash-looping job backs off the coordinator instead of hammering it.
- **preemption notice**: SIGTERM/SIGINT to the manager is forwarded to
  every child (the cloud's 30s warning), children snapshot and exit, no
  relaunch is attempted, and the manager exits 143.
- **reshard notice** (ISSUE 11): with ``reshard="shrink"`` (or
  ``"shrink_expand"``; CLI ``--reshard``, env ``PADDLE_RESHARD_MODE``)
  the manager distinguishes *rank lost, quorum holds* from *world
  lost*: when a rank dies (or the watchdog puts it down) and at least
  ``PADDLE_RESHARD_QUORUM`` of the attempt's ranks survive, the dead
  rank is RETIRED instead of taking the job down — the manager appends
  a JSON notice line to every survivor's
  ``PADDLE_RESHARD_NOTICE_FILE`` and pokes it with SIGUSR1 (the same
  notice-channel pattern as the SIGTERM preemption protocol); survivors
  consume the notice at their next step boundary and reshard
  device-to-device (distributed/resharding.py). Below quorum — or with
  resharding off — the old semantics stand: teardown, budgeted
  relaunch, checkpoint reload. The expand half of ``shrink_expand`` is
  an in-process affair (a fresh OS rank cannot join a live
  jax.distributed world on this runtime): the launcher treats it as
  shrink and leaves re-absorption to jobs that inject returns in
  process.
- **embedded fleet monitor** (ISSUE 14): when an observability dir
  exists (``--log_dir`` or ``PADDLE_OBS_DIR``), a monitor thread at
  rank −1 tails every child's bus stream live — straggler ranking,
  online percentile digests, incident correlation
  (``observability/monitor.py``); kill attribution folds the active
  incident chain in, and the final incident/snapshot rows are flushed
  before the manager returns. ``PADDLE_MON=0`` disables.
- **embedded co-tenancy controller** (ISSUE 16): ``PADDLE_CTL=dryrun``
  (or ``controller="dryrun"``) starts the lend/reclaim state machine
  (``distributed/fleet_controller.py``) next to the monitor at
  rank −1. The launcher runs it journal-only — decisions, hysteresis,
  and the crash-recoverable ctl_lend/ctl_reclaim journal are real;
  actuation callbacks are not wired (training steps and serving
  engines live in the children; in-process co-tenants construct
  ``FleetController`` themselves with lend/reclaim callbacks).
- **live lend plane** (ISSUE 20): ``PADDLE_CTL=live`` wires the
  controller's :class:`~.fleet_controller.PhaseActuators` to a file
  protocol against the children (:class:`_LiveLendPlane`): a committed
  ``ctl_lend`` drives the lent dp row through depart (a role-carrying
  "lend" reshard notice — survivors shrink in place, the named rank
  reads its new job), deliver (the child loads the
  ``PADDLE_CTL_SERVE_CKPT`` quantized checkpoint, ack deadline
  ``PADDLE_CTL_PHASE_TIMEOUT_S``), and join (the child's serving
  mailbox comes up under ``PADDLE_CTL_SERVE_DIR``); ``ctl_reclaim``
  reverses it (drain marker → drained ack → leave → a "reclaim"
  notice rejoins the row, one ledger-attributed recompile). Every
  phase is its own fsync'd journal pair; a crash at any point recovers
  probe-or-rollback from the journal alone. A LENT rank dying while
  serving (the ``serve:lent_worker_crash`` fault) is a serving-plane
  event, not a training failure: the launcher journals a FORCED
  reclaim — ownership returns to the training plane, where the dead
  process then takes the standard rank-loss path.
"""
from __future__ import annotations

import os
import random
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from . import comm_monitor  # stdlib-pure: safe for the launcher process

try:  # telemetry bus (stdlib-pure too); tolerate exotic standalone loads
    from ..observability import bus as _obs_bus
except ImportError:  # pragma: no cover - package always carries it
    _obs_bus = None

try:  # the live fleet monitor (ISSUE 14, stdlib-pure as well)
    from ..observability import monitor as _obs_monitor
except ImportError:  # pragma: no cover - package always carries it
    _obs_monitor = None

try:  # the train-serve co-tenancy controller (ISSUE 16, stdlib-pure)
    from . import fleet_controller as _fleet_ctl
except ImportError:  # pragma: no cover - package always carries it
    _fleet_ctl = None


def _emit(kind: str, **payload) -> None:
    """Launcher-side bus event (rank -1). Lands only when the operator
    exported PADDLE_OBS_DIR/PADDLE_OBS_BUS_FILE for the manager process;
    the per-rank child streams are provisioned independently in _spawn."""
    if _obs_bus is not None:
        _obs_bus.emit(kind, payload, rank=-1)

__all__ = ["ElasticManager", "RankProc", "heartbeat",
           "install_preempt_notice", "restore_preempt_notice", "HUNG_RC"]

_HEARTBEAT_ENV = "PADDLE_HEARTBEAT_FILE"
_WATCHDOG_ENV = "PADDLE_WATCHDOG_TIMEOUT"
_GRACE_ENV = "PADDLE_WATCHDOG_GRACE"
_BACKOFF_ENV = "PADDLE_ELASTIC_BACKOFF"
_WINDOW_ENV = "PADDLE_ELASTIC_WINDOW"
_LOGDIR_ENV = "PADDLE_LOG_DIR"
_RESHARD_MODE_ENV = "PADDLE_RESHARD_MODE"
_RESHARD_QUORUM_ENV = "PADDLE_RESHARD_QUORUM"
_RESHARD_NOTICE_ENV = "PADDLE_RESHARD_NOTICE_FILE"
_MON_ENV = "PADDLE_MON"
_CTL_ENV = "PADDLE_CTL"

#: exit code the manager reports when the watchdog had to put a rank down
HUNG_RC = 98
#: exit code after a propagated preemption notice (128 + SIGTERM)
PREEMPT_RC = 143


def heartbeat() -> None:
    """Touch this rank's heartbeat file (no-op outside the runner).

    Cheap enough to call per batch; the watchdog only compares mtimes.
    """
    path = os.environ.get(_HEARTBEAT_ENV)
    if not path:
        return
    try:
        with open(path, "a"):
            pass
        os.utime(path, None)
    except OSError:
        pass  # a lost heartbeat must never kill the trainer itself


def install_preempt_notice(on_notice: Callable[[], None]):
    """Install a SIGTERM handler that invokes `on_notice()` — the shared
    trainer-side half of the preemption protocol (TrainEpochRange and
    hapi.TerminateOnPreempt both use it). Returns the previous handler
    for :func:`restore_preempt_notice`, or None when not installable
    (non-main thread / restricted runtime)."""
    if threading.current_thread() is not threading.main_thread():
        return None

    def _handler(signum, frame):
        try:
            # the preemption notice is one of the flight recorder's dump
            # triggers: capture the collective stream before snapshotting
            comm_monitor.dump_flight_recorder("sigterm")
        except Exception:
            pass
        on_notice()

    try:
        return signal.signal(signal.SIGTERM, _handler)
    except (ValueError, OSError):
        return None


def restore_preempt_notice(old) -> None:
    if old is not None:
        signal.signal(signal.SIGTERM, old)


class RankProc:
    """One spawned rank (launch_utils.py TrainerProc analog)."""

    __slots__ = ("proc", "rank", "hb_path", "log_path", "log_file",
                 "ev_path", "guard_ev_path", "notice_path")

    def __init__(self, proc, rank, hb_path, log_path=None, log_file=None,
                 ev_path=None, guard_ev_path=None, notice_path=None):
        self.proc = proc
        self.rank = rank
        self.hb_path = hb_path
        self.log_path = log_path
        self.log_file = log_file
        self.ev_path = ev_path
        self.guard_ev_path = guard_ev_path
        self.notice_path = notice_path


class _LiveLendPlane:
    """The launcher side of the live lend plane (ISSUE 20): phase
    actuators driving CHILD processes over a file protocol, no shared
    memory with them.

    The contract per phase (all acks land in the lend dir the notice
    row names as ``ack_dir``; the launcher waits at most
    ``PADDLE_CTL_PHASE_TIMEOUT_S`` per phase, default 30 s):

    - **depart**: a role-carrying ``lend`` reshard notice goes to every
      live rank. Survivors fold it like a departure at their next step
      boundary (PR 11 — no relaunch); the NAMED rank stops training
      and acks ``rank<r>.departed``.
    - **deliver**: the lent rank loads the serving checkpoint the
      notice named (``PADDLE_CTL_SERVE_CKPT``, the PR-18
      ``load_quantized`` resident path) and acks ``rank<r>.delivered``
      (payload: its ``load_ms``). The deadline bounds a wedged load.
    - **join**: the rank's serving mailbox worker comes up under the
      notice's ``serve_dir`` (``PADDLE_CTL_SERVE_DIR``) and acks
      ``rank<r>.serving`` — the marker a router-side co-tenant polls
      before ``add_host``/``register_capacity`` admits traffic into
      the new worker.
    - **drain**: the launcher writes ``rank<r>.drain``; the worker
      stops taking new mailbox work, finishes what it holds (the PR-14
      zero-drop drain; PR-16 migrates what cannot finish) and acks
      ``rank<r>.drained``.
    - **leave**: serving teardown — the worker retires its mailbox and
      acks ``rank<r>.left``.
    - **rejoin**: a ``reclaim`` notice returns the row to the training
      mesh (survivors expand at a step boundary — the one
      ledger-attributed recompile); the rank acks ``rank<r>.rejoined``
      and the lend-dir state for it is cleared.

    ``probe``/``rollback`` close the crash loop: probe answers "is the
    rank alive AND past its serving ack" from the markers + the
    process table; rollback converges a half-done ladder to what the
    journal says — a failed lend re-sends the ``reclaim`` notice (a
    survivor that never consumed the lend nets the two rows out), a
    failed reclaim cancels the drain marker so the row stays serving.
    """

    __slots__ = ("mgr", "timeout", "ckpt", "serve_dir")

    def __init__(self, mgr: "ElasticManager"):
        self.mgr = mgr
        raw = os.environ.get("PADDLE_CTL_PHASE_TIMEOUT_S", "")
        try:
            self.timeout = float(raw) if raw.strip() else 30.0
        except ValueError:
            self.timeout = 30.0
        self.ckpt = os.environ.get("PADDLE_CTL_SERVE_CKPT") or None
        self.serve_dir = os.environ.get("PADDLE_CTL_SERVE_DIR") or None

    # -- file protocol ----------------------------------------------------
    def lend_dir(self) -> str:
        d = os.path.join(self.mgr._run_dir, "lend")
        os.makedirs(d, exist_ok=True)
        return d

    def _marker(self, rank: int, state: str) -> str:
        return os.path.join(self.lend_dir(), f"rank{rank}.{state}")

    def clear(self, rank: int) -> None:
        for state in ("departed", "delivered", "serving", "drain",
                      "drained", "left", "rejoined"):
            try:
                os.unlink(self._marker(rank, state))
            except OSError:
                pass

    def _live(self) -> List[RankProc]:
        return [rp for rp in self.mgr._procs if rp.proc.poll() is None]

    def _wait_ack(self, rank: int, state: str, phase: str) -> None:
        path = self._marker(rank, state)
        deadline = time.monotonic() + self.timeout
        while time.monotonic() < deadline:
            if os.path.exists(path):
                return
            rp = self.mgr._rank_proc(rank)
            if rp is None or rp.proc.poll() is not None:
                raise RuntimeError(
                    f"live lend {phase}: rank {rank} died before its "
                    f"{state} ack")
            time.sleep(0.02)
        raise TimeoutError(
            f"live lend {phase}: rank {rank} gave no {state} ack "
            f"within {self.timeout}s")

    def _notice_extra(self) -> dict:
        return {"ack_dir": self.lend_dir(), "ckpt": self.ckpt,
                "serve_dir": self.serve_dir}

    # -- the lend ladder --------------------------------------------------
    def depart(self, rank: int, samp) -> None:
        self.clear(rank)  # stale acks from a prior cycle must not
        # satisfy this ladder's waits
        self.mgr._notify_reshard("lend", [rank], self._live(),
                                 extra=self._notice_extra())
        self._wait_ack(rank, "departed", "depart")

    def deliver(self, rank: int, samp) -> None:
        # the load itself runs in the child (PR-18 load_quantized off
        # the resident .pdqparams); this side holds the DEADLINE — a
        # wedged weight load aborts the transition instead of leaving
        # the row neither training nor serving
        self._wait_ack(rank, "delivered", "deliver")

    def join(self, rank: int, samp) -> None:
        self._wait_ack(rank, "serving", "join")

    # -- the reclaim ladder -----------------------------------------------
    def drain(self, rank: int, samp) -> None:
        with open(self._marker(rank, "drain"), "w"):
            pass
        self._wait_ack(rank, "drained", "drain")

    def leave(self, rank: int, samp) -> None:
        self._wait_ack(rank, "left", "leave")

    def rejoin(self, rank: int, samp) -> None:
        self.mgr._notify_reshard("reclaim", [rank], self._live(),
                                 extra=self._notice_extra())
        self._wait_ack(rank, "rejoined", "rejoin")
        self.clear(rank)

    # -- crash loop -------------------------------------------------------
    def probe(self, rank: int) -> bool:
        rp = self.mgr._rank_proc(rank)
        return (rp is not None and rp.proc.poll() is None
                and os.path.exists(self._marker(rank, "serving"))
                and not os.path.exists(self._marker(rank, "left")))

    def rollback(self, verb: str, stage, completed, ranks) -> None:
        for rank in ranks:
            if verb == "lend":
                # converge to training ownership: the reclaim notice
                # undoes the lend for everyone — a survivor that never
                # consumed the lend row nets the pair out in order
                # (resharding folds events sequentially), the named
                # rank drops its serve role
                self.mgr._notify_reshard(
                    "reclaim", [rank], self._live(),
                    extra=self._notice_extra())
                self.clear(rank)
            else:
                # reclaim failed mid-ladder: the journal still says
                # LENT — cancel the drain so the row keeps serving
                try:
                    os.unlink(self._marker(rank, "drain"))
                except OSError:
                    pass

    def actuators(self):
        from .fleet_controller import PhaseActuators

        return PhaseActuators(
            depart=self.depart, deliver=self.deliver, join=self.join,
            drain=self.drain, leave=self.leave, rejoin=self.rejoin,
            probe=self.probe, rollback=self.rollback)


class ElasticManager:
    """Spawn this node's ranks and keep the job alive across failures.

    `envs` is one fully-populated environment dict per local rank (see
    launch.build_cluster_env); the manager adds `PADDLE_LAUNCH_ATTEMPT`
    and `PADDLE_HEARTBEAT_FILE` on top.
    """

    def __init__(self, script: str, script_args: List[str],
                 envs: List[Dict[str, str]], backend: Optional[str] = None,
                 max_restarts: int = 0,
                 watchdog_timeout: Optional[float] = None,
                 grace: Optional[float] = None,
                 backoff_base: Optional[float] = None,
                 backoff_cap: float = 30.0,
                 restart_window: Optional[float] = None,
                 log_dir: Optional[str] = None,
                 poll_interval: float = 0.1,
                 coll_timeout: Optional[float] = None,
                 reshard: Optional[str] = None,
                 reshard_quorum: Optional[float] = None,
                 monitor: Optional[bool] = None,
                 controller: Optional[str] = None):
        def _envf(name, default):
            raw = os.environ.get(name, "")
            return float(raw) if raw.strip() else default

        self.script = script
        self.script_args = list(script_args)
        self.envs = envs
        self.backend = backend
        self.max_restarts = int(max_restarts)
        self.watchdog_timeout = (
            watchdog_timeout if watchdog_timeout is not None
            else _envf(_WATCHDOG_ENV, 0.0))
        self.grace = grace if grace is not None else _envf(_GRACE_ENV, 10.0)
        self.backoff_base = (backoff_base if backoff_base is not None
                             else _envf(_BACKOFF_ENV, 0.5))
        self.backoff_cap = backoff_cap
        self.restart_window = (restart_window if restart_window is not None
                               else _envf(_WINDOW_ENV, 3600.0))
        self.log_dir = log_dir or os.environ.get(_LOGDIR_ENV) or None
        self.poll_interval = poll_interval
        self.coll_timeout = coll_timeout
        self.reshard = (reshard if reshard is not None
                        else os.environ.get(_RESHARD_MODE_ENV, "off")) \
            .strip().lower() or "off"
        if self.reshard not in ("off", "shrink", "shrink_expand"):
            raise ValueError(
                f"reshard={self.reshard!r}: want off|shrink|shrink_expand")
        self.reshard_quorum = (reshard_quorum if reshard_quorum is not None
                               else _envf(_RESHARD_QUORUM_ENV, 0.5))
        if monitor is None:
            monitor = os.environ.get(_MON_ENV, "1").strip().lower() \
                not in ("0", "false", "off")
        self.monitor_enabled = bool(monitor)
        #: the embedded live fleet monitor (rank −1, next to the
        #: watchdog — ISSUE 14); started at first spawn when an obs
        #: dir exists, so kill attribution can ask it for incident
        #: context and the incident rows land before the manager exits
        self.monitor = None
        self._mon_thread: Optional[threading.Thread] = None
        self._mon_stop = threading.Event()
        if controller is None:
            controller = os.environ.get(_CTL_ENV, "off")
        self.controller_mode = (controller or "off").strip().lower() or "off"
        if self.controller_mode not in ("off", "dryrun", "live"):
            raise ValueError(
                f"controller={self.controller_mode!r}: want "
                f"off|dryrun|live")
        if self.controller_mode == "live" and self.reshard == "off":
            raise ValueError(
                "controller='live' needs reshard='shrink'/"
                "'shrink_expand': the depart/rejoin phases ride the "
                "reshard notice channel")
        #: the embedded co-tenancy controller (ISSUE 16): rides next to
        #: the monitor at rank -1, consuming its serving aggregates.
        #: ``dryrun`` journals decisions without actuating; ``live``
        #: (ISSUE 20) wires the _LiveLendPlane phase actuators so a
        #: committed decision really migrates the rank between jobs
        self.controller = None
        self._ctl_thread: Optional[threading.Thread] = None
        self._ctl_stop = threading.Event()
        self._lend_plane = None
        self._run_dir = None          # heartbeat-file home, made lazily
        self._procs: List[RankProc] = []
        self._retired: List[RankProc] = []  # resharded-away ranks
        self._spawn_total = 0         # this attempt's quorum denominator
        self._restarts = deque()      # monotonic stamps of past relaunches
        self._preempted = False

    # -- spawning ---------------------------------------------------------
    def _spawn(self, attempt: int) -> None:
        if self._run_dir is None:
            self._run_dir = tempfile.mkdtemp(prefix="pdtpu_elastic_")
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
        # comm-monitor plumbing: a per-ATTEMPT sync dir (stale round files
        # from a previous incarnation must not satisfy fresh barriers), a
        # per-rank event file the kill attribution reads, and the dump
        # destination next to the workerlogs
        sync_dir = os.path.join(self._run_dir, f"collsync.{attempt}")
        os.makedirs(sync_dir, exist_ok=True)
        debug_dir = self.log_dir or self._run_dir
        # telemetry-bus home for the children (observability/bus.py):
        # next to the workerlogs so tools/timeline.py finds every rank's
        # stream beside the flight-recorder dumps. Only a durable
        # destination qualifies — the tmp run dir is removed at manager
        # exit, so without --log_dir (or an operator-exported
        # PADDLE_OBS_DIR riding in via the env dicts) the bus stays off.
        obs_dir = os.environ.get("PADDLE_OBS_DIR") or self.log_dir
        self._procs = []
        self._retired = []
        for env in self.envs:
            env = dict(env)
            if self.backend:
                # both spellings: JAX_PLATFORMS is the live knob, the
                # legacy JAX_PLATFORM_NAME covers older jax — without the
                # former, a grafted jax still probes the TPU plugin (30s+
                # of metadata fetches) despite the cpu request
                env["JAX_PLATFORMS"] = self.backend
                env["JAX_PLATFORM_NAME"] = self.backend
            env["PADDLE_LAUNCH_ATTEMPT"] = str(attempt)
            rank = int(env.get("PADDLE_TRAINER_ID", "0"))
            hb = os.path.join(self._run_dir, f"hb.{rank}")
            env[_HEARTBEAT_ENV] = hb
            # pre-touch so the stale clock starts at spawn, not epoch 1
            with open(hb, "a"):
                pass
            os.utime(hb, None)
            ev = os.path.join(self._run_dir, f"collev.{rank}")
            with open(ev, "w"):
                pass  # fresh per attempt: attribution reflects THIS run
            env["PADDLE_COLL_EVENT_FILE"] = ev
            # the numerical guard's event stream (train_guard.py): same
            # JSONL contract, read for kill attribution alongside the
            # collective events
            gev = os.path.join(self._run_dir, f"guardev.{rank}")
            with open(gev, "w"):
                pass
            env["PADDLE_GUARD_EVENT_FILE"] = gev
            notice = None
            if self.reshard != "off":
                # per-attempt reshard-notice channel (resharding.py
                # consumes it at step boundaries after a SIGUSR1 poke)
                notice = os.path.join(
                    self._run_dir, f"reshard.notice.{attempt}.{rank}")
                with open(notice, "w"):
                    pass
                env[_RESHARD_NOTICE_ENV] = notice
            env["PADDLE_COLL_SYNC_DIR"] = sync_dir
            env.setdefault("PADDLE_COLL_DEBUG_DIR", debug_dir)
            if obs_dir:
                env.setdefault("PADDLE_OBS_DIR", obs_dir)
            if self.coll_timeout is not None:
                env["PADDLE_COLL_TIMEOUT"] = str(self.coll_timeout)
            log_path = log_file = None
            if self.log_dir:
                log_path = os.path.join(self.log_dir, f"workerlog.{rank}")
                log_file = open(log_path, "ab", buffering=0)
                log_file.write(
                    f"==== attempt {attempt} rank {rank} ====\n".encode())
            p = subprocess.Popen(
                [sys.executable, self.script] + self.script_args,
                env=env, stdout=log_file, stderr=log_file)
            self._procs.append(RankProc(p, rank, hb, log_path, log_file,
                                        ev_path=ev, guard_ev_path=gev,
                                        notice_path=notice))
        self._spawn_total = len(self._procs)
        self._start_monitor(obs_dir)
        self._start_controller(obs_dir)
        _emit("elastic_spawn", attempt=attempt,
              ranks=[rp.rank for rp in self._procs],
              pids=[rp.proc.pid for rp in self._procs],
              obs_dir=obs_dir)

    # -- embedded fleet monitor (ISSUE 14) --------------------------------
    def _start_monitor(self, obs_dir: Optional[str]) -> None:
        """Tail the children's bus streams from the launcher (rank −1,
        next to the watchdog): straggler ranking, percentile digests,
        and incident correlation DURING the run. One monitor for the
        whole job — relaunch attempts append to the same streams."""
        if (self.monitor is not None or not self.monitor_enabled
                or not obs_dir or _obs_monitor is None):
            return
        try:
            self.monitor = _obs_monitor.FleetMonitor(obs_dir, emit=True)
        except Exception:  # noqa: BLE001 — monitoring never blocks spawn
            self.monitor = None
            return

        def _loop():
            while not self._mon_stop.wait(self.monitor.poll_s):
                try:
                    self.monitor.poll()
                    self.monitor.maybe_snapshot()
                except Exception:  # noqa: BLE001 — keep tailing
                    pass

        self._mon_thread = threading.Thread(
            target=_loop, name="pdtpu-fleet-monitor", daemon=True)
        self._mon_thread.start()

    def _stop_monitor(self) -> None:
        """Final drain BEFORE the manager returns: the open incident is
        force-closed and written, so a failure in the job's last window
        still gets its `incident` row."""
        if self.monitor is None:
            return
        self._mon_stop.set()
        if self._mon_thread is not None:
            self._mon_thread.join(timeout=5.0)
        try:
            self.monitor.finalize()
        except Exception:  # noqa: BLE001 — diagnostics stay best-effort
            pass

    # -- embedded co-tenancy controller (ISSUE 16) ------------------------
    def _start_controller(self, obs_dir: Optional[str]) -> None:
        """Run the lend/reclaim state machine at rank -1, next to the
        monitor it feeds from. Every window samples the monitor's
        serving aggregates, the hysteresis policy decides, decisions
        journal to the launcher bus stream (crash-recoverable). In
        ``dryrun`` no actuation is wired — ownership changes are
        declared, not executed; in ``live`` (ISSUE 20) the
        _LiveLendPlane phase actuators drive the children through the
        depart/deliver/join (and drain/leave/rejoin) ladders for real.
        One controller per job; relaunch attempts keep the journal, so
        recovery re-derives lent state — and rolls half-done ladders
        back — instead of guessing."""
        if (self.controller is not None or self.controller_mode == "off"
                or not obs_dir or _fleet_ctl is None
                or self.monitor is None):
            return
        donors = sorted(rp.rank for rp in self._procs)
        actuators = None
        if self.controller_mode == "live":
            # ISSUE 20: wire the real phase ladder — a committed
            # decision now MOVES the rank between jobs, and the
            # controller's recovery can probe/rollback the children
            self._lend_plane = _LiveLendPlane(self)
            actuators = self._lend_plane.actuators()
        try:
            self.controller = _fleet_ctl.FleetController(
                obs_dir, monitor=self.monitor, donor_ranks=donors,
                actuators=actuators)
        except Exception:  # noqa: BLE001 — the controller never blocks spawn
            self.controller = None
            return

        def _loop():
            while not self._ctl_stop.wait(self.controller.cfg.window_s):
                try:
                    self.controller.window()
                except Exception:  # noqa: BLE001 — keep deciding
                    pass

        self._ctl_thread = threading.Thread(
            target=_loop, name="pdtpu-fleet-controller", daemon=True)
        self._ctl_thread.start()

    def _stop_controller(self) -> None:
        if self.controller is None:
            return
        self._ctl_stop.set()
        if self._ctl_thread is not None:
            self._ctl_thread.join(timeout=5.0)

    # -- teardown ---------------------------------------------------------
    def _kill_rank(self, rp: RankProc, why: str) -> None:
        """SIGTERM → grace → SIGKILL one rank."""
        if rp.proc.poll() is not None:
            return
        print(f"paddle_tpu.elastic: {why}; terminating rank {rp.rank} "
              f"(pid {rp.proc.pid}, grace {self.grace}s)",
              file=sys.stderr, flush=True)
        rp.proc.send_signal(signal.SIGTERM)
        try:
            rp.proc.wait(timeout=self.grace)
        except subprocess.TimeoutExpired:
            rp.proc.kill()
            rp.proc.wait()

    def _teardown(self, why: str) -> None:
        # signal everyone FIRST, then share one grace deadline — serial
        # per-rank waits would stretch teardown to N*grace and eat the
        # cloud's eviction window before later ranks could snapshot
        live = [rp for rp in self._procs if rp.proc.poll() is None]
        if live:
            print(f"paddle_tpu.elastic: {why}; terminating "
                  f"{len(live)} rank(s) (grace {self.grace}s)",
                  file=sys.stderr, flush=True)
            for rp in live:
                try:
                    rp.proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
            deadline = time.monotonic() + self.grace
            for rp in live:
                try:
                    rp.proc.wait(max(deadline - time.monotonic(), 0))
                except subprocess.TimeoutExpired:
                    rp.proc.kill()
                    rp.proc.wait()
        for rp in self._procs + self._retired:
            if rp.log_file is not None:
                try:
                    rp.log_file.close()
                except OSError:
                    pass

    # -- kill attribution (comm_monitor event reader) ---------------------
    def _attribute(self, rp: RankProc, why: str) -> None:
        """Name the collective — or the numerical-guard verdict — behind
        a rank's death, when a monitor managed to write an event line
        before the end: a generic 'hung rank' becomes 'stalled in
        all_reduce(seq 5, ...)', a guard abort (rc=96) becomes
        'divergence: N consecutive bad steps (grads nonfinite, ...)'."""
        events = []
        for path in (rp.ev_path, rp.guard_ev_path):
            if path:
                events.extend(comm_monitor.read_events(path))
        # the embedded fleet monitor's incident context (ISSUE 14):
        # sitting next to the watchdog means the kill attribution sees
        # the cross-rank chain ("rank 3 recompile storm → dp collective
        # stall") for free — drain its streams once so events from the
        # dying rank's last seconds are in
        incident = None
        if self.monitor is not None:
            try:
                self.monitor.poll()
                incident = self.monitor.incident_context(rp.rank)
            except Exception:  # noqa: BLE001 — attribution best-effort
                incident = None
        if not events and not incident:
            return
        if events:
            ev = max(events, key=lambda e: e.get("time", 0.0))
            cause = ev.get("event", "?")
            what = (ev.get("detail") or ev.get("describe") or cause)
        else:
            cause, what = "incident", incident
        _emit("elastic_attribution", rank=rp.rank, why=why,
              cause=cause, detail=what, incident=incident)
        print(
            f"paddle_tpu.elastic: rank {rp.rank} {why} attributed to "
            f"{cause}: {what}"
            # when there were no monitor events, `what` already IS the
            # incident chain — don't print it twice
            + (f" [incident: {incident}]" if incident and events
               else ""),
            file=sys.stderr, flush=True)

    # -- reshard notice channel (quorum-holding rank loss) ----------------
    def _quorum_holds(self, n_alive: int) -> bool:
        if self.reshard == "off" or n_alive < 1:
            return False
        return (n_alive / max(self._spawn_total, 1)) >= self.reshard_quorum

    def _retire(self, rp: RankProc) -> None:
        """Drop a departed rank from the watch set without taking the
        job down (its workerlog closes at teardown like everyone's)."""
        self._procs.remove(rp)
        self._retired.append(rp)

    def _rank_proc(self, rank: int) -> Optional[RankProc]:
        for rp in self._procs:
            if rp.rank == rank:
                return rp
        return None

    def _notify_reshard(self, event: str, ranks: List[int],
                        survivors: List[RankProc],
                        extra: Optional[dict] = None) -> None:
        """Append one notice row to every survivor's notice file and
        poke it with SIGUSR1 (resharding.install_reshard_notice) — the
        step-boundary poller does the rest in-process. ``extra`` rides
        extra row fields (the live lend plane's ack_dir/ckpt/serve_dir
        — ISSUE 20)."""
        import json

        row = {"event": event, "ranks": ranks, "time": time.time(),
               "survivors": [s.rank for s in survivors]}
        if extra:
            row.update(extra)
        for rp in survivors:
            if rp.notice_path:
                try:
                    with open(rp.notice_path, "a") as f:
                        f.write(json.dumps(row) + "\n")
                except OSError:
                    pass
            # the poke is prompt-pickup only, and only for ranks whose
            # handler is armed (the .armed marker from
            # resharding.install_reshard_notice): to an un-armed child
            # — still importing, first compile — the default SIGUSR1
            # disposition is TERMINATION. Un-poked survivors still see
            # the notice at their next step-boundary file poll.
            if rp.notice_path and os.path.exists(
                    rp.notice_path + ".armed"):
                try:
                    rp.proc.send_signal(signal.SIGUSR1)
                except (OSError, AttributeError):
                    pass
        _emit("elastic_reshard_notice", event=event, ranks=ranks,
              survivors=[s.rank for s in survivors],
              quorum=self.reshard_quorum)
        print(f"paddle_tpu.elastic: rank(s) {ranks} {event}ed; quorum "
              f"holds ({len(survivors)}/{self._spawn_total}) — reshard "
              f"notice sent, job continues",
              file=sys.stderr, flush=True)

    # -- the watch loop (launch_utils.py:996-1118) ------------------------
    def _watch(self) -> int:
        rc = 0
        while True:
            alive = []
            failed = []
            for rp in self._procs:
                code = rp.proc.poll()
                if code is None:
                    alive.append(rp)
                elif code != 0:
                    failed.append((rp, code))
            for rp, code in failed:
                # a LENT rank dying is a serving-plane event (ISSUE 20,
                # the serve:lent_worker_crash fault): the row already
                # left the training mesh at depart, so survivors need
                # no new notice — journal the FORCED reclaim (ownership
                # back to the training plane, never half-lent) and let
                # the router's failover re-home its in-flight requests
                if (self.controller is not None
                        and rp.rank in self.controller.lent):
                    self._attribute(rp, f"lent worker death (rc={code})")
                    self._retire(rp)
                    if self._lend_plane is not None:
                        self._lend_plane.clear(rp.rank)
                    try:
                        self.controller.force_reclaim(
                            rp.rank, f"lent_worker_crash rc={code}")
                    except Exception:  # noqa: BLE001 — journal-only path
                        pass
                    continue
                # rank lost: an in-job event when the quorum holds and
                # resharding is on; a job failure otherwise
                if self._quorum_holds(len(alive)):
                    self._attribute(rp, f"departure (rc={code})")
                    self._retire(rp)
                    self._notify_reshard("depart", [rp.rank], alive)
                elif rc == 0:
                    rc = code  # first failure wins; tear the job down
                    self._attribute(rp, f"failure (rc={code})")
            if rc != 0 or not alive:
                break
            if self._preempted:
                # notice already forwarded by the signal handler; give
                # the children their grace window to snapshot + exit
                self._teardown("preemption notice")
                return PREEMPT_RC
            if self.watchdog_timeout > 0:
                now = time.time()
                for rp in alive:
                    try:
                        age = now - os.path.getmtime(rp.hb_path)
                    except OSError:
                        continue  # heartbeat file raced away; skip a beat
                    if age > self.watchdog_timeout:
                        _emit("elastic_watchdog_kill", rank=rp.rank,
                              stale_s=round(age, 1),
                              timeout_s=self.watchdog_timeout)
                        self._kill_rank(
                            rp, f"rank {rp.rank} heartbeat stale "
                                f"{age:.1f}s > {self.watchdog_timeout}s")
                        # a rank wedged in a collective stops heartbeating
                        # too: its monitor's event line says WHERE
                        self._attribute(rp, "watchdog kill")
                        survivors = [s for s in alive if s is not rp]
                        if self._quorum_holds(len(survivors)):
                            # a hung rank is put down, then treated as a
                            # departure: survivors reshard, no relaunch
                            self._retire(rp)
                            self._notify_reshard("depart", [rp.rank],
                                                 survivors)
                        else:
                            rc = HUNG_RC
                        break
                if rc != 0:
                    break
            time.sleep(self.poll_interval)
        self._teardown("peer failure" if rc else "job done")
        return rc  # 0 here means every rank exited clean (even post-notice)

    # -- restart policy ---------------------------------------------------
    def _backoff_delay(self, n_recent: int) -> float:
        """Exponential in the number of recent restarts, capped, with
        ±50% jitter so restarting hosts don't stampede the coordinator."""
        base = min(self.backoff_cap,
                   self.backoff_base * (2.0 ** max(n_recent - 1, 0)))
        return base * (0.5 + random.random())

    def _budget_left(self) -> bool:
        now = time.monotonic()
        while self._restarts and now - self._restarts[0] > self.restart_window:
            self._restarts.popleft()
        return len(self._restarts) < self.max_restarts

    # -- signals ----------------------------------------------------------
    def _on_notice(self, signum, frame):
        self._preempted = True
        for rp in self._procs:
            if rp.proc.poll() is None:
                try:
                    rp.proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass

    def _install_handlers(self):
        old = {}
        try:
            for sig in (signal.SIGTERM, signal.SIGINT):
                old[sig] = signal.signal(sig, self._on_notice)
        except ValueError:
            pass  # not the main thread; the caller owns signal routing
        return old

    # -- the job ----------------------------------------------------------
    def run(self) -> int:
        old_handlers = self._install_handlers()
        attempt = 0
        try:
            while True:
                self._spawn(attempt)
                rc = self._watch()
                if self._preempted:
                    _emit("elastic_preempt", attempt=attempt, rc=rc)
                    # the notice wins even over a clean rank exit: the
                    # host is going away, so report "interrupted" (143)
                    # and let the next incarnation's restore() decide
                    # whether anything is actually left to do
                    return rc or PREEMPT_RC
                if rc == 0:
                    return 0
                if not self._budget_left():
                    print(
                        f"paddle_tpu.elastic: restart budget exhausted "
                        f"({self.max_restarts} per "
                        f"{self.restart_window:.0f}s); giving up rc={rc}",
                        file=sys.stderr, flush=True)
                    return rc
                self._restarts.append(time.monotonic())
                delay = self._backoff_delay(len(self._restarts))
                _emit("elastic_relaunch", attempt=attempt, rc=rc,
                      delay_s=round(delay, 2),
                      restarts_left=self.max_restarts - len(self._restarts))
                print(
                    f"paddle_tpu.elastic: attempt {attempt} failed rc={rc}; "
                    f"relaunching in {delay:.2f}s "
                    f"({self.max_restarts - len(self._restarts)} restarts "
                    f"left in window)", file=sys.stderr, flush=True)
                time.sleep(delay)
                if self._preempted:
                    # notice arrived during the backoff nap: don't burn
                    # the eviction window on a doomed respawn
                    return PREEMPT_RC
                attempt += 1
        finally:
            self._stop_controller()  # last decision journals first
            self._stop_monitor()  # incident rows land BEFORE exit
            self._teardown("manager exit")
            for sig, h in old_handlers.items():
                signal.signal(sig, h)
            if self._run_dir is not None:
                shutil.rmtree(self._run_dir, ignore_errors=True)
