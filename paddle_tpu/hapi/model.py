"""paddle.Model — the high-level trainer.

Reference: python/paddle/hapi/model.py — Model :810, prepare :1244,
fit :1299, evaluate :1515, predict :1609, train_batch/eval_batch/
predict_batch :880-1040, save/load :1041-1200; the dygraph backend
(DynamicGraphAdapter :724) is the semantic model here.

TPU-first: the training backend is the fused `jit.TrainStep` (one donated
XLA program per step) instead of per-op dygraph dispatch; eval/predict run
the jit-cached functional forward. When `paddle.distributed` is
initialized, the network is wrapped in DataParallel and batches shard over
the dp mesh axis (prepare_distributed_context analog, model.py:165).
"""
from __future__ import annotations

import os
import pickle
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..framework import io as fio
from ..io.dataloader import DataLoader
from ..io.dataset import Dataset
from ..jit.train_step import TrainStep
from ..metric import Metric
from ..nn.layer import Layer
from .callbacks import config_callbacks

__all__ = ["Model"]


from ..jit.train_step import _as_list as _to_list  # shared normalization


def _numpy(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Model:
    """An h(igh-level)api over Layer + TrainStep + DataLoader (model.py:810).

    Usage (reference parity)::

        model = paddle.Model(network)
        model.prepare(optimizer, paddle.nn.CrossEntropyLoss(),
                      paddle.metric.Accuracy())
        model.fit(train_dataset, eval_dataset, batch_size=64, epochs=2)
        model.evaluate(eval_dataset)
        model.predict(test_dataset)
    """

    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self.stop_training = False
        self._inputs = _to_list(inputs)
        self._labels = _to_list(labels)
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._train_step: Optional[TrainStep] = None
        self._dp_model = None
        self._save_dir = None
        self._prepared = False

    # -- setup ---------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        """model.py:1244. `loss` is a Layer (e.g. CrossEntropyLoss()) or a
        callable; `metrics` paddle.metric instances."""
        self._optimizer = optimizer
        if loss is not None and not isinstance(loss, Layer) \
                and not callable(loss):
            raise TypeError("loss should be a Layer or a callable")
        self._loss = loss
        for m in _to_list(metrics):
            if not isinstance(m, Metric):
                raise TypeError(
                    f"metric should be paddle.metric.Metric, got {type(m)}"
                )
        self._metrics = _to_list(metrics)
        if amp_configs is not None:
            raise NotImplementedError(
                "amp via Model.prepare: use fleet DistributedStrategy.amp "
                "(the TrainStep consumes it)"
            )
        # prepare_distributed_context analog (model.py:165): under an
        # initialized parallel env, lay params out over the mesh
        from ..distributed import comm
        from ..distributed.parallel import DataParallel

        if comm.is_initialized() and comm._default_group().nranks > 1 \
                and not isinstance(self.network, DataParallel):
            self._dp_model = DataParallel(self.network)
        self._prepared = True
        return self

    def _net(self):
        return self._dp_model if self._dp_model is not None else self.network

    def _loss_fn(self, outs, *labels):
        if self._loss is None:
            # network computes its own loss (model.py allows loss-less
            # prepare when outputs ARE the loss)
            return outs if not isinstance(outs, (list, tuple)) else outs[0]
        outs = _to_list(outs)
        return self._loss(*(outs + list(labels)))

    def _shard(self, arrs):
        """Shard batches over dp when active and divisible."""
        if self._dp_model is None:
            return arrs
        n = self._dp_model.group.nranks
        out = []
        for a in arrs:
            raw = a._data if isinstance(a, Tensor) else jnp.asarray(a)
            out.append(
                self._dp_model.shard_input(raw)
                if raw.ndim > 0 and raw.shape[0] % n == 0 else a
            )
        return out

    # -- the three batch engines (model.py:880-1040) -------------------------
    def train_batch(self, inputs, labels=None, update=True):
        if not self._prepared or self._optimizer is None:
            raise RuntimeError(
                "call model.prepare(optimizer, loss, ...) before training"
            )
        if not update:
            raise NotImplementedError(
                "update=False (gradient accumulation) rides through "
                "DistributedStrategy.gradient_merge instead"
            )
        if self._train_step is None:
            self._train_step = TrainStep(
                self._net(), self._loss_fn, self._optimizer,
                return_outputs=bool(self._metrics),
            )
        inputs = self._shard(_to_list(inputs))
        labels = self._shard(_to_list(labels))
        self.network.train()
        if self._metrics:
            # metrics come from the SAME forward the loss used (one fused
            # program; DynamicGraphAdapter.train_batch behavior)
            loss, outs = self._train_step(inputs, labels)
            metrics = [float(_numpy(loss).reshape(-1)[0])]
            outs = jax.tree_util.tree_map(
                lambda r: Tensor._wrap(r, stop_gradient=True)
                if not isinstance(r, Tensor) else r, outs,
            )
            metrics += self._update_metrics(outs, labels)
        else:
            loss = self._train_step(inputs, labels)
            metrics = [float(_numpy(loss).reshape(-1)[0])]
        return metrics if len(metrics) > 1 else metrics[0]

    def _update_metrics(self, outs, labels):
        vals = []
        outs = _to_list(outs)
        labels = [
            y if isinstance(y, Tensor) else Tensor(y) for y in labels
        ]
        for m in self._metrics:
            state = m.compute(*(outs + labels))
            m.update(*_to_list(state))
            vals.append(m.accumulate())
        return vals

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = self._shard(_to_list(inputs))
        labels = self._shard(_to_list(labels))
        from ..core import autograd as AG

        with AG.no_grad():
            outs = self._net()(*[
                x if isinstance(x, Tensor) else Tensor(x) for x in inputs
            ])
            loss = self._loss_fn(
                outs, *[y if isinstance(y, Tensor) else Tensor(y)
                        for y in labels]
            )
        metrics = [float(_numpy(loss).reshape(-1)[0])]
        metrics += self._update_metrics(outs, labels)
        return metrics if len(metrics) > 1 else metrics[0]

    def predict_batch(self, inputs):
        self.network.eval()
        from ..core import autograd as AG

        with AG.no_grad():
            outs = self._net()(*[
                x if isinstance(x, Tensor) else Tensor(x)
                for x in _to_list(inputs)
            ])
        return [
            _numpy(o) for o in _to_list(outs)
        ]

    # -- loops ---------------------------------------------------------------
    def _loader(self, data, batch_size, shuffle, num_workers, drop_last):
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(
                data, batch_size=batch_size, shuffle=shuffle,
                num_workers=num_workers, drop_last=drop_last,
            )
        return data  # any iterable of batches

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, num_iters=None):
        """model.py:1299."""
        loader = self._loader(
            train_data, batch_size, shuffle, num_workers, drop_last
        )
        eval_loader = self._loader(
            eval_data, batch_size, False, num_workers, False
        )
        self._save_dir = save_dir
        steps = len(loader) if hasattr(loader, "__len__") else None
        cbks = config_callbacks(
            callbacks, model=self, batch_size=batch_size, epochs=epochs,
            steps=steps, log_freq=log_freq, verbose=verbose,
            save_freq=save_freq, save_dir=save_dir,
            metrics=["loss"] + [m.name() for m in self._metrics],
        )
        self.stop_training = False
        cbks.on_train_begin()
        done_iters = 0
        logs = {}
        try:
            for epoch in range(epochs):
                cbks.on_epoch_begin(epoch)
                for m in self._metrics:
                    m.reset()
                logs = {}
                for step, batch in enumerate(loader):
                    cbks.on_train_batch_begin(step)
                    ins, labs = self._split_batch(batch)
                    vals = _to_list(self.train_batch(ins, labs))
                    logs = self._logs(vals)
                    cbks.on_train_batch_end(step, logs)
                    done_iters += 1
                    if num_iters is not None and done_iters >= num_iters:
                        self.stop_training = True
                        break
                cbks.on_epoch_end(epoch, logs)
                # a stopping run (early stop via num_iters, or a
                # preemption notice with its ticking eviction clock)
                # skips the final eval pass and exits promptly
                if eval_loader is not None \
                        and (epoch + 1) % eval_freq == 0 \
                        and not self.stop_training:
                    self.evaluate(
                        eval_loader, batch_size=batch_size,
                        log_freq=log_freq, verbose=verbose, callbacks=cbks,
                    )
                if self.stop_training:
                    break
        finally:
            # guaranteed even when training raises, so callbacks that own
            # process state (TerminateOnPreempt's SIGTERM handler) always
            # get to clean up
            cbks.on_train_end(logs)

    def _split_batch(self, batch):
        batch = _to_list(batch)
        n_in = max(len(self._inputs), 1)
        if len(batch) == 1:
            return batch, []
        return batch[:n_in], batch[n_in:]

    def _logs(self, vals):
        names = ["loss"] + [m.name() for m in self._metrics]
        return dict(zip(names, vals))

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        """model.py:1515. Returns {metric_name: value}."""
        loader = self._loader(eval_data, batch_size, False, num_workers,
                              False)
        from .callbacks import CallbackList

        own_cbks = not isinstance(callbacks, CallbackList)
        cbks = callbacks if not own_cbks else config_callbacks(
            callbacks, model=self, batch_size=batch_size, verbose=verbose,
            log_freq=log_freq,
            metrics=["loss"] + [m.name() for m in self._metrics],
        )
        for m in self._metrics:
            m.reset()
        steps = len(loader) if hasattr(loader, "__len__") else None
        cbks.on_eval_begin({"steps": steps})
        logs, losses = {}, []
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            ins, labs = self._split_batch(batch)
            vals = _to_list(self.eval_batch(ins, labs))
            losses.append(vals[0])
            logs = self._logs([float(np.mean(losses))] + vals[1:])
            cbks.on_eval_batch_end(step, logs)
            if num_iters is not None and step + 1 >= num_iters:
                break
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        """model.py:1609. Returns per-output lists of batch arrays (or
        concatenated when stack_outputs)."""
        loader = self._loader(test_data, batch_size, False, num_workers,
                              False)
        cbks = config_callbacks(
            callbacks, model=self, batch_size=batch_size, verbose=verbose,
            metrics=[],
        )
        cbks.on_predict_begin()
        outputs = None
        for step, batch in enumerate(loader):
            cbks.on_predict_batch_begin(step)
            ins, _ = self._split_batch(batch)
            outs = self.predict_batch(ins)
            if outputs is None:
                outputs = [[] for _ in outs]
            for slot, o in zip(outputs, outs):
                slot.append(o)
            cbks.on_predict_batch_end(step)
        cbks.on_predict_end()
        if outputs is None:
            return []
        if stack_outputs:
            outputs = [np.concatenate(slot, axis=0) for slot in outputs]
        return outputs

    # -- persistence (model.py:1041 save / :1135 load) -----------------------
    def save(self, path, training=True):
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        fio.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            opt = getattr(self._optimizer, "_inner", self._optimizer)
            fio.save(opt.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = fio.load(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None \
                and os.path.exists(opt_path):
            opt = getattr(self._optimizer, "_inner", self._optimizer)
            opt.set_state_dict(fio.load(opt_path))

    # -- misc ----------------------------------------------------------------
    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary

        if input_size is None and not self._inputs:
            raise ValueError("summary needs input_size or Model inputs spec")
        if input_size is None:
            input_size = [tuple(s.shape) for s in self._inputs]
        return summary(self.network, input_size, dtypes=dtype)
