"""paddle_tpu.hapi — the high-level API (reference: python/paddle/hapi/:
model.py Model trainer, callbacks.py, model_summary.py)."""
from .model import Model  # noqa: F401
from .summary import flops, summary  # noqa: F401
from . import callbacks  # noqa: F401
