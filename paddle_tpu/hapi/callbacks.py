"""hapi callbacks (reference: python/paddle/hapi/callbacks.py — Callback
:117, CallbackList :23, ProgBarLogger :313, ModelCheckpoint :503,
LRScheduler :583, EarlyStopping :653; VisualDL sink accepted as a stub,
SURVEY.md §5 observability)."""
from __future__ import annotations

import os
import time
from typing import List, Optional

import numpy as np

__all__ = [
    "Callback", "ProgBarLogger", "ModelCheckpoint", "LRScheduler",
    "EarlyStopping", "VisualDL", "TerminateOnPreempt", "GuardCallback",
]


class Callback:
    """reference callbacks.py:117. Every hook is optional."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None): pass
    def on_train_end(self, logs=None): pass
    def on_eval_begin(self, logs=None): pass
    def on_eval_end(self, logs=None): pass
    def on_predict_begin(self, logs=None): pass
    def on_predict_end(self, logs=None): pass
    def on_epoch_begin(self, epoch, logs=None): pass
    def on_epoch_end(self, epoch, logs=None): pass
    def on_train_batch_begin(self, step, logs=None): pass
    def on_train_batch_end(self, step, logs=None): pass
    def on_eval_batch_begin(self, step, logs=None): pass
    def on_eval_batch_end(self, step, logs=None): pass
    def on_predict_batch_begin(self, step, logs=None): pass
    def on_predict_batch_end(self, step, logs=None): pass


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_params(self, params):
        for cb in self.callbacks:
            cb.set_params(params)

    def set_model(self, model):
        for cb in self.callbacks:
            cb.set_model(model)

    def _call(self, name, *args):
        for cb in self.callbacks:
            getattr(cb, name)(*args)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *args: self._call(name, *args)
        raise AttributeError(name)


def config_callbacks(callbacks=None, model=None, batch_size=None,
                     epochs=None, steps=None, log_freq=2, verbose=2,
                     save_freq=1, save_dir=None, metrics=None,
                     mode="train"):
    """callbacks.py:23 config_callbacks: user callbacks + defaults."""
    if isinstance(callbacks, Callback):
        callbacks = [callbacks]
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks):
        cbks.append(ProgBarLogger(log_freq, verbose=verbose))
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    if model is not None and not any(
        isinstance(c, LRScheduler) for c in cbks
    ):
        cbks.append(LRScheduler())
    cbk_list = CallbackList(cbks)
    cbk_list.set_model(model)
    cbk_list.set_params({
        "batch_size": batch_size, "epochs": epochs, "steps": steps,
        "verbose": verbose, "metrics": metrics or ["loss"],
    })
    return cbk_list


class ProgBarLogger(Callback):
    """Per-epoch progress logging (callbacks.py:313). verbose 0 silent,
    1 epoch summaries, 2 per-log_freq step lines."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def _fmt(self, logs):
        out = []
        for k in self.params.get("metrics", []):
            if k in (logs or {}):
                v = logs[k]
                if isinstance(v, (list, tuple, np.ndarray)):
                    v = np.asarray(v).reshape(-1)
                    out.append(f"{k}: " + "/".join(f"{x:.4f}" for x in v))
                else:
                    out.append(f"{k}: {v:.4f}")
        return " - ".join(out)

    def on_train_begin(self, logs=None):
        self._t0 = time.time()

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        if self.verbose and self.params.get("epochs"):
            print(f"Epoch {epoch + 1}/{self.params['epochs']}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and step % self.log_freq == 0:
            total = self.steps if self.steps is not None else "?"
            print(f"step {step + 1}/{total} - {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            print(f"Epoch {epoch + 1} - {self._fmt(logs)}")

    def on_eval_begin(self, logs=None):
        if self.verbose:
            n = (logs or {}).get("steps")
            print(f"Eval begin ({n} steps)" if n else "Eval begin")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    """Save every `save_freq` epochs + final (callbacks.py:503)."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """Step the optimizer's LRScheduler (callbacks.py:583): per epoch by
    default, or per `by_step` batches."""

    def __init__(self, by_step=False, by_epoch=True):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched

        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None) if opt else None
        return lr if isinstance(lr, Sched) else None

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()


class EarlyStopping(Callback):
    """Stop when `monitor` stops improving (callbacks.py:653)."""

    def __init__(self, monitor="loss", mode="auto", patience=0,
                 verbose=1, min_delta=0, baseline=None,
                 save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode not in ("auto", "min", "max"):
            mode = "auto"
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.wait = 0
        self.best = None
        self.stopped_epoch = 0

    def _better(self, cur, ref):
        d = self.min_delta if self.mode == "max" else -self.min_delta
        return cur > ref + d if self.mode == "max" else cur < ref + d

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.best = self.baseline

    def on_eval_end(self, logs=None):
        logs = logs or {}
        if self.monitor not in logs:
            return
        cur = logs[self.monitor]
        if isinstance(cur, (list, tuple, np.ndarray)):
            cur = float(np.asarray(cur).reshape(-1)[0])
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
            if self.save_best_model and getattr(
                self.model, "_save_dir", None
            ):
                self.model.save(
                    os.path.join(self.model._save_dir, "best_model")
                )
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(
                        f"Early stopping: {self.monitor} did not improve "
                        f"for {self.wait} evals (best {self.best:.5f})"
                    )


class TerminateOnPreempt(Callback):
    """Preemption-notice handler — the hapi face of the elastic runtime.

    On SIGTERM (the cloud's eviction warning, forwarded to every rank by
    the elastic launcher): finish the in-flight batch/epoch, save a
    `save_dir/preempt` checkpoint, and stop training cleanly. Also emits
    a rank heartbeat (distributed.elastic.heartbeat) per batch so the
    launcher's hung-rank watchdog sees a live trainer between epochs.
    """

    def __init__(self, save_dir=None, verbose=1):
        super().__init__()
        self.save_dir = save_dir
        self.verbose = verbose
        self.preempted = False
        self._old_handler = None

    def _on_notice(self):
        self.preempted = True

    def on_train_begin(self, logs=None):
        from ..distributed.elastic import install_preempt_notice

        self.preempted = False
        self._old_handler = install_preempt_notice(self._on_notice)

    def on_train_batch_end(self, step, logs=None):
        from ..distributed.elastic import heartbeat

        heartbeat()
        if self.preempted:
            self.model.stop_training = True

    def on_epoch_end(self, epoch, logs=None):
        if not self.preempted:
            return
        self.model.stop_training = True
        save_dir = self.save_dir or getattr(self.model, "_save_dir", None)
        if save_dir:
            path = os.path.join(save_dir, "preempt")
            self.model.save(path)
            if self.verbose:
                print(f"TerminateOnPreempt: SIGTERM received — saved "
                      f"{path}, stopping after epoch {epoch}")
        if self.verbose:
            # surface the comm-monitor flight recorder (already dumped by
            # the chained notice handler) so the operator reading the
            # hapi log finds the collective stream next to the workerlog
            from ..distributed import comm_monitor

            dump = comm_monitor.dump_flight_recorder("preempt")
            if dump:
                print(f"TerminateOnPreempt: collective flight recorder "
                      f"at {dump}")

    def on_train_end(self, logs=None):
        from ..distributed.elastic import restore_preempt_notice

        restore_preempt_notice(self._old_handler)
        self._old_handler = None


class GuardCallback(Callback):
    """Numerical-guardrail face of hapi training (utils/train_guard.py).

    `Model.fit` already trains through the fused `jit.TrainStep`, so the
    in-graph sentinel and skip-and-rescue masking apply automatically
    under `PADDLE_GUARD_MODE=skip|abort`. This callback adds the
    hapi-level policy on top, using the per-batch loss the fit loop
    already pulled to the host (so it costs nothing extra):

    - a nonfinite logged loss — or, with ``spike_factor`` > 0, a finite
      loss above ``spike_factor x EWMA`` — counts as a *bad batch*;
    - every healthy epoch end writes a ``save_dir/guard_last_good``
      snapshot (rescue anchor; reuses `Model.save`);
    - past ``max_skips`` consecutive bad batches it restores that
      snapshot (`Model.load`) when one exists, else stops training —
      emitting a `guard_rollback` / `guard_stop` JSONL event either way
      (`PADDLE_GUARD_EVENT_FILE`, the stream the ElasticManager reads
      for kill attribution; since round 9 every emit also lands on the
      unified telemetry bus — README "Observability" — so hapi guard
      events merge into the same `tools/timeline.py` view as the
      in-graph guard's).
    """

    def __init__(self, max_skips=None, save_dir=None, spike_factor=None,
                 ewma_decay=0.9, warmup=20, verbose=1):
        super().__init__()
        from ..utils import train_guard as tg

        self.max_skips = (max_skips if max_skips is not None
                          else tg._envi(tg._MAX_SKIPS_ENV, 8))
        self.spike_factor = (spike_factor if spike_factor is not None
                             else tg._envf(tg._SPIKE_ENV, 0.0))
        self.save_dir = save_dir
        self.ewma_decay = float(ewma_decay)
        self.warmup = int(warmup)
        self.verbose = verbose
        self._reset()

    def _reset(self):
        self.consec = 0
        self.total_bad = 0
        self.rollbacks = 0
        self._ewma = None
        self._healthy = 0
        self._anchor = None

    def _loss_of(self, logs):
        v = (logs or {}).get("loss")
        if isinstance(v, (list, tuple, np.ndarray)):
            v = np.asarray(v).reshape(-1)[0]
        return None if v is None else float(v)

    def on_train_begin(self, logs=None):
        self._reset()

    def on_train_batch_end(self, step, logs=None):
        from ..utils import train_guard as tg

        loss = self._loss_of(logs)
        if loss is None:
            return
        bad = not np.isfinite(loss)
        spiked = (not bad and self.spike_factor > 0.0
                  and self._healthy >= self.warmup
                  and self._ewma is not None
                  and loss > self.spike_factor * abs(self._ewma))
        if bad or spiked:
            self.consec += 1
            self.total_bad += 1
            tg.emit_event(
                "guard_skip", step=step, consec=self.consec,
                loss=loss if np.isfinite(loss) else None,
                detail=f"hapi batch {step}: "
                       + ("loss nonfinite" if bad else
                          f"loss spike {loss:.6g} > "
                          f"{self.spike_factor:g}x ewma {self._ewma:.6g}"))
            if self.consec >= self.max_skips:
                self._rescue(step)
            return
        self.consec = 0
        self._healthy += 1
        self._ewma = (loss if self._ewma is None
                      else self.ewma_decay * self._ewma
                      + (1.0 - self.ewma_decay) * loss)

    def _rescue(self, step):
        from ..utils import train_guard as tg

        detail = (f"hapi divergence: {self.consec} consecutive bad "
                  f"batches (budget {self.max_skips})")
        if self._anchor:
            self.model.load(self._anchor)
            self.rollbacks += 1
            self.consec = 0
            tg.emit_event("guard_rollback", step=step,
                          anchor=self._anchor, detail=detail)
            if self.verbose:
                print(f"GuardCallback: {detail}; restored {self._anchor}")
        else:
            self.model.stop_training = True
            tg.emit_event("guard_stop", step=step, detail=detail)
            if self.verbose:
                print(f"GuardCallback: {detail}; no last-good snapshot — "
                      "stopping training")

    def on_epoch_end(self, epoch, logs=None):
        save_dir = self.save_dir or getattr(self.model, "_save_dir", None)
        if save_dir and self.consec == 0:
            path = os.path.join(save_dir, "guard_last_good")
            self.model.save(path)
            self._anchor = path


class VisualDL(Callback):
    """Metrics sink stub: records scalars into an in-memory dict (the
    VisualDL dashboard writer is a GUI dependency; the log structure —
    tag -> [(step, value)] — matches what its add_scalar would receive)."""

    def __init__(self, log_dir="./log"):
        super().__init__()
        self.log_dir = log_dir
        self.scalars = {}
        self._step = 0

    def _record(self, prefix, logs):
        for k, v in (logs or {}).items():
            if isinstance(v, (int, float, np.floating, np.integer)):
                self.scalars.setdefault(f"{prefix}/{k}", []).append(
                    (self._step, float(v))
                )

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        self._record("train", logs)

    def on_eval_end(self, logs=None):
        self._record("eval", logs)
