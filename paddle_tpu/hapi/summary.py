"""paddle.summary (reference: python/paddle/hapi/model_summary.py —
summary() builds a per-layer table via forward hooks and reports parameter
totals)."""
from __future__ import annotations

from typing import List

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer import Layer

__all__ = ["summary"]


def _shapes(out):
    if isinstance(out, Tensor):
        return list(out.shape)
    if isinstance(out, (list, tuple)):
        return [_shapes(o) for o in out]
    return []


def summary(net: Layer, input_size, dtypes=None):
    """Print a layer table; returns {'total_params', 'trainable_params'}.

    `input_size`: a shape tuple, or list of shape tuples for multi-input
    forwards. A -1 leading dim means batch (replaced by 1)."""
    if isinstance(input_size, tuple):
        input_sizes = [input_size]
    elif isinstance(input_size, list) and input_size \
            and isinstance(input_size[0], int):
        input_sizes = [tuple(input_size)]
    else:
        input_sizes = [tuple(s) for s in input_size]
    dtypes = dtypes or ["float32"] * len(input_sizes)
    if isinstance(dtypes, str):
        dtypes = [dtypes] * len(input_sizes)

    rows: List[tuple] = []
    hooks = []

    def make_hook(name, layer):
        def hook(lyr, inputs, output=None):
            n_params = sum(
                int(np.prod(p.shape)) for p in lyr.parameters(
                    include_sublayers=False
                )
            )
            rows.append(
                (f"{type(lyr).__name__}-{len(rows) + 1}",
                 _shapes(output), n_params)
            )
        return hook

    for name, sub in net.named_sublayers():
        if not sub.sublayers():  # leaf layers only
            hooks.append(sub.register_forward_post_hook(make_hook(name, sub)))

    was_training = net.training
    net.eval()
    try:
        ins = [
            Tensor(np.zeros(
                tuple(1 if d == -1 else d for d in shape), dtype=dt
            ))
            for shape, dt in zip(input_sizes, dtypes)
        ]
        net(*ins)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(
        int(np.prod(p.shape)) for p in net.parameters() if p.trainable
    )
    name_w = max([len(r[0]) for r in rows] + [12]) + 2
    print("-" * (name_w + 40))
    print(f"{'Layer (type)':<{name_w}}{'Output Shape':<24}{'Param #':>10}")
    print("=" * (name_w + 40))
    for name, shape, n in rows:
        print(f"{name:<{name_w}}{str(shape):<24}{n:>10,}")
    print("=" * (name_w + 40))
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print("-" * (name_w + 40))
    return {"total_params": total, "trainable_params": trainable}
