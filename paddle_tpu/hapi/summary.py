"""paddle.summary (reference: python/paddle/hapi/model_summary.py —
summary() builds a per-layer table via forward hooks and reports parameter
totals)."""
from __future__ import annotations

from typing import List

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer import Layer

__all__ = ["summary", "flops"]


def _shapes(out):
    if isinstance(out, Tensor):
        return list(out.shape)
    if isinstance(out, (list, tuple)):
        return [_shapes(o) for o in out]
    return []


def _canon_input_sizes(input_size):
    """int-sequence | shape tuple | sequence of shape tuples -> list of
    shape tuples (shared by summary and flops)."""
    seq = list(input_size)
    if seq and isinstance(seq[0], (tuple, list)):
        return [tuple(s) for s in seq]
    return [tuple(seq)]


def _build_dummy_inputs(input_sizes, dtypes):
    dtypes = dtypes or ["float32"] * len(input_sizes)
    if isinstance(dtypes, str):
        dtypes = [dtypes] * len(input_sizes)
    return [
        Tensor(np.zeros(
            tuple(1 if d == -1 else d for d in shape), dt
        ))
        for shape, dt in zip(input_sizes, dtypes)
    ]


def _run_with_leaf_hooks(net, input_sizes, dtypes, make_hook):
    """Register `make_hook()` on every leaf sublayer, run a dummy eval
    forward, restore mode, always remove hooks."""
    hooks = [
        sub.register_forward_post_hook(make_hook())
        for _, sub in net.named_sublayers() if not sub.sublayers()
    ]
    was_training = net.training
    net.eval()
    try:
        net(*_build_dummy_inputs(input_sizes, dtypes))
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()


def summary(net: Layer, input_size, dtypes=None):
    """Print a layer table; returns {'total_params', 'trainable_params'}.

    `input_size`: a shape tuple, or list of shape tuples for multi-input
    forwards. A -1 leading dim means batch (replaced by 1)."""
    if isinstance(input_size, tuple):
        input_sizes = [input_size]
    elif isinstance(input_size, list) and input_size \
            and isinstance(input_size[0], int):
        input_sizes = [tuple(input_size)]
    else:
        input_sizes = [tuple(s) for s in input_size]
    dtypes = dtypes or ["float32"] * len(input_sizes)
    if isinstance(dtypes, str):
        dtypes = [dtypes] * len(input_sizes)

    rows: List[tuple] = []
    hooks = []

    def make_hook(name, layer):
        def hook(lyr, inputs, output=None):
            n_params = sum(
                int(np.prod(p.shape)) for p in lyr.parameters(
                    include_sublayers=False
                )
            )
            rows.append(
                (f"{type(lyr).__name__}-{len(rows) + 1}",
                 _shapes(output), n_params)
            )
        return hook

    for name, sub in net.named_sublayers():
        if not sub.sublayers():  # leaf layers only
            hooks.append(sub.register_forward_post_hook(make_hook(name, sub)))

    was_training = net.training
    net.eval()
    try:
        ins = [
            Tensor(np.zeros(
                tuple(1 if d == -1 else d for d in shape), dtype=dt
            ))
            for shape, dt in zip(input_sizes, dtypes)
        ]
        net(*ins)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(
        int(np.prod(p.shape)) for p in net.parameters() if p.trainable
    )
    name_w = max([len(r[0]) for r in rows] + [12]) + 2
    print("-" * (name_w + 40))
    print(f"{'Layer (type)':<{name_w}}{'Output Shape':<24}{'Param #':>10}")
    print("=" * (name_w + 40))
    for name, shape, n in rows:
        print(f"{name:<{name_w}}{str(shape):<24}{n:>10,}")
    print("=" * (name_w + 40))
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print("-" * (name_w + 40))
    return {"total_params": total, "trainable_params": trainable}


def flops(net: Layer, input_size, custom_ops=None, print_detail=False,
          dtypes=None):
    """paddle.flops (reference: hapi/dynamic_flops.py): per-layer FLOP
    accounting via forward hooks. Counts multiply-accumulates for
    conv/linear (the reference's convention) and elementwise costs for
    norm/activation/pool; `custom_ops` maps Layer type -> fn(layer,
    input_shape, output_shape) -> flops. `dtypes` matches summary's (int
    dtypes let embedding-first models be measured)."""
    custom_ops = custom_ops or {}
    rows = []

    def count(lyr, inputs, output):
        in_shape = list(inputs[0].shape) if inputs else []
        out_shape = _shapes(output)
        n_out = int(np.prod(out_shape)) if out_shape and isinstance(
            out_shape[0], int
        ) else 0
        cls = type(lyr)
        if cls in custom_ops:
            f = custom_ops[cls](lyr, in_shape, out_shape)
        elif hasattr(lyr, "_kernel_size") or cls.__name__.startswith("Conv"):
            k = getattr(lyr, "_kernel_size", getattr(lyr, "kernel_size", [1]))
            k = k if isinstance(k, (list, tuple)) else [k]
            cin = getattr(lyr, "_in_channels", in_shape[1] if len(in_shape) > 1 else 1)
            groups = getattr(lyr, "_groups", 1) or 1
            f = n_out * int(np.prod(k)) * cin // groups
        elif cls.__name__ == "Linear":
            f = n_out * lyr.weight.shape[0]
        elif cls.__name__ in ("BatchNorm2D", "BatchNorm1D", "BatchNorm",
                              "LayerNorm", "GroupNorm"):
            f = 2 * n_out
        elif cls.__name__.endswith("Pool2D") or cls.__name__ in (
            "ReLU", "GELU", "Sigmoid", "Tanh", "Softmax", "Dropout",
        ):
            f = n_out
        else:
            f = 0
        rows.append((f"{cls.__name__}-{len(rows) + 1}", out_shape, f))

    def make_hook():
        def hook(lyr, inputs, output=None):
            count(lyr, inputs, output)
        return hook

    _run_with_leaf_hooks(net, _canon_input_sizes(input_size), dtypes,
                         make_hook)

    total = sum(r[2] for r in rows)
    if print_detail:
        for name, shape, f in rows:
            print(f"{name:<24}{str(shape):<24}{f:>14,}")
    print(f"Total Flops: {total}     Total Params: "
          f"{sum(int(np.prod(p.shape)) for p in net.parameters()):,}")
    return total
