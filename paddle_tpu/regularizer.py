"""Weight-decay regularizers (reference: python/paddle/fluid/regularizer.py).
Applied to gradients at optimizer.step time (append_regularization_ops
analog)."""
from __future__ import annotations


class WeightDecayRegularizer:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    def grad_term(self, p_raw):
        raise NotImplementedError


class L2Decay(WeightDecayRegularizer):
    def grad_term(self, p_raw):
        return self._coeff * p_raw


class L1Decay(WeightDecayRegularizer):
    def grad_term(self, p_raw):
        import jax.numpy as jnp

        return self._coeff * jnp.sign(p_raw)
