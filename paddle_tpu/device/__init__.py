"""paddle.device parity surface + HBM budgeting.

Reference: python/paddle/device/__init__.py (set_device/get_device) and
python/paddle/device/cuda (memory_allocated / max_memory_allocated /
memory_reserved over the C++ allocator's stats,
memory/allocation/allocator_facade.*).

TPU-native: PJRT owns the allocator; the budgeting surface reads each
device's live allocator statistics (`jax.Device.memory_stats()`), so the
same API answers "how much HBM is this job using / what is the limit"
that the reference's StatAllocator answers for GPU memory.
"""
from __future__ import annotations

from ..core.device import (  # noqa: F401
    get_device,
    is_compiled_with_cuda,
    set_device,
)

__all__ = [
    "set_device", "get_device", "memory_stats", "memory_allocated",
    "max_memory_allocated", "memory_reserved", "device_count", "cuda",
]


def _device(dev=None):
    import jax

    if dev is None:
        return jax.devices()[0]
    if isinstance(dev, int):
        return jax.devices()[dev]
    return dev


def memory_stats(device=None) -> dict:
    """Raw PJRT allocator stats (bytes_in_use, peak_bytes_in_use,
    bytes_limit, ...). Empty dict on backends without stats (CPU)."""
    try:
        return dict(_device(device).memory_stats() or {})
    except Exception:
        return {}


def memory_allocated(device=None) -> int:
    """paddle.device.cuda.memory_allocated analog: live HBM bytes."""
    return int(memory_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    """Peak HBM bytes since process start."""
    return int(memory_stats(device).get("peak_bytes_in_use", 0))


def memory_reserved(device=None) -> int:
    """Total HBM the allocator may use (bytes_limit)."""
    return int(memory_stats(device).get("bytes_limit", 0))


def device_count() -> int:
    import jax

    return jax.device_count()


class _CudaShim:
    """paddle.device.cuda compatibility: scripts probing GPU memory get
    the accelerator's numbers (TPU HBM here)."""

    memory_allocated = staticmethod(memory_allocated)
    max_memory_allocated = staticmethod(max_memory_allocated)
    memory_reserved = staticmethod(memory_reserved)
    max_memory_reserved = staticmethod(memory_reserved)

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def empty_cache():
        return None  # PJRT frees eagerly; parity no-op


cuda = _CudaShim()
