"""Config-driven single-op benchmark harness.

The analog of the reference's op benchmark tester
(paddle/fluid/operators/benchmark/op_tester.h:30 + op_tester_config.h) —
time ONE op at given shapes/dtypes to localize regressions, instead of
inferring from end-to-end steps.

Timing method (validated against known-FLOP matmuls on the tunneled TPU,
see tools/PERF.md):
  - the op runs R times inside ONE jitted ``lax.scan`` so a single device
    dispatch amortizes the host->device round trip (~90ms on the tunnel);
  - the scan carry perturbs the op's first input each iteration, which
    defeats XLA loop-invariant code motion (a loop whose body does not
    depend on the carry is hoisted and executes ONCE — every naive
    timing loop here measures dispatch latency, not the op);
  - the warmup call uses different operand values than the timed call so
    a runtime result-cache cannot serve the timed execution;
  - the barrier is a device_get of a small output slice
    (``jax.block_until_ready`` is a no-op on the axon tunnel platform).

Usage::

    from paddle_tpu.utils.op_bench import bench_op, run_suite
    ms = bench_op(lambda x, w: x @ w, [(1024, 1024), (1024, 1024)])
    rows = run_suite()           # the built-in conv/bn/matmul suite
    python -m paddle_tpu.utils.op_bench [config.json]

Config file: a JSON list of rows ``{"name": ..., "op": "<expr over jnp,
jax, args a,b,c>", "shapes": [[...], ...], "dtype": "bfloat16",
"repeat": 50}``.
"""
from __future__ import annotations

import json
import sys
import time
from typing import Callable, Sequence

import numpy as np

__all__ = ["bench_op", "run_suite", "DEFAULT_SUITE", "scan_overhead_ms"]

_overhead_ms = None


def scan_overhead_ms() -> float:
    """Per-iteration overhead of the chained-scan timing loop itself,
    measured once per process on a trivially small op. Subtracted from
    every measurement (``ms_net``): on the axon tunnel this is ~0.8 ms and
    would otherwise swamp sub-millisecond ops."""
    global _overhead_ms
    if _overhead_ms is None:
        import jax
        import jax.numpy as jnp

        a = jax.device_put(jnp.zeros((8, 128), jnp.float32))

        @jax.jit
        def run(a):
            def body(c, _):
                return (a + c).ravel()[0] * 1e-30, None

            c, _ = jax.lax.scan(
                body, jnp.zeros((), jnp.float32), None, length=200
            )
            return c

        _ = np.asarray(run(a))
        best = float("inf")
        for i in range(3):  # tunnel jitter: keep the best of 3
            t0 = time.perf_counter()
            _ = np.asarray(run(a + (i + 1)))
            best = min(best, (time.perf_counter() - t0) / 200 * 1e3)
        _overhead_ms = best
    return _overhead_ms


def bench_op(
    op: Callable,
    shapes: Sequence[Sequence[int]],
    dtype="float32",
    repeat: int = 50,
    flops: float | None = None,
) -> dict:
    """Time one op. Returns {ms, gbps_read, tflops (if flops given)}.

    ``op`` takes jnp arrays (one per entry of ``shapes``) and returns an
    array or tuple of arrays.
    """
    import jax
    import jax.numpy as jnp

    dt = jnp.dtype(dtype)
    rng = np.random.RandomState(0)
    args = [
        jax.device_put(jnp.asarray(
            rng.rand(*s).astype(np.float32) - 0.5).astype(dt))
        for s in shapes
    ]

    @jax.jit
    def run(*args):
        def body(carry, _):
            # perturb the first operand with the carry: forces the body to
            # stay inside the loop (no LICM) and re-read every operand
            a0 = args[0] + carry.astype(args[0].dtype)
            out = op(a0, *args[1:])
            leaf = out[0] if isinstance(out, (tuple, list)) else out
            return jnp.ravel(leaf)[0].astype(jnp.float32) * 1e-30, None

        carry, _ = jax.lax.scan(
            body, jnp.zeros((), jnp.float32), None, length=repeat
        )
        return carry

    warm_args = [a + 1 for a in args]
    _ = np.asarray(run(*warm_args))  # compile + warm on DIFFERENT values
    dt_s = float("inf")
    for _i in range(3):  # tunnel jitter: keep the best of 3
        t0 = time.perf_counter()
        _ = np.asarray(run(*args))
        dt_s = min(dt_s, (time.perf_counter() - t0) / repeat)

    in_bytes = sum(
        int(np.prod(s)) * jnp.dtype(dtype).itemsize for s in shapes
    )
    ovh_s = scan_overhead_ms() / 1e3
    net_s = max(dt_s - ovh_s, 0.0)
    row = {
        "ms": round(dt_s * 1e3, 4),
        "ms_net": round(net_s * 1e3, 4),
        "overhead_ms": round(ovh_s * 1e3, 4),
    }
    if net_s < 0.5 * dt_s:
        # the scan-loop overhead dominates: the op is faster than the
        # harness can resolve on this platform — treat rates as lower
        # bounds only
        row["overhead_bound"] = True
    rate_s = max(net_s, 0.25 * dt_s)
    row["gbps_read"] = round(in_bytes / rate_s / 1e9, 1)
    if flops is not None:
        row["tflops"] = round(flops / rate_s / 1e12, 2)
    return row


def _conv2d(stride=1, pad=0):
    import jax

    def op(x, w):
        dn = jax.lax.conv_dimension_numbers(
            x.shape, w.shape, ("NHWC", "HWIO", "NHWC")
        )
        return jax.lax.conv_general_dilated(
            x, w, (stride, stride), [(pad, pad), (pad, pad)],
            dimension_numbers=dn,
        )

    return op


def _bn_stats(x):
    import jax.numpy as jnp

    xf = x.astype(jnp.float32)
    return jnp.mean(xf, axis=(0, 1, 2)), jnp.mean(
        jnp.square(xf), axis=(0, 1, 2)
    )


def _suite():
    import jax.numpy as jnp

    def conv_flops(n, h, w, cin, cout, k, stride):
        oh, ow = h // stride, w // stride
        return 2.0 * n * oh * ow * cin * cout * k * k

    return [
        # MXU calibration
        ("matmul_4096_bf16", lambda a, b: a @ b,
         [(4096, 4096), (4096, 4096)], "bfloat16", 2.0 * 4096 ** 3),
        # ResNet-50 conv shapes (NHWC)
        ("conv_stem_7x7s2", _conv2d(2, 3),
         [(256, 224, 224, 3), (7, 7, 3, 64)], "bfloat16",
         conv_flops(256, 224, 224, 3, 64, 7, 2)),
        ("conv_1x1_c64_256", _conv2d(1, 0),
         [(256, 56, 56, 64), (1, 1, 64, 256)], "bfloat16",
         conv_flops(256, 56, 56, 64, 256, 1, 1)),
        ("conv_3x3_c128", _conv2d(1, 1),
         [(256, 28, 28, 128), (3, 3, 128, 128)], "bfloat16",
         conv_flops(256, 28, 28, 128, 128, 3, 1)),
        ("conv_3x3_c512", _conv2d(1, 1),
         [(256, 7, 7, 512), (3, 3, 512, 512)], "bfloat16",
         conv_flops(256, 7, 7, 512, 512, 3, 1)),
        # VPU / HBM: per-channel stat reductions (the BN hot spot)
        ("bn_stats_c64", _bn_stats, [(256, 56, 56, 64)], "bfloat16", None),
        ("bn_stats_c256", _bn_stats, [(256, 56, 56, 256)], "bfloat16", None),
        ("bn_stats_c1024", _bn_stats, [(256, 14, 14, 1024)], "bfloat16",
         None),
        # elementwise HBM
        ("ew_add_411MB", lambda a, b: a + b,
         [(256, 56, 56, 256), (256, 56, 56, 256)], "bfloat16", None),
        ("softmax_s2048", lambda a: jnp.exp(
            a - a.max(-1, keepdims=True)), [(32, 2048, 2048)], "bfloat16",
         None),
    ]


DEFAULT_SUITE = [row[0] for row in _suite()]


def run_suite(names=None) -> list[dict]:
    rows = []
    for name, op, shapes, dtype, flops in _suite():
        if names and name not in names:
            continue
        r = bench_op(op, shapes, dtype=dtype, flops=flops)
        r["name"] = name
        rows.append(r)
    return rows


def _run_config(path: str) -> list[dict]:
    import jax  # noqa: F401  (exposed to config expressions)
    import jax.numpy as jnp  # noqa: F401

    with open(path) as f:
        cfg = json.load(f)
    rows = []
    for item in cfg:
        ns = {"jnp": jnp, "jax": jax, "np": np}
        arity = len(item["shapes"])
        argnames = ["a", "b", "c", "d"][:arity]
        fn = eval(  # noqa: S307 — explicit user-provided config expression
            f"lambda {', '.join(argnames)}: {item['op']}", ns
        )
        r = bench_op(
            fn,
            item["shapes"],
            dtype=item.get("dtype", "float32"),
            repeat=item.get("repeat", 50),
            flops=item.get("flops"),
        )
        r["name"] = item.get("name", item["op"])
        rows.append(r)
    return rows


if __name__ == "__main__":
    out = (
        _run_config(sys.argv[1]) if len(sys.argv) > 1 else run_suite()
    )
    for r in out:
        print(json.dumps(r))
