"""Numerical guardrails for the compiled training step.

The reference framework's numerical tripwire is ``FLAGS_check_nan_inf``
(platform/flags.cc:44 -> CheckVarHasNanOrInf): a per-op, host-syncing
debug flag that only exists in eager mode. The fused ``TrainStep`` XLA
program — the hot path actual training runs through — had zero
protection: one overflowed step silently poisons every donated parameter
buffer in HBM, and the first symptom is a NaN loss thousands of steps
later. At pod scale this is the dominant non-hardware failure mode
(MLPerf-on-pods, PAPERS.md); PR 1/2 built process- and comms-level
rescue (elastic relaunch, collective flight recorder) with no numerical
counterpart.

This module is the numerical counterpart. Three pieces:

- **in-graph sentinel** (``grad_health`` / ``update_guard_state``, used
  by ``jit.TrainStep`` and ``fleet.LocalSGDStep``): every compiled step
  also computes a tiny health word — ``isfinite(loss)``, a single fused
  square-sum reduction over all grads (one extra read; NaN/Inf anywhere
  propagates into the global grad-norm), optionally
  ``isfinite(new_params)`` — and when the word trips, the step becomes a
  no-op via ``jnp.where`` masking: params and optimizer state pass
  through unchanged (donation preserved), the fp16 loss scaler counts a
  bad step and backs off. The guard's policy counters (consecutive bad
  steps, loss EWMA, totals) ride the program as a tiny f32 carry (not
  donated — the host monitor's deferred read must outlive the next
  dispatch), so
  the host never syncs per step.
- **host monitor** (:class:`TrainGuard`): reads the device guard state
  every ``PADDLE_GUARD_SYNC_EVERY`` steps through an async prefetch
  (``copy_to_host_async`` now, read one interval later — zero stall on
  the tunneled platform where a blocking 4-byte devget costs a full
  RTT). Skipped steps are no-ops, so a bounded observation lag loses
  nothing. Past ``PADDLE_GUARD_MAX_SKIPS`` consecutive bad steps the
  monitor *rescues*: restore the last CRC-verified ``auto_checkpoint``
  generation (which PR-this also carries scaler + guard state through),
  or — mode ``abort`` — emit a machine-readable event and exit with
  :data:`GUARD_ABORT_RC` so the ElasticManager attributes the kill,
  exactly like a collective timeout.
- **attribution capture**: the monitor keeps a small ring of recent step
  records (RNG key + input/label arrays); on the first observed bad
  step it dumps the faulting step's bundle (params, batch, key) to
  ``PADDLE_GUARD_DUMP_DIR`` so ``tools/replay_step.py`` can re-execute
  it eagerly under ``FLAGS_check_nan_inf`` and name the first op that
  produced the NaN — "loss is NaN" becomes a file:op diagnosis.

Knobs (all documented in the README "Training guardrails" table)::

    PADDLE_GUARD_MODE          off | skip (default) | abort
    PADDLE_GUARD_MAX_SKIPS     consecutive bad steps before rescue (8)
    PADDLE_GUARD_SYNC_EVERY    host observation interval, steps (4)
    PADDLE_GUARD_CHECK_PARAMS  1 = also isfinite-check updated params
    PADDLE_GUARD_SPIKE_FACTOR  loss > factor * EWMA counts as divergence
                               (0 = spike detection off)
    PADDLE_GUARD_EWMA          loss EWMA decay (0.9)
    PADDLE_GUARD_SPIKE_WARMUP  healthy steps before spikes count (20)
    PADDLE_GUARD_EVENT_FILE    JSONL event stream (set by the launcher)
    PADDLE_GUARD_DUMP_DIR      where replay bundles land (off when unset)
"""
from __future__ import annotations

import os
import sys
import time
import weakref
import zlib
from collections import deque
from typing import Dict, List, Optional

__all__ = [
    "TrainGuard", "GuardDivergenceError", "GUARD_ABORT_RC", "GUARD_LEN",
    "guard_mode", "init_guard_state", "grad_health", "update_guard_state",
    "mask_step", "emit_event", "set_rescue_target",
]

_MODE_ENV = "PADDLE_GUARD_MODE"
_MAX_SKIPS_ENV = "PADDLE_GUARD_MAX_SKIPS"
_SYNC_ENV = "PADDLE_GUARD_SYNC_EVERY"
_CHECK_PARAMS_ENV = "PADDLE_GUARD_CHECK_PARAMS"
_SPIKE_ENV = "PADDLE_GUARD_SPIKE_FACTOR"
_EWMA_ENV = "PADDLE_GUARD_EWMA"
_WARMUP_ENV = "PADDLE_GUARD_SPIKE_WARMUP"
_EVENT_ENV = "PADDLE_GUARD_EVENT_FILE"
_DUMP_ENV = "PADDLE_GUARD_DUMP_DIR"

#: exit code of a guard abort (97 = collective timeout, 98 = launcher
#: watchdog verdict; 96 = the trainer's own numerical verdict)
GUARD_ABORT_RC = 96

#: guard-state vector layout (f32[GUARD_LEN], threaded through the step):
#: 0 consec_bad  1 total_skips  2 total_spikes  3 loss_ewma
#: 4 last_gnorm  5 last_health_bits  6 healthy_steps  7 last_loss
#: 8 gnorm_ewma  9 reserved
GUARD_LEN = 10

#: health-word bits
HEALTH_LOSS = 1      # loss nonfinite
HEALTH_GRAD = 2      # some gradient nonfinite (via the fused norm)
HEALTH_PARAM = 4     # some updated parameter nonfinite
HEALTH_SPIKE = 8     # finite, but loss spiked past factor * EWMA
HEALTH_GNORM = 16    # finite, but grad norm spiked past factor * EWMA


class GuardDivergenceError(RuntimeError):
    """Raised in ``skip`` mode when the consecutive-bad-step budget is
    exhausted and no auto_checkpoint rescue target is registered."""


def guard_mode() -> str:
    mode = os.environ.get(_MODE_ENV, "skip").strip().lower() or "skip"
    if mode not in ("off", "skip", "abort"):
        raise ValueError(
            f"{_MODE_ENV}={mode!r}: want one of off|skip|abort")
    return mode


def _envi(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    return int(raw) if raw.strip() else default


def _envf(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    return float(raw) if raw.strip() else default


# ---------------------------------------------------------------------------
# the pure, in-graph half (shared by TrainStep and LocalSGDStep)
# ---------------------------------------------------------------------------


def init_guard_state():
    """Fresh device guard-state vector (all zeros)."""
    import jax.numpy as jnp

    return jnp.zeros((GUARD_LEN,), jnp.float32)


def grad_health(loss, grads, new_params=None, check_params=None):
    """The sentinel reduction: (ok, health_bits, gnorm), all traced.

    ``gnorm`` is the global gradient norm sqrt(sum g^2) in f32 — ONE
    fused reduction pass over the grads; any NaN/Inf gradient element
    propagates into it, so ``isfinite(gnorm^2)`` doubles as the
    all-grads finite check without a second read. (A finite grad large
    enough to overflow f32 when squared, ~1e19, reads as nonfinite —
    at that magnitude the step is divergent either way.)
    """
    import jax.numpy as jnp

    if check_params is None:
        check_params = _envi(_CHECK_PARAMS_ENV, 0) != 0
    loss32 = jnp.asarray(loss, jnp.float32)
    loss_ok = jnp.isfinite(loss32).all()
    gs = [g for g in grads if g is not None]
    if gs:
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in gs)
        grad_ok = jnp.isfinite(sq)
        gnorm = jnp.sqrt(jnp.where(grad_ok, sq, 0.0))
    else:
        grad_ok = jnp.asarray(True)
        gnorm = jnp.asarray(0.0, jnp.float32)
    bits = (jnp.where(loss_ok, 0, HEALTH_LOSS)
            + jnp.where(grad_ok, 0, HEALTH_GRAD))
    if check_params and new_params is not None:
        p_ok = jnp.stack([
            jnp.isfinite(p).all() if jnp.issubdtype(p.dtype, jnp.inexact)
            else jnp.asarray(True)
            for p in new_params
        ]).all()
        bits = bits + jnp.where(p_ok, 0, HEALTH_PARAM)
    ok = bits == 0
    return ok, bits.astype(jnp.float32), gnorm


def update_guard_state(state, ok, bits, gnorm, loss):
    """Pure policy-counter update (traced; rides the step's carry).

    Spike detection (``PADDLE_GUARD_SPIKE_FACTOR`` > 0, after
    ``PADDLE_GUARD_SPIKE_WARMUP`` healthy steps seeded the EWMAs):

    - a finite **grad norm** above ``factor * gnorm_EWMA`` is masked
      like a nonfinite step (``ok_apply`` False). The loss can only
      reveal an exploded update one step AFTER it applied — the grad
      norm reveals it *before*, which is what keeps params (and the
      next auto_checkpoint generation) clean;
    - a finite **loss** above ``factor * loss_EWMA`` still applies
      (masking on a trailing indicator would skip the wrong step) but
      counts against the same consecutive-bad budget, so a divergence
      that never goes nonfinite still reaches the rescue path.

    Returns (new_state, ok_apply) — the caller masks with ok_apply.
    """
    import jax.numpy as jnp

    factor = _envf(_SPIKE_ENV, 0.0)
    decay = _envf(_EWMA_ENV, 0.9)
    warmup = _envi(_WARMUP_ENV, 20)
    (consec, t_skip, t_spike, ewma, _, prev_bits, healthy, _,
     g_ewma, _spare) = tuple(state)
    loss32 = jnp.asarray(loss, jnp.float32)
    if factor > 0.0:
        warmed = healthy >= warmup
        # the > 0 guards keep an unseeded EWMA (fresh start, or state
        # restored from a snapshot without one) from flagging everything
        spike = ok & warmed & (jnp.abs(ewma) > 0.0) \
            & (loss32 > factor * jnp.abs(ewma))
        g_spike = ok & warmed & (g_ewma > 0.0) \
            & (gnorm > factor * g_ewma)
    else:
        spike = jnp.asarray(False)
        g_spike = jnp.asarray(False)
    ok_apply = ok & ~g_spike
    bad = (~ok_apply) | spike
    consec = jnp.where(bad, consec + 1, 0.0)
    t_skip = t_skip + jnp.where(ok_apply, 0.0, 1.0)
    t_spike = t_spike + jnp.where(spike, 1.0, 0.0)
    good = ok_apply & ~spike
    seeded = healthy > 0
    ewma = jnp.where(
        good,
        jnp.where(seeded, decay * ewma + (1.0 - decay) * loss32, loss32),
        ewma,
    )
    g_ewma = jnp.where(
        good,
        jnp.where(seeded, decay * g_ewma + (1.0 - decay) * gnorm, gnorm),
        g_ewma,
    )
    healthy = healthy + jnp.where(good, 1.0, 0.0)
    bits = (bits + jnp.where(spike, float(HEALTH_SPIKE), 0.0)
            + jnp.where(g_spike, float(HEALTH_GNORM), 0.0))
    # the bits slot is sticky-bad: it names the most recent UNHEALTHY
    # step's health word, so a lazy observer still sees what tripped
    bits = jnp.where(bad, bits, prev_bits)
    new_state = jnp.stack([
        consec, t_skip, t_spike, ewma, gnorm, bits, healthy,
        jnp.where(jnp.isfinite(loss32), loss32, jnp.asarray(-1.0)),
        g_ewma, _spare,
    ])
    return new_state, ok_apply


def mask_step(ok, new_tree, old_tree):
    """Select new-vs-old leafwise on the traced ``ok`` scalar — the
    skip-and-rescue no-op: identical output layout/sharding, so buffer
    donation is preserved and a healthy step's values are bitwise what
    they would have been without the guard."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(ok, n, o), new_tree, old_tree
    )


# ---------------------------------------------------------------------------
# event stream (read by ElasticManager for kill attribution)
# ---------------------------------------------------------------------------


def emit_event(kind: str, **fields) -> None:
    """Emit one guard event through the telemetry bus
    (observability/bus.py). The legacy flat-format line still lands on
    ``PADDLE_GUARD_EVENT_FILE`` when the launcher set it (the
    ElasticManager's kill-attribution reader is unchanged); the unified
    schema row additionally lands on the per-rank bus stream when
    ``PADDLE_OBS_DIR``/``PADDLE_OBS_BUS_FILE`` is configured."""
    from ..observability import bus as _bus

    _bus.emit(kind, fields, step=fields.get("step"),
              legacy_env=_EVENT_ENV)


# ---------------------------------------------------------------------------
# rescue-target registry (auto_checkpoint announces itself here)
# ---------------------------------------------------------------------------

_rescue_ref = None
_active_guards: "weakref.WeakSet" = weakref.WeakSet()


def set_rescue_target(target) -> None:
    """Register the TrainEpochRange whose last-good generation a guard
    rollback restores (weakly held; cleared by passing None)."""
    global _rescue_ref
    _rescue_ref = None if target is None else weakref.ref(target)


def _rescue_target():
    return _rescue_ref() if _rescue_ref is not None else None


def divergence_active() -> bool:
    """Is any live guard inside a bad-step streak? auto_checkpoint asks
    before its periodic save, so a spiking-but-finite epoch (whose
    updates DID apply) is never committed as a 'last-good' generation —
    the snapshot a later rollback restores must predate the divergence.

    Only guards that actually STEPPED since the previous check are
    consulted (a retired step object kept alive by a stray reference
    must not veto another run's snapshots), and the read is
    side-effect-free: it syncs the pending device state but never runs
    the rescue policy — that belongs to the owning step's own observe().
    One device sync per consulted guard; called at epoch boundaries,
    not per step."""
    streak = False
    for g in list(_active_guards):
        if g.closed or not g._stepped_since_check:
            continue
        g._stepped_since_check = False
        g._sync_pending()
        if g._last[0] > 0:
            streak = True
    return streak


# ---------------------------------------------------------------------------
# the host monitor
# ---------------------------------------------------------------------------


def _key_bits(key):
    """Raw uint32 bits of an RNG key (typed or legacy array form)."""
    if key is None:
        return None
    import numpy as np

    try:
        import jax

        return np.asarray(jax.random.key_data(key))
    except Exception:  # noqa: BLE001 — legacy uint32[2] keys
        return np.asarray(key)


class _StepRecord:
    __slots__ = ("step", "key", "inputs", "labels")

    def __init__(self, step, key, inputs, labels):
        self.step = step
        self.key = key
        self.inputs = inputs
        self.labels = labels


class TrainGuard:
    """Host-side divergence monitor for one compiled step object.

    The step calls :meth:`capture` before dispatch (ring-buffers the RNG
    key + batch refs for replay) and :meth:`observe` after, handing over
    the new device guard-state array. ``observe`` syncs only every
    ``sync_every`` steps, through a one-interval async prefetch, and
    returns ``"rollback"`` when it restored a checkpoint (the step must
    then refresh its device carries from the restored host state).
    """

    def __init__(self, mode: Optional[str] = None,
                 max_skips: Optional[int] = None,
                 sync_every: Optional[int] = None,
                 model=None):
        self.mode = mode or guard_mode()
        self.max_skips = (max_skips if max_skips is not None
                          else _envi(_MAX_SKIPS_ENV, 8))
        self.sync_every = max(
            sync_every if sync_every is not None else _envi(_SYNC_ENV, 4),
            1)
        self._model_ref = weakref.ref(model) if model is not None else None
        # step-metrics sampler (observability/metrics.py): rides THIS
        # guard's sync cadence — its records cost no device reads beyond
        # the async prefetch the guard already pays for
        from ..observability.metrics import StepMetricsSampler

        self._sampler = StepMetricsSampler()
        self._step = 0
        self._ring: deque = deque(maxlen=2 * self.sync_every + 4)
        self._pending = None     # (step, state_array) async-prefetched
        self._last = [0.0] * GUARD_LEN   # newest host-read state
        self._last_step = -1
        self._reported_bad = 0.0  # total_skips+spikes already evented
        self._just_restored = False
        self._stepped_since_check = False
        self.closed = False       # set when this guard gave its verdict
        self.rollbacks = 0
        self.dumped: List[str] = []
        #: owner hook, invoked right after a rollback restored the
        #: checkpoint — the compiled step refreshes its device carries
        #: (guard-state vector, LocalSGD re-stacks replicas) here, so a
        #: rollback triggered from ANY sync point (observe, flush,
        #: divergence_active) leaves the step consistent
        self._on_rollback = None
        _active_guards.add(self)

    # -- persistence (rides the auto_checkpoint extras) -------------------
    def state_dict(self) -> Dict:
        return {
            "total_skips": float(self._last[1]),
            "total_spikes": float(self._last[2]),
            "loss_ewma": float(self._last[3]),
            "healthy_steps": float(self._last[6]),
            "gnorm_ewma": float(self._last[8]),
            "rollbacks": int(self.rollbacks),
        }

    def set_state_dict(self, state: Dict) -> None:
        self._last = [0.0] * GUARD_LEN
        self._last[1] = float(state.get("total_skips", 0.0))
        self._last[2] = float(state.get("total_spikes", 0.0))
        self._last[3] = float(state.get("loss_ewma", 0.0))
        self._last[6] = float(state.get("healthy_steps", 0.0))
        self._last[8] = float(state.get("gnorm_ewma", 0.0))
        self.rollbacks = int(state.get("rollbacks", 0))
        self._reported_bad = self._last[1] + self._last[2]
        self._pending = None
        self._just_restored = True

    def restored_device_state(self):
        """Device guard-state vector seeded from the restored counters:
        consec_bad resets (a rescue forgives the streak); totals and the
        loss/gnorm EWMA baselines carry from the snapshot (a zero,
        never-seeded EWMA is guarded against in update_guard_state)."""
        import jax.numpy as jnp

        return jnp.asarray(
            [0.0, self._last[1], self._last[2], self._last[3], 0.0, 0.0,
             self._last[6], 0.0, self._last[8], 0.0], jnp.float32)

    # -- per-step hooks ----------------------------------------------------
    def capture(self, key, inputs, labels) -> None:
        """Ring-buffer this step's replay seed (device refs; nothing is
        copied to host unless a bundle is actually dumped)."""
        self._step += 1
        self._sampler.tick(inputs)   # host ints off static shapes
        if os.environ.get(_DUMP_ENV):
            self._ring.append(
                _StepRecord(self._step, key, tuple(inputs), tuple(labels)))

    def observe(self, guard_state) -> Optional[str]:
        """Hand over the step's new device guard state. Returns None,
        ``"rollback"`` (checkpoint restored — refresh device carries), or
        raises/exits per mode."""
        self._stepped_since_check = True
        if self._step % self.sync_every != 0:
            return None
        prev = self._pending
        self._pending = (self._step, guard_state)
        try:
            guard_state.copy_to_host_async()
        except AttributeError:
            pass  # non-jax array (tests) or backend without async copy
        if prev is None:
            return None
        step, arr = prev
        import numpy as np

        self._last = [float(v) for v in np.asarray(arr)]
        self._last_step = step
        # the host read just landed: the step-metrics record reuses its
        # floats (plus wall-clock deltas) — no additional device access
        self._sampler.sample(step, self._last)
        return self._policy(step)

    def _sync_pending(self) -> None:
        """Pull the pending device state to the host (no policy)."""
        if self._pending is None:
            return
        import numpy as np

        step, arr = self._pending
        self._pending = None
        self._last = [float(v) for v in np.asarray(arr)]
        self._last_step = step

    def flush(self) -> Optional[str]:
        """Synchronously evaluate the newest handed-over state (tests /
        end-of-run checks; observe() is the zero-stall path)."""
        if self._pending is None:
            return None
        self._sync_pending()
        return self._policy(self._last_step)

    # -- policy ------------------------------------------------------------
    def _policy(self, step: int) -> Optional[str]:
        consec = self._last[0]
        total_bad = self._last[1] + self._last[2]
        new_bad = total_bad - self._reported_bad
        if new_bad > 0:
            self._reported_bad = total_bad
            bundle = self._dump_bundle(step)
            emit_event(
                "guard_skip", step=step, consec=int(consec),
                total_skips=int(self._last[1]),
                total_spikes=int(self._last[2]),
                health_bits=int(self._last[5]), gnorm=self._last[4],
                loss=self._last[7], loss_ewma=self._last[3],
                bundle=bundle,
                detail=self._describe(step),
            )
            print(f"paddle_tpu.train_guard: {self._describe(step)}",
                  file=sys.stderr, flush=True)
            # capture-on-anomaly: the first observed bad step arms a
            # bounded device-trace window over the NEXT steps (no-op
            # without a configured trace destination; at most
            # PADDLE_OBS_TRACE_MAX windows per process)
            if os.environ.get("PADDLE_OBS_TRACE_ON_TRIP",
                              "1").strip().lower() not in ("0", "false",
                                                           "off"):
                from .. import profiler as _prof

                _prof.arm_trace(reason="guard_trip")
        if consec < self.max_skips:
            return None
        # budget exhausted: rescue
        detail = (f"divergence: {int(consec)} consecutive bad steps "
                  f"(budget {self.max_skips}) at step ~{step}; "
                  + self._describe(step))
        if self.mode == "abort":
            emit_event("guard_abort", step=step, consec=int(consec),
                       health_bits=int(self._last[5]),
                       gnorm=self._last[4], loss=self._last[7],
                       detail=detail)
            print(f"paddle_tpu.train_guard: {detail}; aborting "
                  f"rc={GUARD_ABORT_RC}", file=sys.stderr, flush=True)
            os._exit(GUARD_ABORT_RC)
        target = _rescue_target()
        if target is None:
            self.closed = True   # verdict given; drop out of the
            #                      divergence_active consultation set
            raise GuardDivergenceError(
                detail + " — no auto_checkpoint range registered to roll "
                "back to (iterate TrainEpochRange, or set "
                "PADDLE_GUARD_MODE=abort to hand the rank to the elastic "
                "launcher)")
        self._just_restored = False
        restored = target.restore()
        self.rollbacks += 1
        if not self._just_restored:
            # guard not carried by the snapshot's extras: keep the
            # cumulative totals as the new reporting baseline
            self._reported_bad = self._last[1] + self._last[2]
        # in-flight pre-restore states must not re-trigger the budget
        self._pending = None
        self._last[0] = 0.0
        if self._on_rollback is not None:
            self._on_rollback()
        emit_event("guard_rollback", step=step, consec=int(consec),
                   restored_epoch=getattr(target, "_restored_epoch", None),
                   detail=detail)
        print(f"paddle_tpu.train_guard: {detail}; restored last-good "
              f"snapshot (next epoch {restored})",
              file=sys.stderr, flush=True)
        return "rollback"

    def _describe(self, step: int) -> str:
        bits = int(self._last[5])
        what = [w for b, w in ((HEALTH_LOSS, "loss nonfinite"),
                               (HEALTH_GRAD, "grads nonfinite"),
                               (HEALTH_PARAM, "params nonfinite"),
                               (HEALTH_SPIKE, "loss spike"),
                               (HEALTH_GNORM, "grad-norm spike"))
                if bits & b] or ["healthy"]
        return (f"step ~{step}: {', '.join(what)} "
                f"(consec {int(self._last[0])}, gnorm {self._last[4]:.3g}, "
                f"loss {self._last[7]:.6g}, ewma {self._last[3]:.6g})")

    # -- replay-bundle dump ------------------------------------------------
    def _dump_bundle(self, step: int) -> Optional[str]:
        """Write the first-bad step's replay bundle (best effort: the
        ring holds the last ~2 sync intervals; the oldest record at or
        after the first bad step serves, since skipped steps leave the
        params the replay needs untouched)."""
        dump_dir = os.environ.get(_DUMP_ENV)
        if not dump_dir or not self._ring:
            return None
        consec = int(self._last[0])
        first_bad = max(self._last_step - consec + 1, 1) if consec \
            else self._last_step
        rec = None
        for r in self._ring:
            if r.step >= first_bad:
                rec = r
                break
        if rec is None:
            rec = self._ring[-1]
        model = self._model_ref() if self._model_ref is not None else None
        try:
            import numpy as np

            from ..framework import io as fio

            ins = [np.asarray(x) for x in rec.inputs]
            labs = [np.asarray(y) for y in rec.labels]
            fp = 0
            for a in ins + labs:
                fp = zlib.crc32(np.ascontiguousarray(a).tobytes(), fp)
            bundle = {
                "step": rec.step, "time": time.time(),
                "health_bits": int(self._last[5]),
                "gnorm": self._last[4], "loss": self._last[7],
                "fingerprint": fp & 0xFFFFFFFF,
                "key_data": _key_bits(rec.key),
                "inputs": ins, "labels": labs,
            }
            if model is not None:
                bundle["state"] = {
                    k: np.asarray(v._data)
                    for k, v in model.state_dict().items()
                }
            os.makedirs(dump_dir, exist_ok=True)
            path = os.path.join(
                dump_dir,
                f"guard_step{rec.step:08d}.rank"
                f"{os.environ.get('PADDLE_TRAINER_ID', '0')}.pdbundle")
            fio.save(bundle, path)
            self.dumped.append(path)
            return path
        except Exception as e:  # noqa: BLE001 — diagnostics stay best-effort
            print(f"paddle_tpu.train_guard: bundle dump failed: {e}",
                  file=sys.stderr, flush=True)
            return None
