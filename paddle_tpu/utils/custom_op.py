"""Custom-op SDK.

Reference: the C++ custom-operator extension surface —
paddle/fluid/extension/include/ext_op_meta_info.h (PD_BUILD_OP macro:
forward/backward KernelFunc + InferShapeFunc registration) and
paddle/fluid/framework/custom_operator.cc (RegisterOperatorWithMetaInfo),
loaded through python/paddle/utils/cpp_extension.

TPU-native: a "kernel" is any jax-traceable function — jnp composition or
a Pallas TPU kernel — so the SDK's job is framework integration, not
compilation: tape/autograd wiring (custom VJP), registration into the
``paddle_tpu.ops`` flat namespace, AMP/static-graph participation (the op
dispatches through the same AG.apply seam as every built-in), and OpTest
compatibility (the registered op takes/returns Tensors).

Usage::

    from paddle_tpu.utils.custom_op import custom_op

    @custom_op("my_scale")                 # paddle_tpu.my_scale appears
    def my_scale(x, factor=2.0):           # body sees jnp arrays
        return x * factor

    @my_scale.def_grad                     # optional analytic backward
    def my_scale_grad(ct, x, *, out, factor=2.0):
        return (ct * factor,)              # one grad per tensor input

Without ``def_grad`` the op differentiates through jax's autodiff (fine
for jnp bodies; Pallas kernels need an explicit grad or `nondiff=True`).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax

from ..core import autograd as AG
from ..core.tensor import Tensor

__all__ = ["custom_op", "register_op", "get_op", "registered_ops"]

_REGISTRY = {}


class CustomOp:
    """One registered op: callable over Tensors, grad attachable."""

    def __init__(self, name: str, fn: Callable, nondiff: bool = False):
        self.name = name
        self._fn = fn
        self._nondiff = nondiff
        self._grad_fn: Optional[Callable] = None
        self._vjp_wrapped: Optional[Callable] = None
        self.__name__ = name
        self.__doc__ = fn.__doc__

    # -- grad registration ---------------------------------------------------
    def def_grad(self, grad_fn: Callable):
        """Attach the backward kernel: grad_fn(cotangent, *raw_inputs,
        out=raw_outputs, **kwargs) -> tuple of input cotangents (None for
        non-differentiable inputs). The forward is NOT re-traced in
        backward — residuals are (inputs, outputs), like the reference's
        separate backward KernelFunc fed X/Out/GradOut."""
        self._grad_fn = grad_fn
        self._vjp_wrapped = None  # rebuild per kwargs at next call
        return grad_fn

    # -- dispatch ------------------------------------------------------------
    def _kernel(self, kwargs):
        if self._grad_fn is None:
            if not kwargs:
                return self._fn
            return lambda *raws: self._fn(*raws, **kwargs)

        @jax.custom_vjp
        def op(*raws):
            return self._fn(*raws, **kwargs)

        def fwd(*raws):
            out = self._fn(*raws, **kwargs)
            return out, (raws, out)

        def bwd(res, ct):
            raws, out = res
            grads = self._grad_fn(ct, *raws, out=out, **kwargs)
            if not isinstance(grads, (tuple, list)):
                grads = (grads,)
            return tuple(
                jax.numpy.zeros_like(r) if g is None else g
                for g, r in zip(grads, raws)
            )

        op.defvjp(fwd, bwd)
        return op

    def __call__(self, *args, **kwargs):
        tensors = []
        for a in args:
            if isinstance(a, Tensor):
                tensors.append(a)
            else:
                import numpy as np

                if isinstance(a, (np.ndarray, jax.Array)):
                    tensors.append(Tensor(a))
                else:
                    raise TypeError(
                        f"custom op '{self.name}' positional args must be "
                        f"tensors; pass {type(a).__name__} values as "
                        "keyword attributes"
                    )
        kernel = self._kernel(kwargs)
        if self._nondiff:
            return AG.apply_nondiff(kernel, tensors)
        return AG.apply(kernel, tensors, name=self.name)


def register_op(name: str, fn: Callable, grad_fn: Optional[Callable] = None,
                nondiff: bool = False) -> CustomOp:
    """Functional registration (custom_operator.cc
    RegisterOperatorWithMetaInfo analog). Exposes the op as
    ``paddle_tpu.<name>`` and ``paddle_tpu.ops.<name>``; re-registering a
    name raises (duplicate PD_BUILD_OP is a C++ link error there)."""
    if name in _REGISTRY:
        raise ValueError(f"custom op '{name}' is already registered")
    op = CustomOp(name, fn, nondiff=nondiff)
    if grad_fn is not None:
        op.def_grad(grad_fn)
    _REGISTRY[name] = op

    import paddle_tpu
    from .. import ops as ops_mod

    setattr(ops_mod, name, op)
    setattr(paddle_tpu, name, op)
    return op


def custom_op(name: str, nondiff: bool = False):
    """Decorator form of register_op."""

    def deco(fn):
        return register_op(name, fn, nondiff=nondiff)

    return deco


def get_op(name: str) -> CustomOp:
    return _REGISTRY[name]


def registered_ops():
    return dict(_REGISTRY)
