"""paddle_tpu.utils — developer tooling (custom ops, op benchmarking,
deterministic fault injection for the elastic runtime)."""
from . import custom_op, download, fault_injection, op_bench  # noqa: F401
from .custom_op import register_op  # noqa: F401
