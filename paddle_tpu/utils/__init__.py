"""paddle_tpu.utils — developer tooling (custom ops, op benchmarking,
deterministic fault injection for the elastic runtime, numerical
training guardrails)."""
from . import (  # noqa: F401
    custom_op, download, fault_injection, op_bench, train_guard,
)
from .custom_op import register_op  # noqa: F401
