"""paddle_tpu.utils — developer tooling (op benchmarking, perf analysis)."""
from . import op_bench  # noqa: F401
