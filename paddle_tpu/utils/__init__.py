"""paddle_tpu.utils — developer tooling (custom ops, op benchmarking)."""
from . import custom_op, op_bench  # noqa: F401
from .custom_op import register_op  # noqa: F401
