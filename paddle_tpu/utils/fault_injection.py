"""Deterministic env-spec fault injection for the elastic runtime tests.

`PADDLE_FAULT_SPEC` is a comma-separated list of rules::

    site:action:nth[:arg]

- ``site``   dotted fault-point name. Instrumented sites today:
  ``io.save`` (before a framework.io.save write), ``io.save.post``
  (after the atomic replace — where ``corrupt`` bites), ``io.load``,
  ``acp.save`` (before an auto-checkpoint snapshot), ``epoch`` (on
  entering each TrainEpochRange epoch), ``coll`` (inside each eager
  collective's monitored region, distributed/comm_monitor.py — the
  collective timeout watchdog's prey), ``grad`` (once per compiled
  TrainStep call, host side — the numerical-guard matrix's prey),
  ``rank`` (once per elastic step-boundary check,
  distributed/resharding.py — the reshard matrix's prey), ``serve``
  (once per serving-router scheduling tick / host-worker poll,
  serving/router.py — the admission-control matrix's prey), ``mon``
  (once per telemetry-bus row write, observability/bus.py — the fleet
  monitor's lossy-stream prey), ``ctl`` (once per fleet-controller
  control window, distributed/fleet_controller.py — the co-tenancy
  state machine's prey).
- ``action`` one of ``fail`` (raise InjectedFault, an IOError),
  ``hang`` (sleep ``arg`` seconds, default 3600 — the watchdog's prey),
  ``kill`` (``os._exit(arg)``, default 17 — a hard preemption),
  ``corrupt`` (truncate the file the site passed via ``path=`` to half
  its bytes — a torn write), ``desync`` (``coll`` only: arm a flag
  the comm monitor consumes to mutate this rank's op fingerprint, as if
  it had issued a DIFFERENT collective; ``arg`` selects the rank the
  rule fires on, default 0, so one job-wide spec desyncs one rank), or
  ``nan`` / ``inf`` / ``spike`` (``grad`` only: arm a flag the compiled
  step consumes to poison that step's gradients IN-GRAPH with NaN /
  Inf / a x1e4 magnitude spike — a traced operand selects the poison,
  so the injection never retraces the program; ``arg`` = how many
  consecutive step calls the rule stays armed, default 1, e.g.
  ``grad:nan:3:5`` poisons steps 3-7), or ``depart`` / ``return``
  (``rank`` only: arm a rank-departure/-arrival notice the elastic
  reshard path consumes at its next step boundary — ``arg`` selects the
  logical rank, default the last rank, so
  ``PADDLE_FAULT_SPEC="rank:depart:3:1"`` loses rank 1 at step 3 and
  ``rank:depart:3:1,rank:return:6:1`` brings it back at step 6), or
  ``burst`` / ``slow_host`` / ``straggler`` / ``host_crash`` (``serve``
  only: arm a serving-tier event the router/worker drains at its next
  tick — ``serve:burst:2:8`` injects an 8-request burst at the
  router's 2nd tick (admission control's prey), ``serve:slow_host:1:0``
  degrades host rank 0 from its 1st poll (the SLO scheduler routes
  away from it), ``serve:straggler:1:2`` adds a fixed per-window decode
  delay on host rank 2 from its 1st poll (the fleet monitor's skew
  detector must NAME that rank), ``serve:host_crash:2:0`` SIGKILLs the
  host-rank-0 worker at its next MID-DECODE window after its 2nd poll
  (the failover path's prey: the process dies with a request half
  served, ISSUE 15), ``serve:kv_corrupt:1[:block]`` bit-flips one
  block of the NEXT KV migration bundle the router extracts (default
  block 0) so the per-block CRC catches it and that one request falls
  back to re-prefill, and ``serve:kv_lost:1`` makes the next migration
  bundle never arrive (the extract verb is swallowed, the router's
  bundle wait times out, same per-request fallback — ISSUE 17),
  ``serve:prefix_stale:1[:k]`` poisons the content hash of one cached
  prefix-cache entry (the ``k``-th oldest, default 0) so the next
  shared-prefix lookup MISSES and the request pays a full prefill —
  never serves wrong-prefix KV (ISSUE 18), and
  ``serve:adapter_missing:1[:id]`` rewrites the router's next submit to
  reference an unloaded adapter id (default an id past any fleet) so
  admission rejects it cleanly with ``router_admit.reason=adapter``
  instead of crashing a compiled step (ISSUE 18);
  ``arg`` defaults: burst 8 requests,
  slow_host/straggler/host_crash rank 0, kv_corrupt block 0. At the
  ``serve`` site the
  generic ``hang`` action is ALSO rank-targeted and event-armed
  (``serve:hang:1:1`` = host rank 1 stops draining its mailbox but
  keeps the process — and its telemetry heartbeat — alive, the
  failure detector's harder prey); everywhere else ``hang`` keeps its
  sleep-``arg``-seconds semantics), or ``drop`` / ``dup`` (``mon`` only:
  the telemetry bus consumes the rule at its nth row write and drops /
  duplicates that one line — the monitor's incremental cursor and
  count-based aggregation must survive a lossy, re-appending stream),
  or ``flap`` / ``die`` / ``lend_crash`` (``ctl`` only: ``flap``
  overrides the fleet controller's measured serving pressure with a
  synthetic square wave — runs of sustain-length hot windows
  alternating with calm ones, for ``arg`` windows total (default 32) —
  the hysteresis/cooldown suppression test's prey; ``die`` SIGKILLs the
  controller process at its nth control window (``arg`` = exit signal
  override, default SIGKILL), mid-lend when aimed between journal
  ``begin`` and ``commit`` — the journal-recovery path's prey;
  ``lend_crash`` (ISSUE 20) is the PHASE-TARGETED die: ``arg`` names a
  live-lend phase (``depart``/``deliver``/``join`` or
  ``drain``/``leave``/``rejoin``, default the first phase of the next
  transition) and the controller SIGKILLs itself between THAT phase's
  journal ``begin`` and ``commit`` rows — the phase-ladder recovery
  matrix's prey). The ``serve`` site additionally accepts
  ``lent_worker_crash`` (ISSUE 20): the LENT worker (``arg`` = its
  rank, a rank serving on loan from training) SIGKILLs itself at its
  next mailbox poll — the router must fail its in-flight requests over
  and the launcher must force-reclaim the row back to training.
- ``nth``    1-based per-process call count at which the rule fires
  (each call to a site increments that site's counter), so a relaunched
  attempt that resumes later in training naturally skips the fault.
- ``arg``    optional action parameter (kill exit code / hang seconds /
  nan-inf-spike repeat count).

Example: ``PADDLE_FAULT_SPEC="io.save:fail:1,epoch:hang:3"`` fails the
first save and hangs the process on entering its 3rd epoch.

A ``corrupt`` rule written against ``io.save`` is normalized to
``io.save.post`` so the short spelling corrupts a *complete* file.
Pure stdlib — safe to import from anywhere in the tree.
"""
from __future__ import annotations

import os
import sys
import time
from typing import Dict, List, Optional

__all__ = ["InjectedFault", "FaultInjector", "fault_point", "consume_flag",
           "has_site", "consume_grad_action", "consume_rank_events",
           "consume_serve_events", "consume_serve_matching",
           "consume_mon_action",
           "consume_ctl_events", "GRAD_POISONS", "LEND_PHASES",
           "RECLAIM_PHASES", "reset"]

_SPEC_ENV = "PADDLE_FAULT_SPEC"
_ACTIONS = ("fail", "hang", "kill", "corrupt", "desync", "nan", "inf",
            "spike", "depart", "return", "burst", "slow_host",
            "straggler", "host_crash", "kv_corrupt", "kv_lost",
            "prefix_stale", "adapter_missing", "lent_worker_crash",
            "drop", "dup", "flap", "die", "lend_crash")
# desync only makes sense where a fingerprint is being recorded
_DESYNC_SITES = ("coll",)
# grad poison only makes sense where a compiled step consumes the flag
_GRAD_ACTIONS = ("nan", "inf", "spike")
_GRAD_SITES = ("grad",)
# rank departure/arrival only makes sense where the elastic reshard
# path polls for notices (resharding.py step-boundary check)
_RANK_ACTIONS = ("depart", "return")
_RANK_SITES = ("rank",)
# serving-tier events only make sense where the router/worker polls
# for them (serving/router.py scheduling tick / host-worker loop);
# `hang` doubles as a serve event when a rule targets that site (the
# worker consumes it as "stop draining the mailbox, stay alive")
_SERVE_ACTIONS = ("burst", "slow_host", "straggler", "host_crash",
                  "kv_corrupt", "kv_lost", "prefix_stale",
                  "adapter_missing", "lent_worker_crash")
_SERVE_SITES = ("serve",)
# bus-line faults only make sense where a bus row is being written
# (observability/bus.py emit — the fleet monitor's cursor prey)
_MON_ACTIONS = ("drop", "dup")
_MON_SITES = ("mon",)
# controller faults only make sense where the fleet controller's
# control window polls for them (distributed/fleet_controller.py)
_CTL_ACTIONS = ("flap", "die", "lend_crash")
_CTL_SITES = ("ctl",)
#: the live-lend phase ladder (ISSUE 20) — a `lend_crash` arg must name
#: one of these; kept here (stdlib-pure) so the parser rejects a typo'd
#: phase at spec time instead of silently never firing
LEND_PHASES = ("depart", "deliver", "join")
RECLAIM_PHASES = ("drain", "leave", "rejoin")
# sites that pass a file path to fault_point (the only places a corrupt
# rule can bite) — a corrupt rule elsewhere would be a silent no-op, so
# the parser rejects it loudly instead
_CORRUPT_SITES = ("io.save.post",)


class InjectedFault(IOError):
    """Raised by a ``fail`` rule (an IOError so I/O retry paths see it)."""


class _Rule:
    __slots__ = ("site", "action", "nth", "arg")

    def __init__(self, site: str, action: str, nth: int,
                 arg: Optional[str]):
        self.site = site
        self.action = action
        self.nth = nth
        self.arg = arg


class FaultInjector:
    """Parsed spec + per-site hit counters (one injector per process)."""

    def __init__(self, spec: str = ""):
        self.spec = spec
        self._rules: List[_Rule] = []
        self._counts: Dict[str, int] = {}
        self.flags: set = set()  # armed markers (e.g. "desync")
        self.rank_events: List = []  # armed (action, rank|None), ordered
        self.serve_events: List = []  # armed (action, arg|None), ordered
        self.mon_events: List = []  # armed drop/dup bus-line actions
        self.ctl_events: List = []  # armed (action, arg|None), ordered
        for item in filter(None, (s.strip() for s in spec.split(","))):
            parts = item.split(":")
            if len(parts) < 3:
                raise ValueError(
                    f"bad {_SPEC_ENV} rule {item!r}: want site:action:nth"
                )
            site, action, nth = parts[0], parts[1], int(parts[2])
            if action not in _ACTIONS:
                raise ValueError(
                    f"bad {_SPEC_ENV} action {action!r} (one of {_ACTIONS})"
                )
            if action == "corrupt":
                if not site.endswith(".post"):
                    site += ".post"
                if site not in _CORRUPT_SITES:
                    raise ValueError(
                        f"corrupt rule targets un-instrumented site "
                        f"{site!r} (path-carrying sites: {_CORRUPT_SITES})"
                    )
            if action == "desync" and site not in _DESYNC_SITES:
                raise ValueError(
                    f"desync rule targets un-instrumented site {site!r} "
                    f"(fingerprint-recording sites: {_DESYNC_SITES})"
                )
            if action in _GRAD_ACTIONS and site not in _GRAD_SITES:
                raise ValueError(
                    f"{action} rule targets un-instrumented site {site!r} "
                    f"(grad-poisoning sites: {_GRAD_SITES})"
                )
            if action in _RANK_ACTIONS and site not in _RANK_SITES:
                raise ValueError(
                    f"{action} rule targets un-instrumented site {site!r} "
                    f"(rank-event sites: {_RANK_SITES})"
                )
            if action in _SERVE_ACTIONS and site not in _SERVE_SITES:
                raise ValueError(
                    f"{action} rule targets un-instrumented site {site!r} "
                    f"(serving-event sites: {_SERVE_SITES})"
                )
            if action in _MON_ACTIONS and site not in _MON_SITES:
                raise ValueError(
                    f"{action} rule targets un-instrumented site {site!r} "
                    f"(bus-line sites: {_MON_SITES})"
                )
            if action in _CTL_ACTIONS and site not in _CTL_SITES:
                raise ValueError(
                    f"{action} rule targets un-instrumented site {site!r} "
                    f"(controller sites: {_CTL_SITES})"
                )
            arg = parts[3] if len(parts) > 3 else None
            if action == "lend_crash" and arg is not None \
                    and arg not in LEND_PHASES + RECLAIM_PHASES:
                raise ValueError(
                    f"bad {_SPEC_ENV} lend_crash phase {arg!r} (one of "
                    f"{LEND_PHASES + RECLAIM_PHASES})"
                )
            self._rules.append(_Rule(site, action, nth, arg))

    def fire(self, site: str, path: Optional[str] = None) -> None:
        count = self._counts[site] = self._counts.get(site, 0) + 1
        for r in self._rules:
            if r.site != site:
                continue
            if r.action in _GRAD_ACTIONS:
                # grad poison stays armed for `arg` consecutive calls
                repeat = int(r.arg) if r.arg else 1
                if r.nth <= count < r.nth + repeat:
                    print(f"fault_injection: arming grad:{r.action} at "
                          f"{site} (hit {count})", file=sys.stderr,
                          flush=True)
                    self.flags.add(f"grad:{r.action}")
                continue
            if r.nth != count:
                continue
            self._act(r, site, count, path)

    def _act(self, r: _Rule, site, count, path):
        tag = f"{site} (hit {count})"
        if r.action == "fail":
            raise InjectedFault(f"injected failure at {tag}")
        if r.action == "kill":
            code = int(r.arg) if r.arg else 17
            print(f"fault_injection: killing process at {tag} "
                  f"exit={code}", file=sys.stderr, flush=True)
            os._exit(code)
        if r.action == "hang" and site in _SERVE_SITES:
            # serve-site hang is an EVENT, not a sleep: the targeted
            # worker (arg = host rank, default 0) stops draining its
            # mailbox while its process — and telemetry heartbeat —
            # stays alive; sleeping here would stall the router's own
            # scheduling tick instead of the host under test
            arg = int(r.arg) if r.arg else None
            print(f"fault_injection: arming serve:hang"
                  f"{'' if arg is None else f':{arg}'} at {tag}",
                  file=sys.stderr, flush=True)
            self.serve_events.append(("hang", arg))
            return
        if r.action == "hang":
            secs = float(r.arg) if r.arg else 3600.0
            print(f"fault_injection: hanging {secs}s at {tag}",
                  file=sys.stderr, flush=True)
            deadline = time.monotonic() + secs
            while time.monotonic() < deadline:
                time.sleep(min(1.0, deadline - time.monotonic() + 0.01))
            return
        if r.action in _RANK_ACTIONS:
            rank = int(r.arg) if r.arg else None
            print(f"fault_injection: arming rank:{r.action}"
                  f"{'' if rank is None else f':{rank}'} at {tag}",
                  file=sys.stderr, flush=True)
            self.rank_events.append((r.action, rank))
            return
        if r.action in _SERVE_ACTIONS:
            arg = int(r.arg) if r.arg else None
            print(f"fault_injection: arming serve:{r.action}"
                  f"{'' if arg is None else f':{arg}'} at {tag}",
                  file=sys.stderr, flush=True)
            self.serve_events.append((r.action, arg))
            return
        if r.action in _CTL_ACTIONS:
            # lend_crash's arg is a PHASE NAME, not a number
            arg = (r.arg if r.action == "lend_crash"
                   else int(r.arg) if r.arg else None)
            print(f"fault_injection: arming ctl:{r.action}"
                  f"{'' if arg is None else f':{arg}'} at {tag}",
                  file=sys.stderr, flush=True)
            self.ctl_events.append((r.action, arg))
            return
        if r.action in _MON_ACTIONS:
            # consumed synchronously by the bus write that fired this
            # hit — the armed action applies to THAT row
            print(f"fault_injection: arming mon:{r.action} at {tag}",
                  file=sys.stderr, flush=True)
            self.mon_events.append(r.action)
            return
        if r.action == "desync":
            target = int(r.arg) if r.arg else 0
            if int(os.environ.get("PADDLE_TRAINER_ID", "0")) != target:
                return  # the rule desyncs exactly one rank of the job
            print(f"fault_injection: arming desync at {tag}",
                  file=sys.stderr, flush=True)
            self.flags.add("desync")
            return
        if r.action == "corrupt":
            if path is None:
                return  # site carries no file — nothing to corrupt
            size = os.path.getsize(path)
            with open(path, "r+b") as f:
                f.truncate(size // 2)
            print(f"fault_injection: truncated {path} "
                  f"{size}->{size // 2}B at {tag}",
                  file=sys.stderr, flush=True)


_active: Optional[FaultInjector] = None


def _injector() -> FaultInjector:
    global _active
    spec = os.environ.get(_SPEC_ENV, "")
    if _active is None or _active.spec != spec:
        _active = FaultInjector(spec)
    return _active


def fault_point(site: str, path: Optional[str] = None) -> None:
    """Instrumentation hook: no-op unless a spec rule matches this hit."""
    _injector().fire(site, path)


def consume_flag(flag: str) -> bool:
    """One-shot read of a marker an action armed (e.g. ``desync``): True
    exactly once after the rule fires, then cleared."""
    inj = _active
    if inj is not None and flag in inj.flags:
        inj.flags.discard(flag)
        return True
    return False


def has_site(site: str) -> bool:
    """Does the active spec carry any rule for `site`? Compiled steps use
    this ONCE at trace time to decide whether to thread the in-graph
    poison operand (a clean spec keeps the program byte-identical)."""
    return any(r.site == site for r in _injector()._rules)


#: traced poison selector values the compiled step consumes
GRAD_POISONS = {"nan": 1, "inf": 2, "spike": 3}


def consume_rank_events() -> List:
    """Fire the ``rank`` site for this step-boundary check and drain any
    armed rank events; returns an ordered list of ``(action, rank)``
    pairs (``rank`` is None when the rule named no rank — the consumer
    picks its default, conventionally the highest live rank)."""
    fault_point("rank")
    inj = _active
    if inj is None or not inj.rank_events:
        return []
    out, inj.rank_events = inj.rank_events, []
    return out


def consume_serve_events() -> List:
    """Fire the ``serve`` site for this router tick / worker poll and
    drain any armed serving events; returns an ordered list of
    ``(action, arg)`` pairs (``arg`` is None when the rule named none —
    the consumer picks its default: burst size 8, host rank 0)."""
    fault_point("serve")
    inj = _active
    if inj is None or not inj.serve_events:
        return []
    out, inj.serve_events = inj.serve_events, []
    return out


def consume_serve_matching(actions, *, fire: bool = False) -> List:
    """Drain ONLY the armed serve events whose action is in ``actions``
    (leaving the rest for the router/worker consumers); with ``fire``
    the serve site is hit first — the prefix cache uses that form so an
    engine driven WITHOUT a router still arms ``serve:prefix_stale``
    rules on its own lookups. The fire is suppressed when the spec
    carries no rule for any of ``actions``: these hooks sit on hot
    paths (every router submit, every prefix lookup), and a spec that
    never names them must keep serve-hit arithmetic identical to a
    build without the hooks (``serve:burst:2`` still means the second
    router tick). Returns ordered ``(action, arg)`` pairs."""
    if fire:
        inj = _injector()
        if any(r.site == "serve" and r.action in actions
               for r in inj._rules):
            fault_point("serve")
    inj = _active
    if inj is None or not inj.serve_events:
        return []
    out = [e for e in inj.serve_events if e[0] in actions]
    if out:
        inj.serve_events = [e for e in inj.serve_events
                            if e[0] not in actions]
    return out


def consume_mon_action() -> Optional[str]:
    """Fire the ``mon`` site for this bus-row write and consume any
    armed ``drop`` / ``dup`` action; returns the action name for the
    CURRENT row (the rule fires and is consumed within one write), or
    None for a clean row."""
    fault_point("mon")
    inj = _active
    if inj is None or not inj.mon_events:
        return None
    return inj.mon_events.pop(0)


def consume_ctl_events() -> List:
    """Fire the ``ctl`` site for this fleet-controller control window and
    drain any armed controller events; returns an ordered list of
    ``(action, arg)`` pairs (``arg`` is None when the rule named none —
    the consumer picks its default: flap 32 windows, die SIGKILL)."""
    fault_point("ctl")
    inj = _active
    if inj is None or not inj.ctl_events:
        return []
    out, inj.ctl_events = inj.ctl_events, []
    return out


def consume_grad_action() -> int:
    """Fire the ``grad`` site for this step call and consume any armed
    poison flag; returns the GRAD_POISONS code (0 = clean step)."""
    fault_point("grad")
    for name, code in GRAD_POISONS.items():
        if consume_flag(f"grad:{name}"):
            return code
    return 0


def reset() -> None:
    """Drop counters/rules (tests re-arm between cases)."""
    global _active
    _active = None
