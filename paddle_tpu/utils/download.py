"""Dataset staging paths (reference: python/paddle/utils/download.py,
egress-free).

The reference downloads datasets into `~/.cache/paddle/dataset/<name>/`;
this environment has no egress, so the same layout is a *staging* dir:
loaders in text/ and vision/ resolve default file paths under it, and the
verbatim-script harness (tests/test_reference_scripts.py) pre-writes
files there so reference scripts run with no path arguments.
"""
from __future__ import annotations

import os

__all__ = ["dataset_home", "get_path_from_url"]


def dataset_home() -> str:
    """Root for pre-staged dataset files; `PADDLE_DATASET_HOME`
    overrides the default cache dir."""
    return os.environ.get(
        "PADDLE_DATASET_HOME",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                     "dataset"),
    )


def get_path_from_url(url: str, root_dir: str | None = None, **kw) -> str:
    """download.py get_path_from_url, egress-free: resolve where the
    file WOULD be cached and require it staged there."""
    path = os.path.join(root_dir or dataset_home(), os.path.basename(url))
    if not os.path.exists(path):
        raise RuntimeError(
            f"automatic download is unavailable in this environment; "
            f"fetch {url} and place it at {path}"
        )
    return path
