"""paddle.inference — the deployment predictor (SURVEY.md §2.10, L11).

Reference: paddle/fluid/inference/api/analysis_predictor.cc — load a
saved program + params, run an analysis/optimization pass pipeline, serve
through zero-copy input/output handles (paddle_infer::Config /
create_predictor / Predictor.run).

TPU-native: the artifact is `paddle_tpu.jit.save`'s serialized StableHLO
+ params (the __model__ analog); the "analysis pass pipeline" is XLA —
the program was optimized at export and compiles natively on load. The
handle API shape (names, reshape, copy_from_cpu/copy_to_cpu) is kept so
reference serving code ports directly.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Config", "Predictor", "create_predictor", "Tensor_"]


class Config:
    """paddle_infer.Config parity: artifact paths + accepted-but-inert
    device knobs (XLA owns placement/optimization)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file is not None and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._path = prog_file
        self._enable_memory_optim = True
        self._switch_ir_optim = True

    def set_prog_file(self, path):
        self._path = path[:-len(".pdmodel")] if path.endswith(".pdmodel") \
            else path

    def prog_file(self):
        return self._path

    # accepted device/optimization toggles (ir passes ≙ XLA; no-ops here)
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        pass

    def disable_gpu(self):
        pass

    def enable_memory_optim(self):
        self._enable_memory_optim = True

    def switch_ir_optim(self, flag=True):
        self._switch_ir_optim = flag

    def disable_glog_info(self):
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass


class Tensor_:
    """Input/output handle (paddle_infer.Tensor parity): stages a host
    array in, reads results out."""

    def __init__(self, name: str, shape=None):
        self.name = name
        self._shape = list(shape) if shape is not None else None
        self._value: Optional[np.ndarray] = None

    def reshape(self, shape):
        self._shape = list(shape)

    def copy_from_cpu(self, arr: np.ndarray):
        self._value = np.ascontiguousarray(arr)
        self._shape = list(arr.shape)

    def copy_to_cpu(self) -> np.ndarray:
        if self._value is None:
            raise RuntimeError(f"handle '{self.name}' holds no data yet")
        return self._value

    def shape(self):
        return self._shape


class Predictor:
    """AnalysisPredictor analog over a jit.save artifact."""

    def __init__(self, config: Config):
        from ..jit.save_load import load

        if config.prog_file() is None:
            raise ValueError("Config needs the artifact path (prog_file)")
        self._layer = load(config.prog_file())
        with open(config.prog_file() + ".pdmeta") as f:
            meta = json.load(f)
        self._input_specs = meta["input_specs"]
        import pickle

        treedef = pickle.loads(bytes.fromhex(meta["out_treedef"]))
        self._n_outputs = max(getattr(treedef, "num_leaves", 1), 1)
        self._inputs: Dict[str, Tensor_] = {}
        for i, (shape, dtype) in enumerate(self._input_specs):
            name = f"input_{i}"
            self._inputs[name] = Tensor_(name, shape)
        # handles are persistent: fetch-before-run works, run() fills them
        self._outputs: Dict[str, Tensor_] = {}

    def get_input_names(self) -> List[str]:
        return list(self._inputs)

    def get_input_handle(self, name: str) -> Tensor_:
        return self._inputs[name]

    def get_output_names(self) -> List[str]:
        # known from the artifact metadata BEFORE the first run
        return [f"output_{i}" for i in range(self._n_outputs)]

    def get_output_handle(self, name: str) -> Tensor_:
        if name not in self._outputs:
            self._outputs[name] = Tensor_(name)
        return self._outputs[name]

    def run(self) -> bool:
        args = []
        for name, handle in self._inputs.items():
            if handle._value is None:
                raise RuntimeError(f"input '{name}' was not fed")
            args.append(handle._value)
        out = self._layer(*args)
        import jax

        # full pytree flatten: nested outputs line up with the leaf count
        # get_output_names advertised from the artifact treedef
        outs = jax.tree_util.tree_leaves(
            out, is_leaf=lambda v: isinstance(v, Tensor)
        )
        for i, o in enumerate(outs):
            h = self.get_output_handle(f"output_{i}")
            h.copy_from_cpu(
                o.numpy() if isinstance(o, Tensor) else np.asarray(o)
            )
        return True


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
