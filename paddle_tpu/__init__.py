"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle(~2.0)
capabilities, built on JAX/XLA/Pallas/pjit.

Top-level namespace mirrors `paddle` (reference: python/paddle/__init__.py):
tensor creation/math ops, Tensor, no_grad, save/load, set_device, plus the
subpackages nn/optimizer/io/vision/metric/amp/jit/static/distributed.

Architecture is TPU-first, not a port (see SURVEY.md): eager ops dispatch to
XLA via jax with a tape recording per-op VJPs (imperative/ analog); the
static/jit path traces whole programs into single compiled executables
(framework/executor analog); distribution is jax.sharding meshes + XLA
collectives, not comm rings.
"""
from __future__ import annotations

# core first (no heavy deps)
from .core import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    Parameter,
    Place,
    TPUPlace,
    Tensor,
    enable_grad,
    get_default_dtype,
    get_device,
    grad,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    is_grad_enabled,
    no_grad,
    seed,
    set_default_dtype,
    set_device,
    set_grad_enabled,
)
from .core.flags import get_flags, set_flags  # noqa: F401

# the full flat op namespace (paddle.add, paddle.matmul, ...)
from .ops import *  # noqa: F401,F403
from . import nn  # noqa: F401
from .nn.layer import ParamAttr  # noqa: F401
from . import optimizer  # noqa: F401
from . import regularizer  # noqa: F401
from . import io  # noqa: F401
from . import metric  # noqa: F401
from . import vision  # noqa: F401
from . import amp  # noqa: F401
from . import jit  # noqa: F401
from . import distributed  # noqa: F401
from .distributed.parallel import DataParallel  # noqa: F401
from .framework.io import load, save  # noqa: F401
from . import hapi  # noqa: F401
from . import profiler  # noqa: F401
from . import static  # noqa: F401
from .hapi import Model, flops, summary  # noqa: F401
from .ops import creation, linalg, logic, manipulation, math, search  # noqa: F401
from .ops.creation import to_tensor  # noqa: F401
from .ops.logic import is_tensor  # noqa: F401

__version__ = "0.1.0"


def disable_static(place=None):
    """2.0 default mode is dygraph."""
    from . import static as static_mod

    static_mod._disable()


def enable_static():
    """Switch to static-graph mode: supported via paddle_tpu.static."""
    from . import static as static_mod

    static_mod._enable()


def in_dynamic_mode() -> bool:
    from . import static as static_mod

    return not static_mod._static_mode_on()


# paddle.abs etc. come from ops import *; math.max/min shadow builtins only
# inside this namespace, matching paddle's own API.
from . import text  # noqa: F401,E402
from . import incubate  # noqa: F401,E402
from . import inference  # noqa: F401,E402
from . import utils  # noqa: F401,E402
from . import device  # noqa: F401,E402
from . import distribution  # noqa: F401,E402
from . import onnx  # noqa: F401,E402
from . import sysconfig  # noqa: F401,E402
from .batch import batch  # noqa: F401,E402
from . import reader  # noqa: F401,E402
from . import dataset  # noqa: F401,E402
from . import tensor  # noqa: F401,E402
