"""paddle.fluid.io — 1.x checkpoint/reader spellings.

Reference: python/paddle/fluid/io.py (save_params/save_persistables over
Program variables) and fluid/reader.py (DataLoader). Static-graph state
here is the live Parameter objects the Program leaves resolve to, so
"save the persistables of a program" is the program's parameter leaves as
a state dict through the hardened framework/io path (atomic replace +
CRC, PR 1).
"""
from __future__ import annotations

import os

import paddle_tpu as _P
from paddle_tpu.io import DataLoader  # noqa: F401
from paddle_tpu.batch import batch  # noqa: F401

__all__ = [
    "DataLoader", "batch", "save", "load", "save_params", "load_params",
    "save_persistables", "load_persistables", "save_inference_model",
    "load_inference_model",
]

save = _P.save
load = _P.load


def _program_params(main_program=None):
    from paddle_tpu.static import default_main_program

    prog = main_program or default_main_program()
    out = {}
    for i, p in enumerate(prog.all_parameters()):
        out[p.name or f"param_{i}"] = p
    return out


def save_params(executor, dirname, main_program=None, filename=None):
    """io.py:117 save_params: the program's parameter leaves."""
    params = _program_params(main_program)
    os.makedirs(dirname, exist_ok=True)
    target = os.path.join(dirname, filename or "params.pdparams")
    _P.save({k: v for k, v in params.items()}, target)


def load_params(executor, dirname, main_program=None, filename=None):
    params = _program_params(main_program)
    target = os.path.join(dirname, filename or "params.pdparams")
    loaded = _P.load(target)
    for k, v in params.items():
        if k in loaded:
            v.set_value(loaded[k])


# persistables == params + opt state; state is live objects here, the
# same leaves cover both spellings
save_persistables = save_params
load_persistables = load_params


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, **kw):
    """io.py:1002: the deployment artifact. The TPU-native deployment
    format is a StableHLO export (paddle_tpu.onnx / jit.save); a fluid
    Program-desc file has no interpreter here."""
    raise NotImplementedError(
        "fluid.io.save_inference_model is out of scope: export compiled "
        "programs with paddle.jit.save (StableHLO), see paddle_tpu.jit"
    )


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    raise NotImplementedError(
        "fluid.io.load_inference_model is out of scope: load StableHLO "
        "exports with paddle.jit.load"
    )
