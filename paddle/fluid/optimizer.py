"""paddle.fluid.optimizer — 1.x optimizer spellings.

Reference: python/paddle/fluid/optimizer.py. The fluid classes are the
modern `paddle_tpu.optimizer` ones with three renames folded in:
`parameter_list=` -> `parameters=`, `regularization=` -> `weight_decay=`,
and the `...Optimizer` class-name suffix. `opt.minimize(avg_cost)` in
static mode records the backward+update into the default program exactly
as the modern classes do.
"""
from __future__ import annotations

from paddle_tpu import optimizer as _opt
from paddle_tpu.optimizer import (  # noqa: F401
    ExponentialMovingAverage,
    LookaheadOptimizer,
    ModelAverage,
    Optimizer,
)

__all__ = [
    "Optimizer", "SGD", "SGDOptimizer", "Momentum", "MomentumOptimizer",
    "Adagrad", "AdagradOptimizer", "Adam", "AdamOptimizer", "Adamax",
    "AdamaxOptimizer", "Adadelta", "AdadeltaOptimizer", "RMSProp",
    "RMSPropOptimizer", "Lamb", "LambOptimizer", "LarsMomentum",
    "LarsMomentumOptimizer", "ExponentialMovingAverage",
    "LookaheadOptimizer", "ModelAverage",
]


def _fluidize(cls):
    """Wrap a modern optimizer class with the fluid kwarg spellings."""

    class _Fluid(cls):
        def __init__(self, *args, **kwargs):
            if "parameter_list" in kwargs:
                kwargs["parameters"] = kwargs.pop("parameter_list")
            if "regularization" in kwargs:
                kwargs["weight_decay"] = kwargs.pop("regularization")
            kwargs.pop("use_global_beta_pow", None)  # fluid-only perf knob
            super().__init__(*args, **kwargs)

    _Fluid.__name__ = cls.__name__ + "Optimizer"
    _Fluid.__qualname__ = _Fluid.__name__
    return _Fluid


SGDOptimizer = _fluidize(_opt.SGD)
MomentumOptimizer = _fluidize(_opt.Momentum)
AdagradOptimizer = _fluidize(_opt.Adagrad)
AdamOptimizer = _fluidize(_opt.Adam)
AdamaxOptimizer = _fluidize(_opt.Adamax)
AdadeltaOptimizer = _fluidize(_opt.Adadelta)
RMSPropOptimizer = _fluidize(_opt.RMSProp)
LambOptimizer = _fluidize(_opt.Lamb)
LarsMomentumOptimizer = _fluidize(_opt.LarsMomentum)

# fluid also exposed the bare names
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Lamb = LambOptimizer
LarsMomentum = LarsMomentumOptimizer
