"""paddle.fluid.dygraph — the 1.x imperative-mode surface.

Reference: python/paddle/fluid/dygraph/ (base.py `guard`/`to_variable`,
layers.py `Layer`, checkpoint.py `save_dygraph`/`load_dygraph`). Fluid
semantics: the process default is static graph, and imperative execution
lives inside `with fluid.dygraph.guard(place):`. Here dygraph is the
native mode, so `guard` *forces static off* for its scope and restores
the previous mode on exit — a 1.x dygraph script and a 1.x static script
can share one process, each seeing its expected default.
"""
from __future__ import annotations

import contextlib

import numpy as np

import paddle_tpu as _P
import paddle_tpu.static as _static
from paddle_tpu.core import Tensor, no_grad  # noqa: F401
from paddle_tpu.nn import Layer, LayerList, Sequential, ParameterList  # noqa: F401
from paddle_tpu.distributed.parallel import DataParallel  # noqa: F401

from .nn import BatchNorm, Conv2D, Embedding, Linear, Pool2D  # noqa: F401
from . import nn  # noqa: F401

__all__ = [
    "guard", "enabled", "enable_dygraph", "disable_dygraph",
    "to_variable", "Layer", "LayerList", "Sequential", "ParameterList",
    "Linear", "Conv2D", "Pool2D", "BatchNorm", "Embedding",
    "no_grad", "save_dygraph", "load_dygraph", "DataParallel",
    "prepare_context", "TracedLayer",
]


@contextlib.contextmanager
def guard(place=None):
    """dygraph/base.py:169. Scope-local imperative mode; `place` is
    accepted for parity (XLA owns placement; .cuda()->TPU policy)."""
    was_static = _static._static_mode_on()
    _static._disable()
    try:
        yield
    finally:
        if was_static:
            _static._enable()


def enabled() -> bool:
    return not _static._static_mode_on()


def enable_dygraph(place=None):
    _static._disable()


def disable_dygraph():
    _static._enable()


def to_variable(value, name=None, zero_copy=None, dtype=None):
    """dygraph/base.py:519: ndarray -> Tensor on the current device."""
    if isinstance(value, Tensor):
        return value.astype(dtype) if dtype else value
    arr = np.asarray(value)
    t = _P.to_tensor(arr, dtype=dtype)
    # fluid to_variable returns a LEAF that participates in autograd
    t.stop_gradient = True
    return t


def save_dygraph(state_dict, model_path):
    """checkpoint.py save_dygraph: appends .pdparams/.pdopt by content —
    a parameter dict is all tensors; optimizer state carries non-tensor
    entries (@step counter, LR_Scheduler dict)."""
    all_tensors = all(hasattr(v, "numpy") for v in state_dict.values())
    suffix = ".pdparams" if all_tensors else ".pdopt"
    _P.save(state_dict, model_path + suffix)


def load_dygraph(model_path):
    """checkpoint.py load_dygraph -> (param_dict, opt_dict)."""
    import os

    params, opt = None, None
    if os.path.exists(model_path + ".pdparams"):
        params = _P.load(model_path + ".pdparams")
    if os.path.exists(model_path + ".pdopt"):
        opt = _P.load(model_path + ".pdopt")
    if params is None and opt is None:
        params = _P.load(model_path)
    return params, opt


def prepare_context(strategy=None):
    """dygraph/parallel.py prepare_context: multi-device init."""
    from paddle_tpu.distributed import init_parallel_env

    init_parallel_env()
    return strategy


class TracedLayer:
    """dygraph_to_static TracedLayer: out of the alias scope — tracing
    here is `paddle.jit.to_static`/`paddle.jit.save` (jit/ast_transform).
    Named raise so scripts fail with direction, not AttributeError."""

    def __init__(self, *a, **kw):
        raise NotImplementedError(
            "fluid.dygraph.TracedLayer is out of scope: use "
            "paddle.jit.to_static / paddle.jit.save (the TPU path traces "
            "whole programs through XLA, not a per-op static graph)"
        )

    @staticmethod
    def trace(layer, inputs):
        raise NotImplementedError(
            "fluid.dygraph.TracedLayer.trace: use paddle.jit.to_static"
        )
