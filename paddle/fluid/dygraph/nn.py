"""paddle.fluid.dygraph.nn — the 1.x dygraph layer classes.

Reference: python/paddle/fluid/dygraph/nn.py. The 1.x constructors differ
from 2.x in *spelling*, not semantics: `Conv2D(num_channels, num_filters,
filter_size, act=...)` vs `Conv2D(in_channels, out_channels,
kernel_size)`; `Linear(input_dim, output_dim, act=...)`; `Pool2D` as a
layer over the pool functional; `BatchNorm(num_channels, act=...)`. Each
wrapper subclasses the modern layer so parameters, state_dict structure,
and the tape path are identical — only __init__ remaps and `act` fuses.
"""
from __future__ import annotations

import paddle_tpu.nn as _nn
import paddle_tpu.nn.functional as _F


def _act_fn(act):
    if act is None:
        return None
    fn = getattr(_F, act, None)
    if fn is None:
        raise ValueError(f"unknown activation {act!r}")
    return fn


class Linear(_nn.Linear):
    """dygraph/nn.py:971 Linear(input_dim, output_dim, act=None)."""

    def __init__(self, input_dim, output_dim, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__(input_dim, output_dim, weight_attr=param_attr,
                         bias_attr=bias_attr)
        self._act = _act_fn(act)

    def forward(self, x):
        out = super().forward(x)
        return self._act(out) if self._act else out


class Conv2D(_nn.Conv2D):
    """dygraph/nn.py:57 Conv2D(num_channels, num_filters, filter_size)."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None,
                 dtype="float32"):
        super().__init__(num_channels, num_filters, filter_size,
                         stride=stride, padding=padding, dilation=dilation,
                         groups=groups, weight_attr=param_attr,
                         bias_attr=bias_attr)
        self._fluid_act = _act_fn(act)

    def forward(self, x):
        out = super().forward(x)
        return self._fluid_act(out) if self._fluid_act else out


class Pool2D(_nn.Layer):
    """dygraph/nn.py:199 Pool2D — a layer shell over pool2d."""

    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False, use_cudnn=True,
                 ceil_mode=False, exclusive=True, data_format="NCHW"):
        super().__init__()
        self._cfg = dict(
            pool_size=pool_size, pool_type=pool_type,
            pool_stride=pool_stride, pool_padding=pool_padding,
            global_pooling=global_pooling, ceil_mode=ceil_mode,
            exclusive=exclusive,
        )

    def forward(self, x):
        from ..layers import pool2d

        return pool2d(x, **self._cfg)


class BatchNorm(_nn.BatchNorm):
    """dygraph/nn.py:1102 — `paddle_tpu.nn.BatchNorm` already carries the
    fluid signature (num_channels, act=...); only `is_test` needs the
    train/eval-mode translation."""

    def __init__(self, num_channels, act=None, is_test=False, **kw):
        kw.pop("moving_mean_name", None)
        kw.pop("moving_variance_name", None)
        kw.pop("do_model_average_for_mean_and_var", None)
        super().__init__(num_channels, act=act, **kw)
        if is_test:
            self.eval()


class Embedding(_nn.Embedding):
    """dygraph/nn.py:1322 Embedding(size=[vocab, dim])."""

    def __init__(self, size, is_sparse=False, is_distributed=False,
                 padding_idx=None, param_attr=None, dtype="float32"):
        super().__init__(int(size[0]), int(size[1]),
                         padding_idx=padding_idx, sparse=is_sparse,
                         weight_attr=param_attr)


__all__ = ["Linear", "Conv2D", "Pool2D", "BatchNorm", "Embedding"]
