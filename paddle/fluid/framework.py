"""paddle.fluid.framework — graph/mode plumbing in fluid-1.x spellings.

Reference: python/paddle/fluid/framework.py. The Program/Variable objects
are `paddle_tpu.static`'s deferred-trace builders; the fluid-era twist is
the *mode default*: a fluid script is static-graph unless it is inside
`fluid.dygraph.guard()`. Rather than flipping the whole process to static
at import (which would break 2.x-style dygraph code sharing the process),
static mode engages lazily the first time a graph-building entry point is
touched (`fluid.data`, `fluid.layers.data`, `program_guard`), and
`dygraph.guard()` forces it off for its scope — the observable fluid
semantics, without a global import side effect.
"""
from __future__ import annotations

import paddle_tpu.static as _static
from paddle_tpu.static import (  # noqa: F401
    Program,
    Variable,
    default_main_program,
    default_startup_program,
)
from paddle_tpu.static import program_guard as _program_guard
from paddle_tpu.core import CPUPlace, CUDAPlace  # noqa: F401

__all__ = [
    "Program", "Variable", "default_main_program",
    "default_startup_program", "program_guard", "in_dygraph_mode",
    "cpu_places", "cuda_places", "name_scope", "_ensure_static",
]


def _ensure_static() -> None:
    """Fluid graph-building entry points imply static mode (a 1.x script
    never calls enable_static — static WAS the default)."""
    if not _static._static_mode_on():
        _static._enable()


def program_guard(main_program, startup_program=None):
    _ensure_static()
    return _program_guard(main_program, startup_program)


def in_dygraph_mode() -> bool:
    return not _static._static_mode_on()


def cpu_places(device_count=None):
    return [CPUPlace()]


def cuda_places(device_ids=None):
    ids = device_ids if device_ids is not None else [0]
    return [CUDAPlace(i) for i in ids]


class _NameScope:
    def __init__(self, prefix):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def name_scope(prefix=None):
    """fluid.name_scope: a debug-visualization grouping; op naming here
    comes from the recorded closures, so the scope is accepted and inert."""
    return _NameScope(prefix)
