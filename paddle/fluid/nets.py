"""paddle.fluid.nets — the composite "net" helpers the book scripts use.

Reference: python/paddle/fluid/nets.py (simple_img_conv_pool:28,
img_conv_group:100, sequence_conv_pool:229, glu:312,
scaled_dot_product_attention:340). Compositions of fluid.layers calls,
so they work in both modes like the layers they wrap.
"""
from __future__ import annotations

from . import layers

__all__ = [
    "simple_img_conv_pool", "img_conv_group", "sequence_conv_pool", "glu",
    "scaled_dot_product_attention",
]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1,
                         conv_padding=0, conv_dilation=1, conv_groups=1,
                         param_attr=None, bias_attr=None, act=None,
                         use_cudnn=True):
    """nets.py:28 — conv2d + pool2d, the recognize_digits backbone."""
    conv_out = layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=conv_stride, padding=conv_padding, dilation=conv_dilation,
        groups=conv_groups, param_attr=param_attr, bias_attr=bias_attr,
        act=act,
    )
    return layers.pool2d(
        input=conv_out, pool_size=pool_size, pool_type=pool_type,
        pool_stride=pool_stride, pool_padding=pool_padding,
        global_pooling=global_pooling,
    )


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    """nets.py:100 — the VGG block: N convs (+BN +dropout) then a pool."""
    tmp = input
    filters = conv_num_filter if isinstance(conv_num_filter, (list, tuple)) \
        else [conv_num_filter]

    def _per(v, i):
        return v[i] if isinstance(v, (list, tuple)) else v

    bns = conv_with_batchnorm if isinstance(conv_with_batchnorm, (list, tuple)) \
        else [conv_with_batchnorm] * len(filters)
    drops = conv_batchnorm_drop_rate \
        if isinstance(conv_batchnorm_drop_rate, (list, tuple)) \
        else [conv_batchnorm_drop_rate] * len(filters)
    for i, nf in enumerate(filters):
        tmp = layers.conv2d(
            input=tmp, num_filters=nf,
            filter_size=_per(conv_filter_size, i),
            padding=_per(conv_padding, i),
            param_attr=_per(param_attr, i),
            act=None if bns[i] else conv_act,
        )
        if bns[i]:
            tmp = layers.batch_norm(input=tmp, act=conv_act)
            if drops[i]:
                tmp = layers.dropout(x=tmp, dropout_prob=drops[i])
    return layers.pool2d(input=tmp, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride)


def sequence_conv_pool(input, lengths, num_filters, filter_size,
                       param_attr=None, act="sigmoid", pool_type="max",
                       bias_attr=None):
    """nets.py:229 under the dense+lengths LoD policy: the ragged input
    travels as (padded [B, T, D], lengths [B]) and the pool masks by
    lengths (ops/sequence.py sequence_pool)."""
    from paddle_tpu.ops import sequence as _seq
    from paddle_tpu.static.nn import create_parameter

    D = int(input.shape[-1])
    w = create_parameter(
        [int(filter_size) * D, int(num_filters)], "float32",
        attr=param_attr,
    )
    conv = _seq.sequence_conv(input, w, lengths, int(filter_size))
    if act:
        import paddle_tpu.nn.functional as F

        conv = getattr(F, act)(conv)
    return _seq.sequence_pool(conv, pool_type, lengths)


def glu(input, dim=-1):
    """nets.py:312 gated linear unit."""
    import paddle_tpu.nn.functional as F

    return F.glu(input, axis=dim)


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """nets.py:340: multi-head attention over dense [B, T, D] operands."""
    import paddle_tpu as _P
    import paddle_tpu.nn.functional as F

    B, Tq, D = queries.shape
    Tk = keys.shape[1]
    dh = D // num_heads

    def split_heads(x, T):
        return _P.transpose(
            _P.reshape(x, [B, T, num_heads, dh]), [0, 2, 1, 3]
        )

    q = split_heads(queries, Tq)
    k = split_heads(keys, Tk)
    v = split_heads(values, Tk)
    scores = _P.matmul(q, k, transpose_y=True) * (dh ** -0.5)
    attn = F.softmax(scores, axis=-1)
    if dropout_rate:
        attn = F.dropout(attn, p=dropout_rate)
    ctx = _P.matmul(attn, v)
    return _P.reshape(_P.transpose(ctx, [0, 2, 1, 3]), [B, Tq, D])
