"""paddle.fluid.executor — Executor/scope under the 1.x module path.

Reference: python/paddle/fluid/executor.py. `Executor.run` compiles the
recorded Program into one jitted XLA executable per feed signature
(paddle_tpu.static.executor); `scope_guard` is accepted for script parity
— variable storage is the live Tensor objects, there is no C++ scope tree
to swap.
"""
from __future__ import annotations

import contextlib

from paddle_tpu.static import CompiledProgram, Executor, global_scope  # noqa: F401

__all__ = ["Executor", "global_scope", "scope_guard", "Scope",
           "CompiledProgram"]


class Scope:
    """executor.py Scope stand-in: find_var resolves through the single
    global scope (parameters/fetches are live objects here)."""

    def find_var(self, name):
        return global_scope().find_var(name)


@contextlib.contextmanager
def scope_guard(scope):
    yield scope
