"""paddle.fluid.core — the pybind-surface names scripts actually touch.

Reference: paddle/fluid/pybind/ exposed as `fluid.core`. Scripts reach
into it for places and device counts; everything else of the pybind
surface is owned by XLA/PJRT here and is out of the alias scope (see
tools/check_alias.py OUT_OF_SCOPE).
"""
from __future__ import annotations

import jax

from paddle_tpu.core import CPUPlace, CUDAPlace, TPUPlace  # noqa: F401
from paddle_tpu.core import is_compiled_with_cuda  # noqa: F401

from .executor import Scope  # noqa: F401

__all__ = [
    "CPUPlace", "CUDAPlace", "TPUPlace", "CUDAPinnedPlace",
    "is_compiled_with_cuda", "get_cuda_device_count", "Scope",
]


def CUDAPinnedPlace():
    """Pinned host memory is a CUDA-transfer concept; host staging under
    PJRT is always pinned-equivalent, so this is CPUPlace."""
    return CPUPlace()


def get_cuda_device_count() -> int:
    """Device count of the accelerator backend (the .cuda()->TPU alias
    policy, core/tensor.py): TPU chips when present, else 0."""
    try:
        return len(jax.devices("tpu"))
    except RuntimeError:
        return 0
