"""paddle.fluid.param_attr — ParamAttr under its 1.x module path.

Reference: python/paddle/fluid/param_attr.py. The object itself is the
modern `paddle_tpu.nn.ParamAttr`; fluid scripts spell the module path
differently, nothing else.
"""
from paddle_tpu.nn import ParamAttr  # noqa: F401

__all__ = ["ParamAttr", "WeightNormParamAttr"]


class WeightNormParamAttr(ParamAttr):
    """param_attr.py:226 — ParamAttr that also requests weight
    normalization. The reparameterization is applied by the consuming
    layer when it honors `dim`; as a ParamAttr it carries the same
    initializer/regularizer fields."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        super().__init__(
            name=name, initializer=initializer,
            learning_rate=learning_rate, regularizer=regularizer,
            trainable=trainable, need_clip=need_clip,
        )
        self.dim = dim
