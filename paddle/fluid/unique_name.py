"""paddle.fluid.unique_name — deterministic name generator.

Reference: python/paddle/fluid/unique_name.py (UniqueNameGenerator :27,
guard :119). Scripts use it to name parameters reproducibly across two
program builds; the counter map + guard semantics are preserved.
"""
from __future__ import annotations

import contextlib

__all__ = ["generate", "switch", "guard"]

_counters: dict = {}


def generate(key: str) -> str:
    n = _counters.get(key, 0)
    _counters[key] = n + 1
    return f"{key}_{n}"


def switch(new_generator=None):
    global _counters
    old = _counters
    _counters = new_generator if isinstance(new_generator, dict) else {}
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
