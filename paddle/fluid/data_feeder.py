"""paddle.fluid.data_feeder — DataFeeder for reader-protocol loops.

Reference: python/paddle/fluid/data_feeder.py:271 (`DataFeeder.feed`
converts a minibatch of reader samples into the feed dict, casting each
column to its placeholder's dtype and reshaping to the placeholder's
static shape with -1 batch).
"""
from __future__ import annotations

import numpy as np

__all__ = ["DataFeeder", "convert_dtype"]


def convert_dtype(dtype):
    from paddle_tpu.core.dtype import convert_dtype as _cd

    return np.dtype(_cd(dtype)).name


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        self.feed_list = list(feed_list)
        self.place = place
        self._vars = []
        for f in self.feed_list:
            v = getattr(f, "_static_var", None)
            if v is None:
                raise TypeError(
                    "DataFeeder feed_list entries must be fluid.data/"
                    f"fluid.layers.data placeholders, got {type(f)}"
                )
            self._vars.append(v)

    def feed(self, iterable):
        """list of per-sample tuples -> {name: batched ndarray}."""
        rows = list(iterable)
        out = {}
        for i, v in enumerate(self._vars):
            col = [np.asarray(r[i]) for r in rows]
            arr = np.stack(col, axis=0).astype(v.dtype)
            tail = tuple(d for d in v.shape[1:])
            if all(d is not None and d >= 0 for d in tail):
                arr = arr.reshape((arr.shape[0],) + tail)
            out[v.name] = arr
        return out
