"""paddle.fluid — the fluid-era (Paddle 1.x) top-level namespace.

Reference: python/paddle/fluid/__init__.py. A 1.x training script touches
this module for places, the Executor, graph entry points (`fluid.data`,
`fluid.layers.*`), the DataFeeder, and `fluid.dygraph`; each submodule
maps the fluid spelling onto the existing paddle_tpu facade and shares
its objects (same classes, same static-mode flag, same Programs).

Mode policy (see framework.py): static engages lazily on the first
graph-building call — a 1.x script never calls enable_static — and
`fluid.dygraph.guard()` scopes imperative mode, both restoring cleanly.
"""
from __future__ import annotations

from paddle_tpu.core import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    TPUPlace,
    Tensor,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
)
from paddle_tpu.core.flags import get_flags, set_flags  # noqa: F401
from paddle_tpu.static import (  # noqa: F401
    CompiledProgram,
    Executor,
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    global_scope,
)

from . import backward  # noqa: F401
from . import core  # noqa: F401
from . import data_feeder  # noqa: F401
from . import dygraph  # noqa: F401
from . import executor  # noqa: F401
from . import framework  # noqa: F401
from . import initializer  # noqa: F401
from . import io  # noqa: F401
from . import layers  # noqa: F401
from . import nets  # noqa: F401
from . import optimizer  # noqa: F401
from . import param_attr  # noqa: F401
from . import regularizer  # noqa: F401
from . import unique_name  # noqa: F401
from .data_feeder import DataFeeder  # noqa: F401
from .executor import Scope, scope_guard  # noqa: F401
from .framework import (  # noqa: F401
    _ensure_static,
    cpu_places,
    cuda_places,
    in_dygraph_mode,
    name_scope,
    program_guard,
)
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401

__all__ = [
    "CPUPlace", "CUDAPlace", "TPUPlace", "CUDAPinnedPlace", "Tensor",
    "Executor", "Program", "Variable", "CompiledProgram",
    "default_main_program", "default_startup_program", "program_guard",
    "global_scope", "scope_guard", "Scope", "DataFeeder", "ParamAttr",
    "WeightNormParamAttr", "data", "embedding", "one_hot",
    "is_compiled_with_cuda", "is_compiled_with_tpu", "get_flags",
    "set_flags", "in_dygraph_mode", "enable_dygraph", "disable_dygraph",
    "name_scope", "cpu_places", "cuda_places", "require_version",
    "layers", "nets", "dygraph", "optimizer", "initializer",
    "regularizer", "io", "backward", "framework", "executor", "core",
    "unique_name", "param_attr", "data_feeder",
]

from .core import CUDAPinnedPlace  # noqa: F401,E402
from .dygraph import disable_dygraph, enable_dygraph  # noqa: F401,E402


def data(name, shape, dtype="float32", lod_level=0):
    """fluid.data (fluid/data.py:28): a feed placeholder with the shape
    taken literally (no implicit batch dim — that is layers.data)."""
    import paddle_tpu.static as _static

    _ensure_static()
    return _static.data(name, shape, dtype)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """fluid.embedding (input.py:203) — the 2.0-signature variant that
    does NOT squeeze a trailing [.., 1] id dim (layers.embedding does)."""
    from paddle_tpu.static.nn import embedding as _emb

    return _emb(input, size, is_sparse=is_sparse,
                is_distributed=is_distributed, padding_idx=padding_idx,
                param_attr=param_attr, dtype=dtype)


def one_hot(input, depth, allow_out_of_range=False):
    """fluid.one_hot (input.py:121)."""
    return layers.one_hot(input, depth, allow_out_of_range)


def require_version(min_version, max_version=None):
    """fluid.require_version: scripts gate on the installed Paddle
    version; the alias package satisfies any requested 1.x/2.x floor
    (API presence is what the linter enforces)."""
    return None
