"""paddle.fluid.regularizer — 1.x regularizer spellings.

Reference: python/paddle/fluid/regularizer.py (L1DecayRegularizer /
L2DecayRegularizer, with L1Decay/L2Decay as the short aliases — the 2.x
names kept only the short form).
"""
from paddle_tpu.regularizer import (  # noqa: F401
    L1Decay,
    L2Decay,
    WeightDecayRegularizer,
)

__all__ = [
    "L1Decay", "L2Decay", "L1DecayRegularizer", "L2DecayRegularizer",
    "WeightDecayRegularizer",
]

L1DecayRegularizer = L1Decay
L2DecayRegularizer = L2Decay
