"""paddle.fluid.initializer — 1.x initializer spellings.

Reference: python/paddle/fluid/initializer.py. Fluid names carry flags the
2.x split classes encode in the class name (`Xavier(uniform=True)` vs
`XavierUniform`); each alias resolves the flag and returns the modern
initializer object, so `ParamAttr(initializer=fluid.initializer.Xavier())`
feeds the existing create_parameter path unchanged.
"""
from __future__ import annotations

from paddle_tpu.nn import initializer as _init
from paddle_tpu.nn.initializer import (  # noqa: F401
    Assign,
    Constant,
    Initializer,
    KaimingNormal,
    KaimingUniform,
    Normal,
    TruncatedNormal,
    Uniform,
    XavierNormal,
    XavierUniform,
)

__all__ = [
    "Initializer", "Constant", "Uniform", "Normal", "TruncatedNormal",
    "Xavier", "MSRA", "Assign", "NumpyArrayInitializer", "Bilinear",
    "ConstantInitializer", "UniformInitializer", "NormalInitializer",
    "TruncatedNormalInitializer", "XavierInitializer", "MSRAInitializer",
    "KaimingNormal", "KaimingUniform", "XavierNormal", "XavierUniform",
]


def Xavier(uniform=True, fan_in=None, fan_out=None, seed=0):
    """initializer.py:487 XavierInitializer."""
    cls = _init.XavierUniform if uniform else _init.XavierNormal
    return cls(fan_in=fan_in, fan_out=fan_out)


def MSRA(uniform=True, fan_in=None, seed=0, negative_slope=0.0,
         nonlinearity="relu"):
    """initializer.py:613 MSRAInitializer (Kaiming He)."""
    cls = _init.KaimingUniform if uniform else _init.KaimingNormal
    try:
        return cls(fan_in=fan_in, negative_slope=negative_slope,
                   nonlinearity=nonlinearity)
    except TypeError:  # older signature without the slope kwargs
        return cls(fan_in=fan_in)


def NumpyArrayInitializer(value):
    """initializer.py:872 — Assign in fluid spelling."""
    return _init.Assign(value)


def Bilinear():
    """initializer.py:770 BilinearInitializer: upsampling-kernel init for
    conv-transpose. Out of the alias scope (no consumer in the tree);
    listed so scripts fail with a named error, not an AttributeError."""
    raise NotImplementedError(
        "fluid.initializer.Bilinear is out of scope: no deconv-upsampling "
        "consumer in this tree; use nn.initializer.Assign with a "
        "precomputed bilinear kernel"
    )


# the verbose 1.x class names are the same factories
ConstantInitializer = Constant
UniformInitializer = Uniform
NormalInitializer = Normal
TruncatedNormalInitializer = TruncatedNormal
XavierInitializer = Xavier
MSRAInitializer = MSRA
