"""paddle.fluid.backward — append_backward in the deferred-trace design.

Reference: python/paddle/fluid/backward.py:1337 append_backward builds
grad-op descs into the program. Here the backward is traced by
`jax.value_and_grad` inside the ONE compiled executable Executor.run
builds, and `optimizer.minimize(loss)` is what records the
backward+update directive — so append_backward's program-rewriting job
does not exist as a separate phase. The entry point is kept for scripts
that call it before minimize: it validates the loss is a graph output
and returns an empty param_grads list (grads are not separately
fetchable program variables; fetch parameters after the update instead).
"""
from __future__ import annotations

__all__ = ["append_backward"]


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    if getattr(loss, "_static_var", None) is None:
        raise TypeError(
            "append_backward expects a static-graph loss (a fluid.data/"
            "layers output inside the default program)"
        )
    return []
