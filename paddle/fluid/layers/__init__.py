"""paddle.fluid.layers — the 1.x flat layer/op namespace.

Reference: python/paddle/fluid/layers/ (nn.py, tensor.py, ops.py,
control_flow.py, sequence ops). Fluid put *everything* in one flat
module; this alias rebuilds it from three modern facades — the flat op
namespace (`paddle_tpu.ops`), the functional layer namespace
(`paddle_tpu.nn.functional`), and the graph-building layer factories
(`paddle_tpu.static.nn`) — then layers the fluid-only spellings on top:
`data` (append_batch_size), `reduce_*` (dim/keep_dim), `cross_entropy`
over *probabilities* (1.x took post-softmax inputs; the 2.x spelling
takes logits), `dropout(dropout_prob=)`, `pool2d`, op-based `accuracy`.

Graph-building entry points engage static mode implicitly — a fluid
script never calls enable_static (see ../framework.py).
"""
from __future__ import annotations

import numpy as np

# bulk surfaces first; fluid-specific wrappers below override name-by-name
from paddle_tpu.ops import *  # noqa: F401,F403
from paddle_tpu.nn.functional import *  # noqa: F401,F403
from paddle_tpu.static.nn import *  # noqa: F401,F403

import paddle_tpu as _P
import paddle_tpu.nn.functional as _F
import paddle_tpu.static as _static
import paddle_tpu.static.nn as _snn
from paddle_tpu.ops import sequence as _seq  # noqa: F401
from paddle_tpu.core.tensor import Tensor as _Tensor

from ..framework import _ensure_static


def data(name, shape, dtype="float32", append_batch_size=True,
         lod_level=0, type=None, stop_gradient=True):
    """fluid.layers.data (layers/io.py:54): unlike fluid.data, the 1.x
    spelling prepends a -1 batch dim unless the shape already carries
    one."""
    _ensure_static()
    shape = list(shape)
    if append_batch_size and (not shape or shape[0] != -1):
        shape = [-1] + shape
    return _static.data(name, shape, dtype)


def fc(input=None, size=None, num_flatten_dims=1, param_attr=None,
       bias_attr=None, act=None, is_test=False, name=None, **kw):
    """fluid.layers.fc (nn.py:87): the 1.x keyword spellings (`input=`,
    `param_attr=`, `act=`) over static.nn.fc (`x=`, `weight_attr=`,
    `activation=`)."""
    if input is None:
        input = kw.pop("x")
    return _snn.fc(input, size, num_flatten_dims=num_flatten_dims,
                   weight_attr=kw.pop("weight_attr", param_attr),
                   bias_attr=bias_attr,
                   activation=kw.pop("activation", act), name=name)


# ---- reduce_* family (1.x dim/keep_dim spellings) -----------------------

def _reduce(fn, input, dim=None, keep_dim=False, name=None):
    axis = dim if dim is None or isinstance(dim, (list, tuple)) \
        else [dim]
    return fn(input, axis=axis, keepdim=keep_dim)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce(_P.mean, input, dim, keep_dim)


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce(_P.sum, input, dim, keep_dim)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce(_P.max, input, dim, keep_dim)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce(_P.min, input, dim, keep_dim)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce(_P.prod, input, dim, keep_dim)


# ---- elementwise_* family ----------------------------------------------

def _ew(op):
    def f(x, y, axis=-1, act=None, name=None):
        # fluid axis-aligned broadcasting (elementwise_op_function.h):
        # y's dims align with x starting at `axis` (default -1 = align
        # trailing, i.e. axis = x.ndim - y.ndim) — e.g. x [N,C,H,W] +
        # y [C] with axis=1 is a per-channel add. Numpy broadcasting
        # alone would align y against the TRAILING dims instead.
        xnd = len(x.shape)
        ynd = len(y.shape)
        ax = axis if axis >= 0 else xnd - ynd
        if 0 <= ax and ax + ynd <= xnd and (ax != xnd - ynd):
            y = _P.reshape(
                y, list(y.shape) + [1] * (xnd - ax - ynd)
            )
        out = op(x, y)
        return _snn._act(out, act)

    return f


elementwise_add = _ew(lambda x, y: x + y)
elementwise_sub = _ew(lambda x, y: x - y)
elementwise_mul = _ew(lambda x, y: x * y)
elementwise_div = _ew(lambda x, y: x / y)
elementwise_max = _ew(_P.maximum)
elementwise_min = _ew(_P.minimum)
elementwise_pow = _ew(_P.pow)


# ---- losses / metrics ---------------------------------------------------

def cross_entropy(input, label, soft_label=False, ignore_index=-100,
                  name=None):
    """fluid.layers.cross_entropy (layers/loss.py:231): `input` is a
    PROBABILITY distribution (post-softmax — the 1.x idiom is
    fc(act='softmax') feeding this), returns the per-row -log p[label]
    with shape [N, 1]. The 2.x `F.cross_entropy` takes logits and
    reduces; mapping this name onto it would double-softmax every 1.x
    script."""
    C = input.shape[-1]
    p = _P.clip(input, 1e-10, 1.0)
    if soft_label:
        out = -_P.sum(label * _P.log(p), axis=-1, keepdim=True)
    else:
        lbl = label
        if len(lbl.shape) == len(input.shape):
            lbl = _P.squeeze(lbl, axis=-1)
        oh = _F.one_hot(lbl, C).astype(input.dtype)
        out = -_P.sum(oh * _P.log(p), axis=-1, keepdim=True)
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    """layers/loss.py:1097: fused logits version, per-row [N, 1] loss."""
    loss = _F.cross_entropy(
        logits, label if soft_label or len(label.shape) < len(logits.shape)
        else _P.squeeze(label, axis=-1),
        soft_label=soft_label, reduction="none", axis=axis,
        ignore_index=ignore_index,
    )
    loss = _P.unsqueeze(loss, axis=-1)
    if return_softmax:
        return loss, _F.softmax(logits, axis=axis)
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      name=None, normalize=False):
    """layers/loss.py sigmoid_cross_entropy_with_logits: per-element BCE
    with positions where label == ignore_index zeroed; normalize=True
    divides by the count of non-ignored elements."""
    out = _F.binary_cross_entropy_with_logits(
        x, _P.cast(label, x.dtype if hasattr(x, "dtype") else "float32"),
        reduction="none",
    )
    keep = _P.cast(_P.logical_not(_P.equal(label, ignore_index)), out.dtype)
    out = out * keep
    if normalize:
        out = out / _P.clip(_P.sum(keep), 1.0, None)
    return out


def accuracy(input, label, k=1, correct=None, total=None):
    """layers/metric_op.py:34 as a graph op (the paddle_tpu.metric
    version is numpy-eager and cannot record into a static Program):
    top-k membership, scalar mean."""
    if len(label.shape) == 1:
        label = _P.unsqueeze(label, axis=-1)
    _, topk_idx = _P.topk(input, k=k, axis=-1)
    hit = _P.equal(topk_idx, label.astype(topk_idx.dtype))
    hit = _P.cast(_P.any(hit, axis=-1), "float32")
    return _P.mean(hit)


# ---- shape / dtype / filling -------------------------------------------

def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    return _P.full(shape, value, dtype=dtype)


def shape(input):
    """layers/nn.py shape: static shapes are compile-time constants under
    XLA, so this is the known shape as an int32 tensor."""
    return _P.to_tensor(np.asarray(tuple(input.shape), np.int32))


def one_hot(input, depth, allow_out_of_range=False):
    x = input
    if len(x.shape) > 1 and x.shape[-1] == 1:
        x = _P.squeeze(x, axis=-1)
    return _F.one_hot(x, depth)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    """layers/nn.py mul: flattening matmul (the 1.x fc backbone)."""
    xs, ys = x, y
    if len(x.shape) > x_num_col_dims + 1:
        d = int(np.prod(x.shape[x_num_col_dims:]))
        xs = _P.reshape(x, [-1, d])
    if len(y.shape) > 2:
        d = int(np.prod(y.shape[:y_num_col_dims]))
        ys = _P.reshape(y, [d, -1])
    return _P.matmul(xs, ys)


def dropout(x, dropout_prob=0.5, is_test=False, seed=None,
            name=None, dropout_implementation="downgrade_in_infer"):
    mode = ("downscale_in_infer"
            if dropout_implementation == "downgrade_in_infer"
            else "upscale_in_train")
    return _F.dropout(x, p=dropout_prob, training=not is_test, mode=mode)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True,
           data_format="NCHW"):
    """fluid.layers.pool2d (nn.py:2128) onto the 2.x pool functionals."""
    if global_pooling:
        return _F.adaptive_avg_pool2d(input, 1) if pool_type == "avg" \
            else _F.adaptive_max_pool2d(input, 1)
    if pool_type == "avg":
        return _F.avg_pool2d(input, pool_size, stride=pool_stride,
                             padding=pool_padding, ceil_mode=ceil_mode,
                             exclusive=exclusive)
    return _F.max_pool2d(input, pool_size, stride=pool_stride,
                         padding=pool_padding, ceil_mode=ceil_mode)


# 1.x axes-plural spellings
def squeeze(input, axes=None, name=None):
    return _P.squeeze(input, axis=axes)


def unsqueeze(input, axes, name=None):
    axes = axes if isinstance(axes, (list, tuple)) else [axes]
    out = input
    for a in axes:
        out = _P.unsqueeze(out, axis=a)
    return out


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0,
                   name=None):
    return _P.uniform(shape, dtype=dtype, min=min, max=max)


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32",
                    name=None):
    return _P.normal(mean=mean, std=std, shape=shape).astype(dtype)


def assign(input, output=None):
    out = _P.assign(input) if output is None else _P.assign(input, output)
    return out


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """layers/control_flow.py Print: debug identity. Eager mode prints
    immediately; under a static trace values are symbolic, so the op is
    identity (XLA has no side-effecting print in the recorded program)."""
    data_ = getattr(input, "_data", None)
    if data_ is not None and not _static._static_mode_on():
        arr = np.asarray(data_)
        # reference semantics: summarize=-1 prints EVERYTHING
        print(message or "", arr[:summarize] if summarize > 0 else arr)
    return input


# fluid embedding: [N, 1] int ids were the LoD idiom; squeeze them
def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    x = input
    if len(x.shape) > 1 and x.shape[-1] == 1:
        x = _P.squeeze(x, axis=-1)
    return _snn.embedding(x, size, is_sparse=is_sparse,
                          padding_idx=padding_idx, param_attr=param_attr,
                          dtype=dtype)
