"""Module-identity aliasing: `paddle.X` IS `paddle_tpu.X`.

The alias package re-exports *module objects*, not copies: after
`install()`, ``sys.modules["paddle.nn"] is sys.modules["paddle_tpu.nn"]``,
so classes, functions, and module-level state are single-sourced — there
is no second `Layer` class to defeat isinstance checks and no snapshot of
mutable state (e.g. the static-mode flag) to drift.

Two mechanisms:
  1. `install()` eagerly aliases every `paddle_tpu.*` module already in
     `sys.modules` (importing `paddle_tpu` pulls in the whole public
     tree, so this covers the normal surface).
  2. `_AliasFinder`, inserted at the FRONT of `sys.meta_path`, lazily
     resolves any straggler `import paddle.x.y` to `paddle_tpu.x.y`.
     It must run BEFORE the stock PathFinder: an aliased parent's
     `__path__` is the paddle_tpu directory, so PathFinder would happily
     re-execute a not-yet-imported submodule's file as a SECOND module
     object under the `paddle.` name — duplicate classes, forked state.
     The finder defers (returns None) exactly for names that are real
     files under the `paddle/` package directory (the fluid tree), so
     those still win.
"""
from __future__ import annotations

import importlib
import importlib.abc
import importlib.machinery
import importlib.util
import os
import sys

_SRC = "paddle_tpu"
_DST = "paddle"
_PKG_DIR = os.path.dirname(os.path.abspath(__file__))


def _is_real_file(suffix: str) -> bool:
    """True when paddle/<suffix as path> exists on disk (fluid tree &
    friends) — those modules belong to PathFinder, not the alias."""
    rel = os.path.join(_PKG_DIR, *suffix.split("."))
    return os.path.exists(rel + ".py") or \
        os.path.exists(os.path.join(rel, "__init__.py"))


def _alias_name(fullname: str) -> str | None:
    """'paddle.x.y' -> 'paddle_tpu.x.y', or None if not aliasable."""
    if not fullname.startswith(_DST + "."):
        return None
    suffix = fullname[len(_DST) + 1:]
    if _is_real_file(suffix):
        return None
    return _SRC + "." + suffix


class _AliasLoader(importlib.abc.Loader):
    def __init__(self, target: str):
        self._target = target

    def create_module(self, spec):
        # return the EXISTING paddle_tpu module object: exact identity
        return importlib.import_module(self._target)

    def exec_module(self, module):
        pass  # already executed under its real name


class _AliasFinder(importlib.abc.MetaPathFinder):
    def find_spec(self, fullname, path=None, target=None):
        tgt = _alias_name(fullname)
        if tgt is None:
            return None
        try:
            t_spec = importlib.util.find_spec(tgt)
        except (ImportError, ValueError):
            return None
        if t_spec is None:
            return None
        return importlib.machinery.ModuleSpec(
            fullname,
            _AliasLoader(tgt),
            is_package=t_spec.submodule_search_locations is not None,
        )


def install() -> None:
    import paddle_tpu  # noqa: F401 — materializes the module tree

    for name in sorted(k for k in list(sys.modules)
                       if k.startswith(_SRC + ".")):
        mod = sys.modules[name]
        if mod is None:
            continue
        # real files under paddle/ (fluid) are never in sys.modules under
        # a paddle_tpu name, so setdefault cannot shadow them
        sys.modules.setdefault(_DST + name[len(_SRC):], mod)
    if not any(isinstance(f, _AliasFinder) for f in sys.meta_path):
        sys.meta_path.insert(0, _AliasFinder())
