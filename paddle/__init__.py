"""`paddle` — the stock-script compatibility package.

The north star is Paddle training scripts running **verbatim** on TPU:
``import paddle`` / ``import paddle.fluid as fluid`` must resolve in the
same environment as `paddle_tpu`, not a lookalike spelling of it. This
package is an *alias tree*, not a port: every public name here is the
same object as its `paddle_tpu` counterpart (see `_alias.py` for the
module-identity mechanism), and the fluid-era spellings
(`fluid.layers.fc`, `fluid.dygraph.guard`, `fluid.Executor`) live in the
real `paddle/fluid/` subpackage, mapped onto the existing facades.

Parity is enforced, not asserted: `tools/check_alias.py` lints this
namespace against the reference manifest, and
`tests/test_reference_scripts.py` executes reference-shaped training
scripts verbatim in subprocesses through this package.
"""
import paddle_tpu as _pt

from . import _alias as _alias_mod

_alias_mod.install()

# the full top-level namespace: paddle.add, paddle.Tensor, paddle.nn, ...
# (same objects — functions close over paddle_tpu module state, so
# enable_static()/set_device() et al. act on the single real flag)
globals().update({
    _k: _v for _k, _v in vars(_pt).items()
    if not _k.startswith("__") and _k != "annotations"
})

__version__ = _pt.__version__

# the fluid-era tree is real files (new spellings), imported last so its
# own `import paddle_tpu...` lines see a finished alias table
from . import fluid  # noqa: E402,F401

__all__ = [k for k in globals() if not k.startswith("_")]
