"""Driver benchmark: prints ONE JSON line
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extra": {...}}.

Benchmarks the framework's REAL hot path — `paddle_tpu.jit.TrainStep`
(forward + loss + backward + framework optimizer fused into one donated XLA
program; the analog of the reference's generated `core.ops` bindings +
run_program op, pybind/op_function_generator.cc:488) — exactly the harness
`__graft_entry__.dryrun_multichip` drives on the virtual mesh.

Headline metric (round 5+): `resnet50_bf16_train_imgs_per_sec` — the
compute-bound number (BASELINE.json config 2). The old headline
`lenet_mnist_train_imgs_per_sec` (r01-r04) was tunnel-overhead-bound and
rides in `extra` for continuity. `extra` also carries BERT-base and
GPT-medium bf16 steps and per-model compile times.

Why rounds 1–3 read ~660–724 imgs/sec (~354 ms/step): the old bench
updated params with an EAGER `tree_map(p - lr*g)` outside jit — 8 separate
device-program launches per step, each paying the tunnel's host->device
round-trip latency, serialized against the grad program. TrainStep issues
ONE async program per step with donated buffers, so steps pipeline.

Measurement note (axon tunnel): `jax.block_until_ready` is a NO-OP on
this platform — only a device_get truly waits. Every timed loop here
ends with `np.asarray(...)` of a scalar/slice as the barrier; identical
(executable, args) repeats can be served from a runtime cache, so timed
calls never reuse the warmup arguments.

vs_baseline: BASELINE.json publishes no reference numbers (BASELINE.md), so
the recorded value IS the baseline (1.0); extra.vs_r02 carries the ratio
against round 2's 663.6 on the same metric.
"""
import json
import os
import time

import numpy as np

#: repeats per metric (VERDICT r5 next #3): the chip is tunnel-shared, so
#: a single-shot number carries ±2x jitter; the headline is the MEDIAN of
#: N runs and min/max spread rides in `extra` per metric
REPEATS = max(int(os.environ.get("PADDLE_BENCH_REPEATS", "3") or 3), 1)


def _spread(vals):
    sv = sorted(vals)
    return {"n": len(sv), "median": round(sv[len(sv) // 2], 1),
            "min": round(sv[0], 1), "max": round(sv[-1], 1)}


def _repeat(fn):
    """Run `fn() -> (value, extra_dict)` REPEATS times; return the median
    run's (value, extra) plus the spread record across runs."""
    runs = [fn() for _ in range(REPEATS)]
    runs.sort(key=lambda r: r[0])
    med = runs[len(runs) // 2]
    return med[0], med[1], _spread([r[0] for r in runs])


def _bench_train(model_fn, opt_fn, x_shape, y_classes, batch, steps, label,
                 amp=False):
    """Time `steps` TrainStep calls (one donated XLA program each), async-
    dispatched, single block at the end. Returns (imgs/sec, breakdown).
    amp=True routes the optimizer through the fleet bf16 strategy."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.jit import TrainStep

    paddle.seed(0)
    model = model_fn()
    opt = opt_fn(model)
    if amp:
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.fleet import DistributedStrategy

        strategy = DistributedStrategy()
        strategy.amp = True
        fleet.init(is_collective=True, strategy=strategy)
        opt = fleet.distributed_optimizer(opt)
    step = TrainStep(
        model, lambda out, y: nn.functional.cross_entropy(out, y), opt
    )

    # stage the batch in HBM once (DataLoader's double-buffer analog,
    # operators/reader/buffered_reader.cc) — the tunnel's host->device
    # bandwidth must not be inside the timed loop
    import jax.numpy as jnp

    x = jax.device_put(
        jnp.asarray(np.random.rand(batch, *x_shape).astype(np.float32))
    )
    y = jax.device_put(jnp.asarray((np.arange(batch) % y_classes).astype(np.int32)))
    _ = np.asarray(x.ravel()[:1])  # devget barrier: upload must finish here

    t0 = time.perf_counter()
    loss = step(x, y)  # compile + first step
    _ = np.asarray(loss._data)  # devget barrier (block_until_ready no-ops)
    compile_s = time.perf_counter() - t0

    # steady state: async dispatch, one block at the end -> steps pipeline
    # optional device-trace artifact (DeviceTracer/GenProfile analog):
    # PADDLE_TPU_TRACE=<dir> captures an XPlane trace of the timed loop
    import os

    trace_dir = os.environ.get("PADDLE_TPU_TRACE")
    if trace_dir:
        from paddle_tpu import profiler as prof

        prof.start_profiler(trace_dir=os.path.join(trace_dir, label))
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(x, y)
    _ = np.asarray(loss._data)  # waits for the whole queued sequence
    dt = time.perf_counter() - t0
    if trace_dir:
        prof.stop_profiler()

    step_ms = dt / steps * 1e3
    bd = {
        f"{label}_step_ms": round(step_ms, 2),
        f"{label}_compile_s": round(compile_s, 1),
    }
    # achieved-FLOPs accounting (ISSUE 8): XLA-cost-model FLOPs of the
    # exact compiled step vs the device-kind peak table — None on CPU CI
    # without a PADDLE_OBS_PEAK_FLOPS override, recorded when known
    mfu = step.mfu_pct(step_ms / 1e3)
    if mfu is not None:
        bd[f"{label}_mfu_pct"] = mfu
    return steps * batch / dt, bd


def _bert_base():
    """BERT-base-shaped encoder (BASELINE config 3): 12 layers, hidden
    768, 12 heads, seq 128 — the encoder dominates FLOPs; the head is a
    2-way classifier. bf16 autocast via the fleet amp strategy (TPU-first
    policy; MXU-bound matmuls cast down, softmax/norms stay f32)."""
    import paddle_tpu as paddle
    from paddle_tpu import nn

    class Bert(nn.Layer):
        def __init__(self):
            super().__init__()
            self.embed = nn.Embedding(30522, 768)
            self.pos = nn.Embedding(512, 768)
            self.encoder = nn.LayerList([
                nn.TransformerEncoderLayer(768, 12, 3072, dropout=0.0)
                for _ in range(12)
            ])
            self.head = nn.Linear(768, 2)

        def forward(self, ids):
            T = ids.shape[1]
            pos_ids = paddle.arange(T, dtype="int64")
            h = self.embed(ids) + self.pos(pos_ids)
            for lyr in self.encoder:
                h = lyr(h)
            return self.head(h.mean(axis=1))

    return Bert()


def _bench_bert(steps=10, batch=32, seq=128):
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.jit import TrainStep

    paddle.seed(0)
    strategy = DistributedStrategy()
    strategy.amp = True  # bf16 autocast inside the fused step
    fleet.init(is_collective=True, strategy=strategy)
    model = _bert_base()
    opt = fleet.distributed_optimizer(
        optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                        parameters=model.parameters())
    )
    step = TrainStep(
        model, lambda out, y: nn.functional.cross_entropy(out, y), opt
    )
    import jax.numpy as jnp

    ids = jax.device_put(jnp.asarray(
        (np.arange(batch * seq) % 30000).reshape(batch, seq)
        .astype(np.int32)
    ))
    y = jax.device_put(jnp.asarray((np.arange(batch) % 2).astype(np.int32)))
    _ = np.asarray(ids.ravel()[:1])

    t0 = time.perf_counter()
    loss = step(ids, y)
    _ = np.asarray(loss._data)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(ids, y)
    _ = np.asarray(loss._data)
    dt = time.perf_counter() - t0
    return steps * batch / dt, {
        "bert_base_bf16_step_ms": round(dt / steps * 1e3, 2),
        "bert_base_bf16_compile_s": round(compile_s, 1),
    }


def _gpt_medium(dense=False):
    """GPT-medium-shaped causal decoder (the single-chip proxy for
    BASELINE config 5's GPT-3 1.3B, which needs the dp x pp x mp hybrid
    dryrun_multichip proves): 24 ParallelGPTBlock layers (trivial 1-chip
    mesh — same code path the hybrid shards), d_model 1024, 16 heads,
    seq 1024, tied-free 32k vocab head.

    Round 6: the decoder hot path is the DEFAULT path — flash attention
    routes automatically inside every block (PADDLE_FLASH_DEFAULT policy)
    and the model returns the pre-head hidden state so the loss can run
    the blockwise fused vocab CE. `dense=True` is the escape-hatch
    configuration (forced dense attention + materialized-logits CE) used
    to record the routed/unrouted pair."""
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed import ParallelGPTBlock, comm

    if comm.hybrid_mesh() is None:
        comm.init_hybrid_mesh(dp=1, mp=1, pp=1, sp=1)

    class GPT(nn.Layer):
        def __init__(self, vocab=32000, d=1024, heads=16, layers=24,
                     seq=1024):
            super().__init__()
            self.embed = nn.Embedding(vocab, d)
            self.pos = nn.Embedding(seq, d)
            self.blocks = nn.LayerList([
                ParallelGPTBlock(
                    d, heads, dropout=0.0,
                    use_flash_attention=False if dense else None,
                )
                for _ in range(layers)
            ])
            self.head = nn.Linear(d, vocab)

        def forward(self, ids):
            T = ids.shape[1]
            pos_ids = paddle.arange(T, dtype="int64")
            h = self.embed(ids) + self.pos(pos_ids)
            for blk in self.blocks:
                h = blk(h)
            # the head projection lives in the LOSS (blockwise fused CE
            # streams it over vocab chunks); the dense escape hatch
            # materializes the logits here as before
            return self.head(h) if dense else h

    return GPT()


def _bench_gpt(steps=10, batch=4, seq=1024, dense=False, guard=None):
    """Causal-LM training step: next-token CE over the full sequence.
    guard: None follows PADDLE_GUARD_MODE (default skip = sentinel ON);
    "off" forces the unguarded seed program for the overhead pair."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.jit import TrainStep

    if guard is not None:
        os.environ["PADDLE_GUARD_MODE"] = guard
    paddle.seed(0)
    strategy = DistributedStrategy()
    strategy.amp = True
    fleet.init(is_collective=True, strategy=strategy)
    model = _gpt_medium(dense=dense)
    opt = fleet.distributed_optimizer(
        optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                        parameters=model.parameters())
    )

    if dense:
        def lm_loss(logits, labels):
            V = logits.shape[-1]
            return nn.functional.cross_entropy(
                logits.reshape([-1, V]), labels.reshape([-1])
            )
    else:
        def lm_loss(h, labels):
            d = h.shape[-1]
            # blockwise fused head-projection + CE: the [B*S, 32k] f32
            # logits/grads never materialize at once (PADDLE_CE_CHUNK)
            return nn.functional.fused_linear_cross_entropy(
                h.reshape([-1, d]), model.head.weight, model.head.bias,
                labels.reshape([-1]),
            )

    step = TrainStep(model, lm_loss, opt)
    ids = jax.device_put(jnp.asarray(
        (np.arange(batch * seq) % 31000).reshape(batch, seq)
        .astype(np.int32)
    ))
    labels = jax.device_put(jnp.asarray(
        ((np.arange(batch * seq) + 1) % 31000).reshape(batch, seq)
        .astype(np.int32)
    ))
    _ = np.asarray(ids.ravel()[:1])

    t0 = time.perf_counter()
    loss = step(ids, labels)
    _ = np.asarray(loss._data)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(ids, labels)
    _ = np.asarray(loss._data)
    dt = time.perf_counter() - t0
    tok_s = steps * batch * seq / dt
    out = {
        "gpt_medium_bf16_step_ms": round(dt / steps * 1e3, 2),
        "gpt_medium_bf16_tokens_per_sec": round(tok_s, 0),
        "gpt_medium_bf16_compile_s": round(compile_s, 1),
    }
    mfu = step.mfu_pct(dt / steps)
    if mfu is not None:
        out["gpt_medium_bf16_mfu_pct"] = mfu
    return out


def _bench_gpt_multichip(steps=10, seq=1024, shard_off=False):
    """GPT-medium training step on a dp x mp2 mesh (ISSUE 6): the
    sharded-flash/fused-LN default vs the `PADDLE_FLASH_SHARD=0` dense
    fallback (the r6 multi-device behavior). Records the pair so the
    shard_map-seam win is tracked by tools/bench_continuity.py's >10%
    gate instead of anecdote. Runs only when the job spans >= 2 devices
    with an even count (mp=2, dp fills the rest)."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.jit import TrainStep

    ndev = len(jax.devices())
    mp = 2
    dp = ndev // mp
    shard_before = os.environ.get("PADDLE_FLASH_SHARD")
    if shard_off:
        os.environ["PADDLE_FLASH_SHARD"] = "0"
    try:
        paddle.seed(0)
        strategy = DistributedStrategy()
        strategy.amp = True
        strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": mp}
        fleet.init(is_collective=True, strategy=strategy)
        model = _gpt_medium()
        fl_model = fleet.distributed_model(model)
        opt = fleet.distributed_optimizer(
            optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                            parameters=model.parameters())
        )

        def lm_loss(h, labels):
            d = h.shape[-1]
            return nn.functional.fused_linear_cross_entropy(
                h.reshape([-1, d]), model.head.weight, model.head.bias,
                labels.reshape([-1]),
            )

        step = TrainStep(fl_model, lm_loss, opt)
        batch = 4 * dp  # 4 per data-parallel shard
        ids = fl_model.shard_input(
            (np.arange(batch * seq) % 31000).reshape(batch, seq)
            .astype(np.int32)
        )
        labels = fl_model.shard_input(
            ((np.arange(batch * seq) + 1) % 31000).reshape(batch, seq)
            .astype(np.int32)
        )
        _ = np.asarray(ids._data.ravel()[:1])

        t0 = time.perf_counter()
        loss = step(ids, labels)
        _ = np.asarray(loss._data)
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(ids, labels)
        _ = np.asarray(loss._data)
        dt = time.perf_counter() - t0
        tok_s = steps * batch * seq / dt
    finally:
        if shard_before is None:
            os.environ.pop("PADDLE_FLASH_SHARD", None)
        else:
            os.environ["PADDLE_FLASH_SHARD"] = shard_before
        # drop the dp x mp fleet mesh: it is process-global routing
        # state, and everything benched after this pair must not
        # silently run as a fleet job (same lingering-mesh class as the
        # dryrun phases, which null it after every section)
        from paddle_tpu.distributed import comm as _comm

        _comm._state.hybrid_mesh = None
    tag = "_dense" if shard_off else ""
    return {
        f"gpt_medium_bf16_dp_mp{tag}_step_ms": round(dt / steps * 1e3, 2),
        f"gpt_medium_bf16_dp_mp{tag}_tokens_per_sec": round(tok_s, 0),
        f"gpt_medium_bf16_dp_mp{tag}_compile_s": round(compile_s, 1),
    }


def _bench_gpt_dp_q8(steps=10, seq=1024, quant=True):
    """GPT-medium training step on a hierarchical dcn x ici dp mesh with
    the dcn hop quantized (ISSUE 10) vs full-width f32: the
    `gpt_medium_bf16_dp_q8_*` / `*_q8_off_*` pair under the
    tools/bench_continuity.py >10% gate. Both configs run the explicit
    per-grad dcn reduction (async_dcn_allreduce), so the ONLY difference
    is the wire width of the slow inter-node hop — int8 payload +
    per-block scales vs f32. The static comm-byte estimate rides along
    report-only (`gpt_medium_bf16_dp_q8_comm_mb`). Runs when the job
    spans >= 4 devices with an even count (dcn = ndev/2 x ici 2)."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.jit import TrainStep

    ndev = len(jax.devices())
    try:
        paddle.seed(0)
        strategy = DistributedStrategy()
        strategy.amp = True
        strategy.hierarchical_allreduce = True
        strategy.hierarchical_allreduce_inter_nranks = 2
        strategy.async_dcn_allreduce = True
        if quant:
            strategy.quantized_allreduce = "int8"
        fleet.init(is_collective=True, strategy=strategy)
        model = _gpt_medium()
        fl_model = fleet.distributed_model(model)
        opt = fleet.distributed_optimizer(
            optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                            parameters=model.parameters())
        )

        def lm_loss(h, labels):
            d = h.shape[-1]
            return nn.functional.fused_linear_cross_entropy(
                h.reshape([-1, d]), model.head.weight, model.head.bias,
                labels.reshape([-1]),
            )

        step = TrainStep(fl_model, lm_loss, opt)
        batch = 4 * ndev  # 4 per data-parallel shard
        ids = fl_model.shard_input(
            (np.arange(batch * seq) % 31000).reshape(batch, seq)
            .astype(np.int32)
        )
        labels = fl_model.shard_input(
            ((np.arange(batch * seq) + 1) % 31000).reshape(batch, seq)
            .astype(np.int32)
        )
        _ = np.asarray(ids._data.ravel()[:1])

        t0 = time.perf_counter()
        loss = step(ids, labels)
        _ = np.asarray(loss._data)
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(ids, labels)
        _ = np.asarray(loss._data)
        dt = time.perf_counter() - t0
        tok_s = steps * batch * seq / dt
        comm = step._grad_comm_info
    finally:
        from paddle_tpu.distributed import comm as _comm

        _comm._state.hybrid_mesh = None
    tag = "" if quant else "_off"
    out = {
        f"gpt_medium_bf16_dp_q8{tag}_step_ms": round(dt / steps * 1e3, 2),
        f"gpt_medium_bf16_dp_q8{tag}_tokens_per_sec": round(tok_s, 0),
        f"gpt_medium_bf16_dp_q8{tag}_compile_s": round(compile_s, 1),
    }
    if quant and comm:
        # report-only (no per_sec/_ms suffix -> never gated): the dcn
        # hop's priced bytes, payload + scales
        out["gpt_medium_bf16_dp_q8_comm_mb"] = round(
            comm["bytes_on_wire"] / 1e6, 1)
        out["gpt_medium_bf16_dp_q8_comm_reduction_x"] = \
            comm["reduction_x"]
    return out


def _bench_gpt_q8m(steps=10, batch=4, seq=1024, quant=True):
    """GPT-medium training step with int8 Adam moments (ISSUE 19):
    `strategy.quantized_moments = "int8"` stores moment1/moment2 as
    int8 payload + per-block f32 scales (moment2 in sqrt domain) and
    the compiled apply dequantizes/requantizes around the unchanged
    AdamW rule. The `gpt_medium_bf16_q8m_*` / `*_q8m_off_*` pair lands
    under the tools/bench_continuity.py >10% gate; the static
    moment-byte estimate rides report-only."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.jit import TrainStep

    try:
        paddle.seed(0)
        strategy = DistributedStrategy()
        strategy.amp = True
        if quant:
            strategy.quantized_moments = "int8"
        fleet.init(is_collective=True, strategy=strategy)
        model = _gpt_medium()
        opt = fleet.distributed_optimizer(
            optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                            parameters=model.parameters()),
            strategy=strategy,
        )

        def lm_loss(h, labels):
            d = h.shape[-1]
            return nn.functional.fused_linear_cross_entropy(
                h.reshape([-1, d]), model.head.weight, model.head.bias,
                labels.reshape([-1]),
            )

        step = TrainStep(model, lm_loss, opt)
        ids = jax.device_put(jnp.asarray(
            (np.arange(batch * seq) % 31000).reshape(batch, seq)
            .astype(np.int32)
        ))
        labels = jax.device_put(jnp.asarray(
            ((np.arange(batch * seq) + 1) % 31000).reshape(batch, seq)
            .astype(np.int32)
        ))
        _ = np.asarray(ids.ravel()[:1])

        t0 = time.perf_counter()
        loss = step(ids, labels)
        _ = np.asarray(loss._data)
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(ids, labels)
        _ = np.asarray(loss._data)
        dt = time.perf_counter() - t0
        tok_s = steps * batch * seq / dt
        minfo = step._moment_bytes_info
    finally:
        from paddle_tpu.distributed import comm as _comm

        _comm._state.hybrid_mesh = None
    tag = "" if quant else "_off"
    out = {
        f"gpt_medium_bf16_q8m{tag}_step_ms": round(dt / steps * 1e3, 2),
        f"gpt_medium_bf16_q8m{tag}_tokens_per_sec": round(tok_s, 0),
        f"gpt_medium_bf16_q8m{tag}_compile_s": round(compile_s, 1),
    }
    if quant and minfo:
        # report-only: resident optimizer-state bytes, payload + scales
        out["gpt_medium_bf16_q8m_moment_mb"] = round(
            minfo["bytes_resident"] / 1e6, 1)
        out["gpt_medium_bf16_q8m_moment_reduction_x"] = \
            minfo["reduction_x"]
    return out


def _bench_decode_q8w(batch_sizes=(1, 8), prompt_len=128,
                      new_tokens=64):
    """Serving bench over an int8 CHECKPOINT (ISSUE 19): the
    GPT-medium-shaped TransformerLM is block-quantized once via
    `jit.save_quantized`, reloaded narrow (`load_quantized`: int8
    payload becomes the resident weight, scales attach as buffers, the
    compiled decode streams the narrow bytes + scales from HBM every
    token), and generate() prices decode at batch 1/8 next to the
    full-width `serve_gpt_medium_tokens_per_sec_bN` keys. The
    checkpoint load itself is timed (`q_ckpt_load_ms`, gated) and the
    on-disk payload/scale bytes ride report-only."""
    import shutil
    import tempfile

    import paddle_tpu as paddle
    from paddle_tpu.jit import DecodeStep, PrefillStep, save_quantized
    from paddle_tpu.serving import generate
    from paddle_tpu.serving.model import TransformerLM

    paddle.seed(0)
    cap = prompt_len + new_tokens
    model = TransformerLM(32000, d_model=1024, num_heads=16,
                          num_layers=24, max_position=cap)
    model.eval()
    tmp = tempfile.mkdtemp(prefix="q8w_ckpt_")
    out = {}
    try:
        path = os.path.join(tmp, "gpt_medium")
        info = save_quantized(model, path, dtype="int8")
        paddle.seed(0)
        qmodel = TransformerLM(32000, d_model=1024, num_heads=16,
                               num_layers=24, max_position=cap)
        qmodel.eval()
        meta = qmodel.load_quantized(path)
        out["q_ckpt_load_ms"] = round(meta["load_ms"], 1)
        # report-only (no _ms/per_sec suffix -> never gated): narrow
        # checkpoint bytes vs the full-width form it replaces
        out["q_ckpt_payload_mb"] = round(
            (info["bytes_payload"] + info["bytes_scales"]) / 1e6, 1)
        # int8 payload is 1 byte/elem, so the f32 form it replaces is
        # exactly 4x the payload bytes
        out["q_ckpt_reduction_x"] = round(
            4.0 * info["bytes_payload"]
            / (info["bytes_payload"] + info["bytes_scales"]), 2)
        pre = PrefillStep(qmodel)
        dec = DecodeStep(qmodel)
        for B in batch_sizes:
            prompts = (np.arange(B * prompt_len) % 31000).reshape(
                B, prompt_len).astype(np.int32)
            _ = generate(qmodel, prompts, 2, max_length=cap,
                         prefill=pre, decode=dec)
            t0 = time.perf_counter()
            toks = generate(qmodel, prompts, new_tokens,
                            max_length=cap, prefill=pre, decode=dec)
            assert toks.shape == (B, new_tokens)
            dt = time.perf_counter() - t0
            out[f"serve_gpt_medium_tokens_per_sec_b{B}_q8w"] = round(
                B * new_tokens / dt, 1)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def _bench_decode(batch_sizes=(1, 8, 64), prompt_len=128, new_tokens=64):
    """Serving bench (ISSUE 9): the compiled prefill/decode pair over
    the GPT-medium-shaped TransformerLM (same decoder the training
    bench prices).

    Throughput: `generate()` at batch 1/8/64 — the loop state stays on
    device and the host syncs ONCE at the end, so the number is the
    device's steady decode rate (`serve_gpt_medium_tokens_per_sec_bN`).

    Latency: batch 1 with a host sync after EVERY token — the per-token
    time a single-stream client observes (`serve_gpt_medium_token_p50_ms`
    / `_p99_ms`), plus the bucketed prefill cost
    (`serve_gpt_medium_prefill_ms`). All keys land under the
    tools/bench_continuity.py >10% gate (per_sec higher-better, _ms
    lower-better)."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.jit import DecodeState, DecodeStep, PrefillStep
    from paddle_tpu.serving import generate
    from paddle_tpu.serving.model import TransformerLM

    paddle.seed(0)
    cap = prompt_len + new_tokens
    model = TransformerLM(32000, d_model=1024, num_heads=16,
                          num_layers=24, max_position=cap)
    model.eval()
    pre = PrefillStep(model)
    dec = DecodeStep(model)
    out = {}
    for B in batch_sizes:
        prompts = (np.arange(B * prompt_len) % 31000).reshape(
            B, prompt_len).astype(np.int32)
        # warm (compiles prefill for this B + the decode step once)
        _ = generate(model, prompts, 2, max_length=cap, prefill=pre,
                     decode=dec)
        t0 = time.perf_counter()
        toks = generate(model, prompts, new_tokens, max_length=cap,
                        prefill=pre, decode=dec)
        assert toks.shape == (B, new_tokens)
        dt = time.perf_counter() - t0
        out[f"serve_gpt_medium_tokens_per_sec_b{B}"] = round(
            B * new_tokens / dt, 1)

    # batch-1 per-token latency: sync every step (client view). The
    # prompt pads to the SAME bucket the warm generate() used, so
    # prefill_ms prices the warm compiled program, not a fresh compile.
    from paddle_tpu.serving.engine import bucket_for

    bucket = bucket_for(prompt_len, cap)
    prompts = np.zeros((1, bucket), np.int32)
    prompts[0, :prompt_len] = np.arange(prompt_len) % 31000
    t0 = time.perf_counter()
    last, cache_raws, pos = pre(
        model.gen_cache(1, cap), prompts,
        np.full((1,), prompt_len, np.int32))
    first = jnp.argmax(last, -1).astype(jnp.int32)
    _ = np.asarray(first)
    out["serve_gpt_medium_prefill_ms"] = round(
        (time.perf_counter() - t0) * 1e3, 2)
    state = DecodeState.make(cache_raws, first, pos)
    lat = []
    for _ in range(new_tokens - 1):
        t0 = time.perf_counter()
        emit, _, state = dec(state)
        _ = np.asarray(emit)
        lat.append((time.perf_counter() - t0) * 1e3)
    lat.sort()
    out["serve_gpt_medium_token_p50_ms"] = round(
        lat[len(lat) // 2], 2)
    out["serve_gpt_medium_token_p99_ms"] = round(
        lat[min(int(len(lat) * 0.99), len(lat) - 1)], 2)
    # the fleet monitor's online log-histogram digest over the SAME
    # samples (ISSUE 14): report-only `_digest` keys pin the stored-vs-
    # merged-counts agreement each round (never gated — the `_ms` pair
    # above is the gated truth; the digest is bin-quantized)
    from paddle_tpu.observability.monitor import LogHistogram

    hist = LogHistogram()
    for v in lat:
        hist.add(v)
    out["serve_gpt_medium_token_p50_ms_digest"] = round(
        hist.percentile(50), 2)
    out["serve_gpt_medium_token_p99_ms_digest"] = round(
        hist.percentile(99), 2)
    return out


def _bench_decode_paged(prompt_len=128, new_tokens=64, block=16,
                        chunk=32):
    """Production-tier serving bench (ISSUE 13): the PAGED-KV decode
    throughput next to round-10's contiguous `serve_gpt_medium_*` keys
    (`_paged` suffix — same >10% continuity gate), the time-to-first-
    token of a loaded engine under CHUNKED prefill
    (`serve_gpt_medium_ttft_ms`, lower-better gated), and the KV HBM
    bytes the paged pool actually holds vs the worst-case contiguous
    reservation for the same slots (report-only extras — the headroom
    PERF.md round-13 prices)."""
    import jax.numpy as jnp  # noqa: F401 — device warm-up parity

    import paddle_tpu as paddle
    from paddle_tpu.serving import (
        InferenceEngine, Request, TransformerLM, generate, paged_kv,
    )

    paddle.seed(0)
    cap = prompt_len + new_tokens
    cap += (-cap) % block  # engine pools splice block-aligned
    model = TransformerLM(32000, d_model=1024, num_heads=16,
                          num_layers=24, max_position=cap)
    model.eval()
    out = {}
    B = 8
    prompts = (np.arange(B * prompt_len) % 31000).reshape(
        B, prompt_len).astype(np.int32)
    from paddle_tpu.jit import DecodeStep, PrefillStep

    pre = PrefillStep(model)
    dec = DecodeStep(model)
    prev = os.environ.get("PADDLE_SERVE_BLOCK_SIZE")
    os.environ["PADDLE_SERVE_BLOCK_SIZE"] = str(block)
    try:
        # warm the SAME step objects the timed call uses (the round-10
        # pattern): the timed interval prices decode, not trace+compile
        _ = generate(model, prompts, 2, max_length=cap, prefill=pre,
                     decode=dec)
        t0 = time.perf_counter()
        toks = generate(model, prompts, new_tokens, max_length=cap,
                        prefill=pre, decode=dec)
        assert toks.shape == (B, new_tokens)
        dt = time.perf_counter() - t0
        out["serve_gpt_medium_tokens_per_sec_b8_paged"] = round(
            B * new_tokens / dt, 1)
    finally:
        if prev is None:
            os.environ.pop("PADDLE_SERVE_BLOCK_SIZE", None)
        else:
            os.environ["PADDLE_SERVE_BLOCK_SIZE"] = prev

    # TTFT under load with chunked prefill: slots stay busy decoding
    # while each new prompt prefills chunk-by-chunk — submit->first-
    # token is what the router's SLO admission bounds
    # pool sized by ACTUAL demand (prompt + 16 new tokens per slot),
    # not capacity — the paged-vs-worstcase byte pair below is the
    # point of the layout
    demand = 4 * paged_kv.blocks_for(prompt_len + 16, block) + 1
    engine = InferenceEngine(model, slots=4, max_length=cap,
                             block_size=block, prefill_chunk=chunk,
                             pool_blocks=demand)
    for i in range(8):
        p = (np.arange(prompt_len) % 31000).astype(np.int32)
        engine.submit(Request(p, max_new_tokens=16, rid=i))
    res = engine.run()
    ttfts = sorted(r.ttft_ms for r in res.values())
    out["serve_gpt_medium_ttft_ms"] = round(ttfts[len(ttfts) // 2], 2)
    # KV HBM: what the paged pool holds vs the contiguous worst case
    # for the same slot count (static shape arithmetic)
    out["serve_kv_hbm_paged_bytes"] = paged_kv.pool_bytes(
        engine._state.caches)
    dh = model.d_model // 16
    itemsize = 1 if os.environ.get("PADDLE_SERVE_KV_QUANT") else 4
    out["serve_kv_hbm_worstcase_bytes"] = paged_kv.worst_case_bytes(
        4, 16, cap, dh, itemsize=itemsize, layers=24)
    return out


def _bench_serve_failover(n_requests=6, budget=48, rate=4000.0):
    """Serving-plane fault tolerance (ISSUE 15): host-kill → first
    post-failover token on a survivor (`serve_failover_recovery_ms`,
    lower-better under the continuity gate) and the tokens the recovery
    dropped (`serve_failover_tokens_lost` — ASSERTED 0: the resume path
    re-prefills prompt + emitted prefix, so greedy continuations are
    token-exact by construction; the forbidden alternative is request
    loss, which PERF.md round-15 prices).

    Runs the jax-free mailbox workers (the dryrun transport) so the
    number measures the CONTROL plane — detection latency (timeout +
    probation backoff) plus re-submission — not model compute; the
    re-prefill cost on a real engine is the round-10 prefill_ms at the
    request's bucket, priced separately in PERF.md."""
    import shutil
    import signal
    import subprocess
    import sys
    import tempfile

    from paddle_tpu.serving.router import FileHost, Router, sim_next_token

    repo = os.path.dirname(os.path.abspath(__file__))
    tmp = tempfile.mkdtemp(prefix="pdtpu_failover_bench_")
    base = os.path.join(tmp, "mail")
    obs = os.path.join(tmp, "obs")
    os.makedirs(obs, exist_ok=True)
    worker = os.path.join(repo, "paddle_tpu", "serving", "router.py")
    procs = []
    out = {}
    try:
        for rank in (0, 1):
            env = dict(os.environ, PADDLE_TRAINER_ID=str(rank),
                       PADDLE_OBS_DIR=obs)
            env.pop("PADDLE_FAULT_SPEC", None)
            env.pop("PADDLE_OBS_BUS_FILE", None)
            procs.append(subprocess.Popen(
                [sys.executable, worker, repo, base, str(rate), "0.005"],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL))
        hosts = [FileHost(os.path.join(base, f"host{r}"), r, obs_dir=obs)
                 for r in (0, 1)]
        # tight detection knobs: the bench prices the recovery path,
        # not the production-default patience
        router = Router(hosts, admit_queue=64, avg_new_tokens=budget,
                        host_timeout_ms=250, retry_backoff_ms=50,
                        retry_max=2)
        prompts = {}
        for i in range(n_requests):
            rid = f"fo{i}"
            prompts[rid] = [i + 1, i + 2, i + 3]
            router.submit({"rid": rid, "prompt_ids": prompts[rid],
                           "max_new_tokens": budget})
        deadline = time.time() + 60
        # let host 0 get mid-decode (progress on the bus) before the kill
        while time.time() < deadline:
            router.tick()
            if any(e.progress for e in router._tracked.values()
                   if e.host == 0):
                break
            time.sleep(0.005)
        t_kill = time.perf_counter()
        procs[0].send_signal(signal.SIGKILL)
        procs[0].wait()
        recovery_ms = None
        while time.time() < deadline and \
                len(router.completed) < n_requests:
            router.tick()
            if recovery_ms is None:
                resumed_live = any(
                    e.attempts > 1 and e.progress
                    for e in router._tracked.values())
                resumed_done = any(
                    r.get("resumed") for r in router.completed.values())
                if resumed_live or resumed_done:
                    recovery_ms = (time.perf_counter() - t_kill) * 1e3
            time.sleep(0.005)
        assert len(router.completed) == n_requests, (
            f"failover bench dropped requests: "
            f"{len(router.completed)}/{n_requests}")
        assert recovery_ms is not None
        lost = 0
        for rid, prompt in prompts.items():
            chain = list(prompt)
            expect = []
            for _ in range(budget):
                t = sim_next_token(chain)
                chain.append(t)
                expect.append(t)
            got = router.completed[rid]["tokens"]
            assert got == expect, (
                f"failover bench: {rid} not token-exact vs the "
                f"uninterrupted chain")
            lost += budget - len(got)
        assert lost == 0, f"failover bench lost {lost} tokens"
        out["serve_failover_recovery_ms"] = round(recovery_ms, 1)
        out["serve_failover_tokens_lost"] = lost
        out["serve_failover_requests_recovered"] = router.failovers
    finally:
        try:
            os.makedirs(base, exist_ok=True)
            open(os.path.join(base, "stop"), "w").close()
            for p in procs:
                try:
                    p.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    p.kill()
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    return out


def _bench_serve_failover_migrate(n_requests=6, budget=48, rate=4000.0):
    """KV block migration plane (ISSUE 17): drain-triggered recovery
    over the MIGRATE fast path — drain_host -> extract verb -> bundle
    blob -> CRC gate -> splice -> first post-migration token on the
    survivor. `serve_failover_recovery_ms_migrate` lands next to the
    round-15 re-prefill key under the continuity gate (the pair IS the
    PERF.md round-17 pricing: block-move vs re-prefill);
    `serve_migrate_bytes` / `serve_migrate_blocks` ride report-only.
    Token-exactness and zero-drop are asserted inside, like the
    re-prefill bench; at least one request must take the fast path
    (migrations >= 1) or the number would silently price the wrong
    ladder rung."""
    import shutil
    import subprocess
    import sys
    import tempfile

    from paddle_tpu.serving.router import FileHost, Router, sim_next_token

    repo = os.path.dirname(os.path.abspath(__file__))
    tmp = tempfile.mkdtemp(prefix="pdtpu_migrate_bench_")
    base = os.path.join(tmp, "mail")
    obs = os.path.join(tmp, "obs")
    os.makedirs(obs, exist_ok=True)
    worker = os.path.join(repo, "paddle_tpu", "serving", "router.py")
    procs = []
    out = {}
    try:
        for rank in (0, 1):
            env = dict(os.environ, PADDLE_TRAINER_ID=str(rank),
                       PADDLE_OBS_DIR=obs)
            env.pop("PADDLE_FAULT_SPEC", None)
            env.pop("PADDLE_OBS_BUS_FILE", None)
            procs.append(subprocess.Popen(
                [sys.executable, worker, repo, base, str(rate), "0.005"],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL))
        hosts = [FileHost(os.path.join(base, f"host{r}"), r, obs_dir=obs)
                 for r in (0, 1)]
        # drain_inplace_tokens small so mid-decode victims clear the
        # cost boundary and take the migrate path (the thing priced)
        router = Router(hosts, admit_queue=64, avg_new_tokens=budget,
                        host_timeout_ms=250, retry_backoff_ms=50,
                        retry_max=2, migrate_timeout_ms=2000,
                        drain_inplace_tokens=4)
        prompts = {}
        for i in range(n_requests):
            rid = f"mg{i}"
            prompts[rid] = [i + 1, i + 2, i + 3]
            router.submit({"rid": rid, "prompt_ids": prompts[rid],
                           "max_new_tokens": budget})
        deadline = time.time() + 60
        # the drained host must be mid-decode: the fast path moves KV
        # that exists, not an empty cache
        while time.time() < deadline:
            router.tick()
            if any(e.progress for e in router._tracked.values()
                   if e.host == 0):
                break
            time.sleep(0.005)
        t_drain = time.perf_counter()
        router.drain_host(0)
        assert router.migrations >= 1, (
            "migrate bench: drain took the re-prefill path "
            f"(migrate_failed={router.migrate_failed})")
        recovery_ms = None
        while time.time() < deadline and \
                len(router.completed) < n_requests:
            router.tick()
            if recovery_ms is None:
                resumed_live = any(
                    e.attempts > 1 and e.progress
                    for e in router._tracked.values())
                resumed_done = any(
                    r.get("resumed") for r in router.completed.values())
                if resumed_live or resumed_done:
                    recovery_ms = (time.perf_counter() - t_drain) * 1e3
            time.sleep(0.005)
        assert len(router.completed) == n_requests, (
            f"migrate bench dropped requests: "
            f"{len(router.completed)}/{n_requests}")
        assert recovery_ms is not None
        for rid, prompt in prompts.items():
            chain = list(prompt)
            expect = []
            for _ in range(budget):
                t = sim_next_token(chain)
                chain.append(t)
                expect.append(t)
            assert router.completed[rid]["tokens"] == expect, (
                f"migrate bench: {rid} not token-exact vs the "
                f"uninterrupted chain")
        out["serve_failover_recovery_ms_migrate"] = round(recovery_ms, 1)
        out["serve_migrate_blocks"] = router.migrate_blocks
        out["serve_migrate_bytes"] = router.migrate_bytes
    finally:
        try:
            os.makedirs(base, exist_ok=True)
            open(os.path.join(base, "stop"), "w").close()
            for p in procs:
                try:
                    p.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    p.kill()
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    return out


def _bench_ctl(waves=8, per_wave=6, budget=8, rate=4000.0):
    """Train-serve co-tenancy (ISSUE 16): what a serving burst sheds
    with the fleet controller OFF vs ON, plus the cost of one lend
    transition. Jax-free like the failover bench — one mailbox worker,
    a small admission bound (admit_queue=2), and bursts of `per_wave`
    submits per control window, so the OFF run rejects most of every
    wave while the ON run's controller sees the rejection rate, lends
    after `sustain_n` hot windows (the bench's lend callback registers
    4x capacity on the host — the stand-in for expand_slots absorbing
    the lent devices), and later waves admit in full.

    `serve_burst_shed_tokens_ctl_off/_on` are report-only (no gated
    suffix); `ctl_lend_ms` (begin->commit journal wall time) lands
    under the continuity gate's lower-is-better `_ms` rule."""
    import shutil
    import subprocess
    import sys
    import tempfile

    from paddle_tpu.distributed.fleet_controller import (
        CtlConfig, FleetController,
    )
    from paddle_tpu.observability.monitor import FleetMonitor
    from paddle_tpu.serving.router import FileHost, Router

    repo = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(repo, "paddle_tpu", "serving", "router.py")
    out = {}

    def _run(with_ctl: bool) -> dict:
        tmp = tempfile.mkdtemp(prefix="pdtpu_ctl_bench_")
        base = os.path.join(tmp, "mail")
        obs = os.path.join(tmp, "obs")
        os.makedirs(obs, exist_ok=True)
        env_prev = os.environ.get("PADDLE_OBS_DIR")
        os.environ["PADDLE_OBS_DIR"] = obs  # router_metrics -> monitor
        proc = None
        try:
            wenv = dict(os.environ, PADDLE_TRAINER_ID="0",
                        PADDLE_OBS_DIR=obs)
            wenv.pop("PADDLE_FAULT_SPEC", None)
            wenv.pop("PADDLE_OBS_BUS_FILE", None)
            proc = subprocess.Popen(
                [sys.executable, worker, repo, base, str(rate), "0.005"],
                env=wenv, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL)
            host = FileHost(os.path.join(base, "host0"), 0, obs_dir=obs)
            router = Router([host], admit_queue=2, avg_new_tokens=budget,
                            admit_ttft_ms=0)
            ctl = None
            if with_ctl:
                mon = FleetMonitor(obs, emit=False)
                ctl = FleetController(
                    obs, monitor=mon, donor_ranks=[7],
                    config=CtlConfig(pressure=0.25, release=0.01,
                                     sustain_n=2, cooldown_n=2,
                                     window_s=0.01),
                    lend=lambda ranks, s: router.register_capacity(0, 4),
                    reclaim=lambda ranks, s: router.register_capacity(0, 1),
                    emit=True)
            rid = 0
            for _ in range(waves):
                for _ in range(per_wave):
                    rid += 1
                    router.submit({"rid": f"b{rid}",
                                   "prompt_ids": [1, 2, 3],
                                   "max_new_tokens": budget})
                deadline = time.time() + 10
                while time.time() < deadline and router.inflight():
                    router.tick()
                    time.sleep(0.005)
                if ctl is not None:
                    mon.poll()
                    ctl.window()
            return {"shed": router.rejected * budget,
                    "admitted": router.admitted,
                    "lend_ms": (ctl.transitions[0]["dur_ms"]
                                if ctl is not None and ctl.transitions
                                else None)}
        finally:
            try:
                os.makedirs(base, exist_ok=True)
                open(os.path.join(base, "stop"), "w").close()
                if proc is not None:
                    try:
                        proc.wait(timeout=20)
                    except subprocess.TimeoutExpired:
                        proc.kill()
            finally:
                if env_prev is None:
                    os.environ.pop("PADDLE_OBS_DIR", None)
                else:
                    os.environ["PADDLE_OBS_DIR"] = env_prev
                shutil.rmtree(tmp, ignore_errors=True)

    off = _run(False)
    on = _run(True)
    assert on["lend_ms"] is not None, "ctl bench: controller never lent"
    assert on["shed"] < off["shed"], (
        f"ctl bench: lend did not reduce shed "
        f"(on {on['shed']} vs off {off['shed']})")
    out["serve_burst_shed_tokens_ctl_off"] = off["shed"]
    out["serve_burst_shed_tokens_ctl_on"] = on["shed"]
    out["ctl_lend_ms"] = round(on["lend_ms"], 1)
    return out


def _bench_ctl_live(steps=30, hot=12):
    """Live lend plane (ISSUE 20): the serving-capacity latency a live
    migration actually delivers. Runs one 2-rank launcher cycle over
    the jax-free ``tiny_rank`` live protocol (``PADDLE_CTL=live``),
    watches the journal for the ``ctl_lend`` commit, drops a probe
    request into the lent rank's mailbox THAT instant, and prices

    - ``ctl_live_lend_ms``: lend commit -> the probe request's done
      file (first served tokens). This is the number the whole phase
      ladder exists to minimize — weight delivery via ``.pdqparams``
      (the 4x-narrower int8 load from round 19) is its dominant term —
      and it lands under the continuity gate's lower-better ``_ms``
      rule;
    - ``ctl_live_reclaim_ms``: the reclaim ladder's begin->commit wall
      time from the journal (drain + leave + rejoin). Report-only: it
      scales with whatever queue depth drain happens to find, so
      gating it would flake.
    """
    import shutil
    import subprocess
    import sys
    import tempfile

    repo = os.path.dirname(os.path.abspath(__file__))
    tmp = tempfile.mkdtemp(prefix="pdtpu_ctl_live_")
    obs = os.path.join(tmp, "obs")
    serve = os.path.join(tmp, "serve")
    ckpt = os.path.join(tmp, "w.pdqparams")
    os.makedirs(obs)
    with open(ckpt, "wb") as f:
        f.write(b"\0" * 1_000_000)
    env = dict(os.environ)
    for k in ("PADDLE_FAULT_SPEC", "PADDLE_OBS_BUS_FILE"):
        env.pop(k, None)
    env.update({
        "PADDLE_OBS_DIR": obs, "PADDLE_CTL": "live",
        "PADDLE_RESHARD_MODE": "shrink", "PADDLE_MON_POLL": "0.05",
        "PADDLE_CTL_WINDOW_S": "0.15", "PADDLE_CTL_SUSTAIN_N": "2",
        "PADDLE_CTL_COOLDOWN_N": "2",
        "PADDLE_CTL_SERVE_CKPT": ckpt, "PADDLE_CTL_SERVE_DIR": serve,
        "TINY_MODE": "live", "TINY_TRAIN_STEPS": str(steps),
        "TINY_TRAIN_DT": "0.05", "TINY_SERVE_HOT": str(hot),
        "JAX_PLATFORMS": "cpu",
    })
    journal = os.path.join(obs, "telemetry.launcher.jsonl")
    done = os.path.join(serve, "host1", "outbox", "done_bench.json")
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node=2",
         os.path.join(repo, "tests", "helpers", "tiny_rank.py")],
        env=env, cwd=repo, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL)
    try:
        # watch for the lend commit, then stage the probe request
        t_commit = None
        deadline = time.time() + 60
        while time.time() < deadline and t_commit is None:
            if proc.poll() is not None:
                break
            if os.path.exists(journal):
                for line in open(journal):
                    try:
                        r = json.loads(line)
                    except ValueError:
                        continue
                    if r.get("kind") == "ctl_lend" and \
                            r["payload"].get("phase") == "commit":
                        t_commit = float(r["time"])
                        break
            time.sleep(0.002)
        assert t_commit is not None, "ctl live bench: lend never committed"
        inbox = os.path.join(serve, "host1", "inbox")
        os.makedirs(inbox, exist_ok=True)
        with open(os.path.join(inbox, "req_bench.json"), "w") as f:
            json.dump({"rid": "bench", "token_ids": [5, 7],
                       "max_new_tokens": 4}, f)
        while time.time() < deadline and not os.path.exists(done):
            if proc.poll() is not None:
                break
            time.sleep(0.002)
        assert os.path.exists(done), "ctl live bench: request never served"
        lend_ms = (os.stat(done).st_mtime - t_commit) * 1e3
        rc = proc.wait(timeout=60)
        assert rc == 0, f"ctl live bench: launcher rc {rc}"
        reclaim_ms = None
        for line in open(journal):
            r = json.loads(line)
            if r.get("kind") == "ctl_reclaim" and \
                    r["payload"].get("phase") == "commit" and \
                    not r["payload"].get("forced"):
                reclaim_ms = float(r["payload"].get("dur_ms") or 0.0)
                break
        assert reclaim_ms is not None, "ctl live bench: never reclaimed"
        return {"ctl_live_lend_ms": round(max(lend_ms, 0.0), 1),
                "ctl_live_reclaim_ms": round(reclaim_ms, 1)}
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        shutil.rmtree(tmp, ignore_errors=True)


def _bench_serve_multitenant(prompt_len=128, new_tokens=32, block=16):
    """Multi-tenant serving plane (ISSUE 18): the submit->first-token
    time of a borrower whose preamble is already PUBLISHED in the
    refcounted CoW prefix cache (`serve_gpt_medium_ttft_ms_prefix_warm`,
    lower-better gated — the shared-prefill saving the cache exists to
    buy; compare against the cold `serve_gpt_medium_ttft_ms` key), and
    the decode-tier throughput when every prefill burns on a DEDICATED
    prefill host and ships across as a KV bundle
    (`serve_gpt_medium_tokens_per_sec_b8_disagg`, gated — the decode
    tier's steady cadence with the prefill steal removed).
    `serve_prefix_hit_rate` and `serve_adapter_count` ride report-only
    (PERF.md round 18 prices both)."""
    import paddle_tpu as paddle
    from paddle_tpu.serving import (
        AdapterSet, InferenceEngine, Request, TransformerLM,
    )
    from paddle_tpu.serving.router import LocalHost, PrefillHost, Router

    paddle.seed(0)
    cap = prompt_len + new_tokens
    cap += (-cap) % block  # engine pools splice block-aligned
    model = TransformerLM(32000, d_model=1024, num_heads=16,
                          num_layers=24, max_position=cap)
    model.eval()
    # adapters attach BEFORE any engine: the compiled steps snapshot
    # the stacked buffers at construction
    adapters = AdapterSet(model, n_adapters=4, rank=8)
    adapters.load(1)
    adapters.load(2)
    out = {"serve_adapter_count": len(adapters.resident) - 1}
    prompt = (np.arange(prompt_len) % 31000).astype(np.int32)

    # -- warm-prefix TTFT: cold publishes, the borrower shares --------
    eng = InferenceEngine(model, slots=2, max_length=cap,
                          block_size=block, prefix_cache=True)
    eng.submit(Request(prompt, max_new_tokens=8, rid="cold"))
    eng.run()
    eng.submit(Request(prompt, max_new_tokens=8, rid="warm"))
    warm = eng.run()["warm"]
    out["serve_gpt_medium_ttft_ms_prefix_warm"] = round(warm.ttft_ms, 2)
    out["serve_prefix_hit_rate"] = round(eng._prefix_hits / 2.0, 3)

    # -- disaggregated decode-tier throughput: B=8 mixed-adapter ------
    B = 8
    decode = LocalHost(InferenceEngine(model, slots=B, max_length=cap,
                                       block_size=block))
    prefill = PrefillHost(InferenceEngine(model, slots=2,
                                          max_length=cap,
                                          block_size=block))
    router = Router([decode], prefill_hosts=[prefill],
                    admit_queue=2 * B, avg_new_tokens=new_tokens)
    t0 = time.perf_counter()
    for i in range(B):
        router.submit({"rid": f"d{i}", "prompt_ids": prompt.tolist(),
                       "max_new_tokens": new_tokens,
                       "adapter": i % 3})
    while len(router.completed) < B:
        router.tick()
        decode.pump()
    dt = time.perf_counter() - t0
    assert router.disagg_prefills == B, (
        f"disagg bench: {router.disagg_fallbacks} handoffs fell back "
        f"to colocated prefill")
    out["serve_gpt_medium_tokens_per_sec_b8_disagg"] = round(
        B * new_tokens / dt, 1)
    return out


def _bench_flash_attention(steps=500):
    """Long-context attention: the Pallas flash kernel vs XLA dense at
    S=2048 causal. The `steps` iterations run INSIDE one jitted lax.scan
    (each output chained into the next query), so a single dispatch
    measures device time — per-call dispatch over the tunneled chip is
    ~100ms RTT and identical-args repeats can be served from a cache,
    both of which corrupt host-side loops."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas import flash_attention

    B, H, S, D = 4, 12, 2048, 64
    # unseeded: operands must differ across bench invocations or a
    # persistent runtime cache could serve the whole timed execution
    q, k, v = [
        jax.device_put(jnp.asarray(
            np.random.rand(B, H, S, D).astype(np.float32) - 0.5
        ))
        for _ in range(3)
    ]

    def dense(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (D ** -0.5)
        pos = jnp.arange(S)
        s = jnp.where(pos[None, :] > pos[:, None], -1e30, s)
        return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)

    def looped(attn):
        @jax.jit
        def run(q, k, v):
            def body(qq, _):
                return attn(qq, k, v), None

            out, _ = jax.lax.scan(body, q, None, length=steps)
            return out

        return run

    flash_l = looped(
        lambda q, k, v: flash_attention(q, k, v, True, 256, 256, None,
                                        False)
    )
    dense_l = looped(dense)

    # the tunnel runtime serves identical (executable, args) repeats
    # from a cache: compile/warm on one input set, time on another; the
    # barrier is a tiny devget slice (block_until_ready no-ops on axon)
    q2 = jax.device_put(q + 1.0)
    _ = np.asarray(q2[0, 0, 0, :2])

    def ms(f):
        _ = np.asarray(f(q2, k, v)[0, 0, 0, :2])  # compile + real sync
        t0 = time.perf_counter()
        _ = np.asarray(f(q, k, v)[0, 0, 0, :2])
        return (time.perf_counter() - t0) / steps * 1e3

    out = {
        "flash_attn_s2048_pallas_ms": round(ms(flash_l), 2),
        "flash_attn_s2048_dense_ms": round(ms(dense_l), 2),
    }

    # long context: 32k causal fwd+bwd through the K/V-streaming kernel
    # (impossible for the dense path: the 32k x 32k score matrix alone is
    # 4GB; the old VMEM-resident kernel capped at 16k)
    q32, k32, v32 = [
        jax.device_put(jnp.asarray(
            np.random.rand(1, 1, 32768, 128).astype(np.float32) - 0.5))
        for _ in range(3)
    ]
    vg = jax.jit(jax.value_and_grad(
        lambda a, b, c: flash_attention(
            a, b, c, True, 512, 512, None, False).sum(),
        (0, 1, 2),
    ))
    val, _ = vg(q32 + 1.0, k32, v32)  # compile+warm on different values
    _ = np.asarray(val)
    t0 = time.perf_counter()
    val, grads = vg(q32, k32, v32)
    _ = np.asarray(val)
    out["flash_attn_s32k_fwdbwd_ms"] = round(
        (time.perf_counter() - t0) * 1e3, 1
    )
    return out


def main():
    from paddle_tpu import optimizer
    from paddle_tpu.vision.models import LeNet, resnet50

    extra = {}

    lenet_ips, bd, sp = _repeat(lambda: _bench_train(
        LeNet,
        lambda m: optimizer.Adam(
            learning_rate=1e-3, parameters=m.parameters()
        ),
        (1, 28, 28), 10, batch=256, steps=50, label="lenet",
    ))
    extra.update(bd)
    # r01-r04 continuity: this was the headline metric; it is tunnel-
    # per-program-overhead-bound (r02 663.6, r03 ~15-26k, r04 58196 —
    # ±2x jitter with tunnel load), so round 5 promotes the compute-bound
    # ResNet-50 bf16 number to `metric` instead (VERDICT r4 weak #8)
    extra["lenet_mnist_train_imgs_per_sec"] = round(lenet_ips, 1)
    extra["lenet_mnist_train_imgs_per_sec_spread"] = sp

    r50_ips, bd, sp = _repeat(lambda: _bench_train(
        lambda: resnet50(num_classes=1000),
        lambda m: optimizer.Momentum(
            learning_rate=0.1, momentum=0.9, parameters=m.parameters()
        ),
        (3, 224, 224), 1000, batch=256, steps=20, label="resnet50",
    ))
    extra.update(bd)
    extra["resnet50_synthetic_imgs_per_sec"] = round(r50_ips, 1)
    extra["resnet50_synthetic_imgs_per_sec_spread"] = sp

    r50_bf16_ips, bd, sp = _repeat(lambda: _bench_train(
        lambda: resnet50(num_classes=1000),
        lambda m: optimizer.Momentum(
            learning_rate=0.1, momentum=0.9, parameters=m.parameters()
        ),
        (3, 224, 224), 1000, batch=256, steps=20, label="resnet50_bf16",
        amp=True,
    ))
    extra.update(bd)
    extra["resnet50_bf16_imgs_per_sec"] = round(r50_bf16_ips, 1)
    extra["resnet50_bf16_imgs_per_sec_spread"] = sp

    bert_ips, bd, sp = _repeat(_bench_bert)
    extra.update(bd)
    extra["bert_base_bf16_samples_per_sec"] = round(bert_ips, 1)
    extra["bert_base_bf16_samples_per_sec_spread"] = sp

    # round 6: the default GPT path IS the overhauled decoder (flash
    # attention auto-routed, Pallas fused LN, blockwise vocab CE) — the
    # old PADDLE_BENCH_GPT_FLASH side channel is retired. The headline
    # pair's other half (forced dense attention + materialized-logits
    # CE, i.e. the PADDLE_FLASH_DEFAULT=0 / PADDLE_CE_CHUNK=0 escape
    # hatches) records under *_dense when PADDLE_BENCH_GPT_DENSE=1.
    gpt_tok, gpt_bd, sp = _repeat(
        lambda: (lambda d: (d["gpt_medium_bf16_tokens_per_sec"], d))(
            _bench_gpt())
    )
    extra.update(gpt_bd)
    extra["gpt_medium_bf16_tokens_per_sec_spread"] = sp

    # numerical-guard overhead pair (ISSUE 5): the default gpt numbers
    # above ran with the in-graph sentinel ON (PADDLE_GUARD_MODE=skip is
    # the default); re-record with the guard compiled out, restoring
    # whatever mode the operator exported afterwards. The pair feeds
    # tools/bench_continuity.py's guard_overhead gate (<2%).
    _guard_env_before = os.environ.get("PADDLE_GUARD_MODE")
    try:
        gpt_off_tok, off_bd, off_sp = _repeat(
            lambda: (lambda d: (d["gpt_medium_bf16_tokens_per_sec"], d))(
                _bench_gpt(guard="off"))
        )
    finally:
        if _guard_env_before is None:
            os.environ.pop("PADDLE_GUARD_MODE", None)
        else:
            os.environ["PADDLE_GUARD_MODE"] = _guard_env_before
    for k in ("step_ms", "tokens_per_sec", "compile_s"):
        extra[f"gpt_medium_bf16_{k}_noguard"] = \
            off_bd[f"gpt_medium_bf16_{k}"]
    extra["gpt_medium_bf16_tokens_per_sec_noguard_spread"] = off_sp
    if gpt_off_tok > 0:
        extra["guard_overhead_pct"] = round(
            max(0.0, (gpt_off_tok - gpt_tok) / gpt_off_tok) * 100.0, 2)

    if os.environ.get("PADDLE_BENCH_GPT_DENSE", "") not in ("", "0"):
        _, dense_d, dsp = _repeat(
            lambda: (lambda d: (d["gpt_medium_bf16_tokens_per_sec"], d))(
                _bench_gpt(dense=True))
        )
        for k in ("step_ms", "tokens_per_sec", "compile_s"):
            extra[f"gpt_medium_bf16_{k}_dense"] = \
                dense_d[f"gpt_medium_bf16_{k}"]
        extra["gpt_medium_bf16_tokens_per_sec_dense_spread"] = dsp
    import jax

    if len(jax.devices()) > 1 and len(jax.devices()) % 2 == 0:
        # multi-device pair (ISSUE 6): sharded-flash dp x mp2 vs the
        # PADDLE_FLASH_SHARD=0 dense fallback — the shard_map-seam win
        # lands under the bench_continuity >10% gate
        _, mc_d, mc_sp = _repeat(
            lambda: (lambda d: (
                d["gpt_medium_bf16_dp_mp_tokens_per_sec"], d))(
                _bench_gpt_multichip())
        )
        extra.update(mc_d)
        extra["gpt_medium_bf16_dp_mp_tokens_per_sec_spread"] = mc_sp
        _, mcd_d, mcd_sp = _repeat(
            lambda: (lambda d: (
                d["gpt_medium_bf16_dp_mp_dense_tokens_per_sec"], d))(
                _bench_gpt_multichip(shard_off=True))
        )
        extra.update(mcd_d)
        extra["gpt_medium_bf16_dp_mp_dense_tokens_per_sec_spread"] = mcd_sp

    if len(jax.devices()) >= 4 and len(jax.devices()) % 2 == 0:
        # quantized dcn-hop pair (ISSUE 10): int8 block-scaled grad
        # allreduce over the slow inter-node hop vs the f32 hop, both on
        # the hierarchical dcn x ici mesh with the explicit per-grad
        # reduction — the wire-width win lands under the >10% gate and
        # the priced comm bytes ride report-only
        _, q8_d, q8_sp = _repeat(
            lambda: (lambda d: (
                d["gpt_medium_bf16_dp_q8_tokens_per_sec"], d))(
                _bench_gpt_dp_q8(quant=True))
        )
        extra.update(q8_d)
        extra["gpt_medium_bf16_dp_q8_tokens_per_sec_spread"] = q8_sp
        _, q8o_d, q8o_sp = _repeat(
            lambda: (lambda d: (
                d["gpt_medium_bf16_dp_q8_off_tokens_per_sec"], d))(
                _bench_gpt_dp_q8(quant=False))
        )
        extra.update(q8o_d)
        extra["gpt_medium_bf16_dp_q8_off_tokens_per_sec_spread"] = q8o_sp

    # int8-moment pair (ISSUE 19): AdamW with quantized moment state vs
    # wide f32 moments, single-mesh — the dequant/requant overhead and
    # the resident-byte win land under the gate / report-only split
    _, q8m_d, q8m_sp = _repeat(
        lambda: (lambda d: (
            d["gpt_medium_bf16_q8m_tokens_per_sec"], d))(
            _bench_gpt_q8m(quant=True))
    )
    extra.update(q8m_d)
    extra["gpt_medium_bf16_q8m_tokens_per_sec_spread"] = q8m_sp
    _, q8mo_d, q8mo_sp = _repeat(
        lambda: (lambda d: (
            d["gpt_medium_bf16_q8m_off_tokens_per_sec"], d))(
            _bench_gpt_q8m(quant=False))
    )
    extra.update(q8mo_d)
    extra["gpt_medium_bf16_q8m_off_tokens_per_sec_spread"] = q8mo_sp

    if jax.default_backend() == "tpu":  # compiled pallas is TPU-only
        # single-shot by design: 500 iterations already run inside ONE
        # dispatched lax.scan, so the device time is self-averaged
        extra.update(_bench_flash_attention())

    # serving bench (ISSUE 9): decode tokens/sec at batch 1/8/64 +
    # batch-1 per-token p50/p99 and prefill cost over the compiled
    # PrefillStep/DecodeStep pair. Median-of-REPEATS like every other
    # metric; the throughput/latency keys land under the continuity
    # gate. PADDLE_BENCH_SERVE=0 skips (the decode sweep adds minutes
    # on a CPU smoke run).
    if os.environ.get("PADDLE_BENCH_SERVE", "1") not in ("0", "false"):
        serve_tok, serve_bd, serve_sp = _repeat(
            lambda: (lambda d: (
                d["serve_gpt_medium_tokens_per_sec_b8"], d))(
                _bench_decode())
        )
        extra.update(serve_bd)
        extra["serve_gpt_medium_tokens_per_sec_b8_spread"] = serve_sp
        # production tier (ISSUE 13): paged-KV throughput next to the
        # contiguous b8 key, TTFT under chunked prefill, and the KV
        # HBM byte pair (paged pool vs worst-case reservation) —
        # throughput/_ms keys gated, byte extras report-only
        pg_tok, pg_bd, pg_sp = _repeat(
            lambda: (lambda d: (
                d["serve_gpt_medium_tokens_per_sec_b8_paged"], d))(
                _bench_decode_paged())
        )
        extra.update(pg_bd)
        extra["serve_gpt_medium_tokens_per_sec_b8_paged_spread"] = pg_sp
        # fault-tolerant serving plane (ISSUE 15): host-kill -> first
        # post-failover token on a survivor, jax-free control-plane
        # workers; recovery_ms gated (lower-better), tokens_lost
        # asserted 0 inside the bench itself
        fo_ms, fo_bd, fo_sp = _repeat(
            lambda: (lambda d: (
                d["serve_failover_recovery_ms"], d))(
                _bench_serve_failover())
        )
        extra.update(fo_bd)
        extra["serve_failover_recovery_ms_spread"] = fo_sp
        # KV block migration plane (ISSUE 17): the recompute-free twin
        # of the key above — drain-triggered extract->blob->splice
        # recovery; gated next to the re-prefill number so the fast
        # path staying fast IS a continuity invariant. bytes/blocks
        # moved ride report-only
        mg_ms, mg_bd, mg_sp = _repeat(
            lambda: (lambda d: (
                d["serve_failover_recovery_ms_migrate"], d))(
                _bench_serve_failover_migrate())
        )
        extra.update(mg_bd)
        extra["serve_failover_recovery_ms_migrate_spread"] = mg_sp
        # train-serve co-tenancy (ISSUE 16): burst tokens shed with the
        # fleet controller off vs on (report-only pair) and the
        # begin->commit cost of the lend transition (gated _ms key)
        ctl_ms, ctl_bd, ctl_sp = _repeat(
            lambda: (lambda d: (d["ctl_lend_ms"], d))(_bench_ctl())
        )
        extra.update(ctl_bd)
        extra["ctl_lend_ms_spread"] = ctl_sp
        # live lend plane (ISSUE 20): lend-commit -> first served token
        # over a real launcher cycle (gated _ms key); the reclaim
        # ladder's wall time rides report-only (drain depth varies)
        cl_ms, cl_bd, cl_sp = _repeat(
            lambda: (lambda d: (d["ctl_live_lend_ms"], d))(
                _bench_ctl_live())
        )
        extra.update(cl_bd)
        extra["ctl_live_lend_ms_spread"] = cl_sp
        # multi-tenant serving plane (ISSUE 18): warm-prefix TTFT and
        # the disaggregated decode-tier throughput land under the gate
        # (_ms lower-better / per_sec higher-better); the prefix hit
        # rate and resident-adapter count ride report-only
        mt_ms, mt_bd, mt_sp = _repeat(
            lambda: (lambda d: (
                d["serve_gpt_medium_ttft_ms_prefix_warm"], d))(
                _bench_serve_multitenant())
        )
        extra.update(mt_bd)
        extra["serve_gpt_medium_ttft_ms_prefix_warm_spread"] = mt_sp
        # int8-checkpoint decode (ISSUE 19): weights load narrow from a
        # save_quantized checkpoint and the compiled decode streams
        # int8 bytes + scales from HBM — b1/b8 tokens/sec next to the
        # full-width serve keys, checkpoint load time gated, on-disk
        # byte accounting report-only
        qw_tok, qw_bd, qw_sp = _repeat(
            lambda: (lambda d: (
                d["serve_gpt_medium_tokens_per_sec_b8_q8w"], d))(
                _bench_decode_q8w())
        )
        extra.update(qw_bd)
        extra["serve_gpt_medium_tokens_per_sec_b8_q8w_spread"] = qw_sp
    # r04 measured the same model/optimizer at batch 64 with two-pass
    # f32-blacklisted batch norm: 41.78 ms / 64 imgs = 1531.7 imgs/sec
    extra["vs_r04_resnet50_bf16"] = round(r50_bf16_ips / 1531.7, 2)
    # recompile-ledger totals (ISSUE 8): jit cache misses this process
    # observed across every benched step object — compile-count drift is
    # reported (never gated) by tools/bench_continuity.py next to the
    # compile-time table
    from paddle_tpu.observability import ledger as _ledger

    extra["compile_count"] = _ledger.compile_count()
    extra["incomparable_to_prev"] = (
        f"r06 methodology change: every metric is now the MEDIAN of "
        f"{REPEATS} repeats with min/max spread recorded per metric "
        f"(*_spread keys); r01-r05 numbers were single-shot on a "
        f"tunnel-shared chip, so cross-round deltas within the recorded "
        f"spread are noise, not regressions. gpt_medium_bf16_* now "
        f"measures the overhauled decoder default (flash attention "
        f"auto-routed, Pallas fused LayerNorm, blockwise vocab CE — "
        f"tools/PERF.md GPT chapter); the r05-equivalent dense "
        f"configuration records under gpt_medium_bf16_*_dense with "
        f"PADDLE_BENCH_GPT_DENSE=1. Other model/optimizer/batch configs "
        f"are unchanged from r05."
    )
    extra["note"] = (
        "TrainStep hot path (fused fwd+bwd+opt, donated, device-staged "
        "inputs; devget barriers — block_until_ready no-ops on the axon "
        "tunnel). Round-5 ResNet work (tools/PERF.md): one-pass f32 BN "
        "stats applied in bf16 (scale+shift form, batch_norm off the amp "
        "black list) + batch 256; framework step now matches a "
        "hand-written pure-JAX step within 1.5% — the residual vs MXU "
        "peak is this chip's reduction/VPU throughput (per-op table in "
        "PERF.md). compile_s values are warm-cache (persistent XLA "
        "compilation cache, core/compile_cache.py)."
    )

    print(
        json.dumps(
            {
                "metric": "resnet50_bf16_train_imgs_per_sec",
                "value": round(r50_bf16_ips, 1),
                "unit": "imgs/sec",
                "vs_baseline": 1.0,
                "extra": extra,
            }
        )
    )


if __name__ == "__main__":
    main()
