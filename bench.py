"""Driver benchmark: prints ONE JSON line
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Current benchmark: LeNet-5 MNIST-shape training throughput on the real chip
(BASELINE.json config 1), using the jit-compiled train step (the framework's
intended hot path). vs_baseline is against BASELINE.json's published numbers
— the reference publishes none (BASELINE.md), so the recorded value IS the
baseline going forward; vs_baseline reports 1.0.

Upgraded across rounds toward ResNet-50/BERT throughput per BASELINE.json.
"""
import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    batch = 256
    model = LeNet()
    opt = optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())

    params = {k: v for k, v in model.state_dict().items()}
    x_np = np.random.rand(batch, 1, 28, 28).astype(np.float32)
    y_np = (np.arange(batch) % 10).astype(np.int32)

    # jit the whole train step over raw arrays: functional forward via the
    # layer with params swapped (the to_static hot path, built in stage 3 —
    # here inlined so the bench exists from round 1).
    from paddle_tpu.core import autograd as AG
    from paddle_tpu.core.tensor import Tensor

    param_list = list(model.named_parameters())

    def loss_fn(param_raws, xr, yr):
        with AG.trace_mode():
            for (name, p), raw in zip(param_list, param_raws):
                p._data = raw
            logits = model(Tensor._wrap(xr))
            loss = paddle.nn.functional.cross_entropy(
                logits, Tensor._wrap(yr)
            )
            return loss._data

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))

    raws = [p._data for _, p in param_list]
    x, y = jnp.asarray(x_np), jnp.asarray(y_np)

    # warmup/compile
    loss, grads = grad_fn(raws, x, y)
    jax.block_until_ready(loss)

    steps = 30
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, grads = grad_fn(raws, x, y)
        raws = jax.tree_util.tree_map(lambda p, g: p - 1e-3 * g, raws, grads)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    imgs_per_sec = steps * batch / dt
    print(
        json.dumps(
            {
                "metric": "lenet_mnist_train_imgs_per_sec",
                "value": round(imgs_per_sec, 1),
                "unit": "imgs/sec",
                "vs_baseline": 1.0,
            }
        )
    )


if __name__ == "__main__":
    main()
