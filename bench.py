"""Driver benchmark: prints ONE JSON line
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extra": {...}}.

Benchmarks the framework's REAL hot path — `paddle_tpu.jit.TrainStep`
(forward + loss + backward + framework optimizer fused into one donated XLA
program; the analog of the reference's generated `core.ops` bindings +
run_program op, pybind/op_function_generator.cc:488) — exactly the harness
`__graft_entry__.dryrun_multichip` drives on the virtual mesh.

Headline metric stays `lenet_mnist_train_imgs_per_sec` for cross-round
comparability (BENCH_r01–r03); `extra` carries the ResNet-50 synthetic
throughput (BASELINE.json config 2) and a per-model step-time breakdown.

Why rounds 1–3 read ~660–724 imgs/sec (~354 ms/step): the old bench
updated params with an EAGER `tree_map(p - lr*g)` outside jit — 8 separate
device-program launches per step, each paying the tunnel's host->device
round-trip latency, serialized against the grad program. TrainStep issues
ONE async program per step with donated buffers, so steps pipeline and the
tunnel latency amortizes away.

vs_baseline: BASELINE.json publishes no reference numbers (BASELINE.md), so
the recorded value IS the baseline (1.0); extra.vs_r02 carries the ratio
against round 2's 663.6 on the same metric.
"""
import json
import time

import numpy as np


def _bench_train(model_fn, opt_fn, x_shape, y_classes, batch, steps, label):
    """Time `steps` TrainStep calls (one donated XLA program each), async-
    dispatched, single block at the end. Returns (imgs/sec, breakdown)."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.jit import TrainStep

    paddle.seed(0)
    model = model_fn()
    opt = opt_fn(model)
    step = TrainStep(
        model, lambda out, y: nn.functional.cross_entropy(out, y), opt
    )

    # stage the batch in HBM once (DataLoader's double-buffer analog,
    # operators/reader/buffered_reader.cc) — the tunnel's host->device
    # bandwidth must not be inside the timed loop
    import jax.numpy as jnp

    x = jax.device_put(
        jnp.asarray(np.random.rand(batch, *x_shape).astype(np.float32))
    )
    y = jax.device_put(jnp.asarray((np.arange(batch) % y_classes).astype(np.int32)))
    jax.block_until_ready(x)

    t0 = time.perf_counter()
    loss = step(x, y)  # compile + first step
    jax.block_until_ready(loss._data)
    compile_s = time.perf_counter() - t0

    # steady state: async dispatch, one block at the end -> steps pipeline
    # optional device-trace artifact (DeviceTracer/GenProfile analog):
    # PADDLE_TPU_TRACE=<dir> captures an XPlane trace of the timed loop
    import os

    trace_dir = os.environ.get("PADDLE_TPU_TRACE")
    if trace_dir:
        from paddle_tpu import profiler as prof

        prof.start_profiler(trace_dir=os.path.join(trace_dir, label))
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(x, y)
    jax.block_until_ready(loss._data)
    dt = time.perf_counter() - t0
    if trace_dir:
        prof.stop_profiler()

    # one blocked step isolates device time from host dispatch overhead
    t0 = time.perf_counter()
    jax.block_until_ready(step(x, y)._data)
    blocked_ms = (time.perf_counter() - t0) * 1e3

    step_ms = dt / steps * 1e3
    return steps * batch / dt, {
        f"{label}_step_ms": round(step_ms, 2),
        f"{label}_blocked_step_ms": round(blocked_ms, 2),
        f"{label}_compile_s": round(compile_s, 1),
    }


def main():
    from paddle_tpu import optimizer
    from paddle_tpu.vision.models import LeNet, resnet50

    extra = {}

    lenet_ips, bd = _bench_train(
        LeNet,
        lambda m: optimizer.Adam(
            learning_rate=1e-3, parameters=m.parameters()
        ),
        (1, 28, 28), 10, batch=256, steps=50, label="lenet",
    )
    extra.update(bd)

    r50_ips, bd = _bench_train(
        lambda: resnet50(num_classes=1000),
        lambda m: optimizer.Momentum(
            learning_rate=0.1, momentum=0.9, parameters=m.parameters()
        ),
        (3, 224, 224), 1000, batch=64, steps=20, label="resnet50",
    )
    extra.update(bd)
    extra["resnet50_synthetic_imgs_per_sec"] = round(r50_ips, 1)
    extra["vs_r02"] = round(lenet_ips / 663.6, 1)
    extra["note"] = (
        "TrainStep hot path (fused fwd+bwd+opt, donated, device-staged "
        "inputs); r1-r3's ~354ms LeNet step was the eager per-param "
        "tree_map update: 8 device-program launches/step, each paying the "
        "tunnel round-trip, serialized against the grad program"
    )

    print(
        json.dumps(
            {
                "metric": "lenet_mnist_train_imgs_per_sec",
                "value": round(lenet_ips, 1),
                "unit": "imgs/sec",
                "vs_baseline": 1.0,
                "extra": extra,
            }
        )
    )


if __name__ == "__main__":
    main()
