#!/usr/bin/env python
"""Merge per-rank telemetry-bus streams (+ collective flight-recorder
dumps) into ONE chrome-trace JSON and a human summary table (ISSUE 8
tentpole e).

Input: an observability dir — what the elastic launcher provisions as
``PADDLE_OBS_DIR`` (next to the workerlogs, where
``PADDLE_COLL_DEBUG_DIR`` drops ``comm_dump.rank*.json``):

    telemetry.rank0.jsonl       per-rank unified bus streams
    telemetry.rank1.jsonl       (observability/bus.py schema)
    telemetry.launcher.jsonl    manager events (rank -1), when present
    comm_dump.rank*.json        flight-recorder dumps, when present

Output:

* ``--out trace.json`` — chrome://tracing / Perfetto-loadable JSON:
  one process per rank; ``step_metrics`` rows become counter tracks
  (loss, step_ms, tokens/sec), ``recompile`` rows duration slices of
  their compile seconds, flight-recorder records duration slices on a
  ``collectives`` track, everything else instant events.
* stdout — the summary table: per-rank step timing percentiles,
  throughput, guard trips, recompiles (+ seconds), an EXPOSED-COMM
  estimate (eager-collective wall time from the flight recorder over
  the covered window — a lower bound: in-graph collectives don't pass
  through the eager monitor), and the slowest-ranks ranking that
  pod-scale debugging starts from (MLPerf-on-pods, PAPERS.md).

Round 14: `incident` rows from the live fleet monitor render as
duration slices + INCIDENT summary lines; request-scoped `span` rows
(router_submit -> engine admission/prefill/decode-window/retire ->
decode_request) group per trace_id, and ``--trace <id>`` prints one
request's life with per-phase attribution.

Round 16: committed `ctl_lend`/`ctl_reclaim` journal rows from the
co-tenancy fleet controller render as duration slices on a
``controller`` track (begin->commit wall time), and the summary grows
a CONTROLLER line: lends/reclaims/aborts, journal recoveries, median
transition cost, and who holds the lent ranks at end of trace.

Stdlib-pure: loads the bus parser standalone, no jax import, safe on a
login node against a dir rsync'd off the pod.

Usage:
    python tools/timeline.py <obs_dir> [--out trace.json] [--json]
        [--trace TRACE_ID]
"""
from __future__ import annotations

import argparse
import glob
import importlib.util
import json
import os
import sys
from typing import Dict, List, Optional, Tuple


def _load_bus():
    """The bus module, standalone (no paddle_tpu package import — that
    would pull jax into a tool meant for login nodes)."""
    mod = sys.modules.get("paddle_tpu.observability.bus")
    if mod is not None:
        return mod
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(os.path.dirname(here), "paddle_tpu",
                        "observability", "bus.py")
    spec = importlib.util.spec_from_file_location("_pdtpu_obs_bus", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def read_flight_dumps(obs_dir: str) -> Dict[int, List[dict]]:
    """comm_dump.rank*.json records keyed by rank (comm_monitor
    flight-recorder format: op/seq/t_start/t_done/status)."""
    out: Dict[int, List[dict]] = {}
    for path in sorted(glob.glob(os.path.join(obs_dir,
                                              "comm_dump.rank*.json"))):
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, ValueError):
            continue
        rank = int(d.get("rank", -1))
        recs = [r for r in d.get("records", []) if isinstance(r, dict)]
        if recs:
            out[rank] = recs
    return out


# ---------------------------------------------------------------------------
# chrome trace
# ---------------------------------------------------------------------------

#: counter tracks extracted from step_metrics payloads
_COUNTERS = ("loss", "step_ms", "tokens_per_sec", "examples_per_sec",
             "gnorm")

#: counter tracks extracted from decode_metrics payloads (ISSUE 13)
_DECODE_COUNTERS = ("tokens_per_sec", "queue_depth", "inflight_slots",
                    "ttft_ms", "blocks_in_use", "block_occupancy",
                    "prefix_hits", "prefix_blocks_shared", "cow_copies",
                    "adapters_resident")


def chrome_trace(streams: Dict[int, List[dict]],
                 dumps: Dict[int, List[dict]]) -> dict:
    """Merge bus streams + flight-recorder dumps into a chrome-trace
    dict ({"traceEvents": [...]}, ts in microseconds, one pid per
    rank)."""
    events: List[dict] = []
    t0 = None
    for rows in streams.values():
        for r in rows:
            t = r.get("time")
            if isinstance(t, (int, float)):
                t0 = t if t0 is None else min(t0, t)
    for recs in dumps.values():
        for r in recs:
            t = r.get("t_start")
            if isinstance(t, (int, float)):
                t0 = t if t0 is None else min(t0, t)
    if t0 is None:
        t0 = 0.0

    def us(t) -> float:
        return max((float(t) - t0) * 1e6, 0.0)

    for rank, rows in sorted(streams.items()):
        pname = "launcher" if rank < 0 else f"rank {rank}"
        events.append({"ph": "M", "name": "process_name", "pid": rank,
                       "args": {"name": pname}})
        for r in rows:
            kind = r.get("kind", "?")
            t = r.get("time", t0)
            payload = r.get("payload") or {}
            if kind == "step_metrics":
                args = {k: payload[k] for k in _COUNTERS if k in payload}
                if args:
                    events.append({"ph": "C", "name": "step_metrics",
                                   "pid": rank, "ts": us(t),
                                   "args": args})
                continue
            if kind == "decode_metrics":
                # serving readback-window gauges (ISSUE 13): decode
                # throughput, engine queue/inflight, TTFT, paged
                # block-pool occupancy — one counter track per rank
                args = {k: payload[k] for k in _DECODE_COUNTERS
                        if isinstance(payload.get(k), (int, float))}
                if args:
                    events.append({"ph": "C", "name": "decode_metrics",
                                   "pid": rank, "ts": us(t),
                                   "args": args})
                continue
            if kind == "router_metrics":
                # the router's per-host queue depths as ONE counter
                # track: the load-balance picture at a glance (a slow
                # host's line climbs while the others stay flat)
                args = {k: payload[k] for k in sorted(payload)
                        if "queue_depth" in k
                        and isinstance(payload[k], (int, float))}
                if args:
                    events.append({
                        "ph": "C", "name": "router_queue_depth",
                        "pid": rank, "ts": us(t), "args": args})
                continue
            if kind == "recompile":
                dur = float(payload.get("compile_wall_s", 0.0)) * 1e6
                events.append({
                    "ph": "X", "name": f"compile:{payload.get('label')}",
                    "pid": rank, "tid": "compiles",
                    "ts": max(us(t) - dur, 0.0), "dur": dur,
                    "args": {"ordinal": payload.get("ordinal"),
                             "changed": payload.get("changed")},
                })
                continue
            if kind == "incident":
                # fleet-monitor correlation (ISSUE 14): one slice per
                # incident spanning its first..last correlated event
                ts0, ts1 = payload.get("t_start"), payload.get("t_end")
                if isinstance(ts0, (int, float)) and \
                        isinstance(ts1, (int, float)):
                    events.append({
                        "ph": "X", "name": f"incident#{payload.get('id')}",
                        "pid": rank, "tid": "incidents",
                        "ts": us(ts0),
                        "dur": max((ts1 - ts0) * 1e6, 1.0),
                        "args": {"chain": payload.get("chain"),
                                 "ranks": str(payload.get("ranks")),
                                 "count": payload.get("count")},
                    })
                    continue
            if kind == "span":
                # request-scoped tracing (ISSUE 14): group each traced
                # request's phases on its own track so one request's
                # life reads as a lane in the trace viewer; a
                # decode_window row names EVERY traced inflight
                # request, so it marks every named lane
                lanes = ([payload["trace_id"]]
                         if payload.get("trace_id") is not None
                         else list(payload.get("trace_ids") or [None]))
                # round 15: failover/drain spans carry dur_ms — the
                # request's life on the abandoned host — and render as
                # DURATION slices ending at the span's emit time, so a
                # recovered request's two-host life reads as
                # slice(host A) → resubmit spans(host B) on ONE lane
                dur_ms = payload.get("dur_ms")
                args = {k: v for k, v in payload.items()
                        if isinstance(v, (str, int, float, bool))}
                for tid_lane in lanes:
                    if isinstance(dur_ms, (int, float)):
                        dur = float(dur_ms) * 1e3
                        events.append({
                            "ph": "X",
                            "name": str(payload.get("name", "span")),
                            "pid": rank, "tid": f"trace {tid_lane}",
                            "ts": max(us(t) - dur, 0.0),
                            "dur": max(dur, 1.0),
                            "args": args,
                        })
                        continue
                    events.append({
                        "ph": "i",
                        "name": str(payload.get("name", "span")),
                        "pid": rank, "tid": f"trace {tid_lane}",
                        "ts": us(t), "s": "t",
                        "args": args,
                    })
                continue
            if kind == "decode_request" and payload.get("trace_id"):
                # the terminal span: a slice covering the request's
                # whole latency, ending at the retire row
                dur = float(payload.get("latency_ms", 0.0)) * 1e3
                events.append({
                    "ph": "X", "name": f"request {payload.get('rid')}",
                    "pid": rank, "tid": f"trace {payload['trace_id']}",
                    "ts": max(us(t) - dur, 0.0), "dur": dur,
                    "args": {k: payload.get(k) for k in
                             ("rid", "tokens", "latency_ms",
                              "prefill_ms", "ttft_ms", "ms_per_token")},
                })
                continue
            if kind in ("ctl_lend", "ctl_reclaim") and \
                    payload.get("phase") == "commit":
                # co-tenancy transitions (ISSUE 16): one slice per
                # committed lend/reclaim, begin->commit wall time from
                # the journal's dur_ms, ending at the commit row; begin
                # and abort rows fall through as instants on the same
                # lane, so an aborted transition reads as begin with no
                # slice
                dur = float(payload.get("dur_ms") or 0.0) * 1e3
                events.append({
                    "ph": "X",
                    "name": f"{kind}:{payload.get('ranks')}",
                    "pid": rank, "tid": "controller",
                    "ts": max(us(t) - dur, 0.0), "dur": max(dur, 1.0),
                    "args": {k: payload.get(k) for k in
                             ("seq", "ranks", "pressure", "lent",
                              "dur_ms", "recovered")},
                })
                continue
            if kind == "ctl_phase" and payload.get("phase") == "commit":
                # live lend phase ladder (ISSUE 20): one slice per
                # committed phase on the controller lane, nested inside
                # the enclosing lend/reclaim slice, so a migration
                # reads depart -> deliver -> join (or drain -> leave ->
                # rejoin) with each stage's wall time; a crashed phase
                # leaves only its begin instant — the visible scar
                dur = float(payload.get("dur_ms") or 0.0) * 1e3
                events.append({
                    "ph": "X",
                    "name": f"{payload.get('verb')}:{payload.get('stage')}",
                    "pid": rank, "tid": "controller",
                    "ts": max(us(t) - dur, 0.0), "dur": max(dur, 1.0),
                    "args": {k: payload.get(k) for k in
                             ("seq", "verb", "stage", "ranks",
                              "dur_ms")},
                })
                continue
            if kind == "reshard":
                # elastic mesh reshard (ISSUE 11): wall_s covers drain +
                # device-to-device moves (+ fallback reload when taken)
                dur = float(payload.get("wall_s", 0.0)) * 1e6
                events.append({
                    "ph": "X",
                    "name": (f"reshard:{payload.get('old')}->"
                             f"{payload.get('new')}"),
                    "pid": rank, "tid": "reshard",
                    "ts": max(us(t) - dur, 0.0), "dur": dur,
                    "args": {k: payload.get(k) for k in
                             ("trigger", "lost", "covered", "fallback",
                              "bytes_moved")},
                })
                continue
            events.append({
                "ph": "i", "name": kind, "pid": rank, "tid": kind.split(
                    "_")[0], "ts": us(t), "s": "p",
                "args": {"step": r.get("step"), **{
                    k: v for k, v in payload.items()
                    if isinstance(v, (str, int, float, bool))
                }},
            })
    for rank, recs in sorted(dumps.items()):
        if rank not in streams:
            events.append({"ph": "M", "name": "process_name",
                           "pid": rank, "args": {"name": f"rank {rank}"}})
        for rec in recs:
            ts, td = rec.get("t_start"), rec.get("t_done")
            if not isinstance(ts, (int, float)):
                continue
            dur = ((td - ts) if isinstance(td, (int, float)) else 0.0) * 1e6
            events.append({
                "ph": "X", "name": rec.get("op", "?"), "pid": rank,
                "tid": "collectives", "ts": us(ts), "dur": max(dur, 0.0),
                "args": {k: rec.get(k) for k in
                         ("seq", "group", "shape", "dtype", "status",
                          "site")},
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# human summary
# ---------------------------------------------------------------------------


def _median(vals: List[float]) -> Optional[float]:
    if not vals:
        return None
    sv = sorted(vals)
    return sv[len(sv) // 2]


def _rank_stats(rows: List[dict], coll: List[dict]) -> dict:
    metrics = [r["payload"] for r in rows if r.get("kind") == "step_metrics"]
    step_ms = [m["step_ms"] for m in metrics
               if isinstance(m.get("step_ms"), (int, float))]
    toks = [m["tokens_per_sec"] for m in metrics
            if isinstance(m.get("tokens_per_sec"), (int, float))]
    steps = [r.get("step") for r in rows if isinstance(r.get("step"), int)]
    recompiles = [r["payload"] for r in rows if r.get("kind") == "recompile"]
    storms = [r for r in rows if r.get("kind") == "recompile_storm"]
    guard = [r for r in rows if str(r.get("kind", "")).startswith("guard_")]
    # grad-comm width accounting (ISSUE 10): the static `grad_comm`
    # record (or its copy riding step_metrics rows) names the grad
    # reduction's wire dtype and bytes — the quantized payload + scale
    # bytes the exposed-comm estimate below is pricing
    grad_comm = None
    for r in rows:
        if r.get("kind") == "grad_comm" and isinstance(
                r.get("payload"), dict):
            grad_comm = r["payload"]
    if grad_comm is None:
        for m in metrics:
            if isinstance(m.get("grad_comm"), dict):
                grad_comm = m["grad_comm"]
    reshards = [r["payload"] for r in rows if r.get("kind") == "reshard"
                and isinstance(r.get("payload"), dict)]
    coll_s = 0.0
    coll_n = 0
    window: Tuple[Optional[float], Optional[float]] = (None, None)
    for rec in coll:
        ts, td = rec.get("t_start"), rec.get("t_done")
        if isinstance(ts, (int, float)) and isinstance(td, (int, float)):
            coll_s += max(td - ts, 0.0)
            coll_n += 1
            lo, hi = window
            window = (ts if lo is None else min(lo, ts),
                      td if hi is None else max(hi, td))
    times = [r.get("time") for r in rows
             if isinstance(r.get("time"), (int, float))]
    lo, hi = window
    for t in times:
        lo = t if lo is None else min(lo, t)
        hi = t if hi is None else max(hi, t)
    span = (hi - lo) if (lo is not None and hi is not None) else 0.0
    return {
        "events": len(rows),
        "last_step": max(steps) if steps else None,
        "median_step_ms": _median(step_ms),
        "tokens_per_sec": _median(toks),
        "guard_trips": len(guard),
        "recompiles": len(recompiles),
        "compile_s": round(sum(
            float(p.get("compile_wall_s", 0.0)) for p in recompiles), 2),
        "storms": [r["payload"].get("detail", "") for r in storms],
        "coll_n": coll_n,
        "coll_s": round(coll_s, 3),
        "exposed_comm_pct": (round(coll_s / span * 100.0, 1)
                             if span > 0 and coll_s else None),
        "grad_comm": grad_comm,
        "reshards": reshards,
    }


def summarize(streams: Dict[int, List[dict]],
              dumps: Dict[int, List[dict]]) -> List[str]:
    lines: List[str] = []
    ranks = sorted(r for r in set(streams) | set(dumps) if r >= 0)
    if not ranks and -1 not in streams:
        return ["timeline: no telemetry streams found"]
    stats = {r: _rank_stats(streams.get(r, []), dumps.get(r, []))
             for r in ranks}
    lines.append(
        f"{'rank':>4}  {'steps':>6}  {'med step_ms':>11}  "
        f"{'tok/s':>9}  {'guard':>5}  {'recompiles':>10}  "
        f"{'compile_s':>9}  {'coll_s':>7}  {'exposed%':>8}")
    for r in ranks:
        s = stats[r]
        fmt = lambda v, nd=2: ("-" if v is None else
                               f"{v:.{nd}f}" if isinstance(v, float) else
                               str(v))
        lines.append(
            f"{r:>4}  {fmt(s['last_step']):>6}  "
            f"{fmt(s['median_step_ms']):>11}  "
            f"{fmt(s['tokens_per_sec'], 0):>9}  {s['guard_trips']:>5}  "
            f"{s['recompiles']:>10}  {fmt(s['compile_s']):>9}  "
            f"{fmt(s['coll_s'], 3):>7}  "
            f"{fmt(s['exposed_comm_pct'], 1):>8}")
    # grad-comm width lines (deduped: every rank of one job runs the
    # same program, so one line per distinct policy)
    seen_comm = []
    for r in ranks:
        gc = stats[r].get("grad_comm")
        if not gc or gc in seen_comm:
            continue
        seen_comm.append(gc)
        wire = gc.get("bytes_on_wire", 0) / 1e6
        f32 = gc.get("bytes_f32", 0) / 1e6
        lines.append(
            f"grad comm: dtype={gc.get('dtype')} "
            f"wire {wire:.1f} MB/step (f32 {f32:.1f} MB, "
            f"{gc.get('reduction_x', 1.0)}x)"
            + (f" block={gc['block']}" if gc.get("block") else ""))
    # elastic reshard slices (ISSUE 11): one line per event — the
    # shrink/expand trajectory and what each transition cost
    for r in ranks:
        for rs in stats[r].get("reshards", []):
            lines.append(
                f"reshard rank {r}: {rs.get('old')} -> {rs.get('new')} "
                f"({rs.get('trigger')}, "
                f"{'fallback' if rs.get('fallback') else 'device-to-device'}"
                f", {float(rs.get('bytes_moved', 0)) / 1e6:.1f} MB, "
                f"{float(rs.get('wall_s', 0.0)):.2f}s)")
    timed = [(s["median_step_ms"], r) for r, s in stats.items()
             if s["median_step_ms"] is not None]
    if len(timed) > 1:
        timed.sort(reverse=True)
        worst = ", ".join(f"rank {r} ({ms:.2f}ms)" for ms, r in timed[:3])
        lines.append(f"slowest ranks: {worst}")
    for r in ranks:
        for detail in stats[r]["storms"]:
            lines.append(f"RECOMPILE STORM rank {r}: {detail}")
    trips = sum(s["guard_trips"] for s in stats.values())
    if trips:
        lines.append(f"guard events: {trips} across "
                     f"{sum(1 for s in stats.values() if s['guard_trips'])}"
                     f" rank(s) — see guard_* rows / replay bundles")
    # fleet-monitor incidents + traced requests (ISSUE 14)
    incidents = []
    traces = set()
    for rows in streams.values():
        for r in rows:
            p = r.get("payload")
            if not isinstance(p, dict):
                continue
            k = r.get("kind")
            if k == "incident":
                incidents.append(p)
            elif k in ("span", "decode_request", "router_admit"):
                if p.get("trace_id"):
                    traces.add(p["trace_id"])
                for t in (p.get("trace_ids") or []):
                    traces.add(t)
    if traces:
        lines.append(f"traced requests: {len(traces)} "
                     f"(--trace <id> renders one request's spans)")
    # serving fault tolerance (ISSUE 15): host deaths, failovers, and
    # drains as one line each — the recovery story at a glance
    for rows in streams.values():
        for r in rows:
            p = r.get("payload")
            if not isinstance(p, dict):
                continue
            k = r.get("kind")
            if k == "router_host_dead":
                lines.append(
                    f"HOST DEAD: host {p.get('host')} "
                    f"(worker rank {p.get('host_rank')}) — "
                    f"{p.get('reason')}, {p.get('inflight')} in-flight "
                    f"request(s) to recover")
            elif k == "router_failover":
                lines.append(
                    f"failover: host {p.get('host')} -> survivors, "
                    f"{p.get('requests')} request(s) resumed"
                    + (f", {p.get('orphaned')} orphaned"
                       if p.get("orphaned") else ""))
            elif k == "router_drain":
                lines.append(
                    f"drain: host {p.get('host')} "
                    f"(worker rank {p.get('host_rank')}) — "
                    f"{p.get('migrated')} migrated, "
                    f"{p.get('in_place')} finished in place")
    # KV block migration (ISSUE 17): recompute-free recoveries vs the
    # fallback ladder — moves, blocks/bytes on the wire, and why any
    # rung broke (the kv_migrate span itself renders as a begin->commit
    # duration slice on the request's trace lane, like every dur_ms
    # span)
    mig_n, mig_blocks, mig_bytes = 0, 0, 0
    mig_ms: List[float] = []
    mig_fail: Dict[str, int] = {}
    for rows in streams.values():
        for r in rows:
            p = r.get("payload")
            if not isinstance(p, dict):
                continue
            k = r.get("kind")
            if k == "span" and p.get("name") == "kv_migrate":
                mig_n += 1
                mig_blocks += int(p.get("blocks") or 0)
                mig_bytes += int(p.get("bytes") or 0)
                if isinstance(p.get("dur_ms"), (int, float)):
                    mig_ms.append(float(p["dur_ms"]))
            elif k == "kv_migrate_fail":
                why = str(p.get("reason") or "?")
                if why == "crc" and p.get("block") is not None:
                    why = f"crc block {p.get('block')}"
                mig_fail[why] = mig_fail.get(why, 0) + 1
    if mig_n or mig_fail:
        med = _median(mig_ms)
        line = (f"kv migration: {mig_n} request(s) moved, "
                f"{mig_blocks} block(s), "
                f"{mig_bytes / 1e6:.2f} MB")
        if med is not None:
            line += f", median {med:.1f} ms"
        if mig_fail:
            why = ", ".join(f"{n}x {w}" for w, n in
                            sorted(mig_fail.items()))
            line += f"; fell back to re-prefill: {why}"
        lines.append(line)
    # multi-tenant serving (ISSUE 18): the prefix-cache counters ride
    # the decode_metrics cadence as CUMULATIVE host ints — the last row
    # per stream is the story; requests completed give the hit rate's
    # denominator. Adapter residency renders per host.
    px_hits, px_blocks, px_cow, px_reqs = 0, 0, 0, 0
    adapters: Dict[int, int] = {}
    disagg_n = 0
    for rank, rows in streams.items():
        last_px = None
        for r in rows:
            p = r.get("payload")
            if not isinstance(p, dict):
                continue
            k = r.get("kind")
            if k == "decode_metrics":
                if "prefix_hits" in p:
                    last_px = p
                if "adapters_resident" in p:
                    adapters[rank] = int(p["adapters_resident"])
            elif k == "decode_request":
                px_reqs += 1
            elif k == "span" and p.get("name") == "disagg_prefill":
                disagg_n += 1
        if last_px is not None:
            px_hits += int(last_px.get("prefix_hits") or 0)
            px_blocks += int(last_px.get("prefix_blocks_shared") or 0)
            px_cow += int(last_px.get("cow_copies") or 0)
    if px_hits or px_blocks:
        line = f"prefix cache: {px_hits} hit(s)"
        if px_reqs:
            line += f" ({px_hits / px_reqs * 100.0:.0f}% of " \
                    f"{px_reqs} request(s))"
        line += f", {px_blocks} block prefill(s) saved, " \
                f"{px_cow} CoW cop(ies)"
        lines.append(line)
    if disagg_n:
        lines.append(f"disaggregated prefill: {disagg_n} handoff(s) "
                     f"to the decode tier")
    if adapters:
        lines.append("adapters resident: " + ", ".join(
            f"rank {r}={n}" for r, n in sorted(adapters.items())))
    # co-tenancy controller (ISSUE 16): the lend/reclaim trajectory —
    # committed transitions, aborts, recoveries, and what each cost
    ctl = {"lend": 0, "reclaim": 0, "abort": 0, "recover": 0}
    ctl_ms: List[float] = []
    ctl_last_lent = None
    # live phase ladder (ISSUE 20): per-stage medians so the summary
    # prices WHERE a migration spends its wall time
    phase_ms: Dict[str, List[float]] = {}
    for rows in streams.values():
        for r in rows:
            p = r.get("payload")
            if not isinstance(p, dict):
                continue
            k = r.get("kind")
            if k in ("ctl_lend", "ctl_reclaim") and \
                    p.get("phase") == "commit":
                ctl["lend" if k == "ctl_lend" else "reclaim"] += 1
                if isinstance(p.get("dur_ms"), (int, float)):
                    ctl_ms.append(float(p["dur_ms"]))
                ctl_last_lent = p.get("lent", ctl_last_lent)
            elif k == "ctl_phase" and p.get("phase") == "commit" and \
                    isinstance(p.get("dur_ms"), (int, float)):
                phase_ms.setdefault(str(p.get("stage")), []).append(
                    float(p["dur_ms"]))
            elif k == "ctl_abort":
                ctl["abort"] += 1
            elif k == "ctl_recover":
                ctl["recover"] += 1
                ctl_last_lent = p.get("lent", ctl_last_lent)
    if any(ctl.values()):
        med = _median(ctl_ms)
        lines.append(
            f"CONTROLLER: {ctl['lend']} lend(s), "
            f"{ctl['reclaim']} reclaim(s), {ctl['abort']} abort(s)"
            + (f", {ctl['recover']} journal recovery(ies)"
               if ctl["recover"] else "")
            + (f", median transition {med:.1f}ms" if med is not None
               else "")
            + (f" — lent now {ctl_last_lent}"
               if ctl_last_lent else " — full mesh restored"))
    if phase_ms:
        order = ("depart", "deliver", "join", "drain", "leave",
                 "rejoin")
        parts = [f"{s} {_median(phase_ms[s]):.1f}ms"
                 for s in order if s in phase_ms]
        parts += [f"{s} {_median(v):.1f}ms"
                  for s, v in sorted(phase_ms.items())
                  if s not in order]
        lines.append("  phase ladder (median): " + ", ".join(parts))
    for p in incidents:
        lines.append(f"INCIDENT #{p.get('id')} ranks {p.get('ranks')}: "
                     f"{p.get('chain')}")
    launcher = streams.get(-1, [])
    if launcher:
        kinds = {}
        for r in launcher:
            kinds[r.get("kind", "?")] = kinds.get(r.get("kind", "?"), 0) + 1
        lines.append("launcher: " + ", ".join(
            f"{k} x{n}" for k, n in sorted(kinds.items())))
    return lines


def trace_spans(streams: Dict[int, List[dict]],
                trace_id: str) -> List[dict]:
    """Every row carrying ``trace_id`` — router_submit span, engine
    admission/prefill/decode-window/retire spans, the decode_request
    terminal — merged across rank streams and time-ordered: one
    request's life (ISSUE 14)."""
    out: List[dict] = []
    for rank, rows in streams.items():
        for r in rows:
            p = r.get("payload")
            if not isinstance(p, dict):
                continue
            k = r.get("kind")
            if p.get("trace_id") == trace_id or \
                    trace_id in (p.get("trace_ids") or []):
                out.append({
                    "time": r.get("time", 0.0),
                    "rank": rank,
                    "name": (p.get("name", "span") if k == "span"
                             else k),
                    "detail": {kk: vv for kk, vv in p.items()
                               if kk not in ("trace_id", "trace_ids",
                                             "name")
                               and isinstance(vv, (str, int, float,
                                                   bool))},
                })
    out.sort(key=lambda e: e["time"])
    return out


def format_trace(spans: List[dict], trace_id: str) -> List[str]:
    """Per-phase attribution for one request: +offset from the root
    span and the delta each phase added."""
    if not spans:
        return [f"trace {trace_id}: no spans found"]
    t0 = spans[0]["time"]
    lines = [f"trace {trace_id}: {len(spans)} span(s)"]
    prev = t0
    for s in spans:
        detail = " ".join(f"{k}={v}" for k, v in sorted(
            s["detail"].items()))
        lines.append(
            f"  +{(s['time'] - t0) * 1e3:9.3f}ms "
            f"(+{(s['time'] - prev) * 1e3:8.3f}ms)  "
            f"rank {s['rank']:>2}  {s['name']:<14} {detail}")
        prev = s["time"]
    return lines


def merge(obs_dir: str):
    """(streams, dumps, chrome_trace_dict, summary_lines) for a dir."""
    bus = _load_bus()
    streams = bus.rank_streams(obs_dir)
    dumps = read_flight_dumps(obs_dir)
    return streams, dumps, chrome_trace(streams, dumps), summarize(
        streams, dumps)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("obs_dir", help="PADDLE_OBS_DIR of the run")
    ap.add_argument("--out", default=None,
                    help="write chrome-trace JSON here")
    ap.add_argument("--json", action="store_true",
                    help="print the summary as JSON instead of a table")
    ap.add_argument("--trace", default=None, metavar="TRACE_ID",
                    help="print one traced request's spans with "
                         "per-phase attribution instead of the summary")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.obs_dir):
        print(f"timeline: {args.obs_dir} is not a directory",
              file=sys.stderr)
        return 2
    streams, dumps, trace, lines = merge(args.obs_dir)
    if not streams and not dumps:
        print(f"timeline: no telemetry.rank*.jsonl / comm_dump.rank*.json "
              f"in {args.obs_dir}", file=sys.stderr)
        return 1
    if args.out:
        with open(args.out, "w") as f:
            json.dump(trace, f)
        n = len(trace["traceEvents"])
        print(f"chrome trace: {args.out} ({n} events; load in "
              f"chrome://tracing or https://ui.perfetto.dev)")
    if args.trace:
        print("\n".join(format_trace(
            trace_spans(streams, args.trace), args.trace)))
    elif args.json:
        ranks = sorted(r for r in set(streams) | set(dumps) if r >= 0)
        print(json.dumps({
            str(r): _rank_stats(streams.get(r, []), dumps.get(r, []))
            for r in ranks}))
    else:
        print("\n".join(lines))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
