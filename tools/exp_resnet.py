"""ResNet-50 perf experiments (round-5 weak #1). Run one variant per process:
    python tools/exp_resnet.py <variant> [batch]
Variants: fw (framework bf16), purejax_nhwc, purejax_nchw.
Prints one line: <variant> batch=<B> step_ms=<ms> imgs_s=<n>.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def timeit(step, args, steps=20, barrier=lambda out: None):
    t0 = time.perf_counter()
    out = step(*args)
    barrier(out)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(steps):
        out = step(*args)
    barrier(out)
    dt = time.perf_counter() - t0
    return dt / steps * 1e3, compile_s


def fw(batch):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    strategy = DistributedStrategy()
    strategy.amp = True
    fleet.init(is_collective=True, strategy=strategy)
    model = resnet50(num_classes=1000)
    opt = fleet.distributed_optimizer(
        optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                           parameters=model.parameters())
    )
    step = TrainStep(
        model, lambda out, y: nn.functional.cross_entropy(out, y), opt
    )
    x = jax.device_put(jnp.asarray(
        np.random.rand(batch, 3, 224, 224).astype(np.float32)))
    y = jax.device_put(jnp.asarray(
        (np.arange(batch) % 1000).astype(np.int32)))
    _ = np.asarray(x.ravel()[:1])
    return timeit(step, (x, y),
                  barrier=lambda l: np.asarray(l._data))


# ---------------- pure-jax ceiling ----------------

def _pj_resnet50(nhwc, bn_dtype="bf16"):
    """Hand-rolled ResNet-50 fwd in bf16 with BN (batch stats), returns
    (init_params, apply). Layout nhwc or nchw decides conv dimension spec."""
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)

    def conv_p(cin, cout, k):
        return jnp.asarray(
            rng.randn(cout, cin, k, k).astype(np.float32) * 0.05)

    def bn_p(c):
        return (jnp.ones((c,), jnp.float32), jnp.zeros((c,), jnp.float32))

    layers = []  # (kind, params-spec)
    # stem
    params = {"stem_w": conv_p(3, 64, 7), "stem_bn": bn_p(64)}
    cfg = [(3, 64, 256, 1), (4, 128, 512, 2), (6, 256, 1024, 2),
           (3, 512, 2048, 2)]
    cin = 64
    for si, (blocks, mid, cout, stride) in enumerate(cfg):
        for bi in range(blocks):
            s = stride if bi == 0 else 1
            p = {}
            p["w1"] = conv_p(cin, mid, 1)
            p["bn1"] = bn_p(mid)
            p["w2"] = conv_p(mid, mid, 3)
            p["bn2"] = bn_p(mid)
            p["w3"] = conv_p(mid, cout, 1)
            p["bn3"] = bn_p(cout)
            if bi == 0:
                p["wd"] = conv_p(cin, cout, 1)
                p["bnd"] = bn_p(cout)
            params[f"s{si}b{bi}"] = p
            cin = cout
    params["fc_w"] = jnp.asarray(
        rng.randn(2048, 1000).astype(np.float32) * 0.01)
    params["fc_b"] = jnp.zeros((1000,), jnp.float32)

    if nhwc:
        dn_spec = ("NHWC", "HWIO", "NHWC")
        ch_axis = 3
        stat_axes = (0, 1, 2)
    else:
        dn_spec = ("NCHW", "OIHW", "NCHW")
        ch_axis = 1
        stat_axes = (0, 2, 3)

    def conv(x, w, stride, pad):
        if nhwc:
            w = jnp.transpose(w, (2, 3, 1, 0))  # OIHW->HWIO
        dn = jax.lax.conv_dimension_numbers(x.shape, w.shape, dn_spec)
        return jax.lax.conv_general_dilated(
            x, w.astype(x.dtype), (stride, stride),
            [(pad, pad), (pad, pad)], dimension_numbers=dn)

    def bn(x, g, b):
        shape = [1] * 4
        shape[ch_axis] = x.shape[ch_axis]
        if bn_dtype == "nostats":  # affine only: measures the stat cost
            return x * g.astype(x.dtype).reshape(shape) + b.astype(
                x.dtype).reshape(shape)
        if bn_dtype == "mmstats_ad":  # MXU stats fwd, plain autodiff bwd
            C = x.shape[ch_axis]
            n = x.size // C
            x2d = x.reshape(n, C)
            ones = jnp.ones((n,), x.dtype)
            dd = (((0,), (0,)), ((), ()))
            mean = jax.lax.dot_general(
                ones, x2d, dd, preferred_element_type=jnp.float32) / n
            meansq = jax.lax.dot_general(
                ones, jnp.square(x2d), dd,
                preferred_element_type=jnp.float32) / n
            var = meansq - jnp.square(mean)
            scale = g * jax.lax.rsqrt(var + 1e-5)
            bias = b - mean * scale
            return (x * scale.astype(x.dtype).reshape(shape)
                    + bias.astype(x.dtype).reshape(shape))
        if bn_dtype == "mmstats":  # ALL per-channel reductions on the MXU
            C = x.shape[ch_axis]
            n = x.size // C

            def dot1(v, m):  # [n] @ [n,C] -> f32 [C] on the MXU
                return jax.lax.dot_general(
                    v, m, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)

            @jax.custom_vjp
            def bn2d(x2d, g, b):
                ones = jnp.ones((n,), x2d.dtype)
                mean = dot1(ones, x2d) / n
                meansq = dot1(ones, jnp.square(x2d)) / n
                var = meansq - jnp.square(mean)
                r = jax.lax.rsqrt(var + 1e-5)
                scale = g * r
                bias = b - mean * scale
                return x2d * scale.astype(x2d.dtype) + bias.astype(x2d.dtype)

            def bn2d_fwd(x2d, g, b):
                ones = jnp.ones((n,), x2d.dtype)
                mean = dot1(ones, x2d) / n
                meansq = dot1(ones, jnp.square(x2d)) / n
                var = meansq - jnp.square(mean)
                r = jax.lax.rsqrt(var + 1e-5)
                scale = g * r
                bias = b - mean * scale
                out = x2d * scale.astype(x2d.dtype) + bias.astype(x2d.dtype)
                return out, (x2d, g, mean, r)

            def bn2d_bwd(res, dy):
                x2d, g, mean, r = res
                ones = jnp.ones((n,), dy.dtype)
                xhat = (x2d.astype(jnp.float32) - mean) * r
                xhat = xhat.astype(x2d.dtype)
                db = dot1(ones, dy)
                dg = dot1(ones, dy * xhat)
                k = (g * r / n).astype(jnp.float32)
                dx = (k * (n * dy.astype(jnp.float32)
                           - db - xhat.astype(jnp.float32) * dg)
                      ).astype(x2d.dtype)
                return dx, dg, db

            bn2d.defvjp(bn2d_fwd, bn2d_bwd)
            return bn2d(x.reshape(n, C), g, b).reshape(x.shape)
        if bn_dtype == "onepass":  # fused mean/meansq, scale+shift form
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=stat_axes)
            meansq = jnp.mean(jnp.square(xf), axis=stat_axes)
            var = meansq - jnp.square(mean)
            scale = g * jax.lax.rsqrt(var + 1e-5)
            bias = b - mean * scale
            return (x * scale.astype(x.dtype).reshape(shape)
                    + bias.astype(x.dtype).reshape(shape))
        cd = jnp.float32 if bn_dtype == "f32" else x.dtype
        xx = x.astype(cd)
        mean = jnp.mean(xx.astype(jnp.float32), axis=stat_axes)
        var = jnp.var(xx.astype(jnp.float32), axis=stat_axes)
        out = (xx - mean.astype(cd).reshape(shape)) * jax.lax.rsqrt(
            var.astype(cd).reshape(shape) + 1e-5)
        return (out * g.astype(cd).reshape(shape)
                + b.astype(cd).reshape(shape)).astype(x.dtype)

    def apply(params, x):
        x = x.astype(jnp.bfloat16)
        if nhwc:
            x = jnp.transpose(x, (0, 2, 3, 1))
        h = conv(x, params["stem_w"], 2, 3)
        h = jax.nn.relu(bn(h, *params["stem_bn"]))
        # 3x3 maxpool stride 2
        pads = [(0, 0)] * 4
        pads[1 if not nhwc else 1] = (1, 1)
        if nhwc:
            window = (1, 3, 3, 1)
            strides = (1, 2, 2, 1)
            pad4 = [(0, 0), (1, 1), (1, 1), (0, 0)]
        else:
            window = (1, 1, 3, 3)
            strides = (1, 1, 2, 2)
            pad4 = [(0, 0), (0, 0), (1, 1), (1, 1)]
        h = jax.lax.reduce_window(
            h, -jnp.inf, jax.lax.max, window, strides, pad4)
        cfg = [(3, 1), (4, 2), (6, 2), (3, 2)]
        for si, (blocks, stride) in enumerate(cfg):
            for bi in range(blocks):
                p = params[f"s{si}b{bi}"]
                s = stride if bi == 0 else 1
                idn = h
                o = jax.nn.relu(bn(conv(h, p["w1"], 1, 0), *p["bn1"]))
                o = jax.nn.relu(bn(conv(o, p["w2"], s, 1), *p["bn2"]))
                o = bn(conv(o, p["w3"], 1, 0), *p["bn3"])
                if "wd" in p:
                    idn = bn(conv(h, p["wd"], s, 0), *p["bnd"])
                h = jax.nn.relu(o + idn)
        h = jnp.mean(h, axis=(1, 2) if nhwc else (2, 3))
        return h.astype(jnp.float32) @ params["fc_w"] + params["fc_b"]

    return params, apply


def purejax(batch, nhwc, bn_dtype="bf16", fwd_only=False):
    import jax
    import jax.numpy as jnp
    import optax

    params, apply = _pj_resnet50(nhwc, bn_dtype)
    if fwd_only:
        fwd = jax.jit(lambda p, x: apply(p, x).sum())
        x = jax.device_put(jnp.asarray(
            np.random.rand(batch, 3, 224, 224).astype(np.float32)))
        _ = np.asarray(x.ravel()[:1])
        return timeit(lambda x: fwd(params, x), (x,),
                      barrier=lambda l: np.asarray(l))
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)

    def loss_fn(params, x, y):
        logits = apply(params, x)
        return jnp.mean(
            -jax.nn.log_softmax(logits)[jnp.arange(x.shape[0]), y])

    @jax.jit
    def step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    x = jax.device_put(jnp.asarray(
        np.random.rand(batch, 3, 224, 224).astype(np.float32)))
    y = jax.device_put(jnp.asarray(
        (np.arange(batch) % 1000).astype(np.int32)))
    _ = np.asarray(x.ravel()[:1])

    state = {"p": params, "o": opt_state}

    def run(x, y):
        state["p"], state["o"], loss = step(state["p"], state["o"], x, y)
        return loss

    return timeit(run, (x, y), barrier=lambda l: np.asarray(l))


if __name__ == "__main__":
    variant = sys.argv[1]
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    if variant == "fw":
        ms, cs = fw(batch)
    elif variant == "purejax_nhwc":
        ms, cs = purejax(batch, True)
    elif variant == "purejax_nchw":
        ms, cs = purejax(batch, False)
    elif variant == "purejax_nhwc_f32bn":
        ms, cs = purejax(batch, True, "f32")
    elif variant == "purejax_nostats":
        ms, cs = purejax(batch, True, "nostats")
    elif variant == "purejax_onepass":
        ms, cs = purejax(batch, True, "onepass")
    elif variant == "purejax_onepass_fwd":
        ms, cs = purejax(batch, True, "onepass", fwd_only=True)
    elif variant == "purejax_mmstats":
        ms, cs = purejax(batch, True, "mmstats")
    elif variant == "purejax_mmstats_ad":
        ms, cs = purejax(batch, True, "mmstats_ad")
    elif variant == "purejax_mmstats_fwd":
        ms, cs = purejax(batch, True, "mmstats", fwd_only=True)
    else:
        raise SystemExit(f"unknown variant {variant}")
    print(f"{variant} batch={batch} step_ms={ms:.2f} "
          f"imgs_s={batch/ms*1e3:.0f} compile_s={cs:.1f}")
