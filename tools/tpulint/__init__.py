"""tpulint — trace/shard/donation static analysis over the compiled-step
surface (ISSUE 7 tentpole).

One sub-second AST pass over `paddle_tpu/` and user training scripts
encoding the hazard classes PRs 1-6 fixed by hand at runtime:

=====================  ====================================================
rule                   bug class (PR-history exemplar)
=====================  ====================================================
pallas-in-gspmd        pallas_call reachable from a jit region without a
                       shard_map seam or mesh guard (PR 6 headline)
host-sync-in-step      .item()/print/np.asarray/device_get/float on traced
                       values inside TrainStep/LocalSGDStep bodies
donation-alias         buffer read after donation; donation of the
                       host-monitored guard carry (PR 5)
divergent-collective   collective call under rank-/data-dependent control
                       flow (the hang class PR 2's monitor attributes)
numpy-on-tracer        np.* math on values dataflowing from jnp inside
                       compiled regions
psum-in-shard-vjp      custom_vjp backward under shard_map whose reduced
                       partials lack an explicit lax.psum (dgamma/dbeta)
env-knob-docs          PADDLE_* knob referenced but undocumented (migrated
                       from test_hygiene's ad-hoc check)
alias-parity           tools/check_alias.py folded in (--alias; imports)
=====================  ====================================================

Entry point: ``python -m tools.tpulint [paths...]``.  Suppress one
finding with a trailing ``# tpulint: disable=<rule>`` comment; park
pre-existing findings in ``tools/tpulint/baseline.json`` (every entry
carries a mandatory tracking note; the gate fails only on NEW findings).
"""
from .core import (  # noqa: F401
    Finding, ModuleSource, ProjectRule, REGISTRY, Rule, apply_baseline,
    load_baseline, register, run, write_baseline,
)
