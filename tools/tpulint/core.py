"""tpulint core: findings, rule registry, suppressions, baseline.

The framework is deliberately jax-free and import-light: every AST rule
works on parsed source only, so the full `paddle_tpu/` sweep stays
sub-second (a hung pod or a 13s GSPMD recompile is the alternative
detector for these bug classes — see ISSUE 7).

Vocabulary:

* **AST rule** — subclass of :class:`Rule`; gets one
  :class:`ModuleSource` per analyzed file and yields
  :class:`Finding`s.
* **Project rule** — subclass of :class:`ProjectRule`; runs once per
  invocation over the whole path set (the env-knob documentation check,
  the alias-parity linter).
* **Suppression** — ``# tpulint: disable=<rule>[,<rule>...]`` trailing
  the finding line or on the line directly above.  ``disable=all``
  silences every rule for that line.
* **Baseline** — a checked-in JSON file of fingerprints for
  pre-existing findings; the gate fails only on findings NOT in the
  baseline.  Every baseline entry must carry a non-empty ``note``
  explaining why it is parked (no silent baseline entries).
"""
from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
from typing import Iterable, List, Optional


# --------------------------------------------------------------------------
# findings
# --------------------------------------------------------------------------


@dataclasses.dataclass
class Finding:
    rule: str
    path: str            # repo-relative, posix separators
    line: int
    col: int
    message: str
    fingerprint: str = ""
    suppressed: bool = False
    baselined: bool = False

    def as_dict(self):
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "col": self.col, "message": self.message,
            "fingerprint": self.fingerprint,
            "suppressed": self.suppressed, "baselined": self.baselined,
        }

    def render(self):
        tags = []
        if self.suppressed:
            tags.append("suppressed")
        if self.baselined:
            tags.append("baselined")
        tag = f" [{','.join(tags)}]" if tags else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule}: {self.message}{tag}")


# --------------------------------------------------------------------------
# per-file source container
# --------------------------------------------------------------------------

# rule names terminate at the first non-name token, so a trailing
# free-text reason ("disable=rule - because ...") never swallows into
# the name; commas separate multiple rules
_SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*disable=([A-Za-z0-9_-]+(?:\s*,\s*[A-Za-z0-9_-]+)*)"
)


def _parse_suppressions(line_text: str) -> set:
    m = _SUPPRESS_RE.search(line_text)
    if not m:
        return set()
    return {s.strip() for s in m.group(1).split(",") if s.strip()}


def suppressed_at(lines: List[str], rule: str, line: int) -> bool:
    """True if `rule` is disabled at `line`: a trailing
    ``# tpulint: disable=`` on the line itself, or on a comment-only
    line directly above (a code line above belongs to its own finding).
    """
    for at in (line, line - 1):
        if not (1 <= at <= len(lines)):
            continue
        names = _parse_suppressions(lines[at - 1])
        if not names or not ("all" in names or rule in names):
            continue
        if at == line - 1 and not lines[at - 1].strip().startswith("#"):
            continue
        return True
    return False


class ModuleSource:
    """One parsed file: source text, AST, and the suppression map."""

    def __init__(self, path: str, relpath: str, text: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self._graph = None

    def graph(self):
        """Memoized ModuleGraph — every AST rule shares one build."""
        if self._graph is None:
            from .astutil import ModuleGraph

            self._graph = ModuleGraph(self.tree)
        return self._graph

    def is_suppressed(self, rule: str, line: int) -> bool:
        return suppressed_at(self.lines, rule, line)

    def line_src(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""


# --------------------------------------------------------------------------
# rule registry
# --------------------------------------------------------------------------


class Rule:
    """AST rule: ``check(mod)`` yields Findings for one file."""

    name: str = ""
    summary: str = ""

    def check(self, mod: ModuleSource) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, mod: ModuleSource, node, message: str) -> Finding:
        return Finding(
            rule=self.name, path=mod.relpath,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            message=message,
        )


class ProjectRule:
    """Whole-invocation rule: ``check_project(paths, repo_root)``."""

    name: str = ""
    summary: str = ""
    default_enabled: bool = True

    def check_project(self, paths: List[str],
                      repo_root: str) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError


REGISTRY: dict[str, object] = {}


def register(rule_cls):
    """Class decorator: instantiate and register a rule by name."""
    inst = rule_cls()
    if not inst.name:
        raise ValueError(f"rule {rule_cls.__name__} has no name")
    if inst.name in REGISTRY:
        raise ValueError(f"duplicate rule name {inst.name!r}")
    REGISTRY[inst.name] = inst
    return rule_cls


def ast_rules():
    return [r for r in REGISTRY.values() if isinstance(r, Rule)]


def project_rules():
    return [r for r in REGISTRY.values() if isinstance(r, ProjectRule)]


# --------------------------------------------------------------------------
# fingerprints + baseline
# --------------------------------------------------------------------------


def _normalized_line(mod_lines: List[str], line: int) -> str:
    if 1 <= line <= len(mod_lines):
        return re.sub(r"\s+", " ", mod_lines[line - 1].strip())
    return ""


def fingerprint_findings(findings: List[Finding],
                         sources: dict) -> None:
    """Stable fingerprints: rule + path + normalized source line +
    occurrence index among identical lines — insensitive to unrelated
    line insertions above the finding."""
    seen: dict[tuple, int] = {}
    for f in findings:
        lines = sources.get(f.path)
        norm = _normalized_line(lines, f.line) if lines else ""
        key = (f.rule, f.path, norm)
        k = seen.get(key, 0)
        seen[key] = k + 1
        h = hashlib.sha1(
            f"{f.rule}:{f.path}:{norm}:{k}".encode()
        ).hexdigest()[:12]
        f.fingerprint = h


class BaselineError(RuntimeError):
    pass


def load_baseline(path: str) -> dict:
    """fingerprint -> entry dict.  Every entry must carry a non-empty
    note (the tracking comment) — silent baseline entries are an error,
    not a workflow."""
    if not path or not os.path.exists(path):
        return {}
    with open(path) as fh:
        data = json.load(fh)
    entries = data.get("entries", [])
    silent = [e for e in entries if not str(e.get("note", "")).strip()]
    if silent:
        names = ", ".join(
            f"{e.get('rule')}@{e.get('path')}:{e.get('fingerprint')}"
            for e in silent
        )
        raise BaselineError(
            f"baseline {path} has {len(silent)} entr"
            f"{'y' if len(silent) == 1 else 'ies'} without a tracking "
            f"note ({names}) — every parked finding needs one"
        )
    return {e["fingerprint"]: e for e in entries}


def write_baseline(path: str, findings: List[Finding],
                   old: Optional[dict] = None,
                   swept_paths: Optional[set] = None) -> dict:
    """Write non-suppressed findings as the new baseline.  Notes of
    surviving entries are preserved; NEW entries get a loud
    ``TODO(triage)`` placeholder that load_baseline will accept but the
    author is expected to replace with a real tracking comment.

    With ``swept_paths`` (the repo-relative files this run actually
    analyzed), old entries for files OUTSIDE the sweep are carried over
    verbatim — a path-subset run must not silently drop (and lose the
    notes of) every other file's parked findings.  Entries for swept
    files are regenerated, so stale ones still drop."""
    old = old or {}
    merged: dict[str, dict] = {}
    if swept_paths is not None:
        for fp, e in old.items():
            if e.get("path") not in swept_paths:
                merged[fp] = dict(e)
    for f in findings:
        if f.suppressed:
            continue
        prev = old.get(f.fingerprint, {})
        note = str(prev.get("note", "")).strip() or (
            "TODO(triage): parked by --write-baseline, replace with a "
            "tracking comment"
        )
        merged[f.fingerprint] = {
            "rule": f.rule, "path": f.path, "line_hint": f.line,
            "fingerprint": f.fingerprint, "note": note,
        }
    entries = sorted(merged.values(),
                     key=lambda e: (e["path"], e["rule"],
                                    e["line_hint"]))
    data = {"version": 1, "entries": entries}
    with open(path, "w") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return {e["fingerprint"]: e for e in entries}


# --------------------------------------------------------------------------
# runner
# --------------------------------------------------------------------------


def collect_files(paths: List[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        out.append(os.path.join(root, fn))
    return out


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def default_baseline_path() -> str:
    env = os.environ.get("PADDLE_LINT_BASELINE", "").strip()
    if env:
        return env
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def disabled_rules() -> set:
    env = os.environ.get("PADDLE_LINT_DISABLE", "").strip()
    return {s.strip() for s in env.split(",") if s.strip()}


def run(paths: List[str], *, rules: Optional[set] = None,
        enable_project: bool = True,
        enable_alias: bool = False,
        root: Optional[str] = None):
    """Run every registered rule over `paths`.

    Returns ``(findings, errors)``: findings carry fingerprints but no
    baseline marks (the CLI applies those); errors are per-file parse
    failures rendered as strings.
    """
    root = root or repo_root()
    skip = disabled_rules()
    findings: List[Finding] = []
    errors: List[str] = []
    sources: dict[str, list] = {}
    mods: List[ModuleSource] = []
    for fp in collect_files(paths):
        rel = os.path.relpath(os.path.abspath(fp), root)
        try:
            with open(fp, encoding="utf-8") as fh:
                text = fh.read()
            mod = ModuleSource(fp, rel, text)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            errors.append(f"{rel}: parse error: {e}")
            continue
        mods.append(mod)
        sources[mod.relpath] = mod.lines
    for rule in ast_rules():
        if rule.name in skip or (rules is not None
                                 and rule.name not in rules):
            continue
        for mod in mods:
            try:
                for f in rule.check(mod):
                    f.suppressed = mod.is_suppressed(rule.name, f.line)
                    findings.append(f)
            except RecursionError:  # pathological nesting: skip file
                errors.append(
                    f"{mod.relpath}: {rule.name}: recursion limit"
                )
    if enable_project:
        for rule in project_rules():
            if rule.name in skip or (rules is not None
                                     and rule.name not in rules):
                continue
            if not rule.default_enabled and not enable_alias:
                continue
            for f in rule.check_project(paths, root):
                lines = sources.get(f.path)
                if lines is None and f.path:
                    ap = os.path.join(root, f.path)
                    if os.path.exists(ap):
                        try:
                            with open(ap, encoding="utf-8") as fh:
                                lines = fh.read().splitlines()
                        except OSError:
                            lines = []
                        sources[f.path] = lines
                if lines:
                    f.suppressed = suppressed_at(lines, rule.name,
                                                 f.line)
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    fingerprint_findings(findings, sources)
    return findings, errors


def apply_baseline(findings: List[Finding], baseline: dict):
    """Mark baselined findings; return (new, stale_entries)."""
    seen = set()
    new = []
    for f in findings:
        if f.fingerprint in baseline:
            f.baselined = True
            seen.add(f.fingerprint)
        elif not f.suppressed:
            new.append(f)
    stale = [e for fp, e in sorted(baseline.items()) if fp not in seen]
    return new, stale
