"""psum-in-shard-vjp: custom_vjp backward bodies under shard_map whose
replicated (reduced-partial) outputs lack an explicit `lax.psum`.

PR-history exemplar (ISSUE 6, the dgamma/dbeta class): the sharded
fused-LayerNorm seam carries an outer custom_vjp; each shard's kernel
emits per-row-block dgamma/dbeta PARTIALS, and the backward body must
reduce them across shards with an explicit `lax.psum` over the row axes
before declaring the output replicated (`out_specs=P()`).  Without the
psum the program either trips shard_map's replication check or — with
the check off — silently returns one shard's partial as the full
gradient.

Statically: for every `X.defvjp(fwd, bwd)`, walk the functions reachable
from `bwd` (direct references and functools.partial targets).  If that
set issues a `shard_map` call whose `out_specs` contain a bare
replicated `P()` entry, a `psum` call must also be reachable; flag the
backward otherwise.  Backwards whose outputs are all sharded (no `P()`
in out_specs) have no cross-shard partials and stay quiet, as do
custom_vjps with no shard_map at all (the single-chip kernels).
"""
from __future__ import annotations

import ast

from ..astutil import dotted, is_wrapper_call, terminal
from ..core import Rule, register


def _bare_pspec_in(expr) -> bool:
    """Does `expr` (an out_specs value) contain a no-arg P() /
    PartitionSpec() — i.e. a fully-replicated output?"""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and terminal(
                dotted(node.func)) in ("P", "PartitionSpec"):
            if not node.args and not node.keywords:
                return True
    return False


def _reachable(graph, start_key):
    seen = set()
    work = [start_key]
    while work:
        key = work.pop()
        if key in seen or key not in graph.funcs:
            continue
        seen.add(key)
        info = graph.funcs[key]
        for node in ast.walk(info.node):
            if isinstance(node, (ast.Name, ast.Attribute)) and \
                    isinstance(getattr(node, "ctx", None), ast.Load):
                tgt = graph.resolve(dotted(node), info.class_name)
                if tgt is not None:
                    work.append(tgt.key)
    return seen


@register
class PsumInShardVjpRule(Rule):
    name = "psum-in-shard-vjp"
    summary = ("custom_vjp backward under shard_map with replicated "
               "outputs but no explicit lax.psum")

    def check(self, mod):
        if "defvjp" not in mod.text:
            return
        graph = mod.graph()
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call) and terminal(
                    dotted(node.func)) == "defvjp"):
                continue
            if len(node.args) < 2:
                continue
            bwd_ref = dotted(node.args[1])
            bwd = graph.resolve(bwd_ref, None)
            if bwd is None:
                continue
            reach = _reachable(graph, bwd.key)
            needs_psum = False
            has_psum = False
            for key in reach:
                info = graph.funcs[key]
                for n in ast.walk(info.node):
                    if not isinstance(n, ast.Call):
                        continue
                    t = terminal(dotted(n.func))
                    if t in ("psum", "psum_scatter", "all_gather"):
                        has_psum = True
                    if is_wrapper_call(n, {"shard_map"}):
                        out_specs = None
                        for kw in n.keywords:
                            if kw.arg == "out_specs":
                                out_specs = kw.value
                        if out_specs is None and len(n.args) >= 4:
                            out_specs = n.args[3]
                        if out_specs is None or _bare_pspec_in(out_specs):
                            # unresolvable out_specs: conservatively
                            # treat as carrying a replicated partial
                            needs_psum = True
            if needs_psum and not has_psum:
                yield self.finding(
                    mod, bwd.node,
                    f"custom_vjp backward `{bwd.node.name}` runs under "
                    "shard_map and declares a replicated output "
                    "(out_specs P()) but no lax.psum is reachable — "
                    "per-shard reduced partials (dgamma/dbeta class) "
                    "need an explicit cross-shard psum",
                )
