"""donation-alias: reads of a buffer after it was donated, and donation
of carries a host monitor still reads.

PR-history exemplar (PR 5, the guard-carry rule): the guard-policy
counters ride the compiled step as a small carry that the HOST monitor
reads through a deferred async prefetch — donating that carry
invalidates the buffer the moment it is re-passed, racing the in-flight
read (`train_step.py` documents why the carry is excluded from
`donate_argnums`).  The sibling hazard is the plain read-after-donate:
touching an array after passing it in a donated position is a
use-after-free on the device buffer.

Statically: resolve `donate_argnums` on `jax.jit(...)` calls (literal
tuples, simple local rebinds, conditional unions); map donated positions
to the jitted callable's parameter names; flag

* donated parameters whose names mark them as host-monitored carries
  (`*guard*`, `*monitor*`) — the encoded PR 5 rule;
* at call sites of the jitted binding, loads of a donated argument
  name after the call statement (without an intervening rebind).
"""
from __future__ import annotations

import ast
from typing import Optional, Set

from ..astutil import dotted, enclosing, terminal
from ..core import Rule, register

_CARRY_HINTS = ("guard", "monitor")


def _const_ints(node) -> Optional[Set[int]]:
    """Literal donate_argnums value -> set of indices (None if not)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out = set()
        for el in node.elts:
            s = _const_ints(el)
            if s is None:
                return None
            out |= s
        return out
    return None


def _resolve_argnums(expr, func: Optional[ast.FunctionDef],
                     _seen: Optional[Set[str]] = None) -> Optional[Set[int]]:
    """Resolve a donate_argnums expression to the UNION of indices it
    can take: literals, `a if c else b`, `name` rebound from literals,
    `name + (lit,)` growth (self-referential rebinds contribute their
    other operand).  None = unresolvable (rule stays quiet)."""
    _seen = _seen if _seen is not None else set()
    s = _const_ints(expr)
    if s is not None:
        return s
    if isinstance(expr, ast.IfExp):
        a = _resolve_argnums(expr.body, func, _seen)
        b = _resolve_argnums(expr.orelse, func, _seen)
        if a is None or b is None:
            return None
        return a | b
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        a = _resolve_argnums(expr.left, func, _seen)
        b = _resolve_argnums(expr.right, func, _seen)
        if a is None or b is None:
            return None
        return a | b
    if isinstance(expr, ast.Name) and func is not None:
        if expr.id in _seen:
            # cycle (`donate = donate + (6,)`): the recursive operand
            # adds nothing beyond its other assignments
            return set()
        _seen = _seen | {expr.id}
        out: Set[int] = set()
        found = False
        for node in ast.walk(func):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == expr.id:
                        v = _resolve_argnums(node.value, func, _seen)
                        if v is None:
                            return None
                        out |= v
                        found = True
            elif isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Name) and node.target.id == expr.id:
                v = _resolve_argnums(node.value, func, _seen)
                if v is None:
                    return None
                out |= v
                found = True
        return out if found else None
    return None


def _param_names(fn: ast.FunctionDef):
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args]
    if names and names[0] in ("self", "cls"):
        names = names[1:]
    return names


@register
class DonationAliasRule(Rule):
    name = "donation-alias"
    summary = ("buffer read after donation, or donation of a "
               "host-monitored carry")

    def check(self, mod):
        if "donate_argnums" not in mod.text:
            return
        graph = mod.graph()
        parents = graph.parents
        # binding (dotted target or local name) -> donated index set
        donated_bindings: dict[str, Set[int]] = {}

        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call) and terminal(
                    dotted(node.func)) in ("jit", "pjit")):
                continue
            dn = None
            for kw in node.keywords:
                if kw.arg in ("donate_argnums", "donate_argnames"):
                    dn = kw
            if dn is None or dn.arg == "donate_argnames":
                continue
            owner = graph.owner_func(node)
            idxs = _resolve_argnums(dn.value, owner)
            if not idxs:
                continue

            # --- carry-donation check on the jitted callable's params
            ctx_cls = None
            if owner is not None:
                cls = enclosing(owner, parents, (ast.ClassDef,))
                ctx_cls = cls.name if cls else None
            target = None
            if node.args:
                if isinstance(node.args[0], ast.Lambda):
                    names = [a.arg for a in node.args[0].args.args]
                    target = None
                else:
                    target = graph.resolve(dotted(node.args[0]), ctx_cls)
                    names = _param_names(target.node) if target else []
            else:
                names = []
            for i in sorted(idxs):
                if i < len(names) and any(
                        h in names[i].lower() for h in _CARRY_HINTS):
                    yield self.finding(
                        mod, node,
                        f"donate_argnums includes position {i} "
                        f"(`{names[i]}`) — a host-monitored carry must "
                        "NOT be donated: the monitor's deferred async "
                        "read outlives the next dispatch and donation "
                        "invalidates the buffer it is still reading "
                        "(PR-5 guard-carry rule)",
                    )

            # --- read-after-donate at call sites of the binding
            asn = enclosing(node, parents, (ast.Assign,))
            if asn is None or asn.value is not node:
                continue
            for tgt in asn.targets:
                d = dotted(tgt)
                if d:
                    donated_bindings[d] = idxs

        for binding, idxs in donated_bindings.items():
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Call)
                        and dotted(node.func) == binding):
                    continue
                owner = graph.owner_func(node)
                if owner is None:
                    continue
                stmt = enclosing(node, parents, (ast.stmt,))
                end = getattr(stmt, "end_lineno", node.lineno)
                for i in sorted(idxs):
                    if i >= len(node.args):
                        continue
                    arg = node.args[i]
                    if not isinstance(arg, ast.Name):
                        continue
                    # ast.walk is breadth-first, NOT source order — a
                    # shallow late rebind must not shadow a deeper
                    # earlier read, so sort by position first
                    uses = sorted(
                        (n for n in ast.walk(owner)
                         if isinstance(n, ast.Name) and n.id == arg.id
                         and n.lineno > end),
                        key=lambda n: (n.lineno, n.col_offset),
                    )
                    for later in uses:
                        if isinstance(later.ctx, ast.Store):
                            break  # rebound: later reads are fresh
                        yield self.finding(
                            mod, later,
                            f"`{arg.id}` is read after being "
                            f"donated (position {i} of "
                            f"`{binding}` at line {node.lineno}) "
                            "— donation hands the buffer to XLA; "
                            "this read races the in-place update",
                        )
                        break
