"""tpulint rule set — importing this package registers every rule.

Each module encodes ONE bug class this repo has actually shipped a fix
for; the rule docstrings name the PR-history exemplar.
"""
from . import (  # noqa: F401  (import-for-registration)
    pallas_in_gspmd,
    host_sync,
    donation,
    collectives,
    numpy_tracer,
    shard_vjp,
    env_knobs,
    alias_parity,
    unscaled_int8,
)
