"""unscaled-int8: a bare narrow-integer cast with no per-block scale in
sight.

PR-history exemplar: the quantization plane (quantized_comm /
quantized_compute, PRs 10 and 19) never casts to int8 naked — every
narrow payload is `round(x / scale)` clipped to the qmax and paired
with an f32 per-block scale tensor, or the dequantized values are off
by the (arbitrary) magnitude of the block.  A raw ``x.astype(jnp.int8)``
on float data silently truncates to [-128, 127] integer steps: unit
tests on toy ranges near ±1 pass (everything rounds to 0 or ±1 and the
loss barely moves), while real weights/moments lose all mantissa.

Statically: flag ``<expr>.astype(int8/uint8)`` and
``jnp/np.asarray(x, dtype=int8)``-family casts inside functions that
neither bind nor read any identifier containing ``scale`` (or ``qmax``)
— the quantization helpers all do, so the real encode paths stay
quiet.  Integer *data* casts (token ids, masks) are the other
legitimate user; those live in functions without float math on the
cast operand, but statically we cannot see dtypes, so the rule keeps
the heuristic one-sided: any scale-free function doing a narrow cast
is worth a human look, and a false positive is silenced by the usual
``# tpulint: disable=unscaled-int8`` or by threading the scale through
the same function (which is the fix anyway).
"""
from __future__ import annotations

import ast

from ..astutil import dotted
from ..core import Rule, register

_NARROW = {"int8", "uint8"}
# identifiers whose presence marks a function as scale-aware
_SCALE_MARKERS = ("scale", "qmax")


def _is_narrow_dtype(node) -> bool:
    """`jnp.int8` / `np.int8` / `"int8"` / bare `int8`."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value in _NARROW
    d = dotted(node)
    return d.split(".")[-1] in _NARROW


def _func_idents(func) -> set:
    out = set()
    for n in ast.walk(func):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
        elif isinstance(n, ast.arg):
            out.add(n.arg)
        elif isinstance(n, ast.keyword) and n.arg:
            out.add(n.arg)
    return out


def _scale_aware(func) -> bool:
    idents = _func_idents(func)
    return any(m in name.lower() for name in idents
               for m in _SCALE_MARKERS)


@register
class UnscaledInt8Rule(Rule):
    name = "unscaled-int8"
    summary = ("narrow int8/uint8 cast in a function with no per-block "
               "scale anywhere in sight")

    def check(self, mod):
        if "int8" not in mod.text:
            return
        graph = mod.graph()
        tree = mod.graph().tree
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            narrow = None
            d = dotted(node.func)
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype" and node.args
                    and _is_narrow_dtype(node.args[0])):
                narrow = dotted(node.args[0]) or "int8"
            elif d.split(".")[-1] in ("asarray", "array", "full",
                                      "zeros", "ones", "empty"):
                for kw in node.keywords:
                    if kw.arg == "dtype" and _is_narrow_dtype(kw.value):
                        narrow = dotted(kw.value) or "int8"
                # zeros/full-style buffers are allocation, not value
                # truncation — only the value-converting forms count
                if d.split(".")[-1] not in ("asarray", "array"):
                    narrow = None
            if narrow is None:
                continue
            func = graph.owner_func(node)
            if func is None:
                # module level: scan the whole module for scale markers
                if any(m in mod.text.lower() for m in _SCALE_MARKERS):
                    continue
                where = "module level"
            else:
                if _scale_aware(func):
                    continue
                where = f"`{func.name}`"
            yield self.finding(
                mod, node,
                f"bare cast to {narrow} at {where} with no scale "
                "bound anywhere in the function — a narrow integer "
                "payload without a paired per-block scale truncates "
                "float data to [-128, 127] steps; quantize via "
                "quantize_blockwise/quantize_lastaxis (payload + f32 "
                "scales) or silence with a tpulint disable if this is "
                "genuinely integer data",
            )
