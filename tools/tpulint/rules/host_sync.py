"""host-sync-in-step: a host synchronization inside a compiled step body.

PR-history exemplars: the pre-round-4 fp16 scaler did a PER-PARAM host
finite check inside the step (one device round-trip per parameter per
step); the round-5 guard work moved every policy read to an interval-
synced async prefetch precisely because an `.item()` / `np.asarray` /
`print` / `device_get` on a traced value either fails under trace or —
worse, on concrete values — silently serializes the pipeline.

Statically: inside compiled-region functions (anything reachable from a
`jax.jit` / trace-wrapper reference, plus `_step_fn`/`_worker` bodies of
`*Step` classes), flag

* ``print(...)`` — always (tracer reprs at best, a device sync at worst)
* ``.item()`` / ``.numpy()`` / ``.tolist()`` method calls
* ``jax.device_get(...)``
* ``np.asarray(x)`` / ``np.array(x)`` with a traced argument
* ``float(x)`` / ``int(x)`` / ``bool(x)`` with a traced argument
  (``int(x.shape[i])`` is static under trace and stays quiet)
* telemetry-bus emits (ISSUE 8): ``emit_event(...)`` (train_guard) and
  ``bus.emit(...)`` / ``emit(...)`` — emits are host-side BY CONTRACT
  (a wall-clock read + file append); inside a compiled body they run at
  trace time (one ghost row per compile, none per step) and any traced
  value in the payload dies a tracer repr. Emit from the host loop on
  the step's RETURNED state instead — that is exactly what the guard's
  interval-synced monitor does.
* the request-scoped span/trace helpers (ISSUE 14):
  ``bus.emit_span(...)`` and the metrics-sampler methods
  ``.span(...)`` / ``.window_span(...)`` / ``.request_done(...)``
  behind a metrics/sampler qualifier — same contract as emits: the
  engine publishes spans on its READBACK cadence from host values, a
  span inside a compiled DecodeStep body would fire per compile with
  tracer reprs.
"""
from __future__ import annotations

import ast

from ..astutil import Taint, dotted, terminal
from ..core import Rule, register

_METHOD_SYNCS = {"item", "numpy", "tolist"}
_CAST_SYNCS = {"float", "int", "bool"}
#: dotted qualifiers that identify an `emit(...)` call as the telemetry
#: bus API (the bare `emit_event` name is the guard's and always counts)
_EMIT_QUALIFIERS = {"bus", "_bus", "_obs_bus", "telemetry", "_telemetry",
                    "obs", "_obs", "observability"}
#: the request-scoped span/trace helpers (ISSUE 14): `emit_span` is the
#: bus-level API (unambiguous, always counts like emit_event); the
#: sampler methods are generic names, so they only count behind a
#: metrics/sampler/bus-ish qualifier (`self._metrics.span(...)`)
_SPAN_METHODS = {"span", "window_span", "request_done"}
_SPAN_QUALIFIERS = _EMIT_QUALIFIERS | {"metrics", "_metrics", "sampler",
                                       "_sampler"}
#: every terminal name the emit branch of the rule dispatches on
EMIT_TERMINALS = frozenset(
    {"emit", "emit_event", "emit_span"} | _SPAN_METHODS)


def _telemetry_emit(d: str) -> bool:
    parts = d.split(".")
    t = parts[-1]
    if t in ("emit_event", "emit_span"):
        return True
    quals = parts[:-1]
    if t == "emit":
        return not quals or any(
            q in _EMIT_QUALIFIERS or q.endswith("bus") for q in quals
        )
    if t in _SPAN_METHODS:
        return any(
            q in _SPAN_QUALIFIERS or q.endswith("bus") for q in quals
        )
    return False


@register
class HostSyncInStepRule(Rule):
    name = "host-sync-in-step"
    summary = ("host synchronization (.item()/print/np.asarray/"
               "device_get/float) inside a compiled step body")

    def check(self, mod):
        graph = mod.graph()
        for info in graph.compiled_funcs():
            func = info.node
            taint = Taint(func)
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                if graph.owner_func(node) is not func:
                    continue  # belongs to a nested def (visited itself)
                d = dotted(node.func)
                t = terminal(d)
                where = f"in compiled step body `{func.name}`"
                if t == "print" and d == "print":
                    yield self.finding(
                        mod, node,
                        f"print() {where} — runs at trace time (or "
                        "syncs the device); use host-side logging on "
                        "the step result or jax.debug.print",
                    )
                elif isinstance(node.func, ast.Attribute) and \
                        t in _METHOD_SYNCS and not node.args:
                    yield self.finding(
                        mod, node,
                        f".{t}() {where} — a device round-trip per "
                        "step; read the value from the step's RETURNED "
                        "arrays on the host instead",
                    )
                elif t == "device_get" and d.split(".")[0] in (
                        "jax", "device_get"):
                    yield self.finding(
                        mod, node,
                        f"jax.device_get {where} — host sync; return "
                        "the value and read it outside the step",
                    )
                elif d in ("np.asarray", "np.array", "numpy.asarray",
                           "numpy.array") and taint.call_arg_tainted(
                               node):
                    yield self.finding(
                        mod, node,
                        f"{d} on a traced value {where} — forces the "
                        "tracer to a concrete host array; use jnp or "
                        "move the read outside the compiled region",
                    )
                elif t in _CAST_SYNCS and d == t and node.args \
                        and taint.call_arg_tainted(node):
                    yield self.finding(
                        mod, node,
                        f"{t}() on a traced value {where} — a host "
                        "sync under concrete execution and a trace "
                        "error under jit; keep it an array",
                    )
                elif t in EMIT_TERMINALS and _telemetry_emit(d):
                    yield self.finding(
                        mod, node,
                        f"telemetry emit `{d}(...)` {where} — bus emits "
                        "are host-side by contract (wall clock + file "
                        "append): under trace this fires once per "
                        "COMPILE, not per step, and traced payload "
                        "values log as tracer reprs; emit from the host "
                        "loop on the step's returned state (the guard's "
                        "interval-synced monitor is the pattern)",
                    )
