"""divergent-collective: a collective call site under rank-dependent
(or traced-data-dependent) control flow.

PR-history exemplar (PR 2): rank-divergent collective call sites are
what the comm-monitor's flight recorder + desync detection exist to
diagnose — AFTER the pod has already hung (one rank enters the
collective, its peers took the other branch).  The static form moves
that detection before dispatch: a call to any monitored collective
lexically nested under an `if`/`while` whose test reads the process
rank diverges by construction unless every rank takes the same branch.

The op list is cross-checked against the comm-monitor site list
(`distributed/collective.py` wraps exactly these in `_watched` /
`_record_spmd`) by `monitored_ops()` + the test suite, so the rule and
the runtime monitor cannot drift.  `jax.lax` SPMD collectives under
TRACED-value conditionals are the in-graph variant of the same hazard
(each shard resolves the branch independently).
"""
from __future__ import annotations

import ast
import os
import re

from ..astutil import Taint, dotted, terminal
from ..core import Rule, register

# the eager/SPMD comm surface (comm-monitor site list) ...
COLLECTIVES = {
    "all_reduce", "reduce", "all_gather", "broadcast", "reduce_scatter",
    "scatter", "alltoall", "barrier", "monitored_barrier",
}
# ... plus point-to-point and the lax SPMD primitives
P2P = {"send", "recv", "isend", "irecv"}
LAX_COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "ppermute",
                   "psum_scatter"}

_RANK_RE = re.compile(
    r"\b(?:get_rank|local_rank|trainer_id|process_index|"
    r"PADDLE_TRAINER_ID|rank)\b"
)


def monitored_ops(repo_root: str = None):
    """Op names the runtime comm monitor records — parsed from
    distributed/collective.py so the static rule's site list cannot
    drift from the runtime one."""
    if repo_root is None:
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
    path = os.path.join(repo_root, "paddle_tpu", "distributed",
                        "collective.py")
    ops = set()
    if os.path.exists(path):
        with open(path) as fh:
            src = fh.read()
        ops |= set(re.findall(r'_watched\(\s*"(\w+)"', src))
        ops |= set(re.findall(r'_record_spmd\(\s*"(\w+)"', src))
    return ops


def _test_src(test: ast.expr) -> str:
    try:
        return ast.unparse(test)
    except Exception:  # pragma: no cover
        return ""


def _rank_dependent(test: ast.expr) -> bool:
    return bool(_RANK_RE.search(_test_src(test)))


def _jnp_comparison(test: ast.expr, jnp_names) -> bool:
    """A test that compares/reads values assigned from jnp/lax results
    — each shard of an SPMD program resolves it independently."""
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id in jnp_names:
            return True
        if isinstance(node, ast.Attribute):
            d = dotted(node)
            if d.startswith(("jnp.", "lax.", "jax.lax.")):
                return True
    return False


@register
class DivergentCollectiveRule(Rule):
    name = "divergent-collective"
    summary = ("collective call under rank-dependent or traced-data-"
               "dependent control flow")

    def check(self, mod):
        graph = mod.graph()
        parents = graph.parents
        compiled_keys = graph.compiled
        # names assigned from jnp per owning function (for the traced-
        # branch variant); the Taint fixpoint is O(function body), so
        # memoize per owner instead of rebuilding per collective call
        jnp_memo: dict = {}

        def owner_jnp_names(owner):
            if owner in jnp_memo:
                return jnp_memo[owner]
            names = set()
            key = None
            for (cname, fname), info in graph.funcs.items():
                if info.node is owner:
                    key = (cname, fname)
            if key in compiled_keys:
                taint = Taint(owner)
                for n in ast.walk(owner):
                    if isinstance(n, ast.Assign) and \
                            taint.expr_tainted(n.value):
                        for tgt in n.targets:
                            for nn in ast.walk(tgt):
                                if isinstance(nn, ast.Name):
                                    names.add(nn.id)
            jnp_memo[owner] = names
            return names

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            t = terminal(dotted(node.func))
            is_comm = t in COLLECTIVES or t in P2P
            is_lax = t in LAX_COLLECTIVES
            if not (is_comm or is_lax):
                continue
            owner = graph.owner_func(node)
            jnp_names = set()
            if is_lax and owner is not None:
                jnp_names = owner_jnp_names(owner)
            cur = parents.get(node)
            while cur is not None and not isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                test = None
                if isinstance(cur, (ast.If, ast.While, ast.IfExp)):
                    test = cur.test
                if test is not None:
                    if _rank_dependent(test):
                        yield self.finding(
                            mod, node,
                            f"collective `{t}` under rank-dependent "
                            f"control flow (`if {_test_src(test)}`) — "
                            "ranks taking different branches deadlock "
                            "in the collective (the comm monitor can "
                            "only attribute this AFTER the hang); "
                            "hoist the collective out of the branch",
                        )
                        break
                    if is_lax and jnp_names and _jnp_comparison(
                            test, jnp_names):
                        yield self.finding(
                            mod, node,
                            f"lax collective `{t}` under traced-data-"
                            f"dependent control flow "
                            f"(`if {_test_src(test)}`) — shards "
                            "resolve the branch independently; use "
                            "jnp.where / lax.cond over the collective "
                            "result instead",
                        )
                        break
                cur = parents.get(cur)
