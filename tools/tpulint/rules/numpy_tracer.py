"""numpy-on-tracer: host `np.*` math applied to traced values inside a
compiled region.

PR-history exemplar: the fluid-era reference scripts (and early ports of
their op implementations) mix `np.sqrt`/`np.mean` into model math; under
`jit.TrainStep` tracing that either raises a TracerArrayConversionError
or — when the value happens to be concrete — constant-folds a stale
value into the compiled program (the bug class behind the verbatim-
script harness's jnp conversions).

Statically: inside compiled-region functions, flag `np.<math>(x)` calls
whose arguments dataflow from traced values (parameters, jnp results).
`np.float32` / `np.pi` / shape reads stay quiet.
"""
from __future__ import annotations

import ast

from ..astutil import Taint, dotted
from ..core import Rule, register

_NP_MATH = {
    "exp", "log", "log2", "log10", "sqrt", "square", "power", "abs",
    "sum", "mean", "var", "std", "prod", "max", "min", "argmax",
    "argmin", "dot", "matmul", "einsum", "tanh", "sin", "cos", "sign",
    "maximum", "minimum", "where", "clip", "floor", "ceil", "round",
    "cumsum", "cumprod", "reshape", "transpose", "concatenate", "stack",
    "split", "linalg", "add", "subtract", "multiply", "divide",
    "true_divide", "isnan", "isinf", "isfinite", "allclose",
    "array_equal",
}


@register
class NumpyOnTracerRule(Rule):
    name = "numpy-on-tracer"
    summary = "np.* math applied to traced values inside a compiled region"

    def check(self, mod):
        if "np." not in mod.text and "numpy" not in mod.text:
            return
        graph = mod.graph()
        for info in graph.compiled_funcs():
            func = info.node
            taint = Taint(func)
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                if graph.owner_func(node) is not func:
                    continue
                d = dotted(node.func)
                parts = d.split(".")
                if len(parts) < 2 or parts[0] not in ("np", "numpy"):
                    continue
                if parts[-1] not in _NP_MATH:
                    continue
                if not taint.call_arg_tainted(node):
                    continue
                yield self.finding(
                    mod, node,
                    f"{d} on a traced value in compiled body "
                    f"`{func.name}` — host numpy cannot consume "
                    "tracers (TracerArrayConversionError under jit, "
                    "or a stale constant folded into the program); "
                    "use jnp." + parts[-1],
                )
