"""alias-parity: the `import paddle` namespace-parity linter
(tools/check_alias.py), folded into the tpulint entry point.

Unlike every other rule this one IMPORTS the package under lint (it has
to resolve names), which pulls in jax — seconds, not milliseconds.  It
is therefore off by default and enabled with ``--alias`` (or
``PADDLE_LINT_ALIAS=1``); test_hygiene runs it through its own
TestAliasParity gate either way, so the coverage is tier-1 regardless.
"""
from __future__ import annotations

import importlib.util
import os

from ..core import Finding, ProjectRule, register


def _load_check_alias(repo_root):
    path = os.path.join(repo_root, "tools", "check_alias.py")
    spec = importlib.util.spec_from_file_location("check_alias", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@register
class AliasParityRule(ProjectRule):
    name = "alias-parity"
    summary = ("reference names missing from the paddle alias / stale "
               "scope entries / unaliased paddle_tpu exports")
    default_enabled = False  # imports paddle_tpu+jax: --alias opts in

    def check_project(self, paths, repo_root):
        ca = _load_check_alias(repo_root)
        rows, missing, stale = ca.check_reference_coverage()
        unaliased = ca.check_alias_completeness()
        path = "tools/check_alias.py"
        for n in missing:
            yield Finding(rule=self.name, path=path, line=1, col=0,
                          message=f"aliased-but-missing reference "
                                  f"name: {n}")
        for n in stale:
            yield Finding(rule=self.name, path=path, line=1, col=0,
                          message=f"stale out-of-scope entry: {n}")
        for n in unaliased:
            yield Finding(rule=self.name, path=path, line=1, col=0,
                          message=f"paddle_tpu public name with no "
                                  f"paddle alias: {n}")
