"""pallas-in-gspmd: a `pallas_call` reachable from a jit region with no
shard_map seam or mesh-routing guard.

PR-history exemplar (ISSUE 6 tentpole): the round-6 attention router
dispatched the Pallas flash kernel straight into multi-device programs —
a `pallas_call` has no GSPMD partition rule, so the program either died
with an opaque XLA partitioning error or fell back to dense everywhere.
The shipped fix routes every kernel dispatch through a mesh-routing
decision (`_shard_plan` / `shard_factoring` / device-count guards) and
runs the multi-device case through the `shard_map` seam
(ops/pallas/sharded.py).

Statically: within a module, find functions whose bodies call
`pl.pallas_call`; walk the local call graph from every jit/trace root;
flag kernel call sites reached WITHOUT crossing a shard_map boundary
and WITHOUT a mesh guard (an `if` testing device_count / mesh /
shard-plan / routability) on the path or around the call site.
"""
from __future__ import annotations

import ast

from ..astutil import (
    JIT_WRAPPERS, dotted, enclosing, is_wrapper_call, terminal,
)
from ..core import Rule, register

# substrings that make an `if` test a mesh-routing guard
_GUARD_HINTS = (
    "device_count", "devices(", "mesh", "shard_plan", "shard_factoring",
    "routable", "flash_plan", "partitioning_axes", "interpret",
    "backend", "plan",
)


def _is_mesh_guard(test: ast.expr) -> bool:
    try:
        src = ast.unparse(test)
    except Exception:  # pragma: no cover
        return False
    low = src.lower()
    return any(h in low for h in _GUARD_HINTS)


def _has_mesh_guard(func: ast.FunctionDef) -> bool:
    for node in ast.walk(func):
        if isinstance(node, (ast.If, ast.IfExp)) and _is_mesh_guard(
                node.test):
            return True
    return False


def _guarded_at(node, parents) -> bool:
    cur = parents.get(node)
    while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
        if isinstance(cur, (ast.If, ast.IfExp)) and _is_mesh_guard(
                cur.test):
            return True
        cur = parents.get(cur)
    return False


@register
class PallasInGspmdRule(Rule):
    name = "pallas-in-gspmd"
    summary = ("pallas_call reachable from a jit region without a "
               "shard_map seam or mesh-routing guard")

    def check(self, mod):
        if "pallas_call" not in mod.text:
            return
        graph = mod.graph()
        parents = graph.parents

        # functions containing a direct pallas_call, with their call
        # sites (skip sites lexically under a mesh guard)
        kernel_sites = {}
        for key, info in graph.funcs.items():
            sites = []
            for node in ast.walk(info.node):
                if isinstance(node, ast.Call) and terminal(
                        dotted(node.func)) == "pallas_call":
                    if graph.owner_func(node) is not info.node:
                        continue
                    if not _guarded_at(node, parents):
                        sites.append(node)
            if sites:
                kernel_sites[key] = sites
        if not kernel_sites:
            return

        # jit roots only (a trace wrapper like value_and_grad does not
        # by itself make a GSPMD program; jit does)
        roots = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and is_wrapper_call(
                    node, JIT_WRAPPERS):
                for key in graph._callable_refs(
                        node.args[0] if node.args else None, node):
                    roots.add(key)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    d = dec.func if isinstance(dec, ast.Call) else dec
                    if terminal(dotted(d)) in JIT_WRAPPERS:
                        cls = enclosing(node, parents, (ast.ClassDef,))
                        roots.add((cls.name if cls else None, node.name))
        roots = {k for k in roots if k in graph.funcs}
        if not roots:
            return

        # BFS with a 'sanitized' bit: crossing a shard_map boundary or
        # a mesh-guarded reference site, or passing through a function
        # that itself routes on the mesh, stops the hazard
        reached_unguarded = set()
        work = list(roots)
        while work:
            key = work.pop()
            if key in reached_unguarded:
                continue
            reached_unguarded.add(key)
            info = graph.funcs[key]
            if _has_mesh_guard(info.node):
                continue  # this function routes on the mesh: sanitized
            for node in ast.walk(info.node):
                if not (isinstance(node, (ast.Name, ast.Attribute))
                        and isinstance(getattr(node, "ctx", None),
                                       ast.Load)):
                    continue
                tgt = graph.resolve(dotted(node), info.class_name)
                if tgt is None or graph.owner_func(node) is None:
                    continue
                # reference passed into a shard_map call: the target
                # runs per shard — a pallas_call there is the FIX shape
                call = enclosing(node, parents, (ast.Call,))
                crossed_seam = False
                cur = call
                while cur is not None:
                    if isinstance(cur, ast.Call) and is_wrapper_call(
                            cur, {"shard_map"}):
                        crossed_seam = True
                        break
                    cur = enclosing(cur, parents, (ast.Call,))
                if crossed_seam or _guarded_at(node, parents):
                    continue
                if tgt.key not in reached_unguarded:
                    work.append(tgt.key)

        for key, sites in sorted(kernel_sites.items(),
                                 key=lambda kv: (kv[0][0] or "",
                                                 kv[0][1])):
            if key not in reached_unguarded:
                continue
            info = graph.funcs[key]
            if _has_mesh_guard(info.node):
                continue
            for site in sites:
                yield self.finding(
                    mod, site,
                    f"pallas_call in `{key[1]}` is reachable from a "
                    "jit region with no shard_map seam or mesh-routing "
                    "guard — a pallas_call has no GSPMD partition rule "
                    "(route through ops/pallas/sharded.py or guard on "
                    "the mesh)",
                )
