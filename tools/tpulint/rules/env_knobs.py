"""env-knob-docs: every `PADDLE_*` env knob the tree mentions must be
documented in README.md.

Migrated from test_hygiene.TestEnvKnobDocs (the ad-hoc check ISSUE 7
folds into the one static-analysis entry point): undocumented knobs rot
into magic the next operator can't discover.  The scan covers the
`paddle_tpu/` package tree PLUS `tools/` (so the linter's own
`PADDLE_LINT_*` knobs are policed too) and any analyzed paths outside
those trees.
"""
from __future__ import annotations

import os
import re

from ..core import Finding, ProjectRule, register

_KNOB_RE = re.compile(r"PADDLE_[A-Z0-9_]+")


@register
class EnvKnobDocsRule(ProjectRule):
    name = "env-knob-docs"
    summary = "PADDLE_* env knob referenced but not documented in README"

    def _scan_roots(self, paths, repo_root):
        roots = [os.path.join(repo_root, "paddle_tpu"),
                 os.path.join(repo_root, "tools")]
        for p in paths:
            ap = os.path.abspath(p)
            if not any(ap.startswith(os.path.abspath(r))
                       for r in roots):
                roots.append(ap)
        return roots

    def check_project(self, paths, repo_root):
        readme_path = os.path.join(repo_root, "README.md")
        try:
            with open(readme_path, encoding="utf-8") as fh:
                readme = fh.read()
        except OSError:
            yield Finding(rule=self.name, path="README.md", line=1,
                          col=0, message="README.md is unreadable — "
                          "knob documentation cannot be checked")
            return
        first_ref: dict[str, tuple] = {}
        for root in self._scan_roots(paths, repo_root):
            if os.path.isfile(root):
                files = [root] if root.endswith(".py") else []
            else:
                files = []
                for r, dirs, fns in os.walk(root):
                    dirs[:] = [d for d in dirs
                               if d not in ("__pycache__", ".git")]
                    files += [os.path.join(r, fn) for fn in sorted(fns)
                              if fn.endswith(".py")]
            for fp in files:
                try:
                    with open(fp, encoding="utf-8") as fh:
                        text = fh.read()
                except OSError:
                    continue
                rel = os.path.relpath(fp, repo_root).replace(
                    os.sep, "/")
                for i, ln in enumerate(text.splitlines(), start=1):
                    for knob in _KNOB_RE.findall(ln):
                        first_ref.setdefault(knob, (rel, i))
        for knob in sorted(first_ref):
            if knob not in readme:
                rel, line = first_ref[knob]
                yield Finding(
                    rule=self.name, path=rel, line=line, col=0,
                    message=f"env knob {knob} is referenced here but "
                            "not documented in README.md — add a row "
                            "to the knob tables",
                )
