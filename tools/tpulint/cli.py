"""tpulint CLI.

Usage::

    python -m tools.tpulint [paths...] [options]

With no paths: lints `paddle_tpu/` and `tests/reference_scripts/`.

Exit codes: 0 = clean (every finding suppressed or baselined),
1 = new findings (or stale baseline entries), 2 = usage/baseline error.

Knobs: ``PADDLE_LINT_BASELINE`` overrides the baseline path,
``PADDLE_LINT_DISABLE`` skips rules (comma-separated),
``PADDLE_LINT_ALIAS=1`` enables the import-time alias-parity rule.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

from . import rules  # noqa: F401  (registers every rule)
from .core import (
    BaselineError, REGISTRY, apply_baseline, collect_files,
    default_baseline_path, disabled_rules, load_baseline, repo_root,
    run, write_baseline,
)


def _parser():
    ap = argparse.ArgumentParser(
        prog="tools.tpulint",
        description="trace/shard/donation static analysis over the "
                    "compiled-step surface",
    )
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: paddle_tpu "
                         "tests/reference_scripts)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default tools/tpulint/"
                         "baseline.json; PADDLE_LINT_BASELINE wins)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="park current findings in the baseline "
                         "(existing notes preserved; new entries get "
                         "a TODO(triage) note you must replace)")
    ap.add_argument("--alias", action="store_true",
                    help="also run the alias-parity rule (imports "
                         "paddle_tpu + jax: slow)")
    ap.add_argument("--rule", action="append", default=None,
                    help="run only this rule (repeatable)")
    ap.add_argument("--list-rules", action="store_true",
                    help="list registered rules and exit")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed/baselined findings")
    return ap


def main(argv=None):
    args = _parser().parse_args(argv)
    if args.list_rules:
        for name in sorted(REGISTRY):
            print(f"{name:22s} {REGISTRY[name].summary}")
        return 0
    root = repo_root()
    paths = args.paths or [
        os.path.join(root, "paddle_tpu"),
        os.path.join(root, "tests", "reference_scripts"),
    ]
    for p in paths:
        if not os.path.exists(p):
            print(f"tpulint: no such path: {p}", file=sys.stderr)
            return 2
    alias_on = args.alias or os.environ.get(
        "PADDLE_LINT_ALIAS", "").strip() in ("1", "true", "on")
    if args.write_baseline:
        # a filtered run sees only a slice of the findings; overwriting
        # the baseline from it would silently drop every other entry
        # (and its curated tracking note)
        if args.rule or disabled_rules():
            print("tpulint: refusing --write-baseline on a rule-"
                  "filtered run (--rule / PADDLE_LINT_DISABLE) — the "
                  "unfiltered rules' baseline entries would be "
                  "dropped; run without the filter", file=sys.stderr)
            return 2
        if args.no_baseline:
            print("tpulint: --no-baseline contradicts --write-baseline"
                  " (existing tracking notes would be reset to "
                  "TODO(triage))", file=sys.stderr)
            return 2
    t0 = time.monotonic()
    findings, errors = run(
        paths, rules=set(args.rule) if args.rule else None,
        enable_alias=alias_on, root=root,
    )
    bl_path = args.baseline or default_baseline_path()
    baseline = {}
    if not args.no_baseline:
        try:
            baseline = load_baseline(bl_path)
        except BaselineError as e:
            print(f"tpulint: {e}", file=sys.stderr)
            return 2
    if args.write_baseline:
        swept = {
            os.path.relpath(os.path.abspath(fp), root).replace(
                os.sep, "/")
            for fp in collect_files(paths)
        }
        baseline = write_baseline(bl_path, findings, baseline,
                                  swept_paths=swept)
        print(f"tpulint: wrote {len(baseline)} entr"
              f"{'y' if len(baseline) == 1 else 'ies'} to {bl_path}")
    new, stale = apply_baseline(findings, baseline)
    dt = time.monotonic() - t0

    if args.json:
        print(json.dumps({
            "version": 1,
            "elapsed_s": round(dt, 3),
            "findings": [f.as_dict() for f in findings],
            "new": [f.fingerprint for f in new],
            "stale_baseline": stale,
            "errors": errors,
        }, indent=2))
    else:
        shown = findings if args.show_suppressed else new
        for f in shown:
            print(f.render())
        for e in errors:
            print(f"ERROR {e}")
        for e in stale:
            print(f"STALE-BASELINE {e['rule']}@{e['path']} "
                  f"({e['fingerprint']}): finding no longer fires — "
                  f"drop the entry (note: {e['note']})")
        n_sup = sum(f.suppressed for f in findings)
        n_bl = sum(f.baselined for f in findings)
        print(f"tpulint: {len(findings)} finding"
              f"{'' if len(findings) == 1 else 's'} "
              f"({len(new)} new, {n_bl} baselined, {n_sup} suppressed"
              f"), {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'}, "
              f"{len(errors)} errors in {dt:.2f}s")
    if errors or new or stale:
        return 1
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
