"""Shared AST machinery for tpulint rules.

Three facilities every trace/shard rule needs:

* **qualified names** — ``dotted(node)`` renders ``jax.lax.psum`` /
  ``self._step_fn`` / ``np.asarray`` call targets as dotted strings so
  rules can match on suffixes without resolving imports.
* **compiled-region call graph** — which functions in a module execute
  under ``jax.jit`` / ``shard_map`` / grad tracing?  Roots are functions
  referenced by a jit/trace wrapper call (or decorator), plus step-body
  methods of ``*Step`` classes; membership propagates through
  module-local references (direct calls, names passed as arguments,
  ``functools.partial`` targets, lambda bodies).
* **taint** — a per-function fixpoint over assignments marking names
  that (conservatively) dataflow from traced values: parameters and
  anything derived from ``jnp``/``jax.lax`` results.  Shape/dtype reads
  sanitize (``x.shape`` is static under trace).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

# wrappers whose function argument runs inside a compiled program
JIT_WRAPPERS = {"jit", "pjit"}              # jax.jit, jax.pjit, bare jit
TRACE_WRAPPERS = {
    "grad", "value_and_grad", "checkpoint", "remat", "vmap", "pmap",
    "make_jaxpr", "custom_vjp", "custom_jvp", "scan", "while_loop",
    "fori_loop", "cond", "switch",
}
SHARD_WRAPPERS = {"shard_map"}              # any *.shard_map / _shard_map


def dotted(node) -> str:
    """Render a Name/Attribute chain as a dotted string ('' if not)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        # functools.partial(f, ...)(...) — render the inner target
        inner = dotted(node.func)
        if inner:
            parts.append(f"{inner}(...)")
    else:
        return ""
    return ".".join(reversed(parts))


def terminal(name: str) -> str:
    return name.rsplit(".", 1)[-1] if name else ""


def is_wrapper_call(call: ast.Call, kinds: Set[str]) -> bool:
    t = terminal(dotted(call.func))
    if t in kinds:
        return True
    # local aliases like `_shard_map` wrapping comm.shard_map
    return any(t.endswith(k) for k in kinds if k == "shard_map")


def parent_map(tree) -> Dict[ast.AST, ast.AST]:
    parents = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def enclosing(node, parents, types):
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, types):
            return cur
        cur = parents.get(cur)
    return None


class FuncInfo:
    def __init__(self, node, class_name: Optional[str]):
        self.node = node
        self.class_name = class_name

    @property
    def key(self):
        return (self.class_name, self.node.name)


class ModuleGraph:
    """Module-local function index + compiled-region membership."""

    def __init__(self, tree: ast.AST):
        self.tree = tree
        self.parents = parent_map(tree)
        # (class_name|None, func_name) -> FuncInfo ; module-level lambda
        # bodies belong to their enclosing def.
        self.funcs: Dict[tuple, FuncInfo] = {}
        self._index()
        self.compiled: Set[tuple] = set()
        self._mark_compiled()

    # -- indexing ----------------------------------------------------------
    def _index(self):
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cls = enclosing(node, self.parents, (ast.ClassDef,))
                cname = cls.name if cls is not None else None
                self.funcs[(cname, node.name)] = FuncInfo(node, cname)

    def owner_func(self, node):
        """The FunctionDef whose body lexically contains `node`."""
        return enclosing(
            node, self.parents, (ast.FunctionDef, ast.AsyncFunctionDef)
        )

    def resolve(self, ref: str, from_class: Optional[str]):
        """Resolve a dotted reference to a module-local FuncInfo."""
        if not ref:
            return None
        if ref.startswith("self.") and from_class:
            return self.funcs.get((from_class, ref[5:]))
        t = terminal(ref)
        # bare module-level function
        if "." not in ref:
            return self.funcs.get((None, ref))
        # Class.method (rare) — try any class with that method
        for (cname, fname), info in self.funcs.items():
            if fname == t and cname is not None and ref.startswith(
                    cname + "."):
                return info
        return None

    # -- compiled-region marking ------------------------------------------
    def _func_refs(self, func: ast.FunctionDef) -> List[str]:
        """Dotted references loaded inside `func` (calls, args passed
        to calls, partial targets) that might name local functions.
        Lambda bodies count as part of the enclosing function."""
        refs = []
        for node in ast.walk(func):
            if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                    getattr(node, "ctx", None), ast.Load):
                d = dotted(node)
                if d:
                    refs.append(d)
        return refs

    def _wrapper_targets(self):
        """Functions referenced as the traced argument of a jit/trace/
        shard wrapper call anywhere in the module (including inside
        lambdas) plus jit-decorated defs."""
        targets = []
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) and is_wrapper_call(
                    node, JIT_WRAPPERS | TRACE_WRAPPERS | SHARD_WRAPPERS):
                for arg in node.args[:1] or []:
                    targets.extend(self._callable_refs(arg, node))
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    d = dec.func if isinstance(dec, ast.Call) else dec
                    t = terminal(dotted(d))
                    if t in JIT_WRAPPERS | TRACE_WRAPPERS:
                        cls = enclosing(node, self.parents,
                                        (ast.ClassDef,))
                        targets.append(
                            ((cls.name if cls else None), node.name))
                    # @functools.partial(jax.jit, ...) /
                    # @functools.partial(jax.custom_vjp, ...)
                    if isinstance(dec, ast.Call) and t == "partial" \
                            and dec.args:
                        t2 = terminal(dotted(dec.args[0]))
                        if t2 in JIT_WRAPPERS | TRACE_WRAPPERS:
                            cls = enclosing(node, self.parents,
                                            (ast.ClassDef,))
                            targets.append(
                                ((cls.name if cls else None), node.name))
        return targets

    def _callable_refs(self, arg, call_node):
        """Resolve a wrapper's traced argument to local function keys:
        a Name/Attribute reference, a functools.partial target, or the
        local functions a Lambda body references."""
        out = []
        ctx_fn = self.owner_func(call_node)
        ctx_cls = None
        if ctx_fn is not None:
            cls = enclosing(ctx_fn, self.parents, (ast.ClassDef,))
            ctx_cls = cls.name if cls else None
        def resolve_ref(d):
            info = self.resolve(d, ctx_cls)
            if info is not None:
                out.append(info.key)
        if isinstance(arg, (ast.Name, ast.Attribute)):
            resolve_ref(dotted(arg))
        elif isinstance(arg, ast.Call) and terminal(
                dotted(arg.func)) == "partial" and arg.args:
            resolve_ref(dotted(arg.args[0]))
        elif isinstance(arg, ast.Lambda):
            for node in ast.walk(arg):
                if isinstance(node, (ast.Name, ast.Attribute)) and \
                        isinstance(getattr(node, "ctx", None), ast.Load):
                    resolve_ref(dotted(node))
        return out

    def _mark_compiled(self):
        roots = set(self._wrapper_targets())
        # step-body methods of *Step classes are compiled by contract
        # even when the jax.jit call lives in another module — this is
        # the list that covers TrainStep/LocalSGDStep AND the serving
        # DecodeStep/PrefillStep (ISSUE 9): host-sync/donation/numpy
        # rules police the decode path through the same suffix match
        for (cname, fname), info in self.funcs.items():
            if cname and cname.endswith("Step") and fname in (
                    "_step_fn", "step_fn", "_worker"):
                roots.add((cname, fname))
        work = [k for k in roots if k in self.funcs]
        self.compiled = set(work)
        while work:
            key = work.pop()
            info = self.funcs.get(key)
            if info is None:
                continue
            for ref in self._func_refs(info.node):
                tgt = self.resolve(ref, info.class_name)
                if tgt is not None and tgt.key not in self.compiled:
                    self.compiled.add(tgt.key)
                    work.append(tgt.key)

    def compiled_funcs(self):
        return [self.funcs[k] for k in sorted(
            self.compiled, key=lambda k: (k[0] or "", k[1])
        ) if k in self.funcs]


# --------------------------------------------------------------------------
# taint
# --------------------------------------------------------------------------

_TRACED_MODULES = ("jnp", "lax", "jax")
_SANITIZE_ATTRS = {"shape", "ndim", "dtype", "size", "__name__"}


def _expr_names(expr) -> Set[str]:
    out = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Name):
            out.add(node.id)
    return out


class Taint:
    """Conservative intra-function dataflow from traced values.

    Seeds: function parameters (minus self/cls and ``*Spec``-ish config
    names), plus anything assigned from an expression that calls
    ``jnp.*`` / ``jax.lax.*`` or reads a tainted name.  ``x.shape`` /
    ``x.dtype`` / ``len(...)`` reads are static under trace and do NOT
    propagate."""

    def __init__(self, func: ast.FunctionDef):
        self.func = func
        self.names: Set[str] = set()
        args = func.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs +
                  ([args.vararg] if args.vararg else []) +
                  ([args.kwarg] if args.kwarg else [])):
            if a.arg not in ("self", "cls"):
                self.names.add(a.arg)
        self._fixpoint()

    def _fixpoint(self):
        for _ in range(10):
            grew = False
            for node in ast.walk(self.func):
                if isinstance(node, ast.Assign):
                    if self.expr_tainted(node.value):
                        for tgt in node.targets:
                            for n in _expr_names(tgt):
                                if n not in self.names:
                                    self.names.add(n)
                                    grew = True
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    val = getattr(node, "value", None)
                    if val is not None and self.expr_tainted(val):
                        for n in _expr_names(node.target):
                            if n not in self.names:
                                self.names.add(n)
                                grew = True
                elif isinstance(node, ast.For):
                    if self.expr_tainted(node.iter):
                        for n in _expr_names(node.target):
                            if n not in self.names:
                                self.names.add(n)
                                grew = True
            if not grew:
                return

    def expr_tainted(self, expr) -> bool:
        if expr is None:
            return False
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute):
                if node.attr in _SANITIZE_ATTRS:
                    # static metadata read — does not carry taint, and
                    # shields its base from the Name check below
                    continue
                d = dotted(node)
                root = d.split(".", 1)[0] if d else ""
                if root in _TRACED_MODULES:
                    return True
            if isinstance(node, ast.Name) and node.id in self.names:
                # bare-name taint; sanitized shapes like int(x.shape[i])
                # are stripped by `call_arg_tainted` where it matters
                return True
        return False

    def call_arg_tainted(self, call: ast.Call) -> bool:
        """Is any argument of `call` tainted, AFTER stripping sanitized
        sub-expressions (shape/dtype/len reads)?"""
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if self._tainted_sans_sanitizers(arg):
                return True
        return False

    def _tainted_sans_sanitizers(self, expr) -> bool:
        if isinstance(expr, ast.Attribute) and \
                expr.attr in _SANITIZE_ATTRS:
            return False
        if isinstance(expr, ast.Subscript):
            return self._tainted_sans_sanitizers(expr.value)
        if isinstance(expr, ast.Call):
            t = terminal(dotted(expr.func))
            if t in ("len", "int", "range"):
                return False
            return any(self._tainted_sans_sanitizers(a)
                       for a in expr.args)
        if isinstance(expr, ast.Name):
            return expr.id in self.names
        if isinstance(expr, ast.BinOp):
            return (self._tainted_sans_sanitizers(expr.left)
                    or self._tainted_sans_sanitizers(expr.right))
        if isinstance(expr, ast.UnaryOp):
            return self._tainted_sans_sanitizers(expr.operand)
        for node in ast.iter_child_nodes(expr):
            if isinstance(node, ast.expr) and \
                    self._tainted_sans_sanitizers(node):
                return True
        return False
