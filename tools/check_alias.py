#!/usr/bin/env python
"""Namespace-parity linter for the `paddle` alias package.

Three checks keep the `import paddle` compatibility subsystem honest:

1. **Reference coverage** — every name in the reference public namespace
   (per-module manifests below; extended by walking
   `/root/reference/python/paddle` via ast when that tree is present)
   must be importable from the aliased `paddle.*` module, or carry an
   explicit OUT_OF_SCOPE entry with a reason. A name that is neither is
   *aliased-but-missing* and fails the lint.

2. **Alias completeness (inverse)** — every public name a `paddle_tpu`
   module exports must be reachable under the same path through
   `paddle.*`. Module-identity aliasing makes this structural for
   submodules; the check guards the two hand-maintained seams (the
   top-level globals copy and the fluid tree) against drift.

3. **Out-of-scope hygiene** — OUT_OF_SCOPE entries must actually be
   missing; an entry for a name that now exists is stale and fails, so
   the scope list can only shrink.

Exit 0 = zero missing + zero stale. `--verbose` lists names per module.

Usage:  python tools/check_alias.py [--verbose] [--module paddle.nn]
"""
from __future__ import annotations

import argparse
import ast
import importlib
import os
import sys

# runnable from anywhere: the repo root (where paddle/ and paddle_tpu/
# live) is this file's parent's parent
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

REFERENCE_ROOT = "/root/reference/python/paddle"

# --------------------------------------------------------------------------
# Reference manifests: the public names a stock script can touch, per
# module — curated from the reference tree (python/paddle/...) the repo
# reproduces. Bias is toward TRAINING-SCRIPT surface: what book/model
# scripts import, not private plumbing.
# --------------------------------------------------------------------------
REFERENCE_MANIFEST: dict[str, tuple[str, ...]] = {
    "paddle": (
        "Tensor", "ParamAttr", "CPUPlace", "CUDAPlace",
        "to_tensor", "save", "load", "seed", "set_device", "get_device",
        "is_compiled_with_cuda", "no_grad", "grad", "set_default_dtype",
        "get_default_dtype", "enable_static", "disable_static",
        "in_dynamic_mode", "batch", "DataParallel", "Model", "summary",
        "flops", "set_grad_enabled", "is_grad_enabled", "is_tensor",
        "get_flags", "set_flags",
        # flat tensor namespace (spot list — the full op surface is
        # checked via the paddle_tpu inverse walk)
        "abs", "add", "arange", "argmax", "argmin", "argsort", "assign",
        "cast", "ceil", "clip", "concat", "cos", "cumsum", "divide",
        "equal", "exp", "expand", "flatten", "floor", "full",
        "full_like", "gather", "linspace", "log", "matmul", "max",
        "maximum", "mean", "min", "minimum", "multiply", "nonzero",
        "normal", "ones", "ones_like", "pow", "prod", "rand", "randint",
        "randn", "reshape", "round", "rsqrt", "scatter", "sign", "sin",
        "slice", "sort", "split", "sqrt", "square", "squeeze", "stack",
        "subtract", "sum", "tanh", "tile", "topk", "transpose", "tril",
        "triu", "unique", "unsqueeze", "where", "zeros", "zeros_like",
        # subpackages reachable as attributes
        "nn", "optimizer", "static", "io", "vision", "metric", "amp",
        "jit", "distributed", "distribution", "device", "text",
        "dataset", "tensor", "fluid", "regularizer", "sysconfig",
        "onnx", "inference", "incubate", "hapi", "utils", "reader",
        "profiler",
    ),
    "paddle.nn": (
        "Layer", "LayerList", "Sequential", "ParameterList", "ParamAttr",
        "Linear", "Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose",
        "Conv2DTranspose", "Conv3DTranspose", "Embedding",
        "BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
        "LayerNorm", "GroupNorm", "InstanceNorm2D", "SyncBatchNorm",
        "Dropout", "Dropout2D", "ReLU", "ReLU6", "GELU", "Sigmoid",
        "Softmax", "Tanh", "LeakyReLU", "PReLU", "Hardswish", "Silu",
        "MaxPool1D", "MaxPool2D", "AvgPool1D", "AvgPool2D",
        "AdaptiveAvgPool1D", "AdaptiveAvgPool2D", "AdaptiveMaxPool2D",
        "Pad1D", "Pad2D", "Flatten", "Upsample", "PixelShuffle",
        "RNN", "LSTM", "GRU", "SimpleRNN", "LSTMCell", "GRUCell",
        "SimpleRNNCell", "MultiHeadAttention", "Transformer",
        "TransformerEncoder", "TransformerEncoderLayer",
        "TransformerDecoder", "TransformerDecoderLayer",
        "CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCELoss",
        "BCEWithLogitsLoss", "KLDivLoss", "SmoothL1Loss", "CTCLoss",
        "MarginRankingLoss", "CosineSimilarity", "PairwiseDistance",
        "ClipGradByGlobalNorm", "ClipGradByNorm", "ClipGradByValue",
        "functional", "initializer",
    ),
    "paddle.nn.functional": (
        "relu", "relu6", "gelu", "sigmoid", "tanh", "softmax",
        "log_softmax", "leaky_relu", "prelu", "elu", "selu", "silu",
        "swish", "mish", "hardswish", "hardsigmoid", "hardtanh", "glu",
        "softplus", "softsign", "tanhshrink", "hardshrink", "softshrink",
        "maxout", "conv1d", "conv2d", "conv3d", "conv2d_transpose",
        "linear", "embedding", "one_hot", "dropout", "pad",
        "max_pool1d", "max_pool2d", "avg_pool1d", "avg_pool2d",
        "adaptive_avg_pool2d", "adaptive_max_pool2d", "interpolate",
        "upsample", "pixel_shuffle", "batch_norm", "layer_norm",
        "group_norm", "instance_norm", "normalize", "cross_entropy",
        "softmax_with_cross_entropy", "binary_cross_entropy",
        "binary_cross_entropy_with_logits", "mse_loss", "l1_loss",
        "nll_loss", "kl_div", "smooth_l1_loss", "ctc_loss",
        "square_error_cost", "margin_ranking_loss", "cosine_similarity",
        "sigmoid_focal_loss", "log_loss", "unfold", "grid_sample",
        "affine_grid", "label_smooth", "temporal_shift",
    ),
    "paddle.optimizer": (
        "Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax",
        "Adagrad", "Adadelta", "RMSProp", "Lamb", "lr",
    ),
    "paddle.optimizer.lr": (
        "LRScheduler", "NoamDecay", "ExponentialDecay", "NaturalExpDecay",
        "InverseTimeDecay", "PolynomialDecay", "LinearWarmup",
        "PiecewiseDecay", "CosineAnnealingDecay", "StepDecay",
        "MultiStepDecay", "LambdaDecay", "ReduceOnPlateau",
    ),
    "paddle.static": (
        "Program", "Variable", "data", "Executor", "CompiledProgram",
        "default_main_program", "default_startup_program",
        "program_guard", "global_scope", "nn",
    ),
    "paddle.static.nn": (
        "fc", "conv2d", "conv2d_transpose", "conv3d", "batch_norm",
        "embedding", "layer_norm", "group_norm", "instance_norm",
        "prelu", "deform_conv2d", "create_parameter",
    ),
    "paddle.io": (
        "Dataset", "IterableDataset", "TensorDataset", "ChainDataset",
        "ComposeDataset", "ConcatDataset", "Subset", "random_split",
        "Sampler", "SequenceSampler", "RandomSampler", "BatchSampler",
        "DistributedBatchSampler", "WeightedRandomSampler",
        "SubsetRandomSampler", "DataLoader",
    ),
    "paddle.metric": (
        "Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy",
    ),
    "paddle.amp": (
        "auto_cast", "GradScaler", "decorate",
    ),
    "paddle.jit": (
        "to_static", "save", "load", "TranslatedLayer", "not_to_static",
    ),
    "paddle.distributed": (
        "init_parallel_env", "get_rank", "get_world_size", "all_reduce",
        "all_gather", "broadcast", "reduce", "scatter", "barrier",
        "split", "spawn", "launch", "ReduceOp", "fleet", "new_group",
        "send", "recv", "reduce_scatter", "alltoall", "wait",
    ),
    "paddle.distributed.fleet": (
        "init", "DistributedStrategy", "UserDefinedRoleMaker",
        "PaddleCloudRoleMaker", "worker_index", "worker_num",
        "is_first_worker", "worker_endpoints", "barrier_worker",
        "distributed_model", "distributed_optimizer",
    ),
    "paddle.vision": ("datasets", "models", "transforms", "ops"),
    "paddle.vision.datasets": (
        "MNIST", "FashionMNIST", "Cifar10", "Cifar100",
    ),
    "paddle.vision.models": (
        "LeNet", "ResNet", "resnet18", "resnet34", "resnet50",
        "resnet101", "resnet152", "VGG", "vgg16", "vgg19", "MobileNetV1",
        "MobileNetV2",
    ),
    "paddle.vision.transforms": (
        "Compose", "Resize", "RandomCrop", "CenterCrop",
        "RandomHorizontalFlip", "RandomVerticalFlip", "Normalize",
        "Transpose", "ToTensor", "BrightnessTransform",
        "ContrastTransform", "SaturationTransform", "HueTransform",
        "ColorJitter", "Pad", "RandomRotation", "Grayscale",
    ),
    "paddle.vision.ops": (
        "yolo_box", "yolo_loss", "prior_box", "box_coder", "roi_align",
        "roi_pool", "nms", "deform_conv2d", "DeformConv2D",
    ),
    "paddle.dataset": (
        "uci_housing", "mnist", "cifar", "imdb", "imikolov", "movielens",
        "conll05", "wmt14", "wmt16",
    ),
    "paddle.text": ("datasets",),
    "paddle.device": (
        "set_device", "get_device", "is_compiled_with_cuda",
    ),
    "paddle.distribution": (
        "Distribution", "Normal", "Uniform", "Categorical",
    ),
    "paddle.regularizer": ("L1Decay", "L2Decay"),
    "paddle.sysconfig": ("get_include", "get_lib"),
    # ---- fluid-era tree --------------------------------------------------
    "paddle.fluid": (
        "CPUPlace", "CUDAPlace", "CUDAPinnedPlace", "Executor", "Program",
        "Variable", "CompiledProgram", "default_main_program",
        "default_startup_program", "program_guard", "global_scope",
        "scope_guard", "DataFeeder", "ParamAttr", "WeightNormParamAttr",
        "data", "embedding", "one_hot", "is_compiled_with_cuda",
        "in_dygraph_mode", "enable_dygraph", "disable_dygraph",
        "name_scope", "cpu_places", "cuda_places", "require_version",
        "get_flags", "set_flags", "layers", "nets", "dygraph",
        "optimizer", "initializer", "regularizer", "io", "backward",
        "framework", "executor", "core", "unique_name", "param_attr",
        "LoDTensor", "create_lod_tensor",
    ),
    "paddle.fluid.layers": (
        "data", "fc", "conv2d", "conv2d_transpose", "conv3d", "pool2d",
        "batch_norm", "layer_norm", "embedding", "cross_entropy",
        "softmax_with_cross_entropy", "sigmoid_cross_entropy_with_logits",
        "square_error_cost", "accuracy", "mean", "mul", "dropout",
        "relu", "sigmoid", "tanh", "softmax", "concat", "reshape",
        "transpose", "cast", "fill_constant", "assign", "shape",
        "reduce_mean", "reduce_sum", "reduce_max", "reduce_min",
        "reduce_prod", "elementwise_add", "elementwise_sub",
        "elementwise_mul", "elementwise_div", "elementwise_max",
        "elementwise_min", "elementwise_pow", "one_hot", "topk",
        "argmax", "argsort", "squeeze", "unsqueeze", "uniform_random",
        "gaussian_random", "clip", "log", "exp", "sqrt", "abs", "pow",
        "stack", "split", "expand", "gather", "scatter", "slice",
        "zeros", "ones", "zeros_like", "ones_like", "Print",
        "create_parameter", "sequence_conv", "sequence_pool",
        "sequence_softmax", "sequence_reshape", "sequence_expand",
        "sequence_expand_as", "sequence_reverse", "sequence_enumerate",
        "sequence_concat", "sequence_slice", "sequence_scatter",
        "sequence_pad", "sequence_unpad", "sequence_mask",
        "sequence_first_step", "sequence_last_step",
        "lod_reset", "While", "IfElse", "Switch", "increment",
        "array_write", "array_read", "create_array", "less_than",
        "equal", "lstm", "gru_unit", "dynamic_lstm", "dynamic_gru",
        "beam_search", "beam_search_decode", "ctc_greedy_decoder",
        "im2sequence", "crf_decoding", "linear_chain_crf",
    ),
    "paddle.fluid.dygraph": (
        "guard", "enabled", "enable_dygraph", "disable_dygraph",
        "to_variable", "Layer", "LayerList", "Sequential",
        "ParameterList", "Linear", "Conv2D", "Pool2D", "BatchNorm",
        "Embedding", "no_grad", "save_dygraph", "load_dygraph",
        "DataParallel", "prepare_context", "TracedLayer", "GRUUnit",
        "NCE", "PRelu", "BilinearTensorProduct", "GroupNorm",
        "SpectralNorm", "TreeConv",
    ),
    "paddle.fluid.optimizer": (
        "SGD", "SGDOptimizer", "Momentum", "MomentumOptimizer",
        "Adagrad", "AdagradOptimizer", "Adam", "AdamOptimizer",
        "Adamax", "AdamaxOptimizer", "Adadelta", "AdadeltaOptimizer",
        "RMSProp", "RMSPropOptimizer", "Lamb", "LambOptimizer",
        "LarsMomentum", "LarsMomentumOptimizer",
        "ExponentialMovingAverage", "LookaheadOptimizer", "ModelAverage",
        "DGCMomentumOptimizer", "PipelineOptimizer",
        "RecomputeOptimizer",
    ),
    "paddle.fluid.initializer": (
        "Constant", "Uniform", "Normal", "TruncatedNormal", "Xavier",
        "MSRA", "Bilinear", "Assign", "NumpyArrayInitializer",
        "ConstantInitializer", "UniformInitializer", "NormalInitializer",
        "TruncatedNormalInitializer", "XavierInitializer",
        "MSRAInitializer",
    ),
    "paddle.fluid.regularizer": (
        "L1Decay", "L2Decay", "L1DecayRegularizer", "L2DecayRegularizer",
    ),
    "paddle.fluid.io": (
        "DataLoader", "batch", "save", "load", "save_params",
        "load_params", "save_persistables", "load_persistables",
        "save_inference_model", "load_inference_model",
    ),
    "paddle.fluid.nets": (
        "simple_img_conv_pool", "img_conv_group", "sequence_conv_pool",
        "glu", "scaled_dot_product_attention",
    ),
    "paddle.fluid.executor": ("Executor", "global_scope", "scope_guard"),
    "paddle.fluid.framework": (
        "Program", "Variable", "default_main_program",
        "default_startup_program", "program_guard", "in_dygraph_mode",
        "cpu_places", "cuda_places", "name_scope",
    ),
    "paddle.fluid.param_attr": ("ParamAttr", "WeightNormParamAttr"),
    "paddle.fluid.unique_name": ("generate", "switch", "guard"),
    "paddle.fluid.backward": ("append_backward",),
    "paddle.fluid.core": (
        "CPUPlace", "CUDAPlace", "CUDAPinnedPlace",
        "is_compiled_with_cuda", "get_cuda_device_count", "Scope",
        "LoDTensor",
    ),
}

# --------------------------------------------------------------------------
# Intentionally out of scope: reference names this TPU-native design does
# not alias, each with the reason. The lint fails if an entry GROWS
# coverage (name now exists — stale entry) so this list only shrinks.
# --------------------------------------------------------------------------
_LOD = ("LoD/ragged runtime type: the dense+lengths policy replaces LoD "
        "tensors (ops/sequence.py module docstring)")
_PS = "parameter-server / ASGD training mode: out of the TPU collective scope"
_RNN_OP = ("fused CPU/CUDA RNN op: use paddle.nn.LSTM/GRU (XLA scan "
           "lowering) instead of the fluid op spelling")
_DECODE = ("dynamic-width decode op over LoD outputs: TPU decoding is the "
           "static-shape jit path; not aliased")
_CRF = "linear-chain CRF family: no consumer config in scope (VERDICT r5)"
_INFER_FMT = ("fluid inference-model format (ProgramDesc protobuf): the "
              "deployment artifact here is StableHLO via paddle.jit.save")
_DYGRAPH_RARE = ("fluid-only dygraph layer with no consumer in the covered "
                 "configs; 2.x spelling exists under paddle.nn")

OUT_OF_SCOPE: dict[str, str] = {
    "paddle.fluid.LoDTensor": _LOD,
    "paddle.fluid.create_lod_tensor": _LOD,
    "paddle.fluid.core.LoDTensor": _LOD,
    "paddle.fluid.layers.lod_reset": _LOD,
    "paddle.fluid.layers.im2sequence": _LOD,
    "paddle.fluid.layers.While": (
        "program-desc control flow: control flow lowers to lax ops inside "
        "the traced program (static/program.py docstring); use python "
        "loops over steps or paddle.jit"
    ),
    "paddle.fluid.layers.IfElse": "see While: lax.cond via paddle.jit",
    "paddle.fluid.layers.Switch": "see While: lax.switch via paddle.jit",
    "paddle.fluid.layers.lstm": _RNN_OP,
    "paddle.fluid.layers.gru_unit": _RNN_OP,
    "paddle.fluid.layers.dynamic_lstm": _RNN_OP,
    "paddle.fluid.layers.dynamic_gru": _RNN_OP,
    "paddle.fluid.layers.beam_search": _DECODE,
    "paddle.fluid.layers.beam_search_decode": _DECODE,
    "paddle.fluid.layers.ctc_greedy_decoder": _DECODE,
    "paddle.fluid.layers.crf_decoding": _CRF,
    "paddle.fluid.layers.linear_chain_crf": _CRF,
    "paddle.fluid.optimizer.DGCMomentumOptimizer": (
        "deep gradient compression rides NCCL allreduce internals; the "
        "strategy flag raises the same way (fleet/base.py dgc)"
    ),
    "paddle.fluid.optimizer.PipelineOptimizer": (
        "1.x program-splitting pipeline: pipeline parallelism lives in "
        "paddle.distributed pipeline stages here"
    ),
    "paddle.fluid.optimizer.RecomputeOptimizer": (
        "2.x spelling exists: paddle.distributed.fleet "
        "DistributedStrategy.recompute / jit recompute"
    ),
    "paddle.fluid.dygraph.GRUUnit": _DYGRAPH_RARE,
    "paddle.fluid.dygraph.NCE": _DYGRAPH_RARE,
    "paddle.fluid.dygraph.PRelu": _DYGRAPH_RARE,
    "paddle.fluid.dygraph.BilinearTensorProduct": _DYGRAPH_RARE,
    "paddle.fluid.dygraph.GroupNorm": _DYGRAPH_RARE,
    "paddle.fluid.dygraph.SpectralNorm": _DYGRAPH_RARE,
    "paddle.fluid.dygraph.TreeConv": _DYGRAPH_RARE,
    "paddle.distributed.fleet.UserDefinedRoleMaker": _PS,
    "paddle.distributed.fleet.PaddleCloudRoleMaker": _PS,
    "paddle.distributed.send": (
        "point-to-point send has no analog in the single-controller SPMD "
        "model: inter-stage transfer is collective permute inside the "
        "compiled program (distributed/pipeline.py)"
    ),
    "paddle.distributed.recv": "see paddle.distributed.send",
}

# paddle_tpu-only public modules that have no reference counterpart to
# lint against (TPU-native additions) — skipped by the inverse walk
_INVERSE_SKIP_PREFIXES = (
    "paddle_tpu.native", "paddle_tpu.ops.pallas", "paddle_tpu.core",
    "paddle_tpu.framework", "paddle_tpu.batch",
)


def _public_names(mod) -> set:
    names = getattr(mod, "__all__", None)
    if names is None:
        names = [n for n in dir(mod) if not n.startswith("_")]
    return set(names)


def _walk_reference(root: str) -> dict[str, set]:
    """Extend manifests by parsing __all__ from the reference tree's
    __init__.py files (ast only — the reference is not importable here)."""
    found: dict[str, set] = {}
    for dirpath, _dirnames, filenames in os.walk(root):
        if "__init__.py" not in filenames:
            continue
        rel = os.path.relpath(dirpath, os.path.dirname(root))
        modname = rel.replace(os.sep, ".")
        if modname not in REFERENCE_MANIFEST:
            continue  # lint only the curated module set
        try:
            tree = ast.parse(
                open(os.path.join(dirpath, "__init__.py")).read()
            )
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.Assign)
                    and any(getattr(t, "id", "") == "__all__"
                            for t in node.targets)
                    and isinstance(node.value, (ast.List, ast.Tuple))):
                found.setdefault(modname, set()).update(
                    e.value for e in node.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                )
    return found


def check_reference_coverage(only=None, verbose=False):
    """Check 1+3: manifest names resolve through paddle.*; out-of-scope
    entries are real."""
    manifest = {k: set(v) for k, v in REFERENCE_MANIFEST.items()}
    if os.path.isdir(REFERENCE_ROOT):
        for mod, names in _walk_reference(REFERENCE_ROOT).items():
            manifest[mod] |= names
    missing, stale, rows = [], [], []
    for modname in sorted(manifest):
        if only and modname != only:
            continue
        try:
            mod = importlib.import_module(modname)
        except Exception as e:  # a whole missing module: every name missing
            missing.extend(f"{modname}.{n} (module import failed: {e})"
                           for n in sorted(manifest[modname]))
            continue
        have = set(dir(mod)) | _public_names(mod)
        oos = {n for n in manifest[modname]
               if f"{modname}.{n}" in OUT_OF_SCOPE}
        cov = manifest[modname] & have
        mis = manifest[modname] - have - oos
        stale.extend(f"{modname}.{n}" for n in sorted(oos & have))
        missing.extend(f"{modname}.{n}" for n in sorted(mis))
        rows.append((modname, len(cov), len(mis), len(oos)))
        if verbose and mis:
            print(f"  {modname} missing: {', '.join(sorted(mis))}")
    return rows, missing, stale


def check_alias_completeness(verbose=False):
    """Check 2: every paddle_tpu public name resolves via paddle.*."""
    import paddle  # noqa: F401
    import paddle_tpu

    unaliased = []
    mods = sorted(
        k for k in list(sys.modules)
        if (k == "paddle_tpu" or k.startswith("paddle_tpu."))
        and sys.modules[k] is not None
        and not any(k.startswith(p) for p in _INVERSE_SKIP_PREFIXES)
    )
    for name in mods:
        alias = "paddle" + name[len("paddle_tpu"):]
        try:
            amod = importlib.import_module(alias)
        except Exception:
            unaliased.append(f"{alias} (module)")
            continue
        src = sys.modules[name]
        for n in sorted(_public_names(src)):
            if n == "annotations":  # `from __future__ import annotations`
                continue
            if not hasattr(amod, n):
                unaliased.append(f"{alias}.{n}")
    if verbose and unaliased:
        print("  unaliased:", ", ".join(unaliased))
    return unaliased


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--verbose", action="store_true")
    ap.add_argument("--module", help="lint a single module path")
    args = ap.parse_args(argv)

    rows, missing, stale = check_reference_coverage(
        only=args.module, verbose=args.verbose
    )
    print(f"{'module':38s} {'covered':>8s} {'missing':>8s} "
          f"{'out-of-scope':>13s}")
    for modname, cov, mis, oos in rows:
        print(f"{modname:38s} {cov:8d} {mis:8d} {oos:13d}")

    unaliased = [] if args.module else check_alias_completeness(
        verbose=args.verbose
    )
    total_cov = sum(r[1] for r in rows)
    print(f"\ncovered {total_cov} reference names across {len(rows)} "
          f"modules; {len(missing)} missing, {len(stale)} stale "
          f"out-of-scope entries, {len(unaliased)} unaliased "
          f"paddle_tpu names")
    for n in missing:
        print(f"MISSING {n}")
    for n in stale:
        print(f"STALE-OUT-OF-SCOPE {n}")
    for n in unaliased:
        print(f"UNALIASED {n}")
    return 1 if (missing or stale or unaliased) else 0


if __name__ == "__main__":
    sys.exit(main())
