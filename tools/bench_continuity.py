#!/usr/bin/env python
"""Bench continuity gate: compare the two latest `BENCH_r*.json` records
and FAIL on any >10% per-metric median regression that the newer round
did not annotate (VERDICT r5 weak #2 — the "explain every regression"
methodology, made enforceable).

Rules
-----
* Metrics: the headline `metric`/`value` pair plus every numeric
  `extra` key. `*_compile_s` (warm-cache compile times), `vs_*` ratios
  and `*_spread` records are excluded. Direction is inferred from the
  name: `*per_sec*` is higher-is-better, `*_ms`/`*_s` lower-is-better;
  anything else is skipped.
* A regression is WAIVED when
    - the newer round's `extra.incomparable_to_prev` is non-empty (a
      declared methodology break applies to the whole record), or
    - the metric's name appears in the newer round's `extra.note` /
      `extra.incomparable_to_prev` text (per-metric annotation).
* Rounds up to r05 were single-shot on a tunnel-shared chip (±2x jitter
  documented in BENCH/PERF notes); enforcement only makes sense on the
  median-of-N methodology, detected by the presence of `*_spread` keys.
  A newer file without spreads downgrades failures to warnings.

Round 14 (ROADMAP item-2 carry-over): the per-phase MULTICHIP
`compile_s` drift table is GATED at >25% (see
:func:`multichip_compile_check`) with the same note/waiver mechanism.

Usage: `python tools/bench_continuity.py [repo_root]` — exit 1 on an
unwaived regression. `tests/test_hygiene.py::TestBenchContinuity` runs
this over the repo's records in CI and unit-tests the gate on synthetic
pairs.
"""
from __future__ import annotations

import glob
import json
import os
import re
import sys

THRESHOLD = 0.10
#: max % the numerical-guard sentinel may cost the GPT step
#: (bench.py records `guard_overhead_pct` from the on/off pair)
GUARD_OVERHEAD_PCT = 2.0
#: compile-time drift gate between the two latest MULTICHIP dryruns
#: (ISSUE 14 satellite / ROADMAP item-2 carry-over): GSPMD partition
#: cliffs surface as per-phase compile blowups long before a chip run.
#: Looser than the 10% perf gate — compile time on a shared host is
#: noisy — but a >25% unannotated jump now FAILS instead of reporting.
COMPILE_THRESHOLD = 0.25


def _parsed(path: str) -> dict:
    with open(path) as f:
        d = json.load(f)
    return d.get("parsed", d)  # harness wrapper or the bare bench line


def load_latest_pair(root: str):
    """The two most recent BENCH_r*.json by round number, or None."""
    paths = glob.glob(os.path.join(root, "BENCH_r*.json"))
    rounds = []
    for p in paths:
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        if m:
            rounds.append((int(m.group(1)), p))
    rounds.sort()
    if len(rounds) < 2:
        return None
    (_, prev_p), (_, cur_p) = rounds[-2], rounds[-1]
    return (prev_p, _parsed(prev_p)), (cur_p, _parsed(cur_p))


def metric_direction(name: str):
    """+1 higher-is-better, -1 lower-is-better, None = not comparable."""
    if name.startswith("vs_") or name.endswith("_spread"):
        return None
    if name.endswith("_compile_s"):
        return None  # warm-cache artifact, not a perf metric
    if name.endswith("_mfu_pct") or name == "compile_count":
        return None  # observability trend lines (mfu_report), never gated
    if "per_sec" in name:
        return 1
    if name == "serve_failover_recovery_ms_migrate":
        return -1  # round-17 migrate twin of the gated _ms key
    if name == "ctl_live_reclaim_ms":
        # round-20 live lend: the reclaim ladder's wall time scales
        # with whatever queue depth drain happens to find — a load
        # artifact, not a regression signal. The lend-side twin
        # (ctl_live_lend_ms) IS gated by the _ms rule below.
        return None
    if name.endswith("_ms") or name.endswith("_s"):
        return -1
    # round-19 quantization byte accounting: static shape arithmetic,
    # not a timed sample — zero noise, so a >10% move is a structural
    # change (a layer silently falling off the narrow path) and IS
    # gated. The round-11 comm_mb key predates this and stays
    # report-only as documented.
    if name in ("q_ckpt_payload_mb", "gpt_medium_bf16_q8m_moment_mb"):
        return -1
    if name in ("q_ckpt_reduction_x",
                "gpt_medium_bf16_q8m_moment_reduction_x"):
        return 1
    return None


def metrics_of(parsed: dict) -> dict:
    out = {}
    if isinstance(parsed.get("value"), (int, float)) and parsed.get("metric"):
        out[parsed["metric"]] = float(parsed["value"])
    for k, v in (parsed.get("extra") or {}).items():
        if isinstance(v, (int, float)) and metric_direction(k) is not None:
            out[k] = float(v)
    # a *_step_ms key is the same measurement as its sibling *per_sec
    # throughput, un-normalized — it double-counts the comparison and
    # flips spuriously when the batch size changes; keep the throughput
    for k in [k for k in out if k.endswith("_step_ms")]:
        prefix = k[: -len("step_ms")]
        if any(o.startswith(prefix) and "per_sec" in o for o in out):
            del out[k]
    return out


def compare(prev: dict, cur: dict):
    """-> (regressions, waived, improvements): lists of
    (name, prev, cur, change_fraction[, reason])."""
    note = str((cur.get("extra") or {}).get("note", ""))
    incomparable = str(
        (cur.get("extra") or {}).get("incomparable_to_prev", "")
    )
    ann_text = note + " " + incomparable
    pm, cm = metrics_of(prev), metrics_of(cur)
    regressions, waived, improvements = [], [], []
    for name in sorted(set(pm) & set(cm)):
        sign = metric_direction(name)
        if sign is None or pm[name] == 0:
            continue
        change = sign * (cm[name] - pm[name]) / abs(pm[name])
        if change >= 0:
            improvements.append((name, pm[name], cm[name], change))
            continue
        if -change <= THRESHOLD:
            continue
        if incomparable.strip():
            waived.append((name, pm[name], cm[name], change,
                           "incomparable_to_prev declared"))
        elif re.search(  # whole-name match: annotating x_per_sec_dense
            #  must not waive its prefix sibling x_per_sec
            r"(?<![A-Za-z0-9_])" + re.escape(name) + r"(?![A-Za-z0-9_])",
            ann_text,
        ):
            waived.append((name, pm[name], cm[name], change,
                           "annotated in note"))
        else:
            regressions.append((name, pm[name], cm[name], change))
    return regressions, waived, improvements


def _multichip_doc(path: str) -> dict:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _compile_times_of(doc: dict) -> dict:
    out = {}
    for m in re.finditer(
        r"dryrun_multichip\(\d+\): (.+?) loss=\S+ compile_s=([0-9.]+)",
        doc.get("tail", ""),
    ):
        out[m.group(1).strip()] = float(m.group(2))
    return out


def multichip_compile_times(path: str) -> dict:
    """Per-phase `compile_s=` values from a MULTICHIP_r*.json dryrun
    tail, keyed by the phase label (the text between the prefix and the
    loss). Older rounds without compile stamps return {}."""
    return _compile_times_of(_multichip_doc(path))


def _phase_annotated(name: str, note: str, all_names) -> bool:
    """Does ``note`` name this phase? Phase labels are multi-word
    ('dp GPT'), so the perf gate's token-boundary regex is not enough:
    an occurrence only counts when it is not merely part of a LONGER
    sibling label's occurrence — annotating 'dp GPT flash' must not
    waive 'dp GPT'."""
    longer = [o for o in all_names
              if o != name and name in o]
    pat = (r"(?<![A-Za-z0-9_])" + re.escape(name)
           + r"(?![A-Za-z0-9_])")
    covers = []
    for o in longer:
        covers.extend((mo.start(), mo.end())
                      for mo in re.finditer(re.escape(o), note))
    for m in re.finditer(pat, note):
        if not any(s <= m.start() and m.end() <= e for s, e in covers):
            return True
    return False


def multichip_compile_check(root: str):
    """GATED compile-time drift between the two latest
    MULTICHIP_r*.json dryruns (ISSUE 6 introduced the report-only
    table; ISSUE 14 / ROADMAP item-2 promotes it): GSPMD partition
    cliffs on the pod-scale CPU mesh show up as compile-time blowups
    long before a chip run. A phase whose `compile_s` grew more than
    COMPILE_THRESHOLD fails — unless the newer record waives it via
    the SAME mechanism the perf gate uses: a top-level
    ``incomparable_to_prev`` declaration (whole record) or the phase
    label (or the literal token ``compile_s``) appearing in a
    top-level ``note``. New phases and shrinks stay report-only.
    Returns ``(rc, lines)``."""
    paths = glob.glob(os.path.join(root, "MULTICHIP_r*.json"))
    rounds = []
    for p in paths:
        m = re.search(r"MULTICHIP_r(\d+)\.json$", p)
        if m:
            rounds.append((int(m.group(1)), p))
    rounds.sort()
    if len(rounds) < 2:
        return 0, []
    (_, prev_p), (_, cur_p) = rounds[-2], rounds[-1]
    cur_doc = _multichip_doc(cur_p)
    prev, cur = multichip_compile_times(prev_p), _compile_times_of(
        cur_doc)
    note = str(cur_doc.get("note", ""))
    incomparable = str(cur_doc.get("incomparable_to_prev", ""))
    lines = []
    rc = 0
    for name in sorted(set(prev) | set(cur)):
        a, b = prev.get(name), cur.get(name)
        if a is not None and b is not None and a > 0:
            change = (b - a) / a
            if change <= COMPILE_THRESHOLD:
                lines.append(
                    f"  ok      compile_s[{name}]: {a:g} -> {b:g} "
                    f"({change:+.1%}, gate {COMPILE_THRESHOLD:.0%})"
                )
            elif incomparable.strip():
                lines.append(
                    f"  waived  compile_s[{name}]: {a:g} -> {b:g} "
                    f"({change:+.1%}) [incomparable_to_prev declared]"
                )
            elif _phase_annotated(name, note, set(prev) | set(cur)) \
                    or re.search(
                        r"(?<![A-Za-z0-9_])compile_s(?![A-Za-z0-9_])",
                        note):
                lines.append(
                    f"  waived  compile_s[{name}]: {a:g} -> {b:g} "
                    f"({change:+.1%}) [annotated in note]"
                )
            else:
                lines.append(
                    f"  REGRESS compile_s[{name}]: {a:g} -> {b:g} "
                    f"({change:+.1%} > {COMPILE_THRESHOLD:.0%} compile "
                    f"budget)"
                )
                rc = 1
        elif b is not None:
            lines.append(f"  report  compile_s[{name}]: {b:g} (new)")
    if lines:
        lines.insert(0, (
            f"multichip compile-time gate ({COMPILE_THRESHOLD:.0%}): "
            f"{os.path.basename(prev_p)} -> {os.path.basename(cur_p)}"
        ))
    return rc, lines




def mfu_report(prev: dict, cur: dict):
    """REPORT-ONLY drift of the ISSUE-8 observability keys between two
    bench rounds: per-model ``*_mfu_pct`` (achieved-FLOPs utilization —
    moves with every legitimate model change, so a trend line, not a
    gate) and ``compile_count`` (recompile-ledger total: a jump means a
    new recompile source landed in the benched path)."""
    pe, ce = (prev.get("extra") or {}), (cur.get("extra") or {})
    keys = sorted(
        k for k in set(pe) | set(ce)
        if k.endswith("_mfu_pct") or k == "compile_count"
    )
    lines = []
    for k in keys:
        a, b = pe.get(k), ce.get(k)
        if not isinstance(b, (int, float)):
            continue
        if isinstance(a, (int, float)):
            lines.append(f"  report  {k}: {a:g} -> {b:g} (not gated)")
        else:
            lines.append(f"  report  {k}: {b:g} (new)")
    return lines


def check(root: str):
    """-> (exit_code, report_lines)."""
    pair = load_latest_pair(root)
    lines = []
    if pair is None:
        crc, clines = multichip_compile_check(root)
        out = (["bench_continuity: fewer than two BENCH_r*.json — skip"]
               + clines)
        if crc:
            out.append(
                "FAIL: unannotated >25% compile_s regression; either "
                "fix it or name the phase (or 'compile_s') in the "
                "MULTICHIP record's note / declare incomparable_to_prev"
            )
        return crc, out
    (prev_p, prev), (cur_p, cur) = pair
    lines.append(
        f"bench_continuity: {os.path.basename(prev_p)} -> "
        f"{os.path.basename(cur_p)} (threshold {THRESHOLD:.0%})"
    )
    regressions, waived, improvements = compare(prev, cur)
    enforce = any(
        k.endswith("_spread") for k in (cur.get("extra") or {})
    )
    for name, a, b, c in improvements:
        lines.append(f"  ok      {name}: {a:g} -> {b:g} ({c:+.1%})")
    for name, a, b, c, why in waived:
        lines.append(f"  waived  {name}: {a:g} -> {b:g} ({c:+.1%}) [{why}]")
    for name, a, b, c in regressions:
        tag = "REGRESS" if enforce else "warn   "
        lines.append(f"  {tag} {name}: {a:g} -> {b:g} ({c:+.1%})")
    if regressions and not enforce:
        lines.append(
            "  (single-shot round — no *_spread keys — regressions "
            "reported, not enforced)"
        )
    rc = 1 if (regressions and enforce) else 0
    # absolute gate: the in-graph numerical sentinel's cost on the GPT
    # step (guard on vs off, recorded by bench.py) must stay under
    # GUARD_OVERHEAD_PCT — waivable by naming guard_overhead_pct in the
    # round's note, like any other regression
    gp = (cur.get("extra") or {}).get("guard_overhead_pct")
    if isinstance(gp, (int, float)):
        note_txt = str((cur.get("extra") or {}).get("note", "")) + " " + \
            str((cur.get("extra") or {}).get("incomparable_to_prev", ""))
        if gp <= GUARD_OVERHEAD_PCT:
            lines.append(f"  ok      guard_overhead_pct: {gp:g}% "
                         f"(gate {GUARD_OVERHEAD_PCT:g}%)")
        elif "guard_overhead_pct" in note_txt:
            lines.append(f"  waived  guard_overhead_pct: {gp:g}% "
                         f"[annotated in note]")
        elif enforce:
            lines.append(f"  REGRESS guard_overhead_pct: {gp:g}% > "
                         f"{GUARD_OVERHEAD_PCT:g}% sentinel budget")
            rc = 1
        else:
            lines.append(f"  warn    guard_overhead_pct: {gp:g}% > "
                         f"{GUARD_OVERHEAD_PCT:g}% (single-shot round)")
    lines.extend(mfu_report(prev, cur))
    crc, clines = multichip_compile_check(root)
    lines.extend(clines)
    rc = rc or crc
    if rc:
        lines.append(
            "FAIL: unannotated >10% regression(s), guard-overhead "
            "budget breach, or >25% compile_s drift; either fix it or "
            "explain it in extra.note / the MULTICHIP note / declare "
            "incomparable_to_prev"
        )
    return rc, lines


def main(argv):
    root = argv[1] if len(argv) > 1 else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    rc, lines = check(root)
    print("\n".join(lines))
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv))
