#!/usr/bin/env python
"""Deterministic replay of a captured diverged training step.

When the in-graph sentinel (utils/train_guard.py) trips inside the fused
``TrainStep`` XLA program, the guard dumps a *replay bundle* to
``PADDLE_GUARD_DUMP_DIR``: the step's parameters/buffers, the batch
(inputs + labels), the RNG key, and the health word. The compiled step
can say *that* the step went nonfinite but not *where* — XLA fused the
whole program. This tool re-executes the captured step **eagerly** (one
op per dispatch, the reference's interpreter granularity) with
``FLAGS_check_nan_inf`` armed, so the per-op tripwire — forward outputs
AND backward cotangents (core/autograd.py) — names the first op that
produced the NaN/Inf: "loss is NaN" becomes a ``phase:op`` diagnosis.

Library use (what tests/test_train_guard.py drives)::

    from tools.replay_step import replay
    report = replay("guard_step00000007.rank0.pdbundle", model, loss_fn)
    report["faulting_op"]   # e.g. "exp"
    report["phase"]         # "forward" | "backward"

CLI use — the builder callable returns ``(model, loss_fn)`` shaped like
the TrainStep ctor arguments (loss_fn receives ``(outputs, *labels)``)::

    python tools/replay_step.py <bundle.pdbundle> --builder mymod:build
    python tools/replay_step.py <bundle.pdbundle> --builder mymod:build \
        --float64     # re-run in f64: still nonfinite => true overflow,
                      # finite => f32/bf16 precision, not the math

RNG fidelity: eager draws are re-seeded from the bundle's recorded step
key, so dropout-bearing replays are deterministic per invocation; the
eager split sequence is not bit-identical to the traced fold_in stream,
which matters only when the divergence is driven by one specific mask
(re-run a few times, or replay with the model in eval()).
"""
from __future__ import annotations

import argparse
import importlib
import json
import os
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _load_bundle(bundle):
    if isinstance(bundle, dict):
        return bundle
    from paddle_tpu.framework import io as fio

    return fio.load(bundle, return_numpy=True)


def _seed_rng(key_data):
    """Re-seed the eager RNG stream from the recorded step key."""
    import jax

    from paddle_tpu.core import random as rnd

    if key_data is None:
        return
    raw = np.asarray(key_data, np.uint32)
    try:
        key = jax.random.wrap_key_data(raw)
    except Exception:  # noqa: BLE001 — older raw uint32[2] key form
        import jax.numpy as jnp

        key = jnp.asarray(raw)
    with rnd._lock:
        rnd._key = key


def _to_float64(model, state):
    """Best-effort f64 mode: enable x64, widen params/buffers so
    set_state_dict keeps the f64 values instead of casting back down."""
    import jax

    jax.config.update("jax_enable_x64", True)
    for t in model.state_dict().values():
        if np.issubdtype(np.dtype(t.dtype), np.floating):
            t._data = t._data.astype("float64")
    return {
        k: (np.asarray(v, np.float64)
            if np.issubdtype(np.asarray(v).dtype, np.floating) else v)
        for k, v in state.items()
    }


def replay(bundle, model, loss_fn, float64=False, check_backward=True):
    """Re-execute the captured step eagerly under FLAGS_check_nan_inf.

    Returns a report dict: ``ok`` (True = replay stayed finite),
    ``faulting_op`` / ``phase`` / ``message`` (the first tripped op),
    plus the bundle's recorded ``step`` / ``health_bits`` /
    ``fingerprint`` for cross-checking against the guard event line.
    """
    import paddle_tpu as paddle
    from paddle_tpu.core import autograd as AG
    from paddle_tpu.core.tensor import Tensor

    data = _load_bundle(bundle)
    report = {
        "bundle": bundle if isinstance(bundle, str) else "<dict>",
        "step": data.get("step"),
        "health_bits": data.get("health_bits"),
        "fingerprint": data.get("fingerprint"),
        "float64": bool(float64),
        "ok": True, "faulting_op": None, "phase": None, "message": "",
    }
    state = data.get("state") or {}
    inputs = [np.asarray(x) for x in data.get("inputs", [])]
    labels = [np.asarray(y) for y in data.get("labels", [])]
    if float64:
        state = _to_float64(model, state)
        inputs = [x.astype(np.float64)
                  if np.issubdtype(x.dtype, np.floating) else x
                  for x in inputs]
    if state:
        model.set_state_dict(state)
    _seed_rng(data.get("key_data"))
    model.train()
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        ins = [paddle.to_tensor(x) for x in inputs]
        labs = [paddle.to_tensor(y) for y in labels]
        out = model(*ins)
        loss = loss_fn(out, *labs)
        loss_raw = loss._data if isinstance(loss, Tensor) else loss
        if not bool(np.isfinite(np.asarray(loss_raw)).all()):
            # every op stayed finite but the composition didn't — the
            # loss_fn itself (outside the per-op dispatch) is the site
            raise AG.NanInfError("loss_fn", "forward")
        if check_backward:
            loss.backward()
            for name, p in model.named_parameters():
                if p.grad is not None and not bool(
                        np.isfinite(np.asarray(p.grad._data)).all()):
                    raise AG.NanInfError(f"param_grad[{name}]", "backward")
    except AG.NanInfError as e:
        report.update(ok=False, faulting_op=e.op_name, phase=e.phase,
                      message=str(e))
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})
    return report


def _resolve_builder(spec: str):
    mod, sep, attr = spec.partition(":")
    if not sep:
        raise SystemExit(f"--builder wants module:callable, got {spec!r}")
    return getattr(importlib.import_module(mod), attr)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("bundle", help="guard_step*.pdbundle path")
    ap.add_argument("--builder", required=True,
                    help="module:callable returning (model, loss_fn)")
    ap.add_argument("--float64", action="store_true",
                    help="re-run in float64 to separate true overflow "
                         "from low-precision artifacts")
    ap.add_argument("--no-backward", action="store_true",
                    help="forward-only replay")
    args = ap.parse_args(argv)
    model, loss_fn = _resolve_builder(args.builder)()
    report = replay(args.bundle, model, loss_fn, float64=args.float64,
                    check_backward=not args.no_backward)
    print(json.dumps(report, indent=1, default=str))
    if report["ok"]:
        print("replay: step stayed finite (divergence is data/state "
              "dependent — check the scaler/optimizer state, or re-run "
              "with --float64 off)", file=sys.stderr)
        return 0
    print(f"replay: first nonfinite at {report['phase']} op "
          f"'{report['faulting_op']}'", file=sys.stderr)
    return 3


if __name__ == "__main__":
    sys.exit(main())
